"""Benchmark: Qwen2-0.5B importance-guided quantization sweep throughput.

Reproduces the reference's headline workload — the Qwen2-0.5B sweep of
``Experiments/Qwen2-0.5B/main.py``: per 32-token stride over a 512-token window,
importance scoring for 4 methods from a full attention pass, then
4 methods x 1 split layer x 5 ratios quantized evaluations. The reference runs
1 eager + 20 quantized FULL forwards per chunk at ~16.0 s/chunk on its Colab GPU
(``Notebooks/qwen2-0.5B_experiment.ipynb`` cell 12, BASELINE.md). Here the same
sweep is one stats forward + window-batched vmapped layer suffixes with the
full-vocab unembed restricted to the scored tail positions.

Stdout contract: the FINAL line is one compact headline JSON object
{"metric", "value", "unit", "vs_baseline", ...} where vs_baseline > 1 means
faster than the reference's s/chunk on its hardware, plus observability
fields: tokens_per_s (scored tokens), model_tflops_per_s, mfu, and (on TPU)
mfu_vs_measured/relevance anchors. Verbose blocks (pallas probe, relevance
detail, flop accounting) are printed as a separate {"detail": ...} line
BEFORE it and written to BENCH_DETAIL.json (BENCH_DETAIL_PATH overrides) —
the driver's tail capture truncates giant lines, so the headline must stay
small and last.

Env knobs: BENCH_MODEL (any model preset, default qwen2-0.5b — the
vs_baseline ratio is only meaningful against the reference's Qwen2-0.5B
anchor), BENCH_CHUNKS (default 96), BENCH_WINDOW_BATCH (default 64 — batches
evaluation windows into one executable to feed the MXU; OOM backs off by
halving instead of dying), BENCH_DTYPE (float32|bfloat16, default bfloat16),
BENCH_PEAK_TFLOPS (assumed bf16 peak for the MFU denominator, default 197 =
TPU v5e), BENCH_MEASURE_PEAK (default 1 on TPU: also measure the chip's
achievable bf16 matmul ceiling and report mfu_vs_measured), BENCH_PALLAS
(default 1 on TPU: append the on-silicon Pallas codec parity+throughput
block), BENCH_RELEVANCE (default 1 on TPU: append LRP head-relevance
extraction throughput, reference anchor 2.1 it/s), BENCH_REL_CHUNKS
(default 24), BENCH_REL_WINDOW_BATCH (requested relevance batch, preflighted
down to fit, default 16), BENCH_HBM_GB (device memory for the window-batch
preflight, default 15.75).

BENCH_DECODE=1 switches the bench to the KV-cached incremental decode
workload instead of the sweep (see ``decode_main``): headline unit becomes
``decode tokens/s``, with the per-step split-boundary hop bytes/token in the
detail sidecar. The stdout contract is identical.

BENCH_FAULTS=1 switches to the boundary-wire robustness workload (see
``faults_main``): a seeded fault-rate sweep over the REAL split runtime — PPL
and per-hop detected/retried/recovered/substituted counters per rate in the
detail sidecar, plus clean-vs-faulty split decode tokens/s when >= 2 devices
are visible. Knobs: BENCH_FAULT_RATES (comma floats, default "0,0.05,0.2"),
BENCH_FAULT_KNOB (drop_rate|bitflip_rate|scale_corrupt_rate),
BENCH_FAULT_RETRIES, BENCH_FAULT_CODEC, BENCH_FAULT_CHUNKS, BENCH_FAULT_SEED.

BENCH_FEC=1 switches to the self-healing-link workload (see ``fec_main``):
the fault sweep with the PR 5 ladder armed — FEC parity repair, hedged
routes, burn-rate link health — reporting PPL, decode tokens/s, the declared
wire overhead of the redundancy, and the repaired-vs-retried hop counter
split. Knobs: BENCH_FEC_RATES, BENCH_FEC_KNOB (default bitflip_rate),
BENCH_FEC_GROUP_SIZE, BENCH_FEC_GROUPS, BENCH_FEC_ROUTES, plus the shared
BENCH_FAULT_* knobs.

Every section preflights the accelerator backend: an environmental outage
(``Unable to initialize backend``) emits a partial artifact whose headline
carries ``"status": "backend_unavailable"`` and the skipped section name,
and the bench exits 0 — the driver gets an auditable artifact instead of a
bare rc=1.

BENCH_LINT=1 runs no workload: it pre-flights the build through the
graphlint static-analysis gate (``python -m edgellm_tpu.lint``, REPRODUCING
§8) and exits with its status — cheap insurance before a long accelerator
reservation.

BENCH_RECOVERY=1 switches to the survivable-decode workload (see
``recovery_main``): clean split decode tokens/s, checkpoint-and-resume
latency (with the DecodeCheckpoint size), and end-to-end throughput across
an injected stage loss with boundary re-planning failover. Knobs:
BENCH_RECOVERY_PROMPT, BENCH_RECOVERY_TOKENS, BENCH_RECOVERY_BATCH,
BENCH_RECOVERY_CODEC.

BENCH_OBS=1 switches to the observability smoke (see ``obs_main``): the full
obs stack armed (metrics registry + span tracer + latency SLOs), a short
instrumented decode (single-device, plus the 2-stage split when >= 2 devices
are visible), then a metrics snapshot written to BENCH_OBS_METRICS_PATH
(default BENCH_OBS_METRICS.json; a .prom/.txt suffix switches to Prometheus
text format) and a Perfetto-loadable Chrome trace to BENCH_OBS_TRACE_PATH
(default BENCH_OBS_TRACE.json). Knobs: BENCH_OBS_PROMPT (default 32),
BENCH_OBS_TOKENS (default 32), BENCH_OBS_BATCH (default 2), plus the shared
BENCH_MODEL / BENCH_DTYPE.

BENCH_OBS_LIVE=1 switches to the live-telemetry chaos smoke (see
``obs_live_main``): the full obs stack plus the flight recorder armed, a
ServeFront over the 2-stage split runtime with the telemetry endpoint on an
OS-assigned port, the chaos soak (mid-soak stage kill) on a background
thread while the foreground scrapes /metrics and /healthz live, and a hard
assertion that the kill produced exactly one CRC-verified flight artifact.
Knobs: BENCH_OBS_LIVE_REQUESTS (default 24), BENCH_OBS_LIVE_RATE (default
2.0), BENCH_OBS_LIVE_FLIGHT_DIR, BENCH_OBS_LIVE_METRICS_PATH,
BENCH_OBS_LIVE_HEALTH_PATH, plus the shared BENCH_MODEL / BENCH_DTYPE.

BENCH_SOAK=1 switches to the deterministic chaos soak over the serving
front (see ``soak_main``): seeded Poisson open-loop arrivals pushed through
a ServeFront on a virtual clock, a mid-soak stage kill and a
link-corruption burst fired by arrival index, and an artifact reporting
goodput tokens/s, SLO attainment, reject/shed rates, p99 TTFT, post-kill
recovery time, retry-budget accounting, and the bit-identity audit of every
completed request against a fault-free reference. Knobs:
BENCH_SOAK_REQUESTS (default 24), BENCH_SOAK_RATE (virtual arrivals/s,
default 0.5 — below the tiny-model service rate so the burst window spans
served requests; raise above the service rate to drive overload),
BENCH_SOAK_PROMPT (default 8), BENCH_SOAK_TOKENS (default 8),
BENCH_SOAK_DEADLINE_S (virtual-seconds deadline per request, default 60),
BENCH_SOAK_CORRUPT (burst-window per-attempt drop rate, default 0.2),
BENCH_SOAK_SEED, plus the shared BENCH_MODEL / BENCH_DTYPE.

BENCH_CLUSTER=1 switches to the replica-router acceptance surface (see
``cluster_main``), two legs in one section. Leg (a), real model: a
2-replica fleet of continuous-batching ServeFronts behind the
prefix-affinity router, replica 0 killed mid-workload, and the SAME
request plan rerun on a fault-free single replica — every completed
request must be token-identical to the rerun (greedy AND sampled via
recorded seeds), every record must report zero decode-step jit misses
(one warm twin heats the fleet's shared cache), and the kill must dump
exactly one flight-recorder post-mortem. Leg (b), simulated scale: the
discrete-event chaos soak (``run_cluster_soak``) at
BENCH_CLUSTER_REQUESTS (default 1_000_000) over BENCH_CLUSTER_REPLICAS
(default 4) simulated replicas with two scheduled kills and a
link-corruption burst, plus two fault-free control runs of the same
arrival plan — the same fleet, and a single replica at equal TOTAL
capacity (per-token service times divided by N, queue depth multiplied
by N). Gates, all in the headline line: chaos-run token identity, zero
accepted loss, exactly one flight dump per induced kill, outage-window
goodput >= 90% of the no-fault run (per kill, over
BENCH_CLUSTER_OUTAGE_S virtual seconds from the kill), and no-fault
fleet goodput/SLO no worse than the equal-capacity single replica.
Knobs: BENCH_CLUSTER_REQUESTS, BENCH_CLUSTER_REPLICAS,
BENCH_CLUSTER_RATE (virtual arrivals/s, default 80), BENCH_CLUSTER_SEED,
BENCH_CLUSTER_OUTAGE_S (default 10), BENCH_CLUSTER_REAL (0 skips the
real-model leg), BENCH_CLUSTER_REAL_REQUESTS (default 12), plus the
shared BENCH_MODEL / BENCH_DTYPE.

BENCH_GRAY=1 switches to the gray-failure acceptance surface (see
``gray_main``): a 3-replica simulated fleet where one replica silently
degrades 20x mid-run (after prefix affinity has captured most groups onto
it), run with the gray plane armed (straggler demotion + latency-quantile
hedging + deadline propagation), disabled, and with no slowdown. Gates:
hedged SLO goodput >= 1.5x the unhedged slowed fleet and >= 0.9x the
no-slowdown fleet, hedge overhead <= max_hedge_fraction, token identity on
every completed request, zero accepted loss / FAILED outcomes. Knobs:
BENCH_GRAY_REQUESTS (default 600), BENCH_GRAY_RATE (virtual arrivals/s,
default 30), BENCH_GRAY_SEED, BENCH_GRAY_REPLICAS (default 3),
BENCH_GRAY_SLOW_MULT (default 20), BENCH_GRAY_SLOW_AT (arrival fraction
where the slowdown fires, default 0.3), BENCH_GRAY_DEADLINE_S (default
0.5).

BENCH_DISAGG=1 switches to the disaggregated prefill/decode acceptance
surface (see ``disagg_main``), two legs in one section. Leg (a), perf: a
mixed long/short Poisson workload (half greedy, half sampled via recorded
seeds) is served twice — once by the DisaggServer (dedicated prefill
workers migrating quantize-at-rest KV pages over the FEC-framed link to
the pull-admission decode worker) and once by the colocated continuous
batcher — and every completed request must be TOKEN-IDENTICAL between the
two; the headline carries disagg vs colocated TTFT and decode tok/s.
Leg (b), chaos: ``run_disagg_soak`` fires a mid-migration prefill-worker
kill, a decode-worker kill, and a link-corruption burst into the same
seeded workload — gates: zero accepted loss, token identity vs the
fault-free colocated reference, no degrade (the ladder absorbs the
burst), and at least one page re-driven or recomputed by the kill.
Knobs: BENCH_DISAGG_REQUESTS (default 16), BENCH_DISAGG_SEED,
BENCH_DISAGG_LONG (long-prompt length, default 48), BENCH_DISAGG_SHORT
(default 8), BENCH_DISAGG_TOKENS (default 8), BENCH_DISAGG_CORRUPT
(burst bitflip rate, default 0.01), plus the shared
BENCH_MODEL / BENCH_DTYPE.

BENCH_SERVE=1 switches to the continuous-batching workload (see
``serve_main``): the SAME seeded Poisson open-loop arrival trace is served
twice on a virtual clock — once by the paged continuous batcher (streams
admitted/evicted mid-flight into one compiled ragged step) and once by
classic static batching (wait for a full batch, pad every row to the
worst case, run ``generate``). The artifact reports sustained tokens/s,
p50/p99 per-token latency, p50/p99 TTFT, and mean cache-slot occupancy
(live tokens per reserved token — static reserves batch x worst-case up
front, the paged server reserves only allocated pages) for both, plus the
occupancy delta (the paged pool's reason to exist). Knobs:
BENCH_SERVE_REQUESTS (default 24), BENCH_SERVE_RATE (virtual arrivals/s,
default 2.0), BENCH_SERVE_PROMPT (max prompt tokens, default 16 — lengths
draw uniformly from [PROMPT/2, PROMPT]), BENCH_SERVE_TOKENS (max new
tokens, default 16, same ragged draw), BENCH_SERVE_SLOTS (concurrent
streams / static batch size, default 8), BENCH_SERVE_PAGE_SIZE (default
8), BENCH_SERVE_PAGES (pool pages incl. the trash page; default sizes the
pool to the static baseline's reservation), BENCH_SERVE_SEED, plus the
shared BENCH_MODEL / BENCH_DTYPE.

BENCH_PREFIX=1 switches to the prefix-sharing workload (see
``prefix_main``): one seeded Poisson trace whose prompts all open with the
same BENCH_PREFIX_SHARED-token system prompt, served twice at the SAME
fixed pool geometry — prefix cache off, then on. The artifact asserts
token parity between the two runs and reports prefill tokens saved, the
prefix-index hit rate, COW fork count, and peak concurrently-running
streams per run (the pool is deliberately sized to half the exclusive
reservation, so the enabled run must admit strictly more concurrent
streams at the same page budget). Knobs: BENCH_PREFIX_REQUESTS (default
24), BENCH_PREFIX_RATE (default 8.0), BENCH_PREFIX_PROMPT (default 24),
BENCH_PREFIX_SHARED (default 16), BENCH_PREFIX_TOKENS (default 8),
BENCH_PREFIX_SLOTS (default 6), BENCH_PREFIX_PAGE_SIZE (default 8),
BENCH_PREFIX_PAGES, BENCH_PREFIX_SEED, plus the shared BENCH_MODEL /
BENCH_DTYPE.

BENCH_KVQ=1 switches to the KV-at-rest quantization workload (see
``kvq_main``): one seeded Poisson trace served once per KV page tier (fp /
int8_per_channel / int4_per_channel) at the SAME pool byte budget — the
quantized tiers fit more pages into the budget, so peak admitted
concurrency is the capacity multiplier, and ``run_kv_tier_eval`` measures
each tier's PPL through the exact serving data path. The artifact records
per-tier pool bytes, live-tokens-per-HBM-byte, peak concurrency, PPL delta
vs fp, and jit_misses. Knobs: BENCH_KVQ_REQUESTS (default 24),
BENCH_KVQ_RATE (default 8.0), BENCH_KVQ_PROMPT (default 24),
BENCH_KVQ_TOKENS (default 8), BENCH_KVQ_SLOTS (default 6),
BENCH_KVQ_PAGE_SIZE (default 8), BENCH_KVQ_POOL_BYTES, BENCH_KVQ_PPL_*
(WINDOW/STRIDE/CHUNKS/BATCH), BENCH_KVQ_SEED, plus the shared BENCH_MODEL
/ BENCH_DTYPE.

BENCH_WIRE=1 switches to the fused boundary-hop workload (see
``wire_main``): every FUSED_CAPABLE codec crosses a real 2-stage boundary
through the fused single-buffer wire hop AND the separate
encode/ppermute/decode ladder; the receiver rows must be bit-identical,
and on TPU the fused-vs-fallback roundtrip ratio is timed and recorded to
the probe cache under ``fused_hop:<codec>`` (the measurement the plan gate
requires — and the artifact asserts no codec that WOULD be substituted
into the default path times slower than its jnp ladder, so a regressed
kernel is demoted before serving ever reuses it). Knobs: BENCH_WIRE_BATCH
/ BENCH_WIRE_SEQ / BENCH_WIRE_DIM (default 8x512x896), BENCH_WIRE_ITERS
(default 20).

BENCH_SPEC=1 switches to the speculative split-decode workload (see
``spec_main``): vanilla ``generate_split`` (one boundary hop per token) vs
the stage-0-draft + k-token batched-verify loop over the same quantized
boundary, asserting greedy token parity and reporting hops-per-token,
acceptance rate, and the tokens/s ratio per k. Knobs: BENCH_SPEC_PROMPT
(default 32), BENCH_SPEC_TOKENS (default 64), BENCH_SPEC_K (headline k,
default 4), BENCH_SPEC_KS (default "1,2,4,8"), BENCH_SPEC_CODEC,
BENCH_SPEC_DRAFT_LAYERS, plus the shared BENCH_MODEL / BENCH_DTYPE /
BENCH_REPEATS.

BENCH_PIPE=1 switches to the micro-batch pipelined split-decode workload
(see ``pipe_main``): the sequential vs pipelined schedule over the same
quantized boundary at n_stages in BENCH_PIPE_STAGES (default "2,3,4"),
asserting greedy token parity ALWAYS and, when timed (real accelerator or
BENCH_PIPE_TIME=1), reporting tokens/s and the measured steady-state
pipeline-bubble fraction per stage count. Knobs: BENCH_PIPE_STAGES,
BENCH_PIPE_MICRO (default 4), BENCH_PIPE_PROMPT (default 16),
BENCH_PIPE_TOKENS (default 32), BENCH_PIPE_CODEC, BENCH_PIPE_BATCH, plus
the shared BENCH_MODEL / BENCH_DTYPE / BENCH_REPEATS.

Every artifact (headline sidecar) carries a ``meta`` provenance block —
schema_version, git commit, jax/jaxlib versions, backend, UTC timestamp —
attached centrally in ``_emit``; readers must tolerate its absence in
artifacts recorded before schema_version 2. When the process-global metrics
registry is enabled, ``_emit`` also folds its snapshot into the sidecar as
``detail["metrics"]``.

An over-large BENCH_WINDOW_BATCH never kills the bench: on TPU an AOT
memory-analysis preflight (tools/wb_preflight.py) halves it to the largest
batch whose estimated peak fits BEFORE anything runs (a real TPU OOM would
poison the process allocator); on other backends the warmup halves in-process
on RESOURCE_EXHAUSTED. The headline reports the effective batch; the detail
block records the requested one.
"""
import json
import os
import time

import numpy as np

REFERENCE_S_PER_CHUNK = 16.0  # qwen2-0.5B_experiment.ipynb cell 12 (BASELINE.md)

# bumped to 2 when the `meta` provenance block + optional `metrics` snapshot
# landed in the detail sidecar; readers must .get() both (v1 artifacts lack
# them)
BENCH_SCHEMA_VERSION = 2


def _bench_meta() -> dict:
    """Provenance block attached to every artifact: enough to tie a recorded
    number back to the exact build + toolchain that produced it. Every field
    degrades to None rather than failing the bench — provenance must never
    cost an artifact."""
    meta: dict = {
        "schema_version": BENCH_SCHEMA_VERSION,
        "timestamp_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_commit": None,
        "jax_version": None,
        "jaxlib_version": None,
        "backend": None,
    }
    try:
        import subprocess

        proc = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            timeout=10, cwd=os.path.dirname(os.path.abspath(__file__)))
        meta["git_commit"] = proc.stdout.strip() or None
    except (OSError, subprocess.SubprocessError):
        pass
    try:
        import jax
        import jaxlib

        meta["jax_version"] = jax.__version__
        meta["jaxlib_version"] = jaxlib.__version__
        meta["backend"] = jax.default_backend()
    except (ImportError, RuntimeError):
        # backend init can fail on an accelerator outage — the artifact (with
        # its backend_unavailable status) still deserves its meta block
        pass
    return meta


def _emit(line: dict, detail: dict) -> None:
    """The stdout/sidecar contract shared by every bench mode: verbose detail
    to an atomic sidecar + an earlier {"detail": ...} line, compact headline
    JSON as the FINAL line (the driver's tail capture truncates giant lines).
    Centrally stamps the ``meta`` provenance block and, when the global
    metrics registry is enabled, folds its snapshot in as
    ``detail["metrics"]``."""
    detail.setdefault("meta", _bench_meta())
    from edgellm_tpu.obs.metrics import get_registry

    reg = get_registry()
    if reg.enabled and "metrics" not in detail:
        detail["metrics"] = reg.snapshot()
    detail_path = os.environ.get("BENCH_DETAIL_PATH", "BENCH_DETAIL.json")
    try:
        # the harness's atomic tmp+rename writer: never a half-written sidecar
        from edgellm_tpu.eval.harness import _save_checkpoint_state

        _save_checkpoint_state(detail_path, detail)
    except OSError as e:
        import sys

        print(f"bench: could not write {detail_path}: {e}", file=sys.stderr)
    print(json.dumps({"detail": detail}))
    print(json.dumps(line))


def decode_main():
    """BENCH_DECODE=1: KV-cached incremental decode throughput (tokens/s).

    One prefill + N decode_step calls per pass via serve.generate; the
    headline value is the best sustained decode tokens/s over BENCH_REPEATS
    passes (same phase-drift rationale as the sweep's best-of-N). Knobs:
    BENCH_DECODE_PROMPT (prompt tokens, default 128), BENCH_DECODE_TOKENS
    (new tokens per row, default 128), BENCH_DECODE_BATCH (default 8),
    BENCH_DECODE_CODEC (split-boundary wire codec accounted in the detail
    sidecar, default int8_per_token), BENCH_DECODE_SPLIT=1 (additionally run
    the 2-stage pipeline-split decode when >= 2 devices are visible and
    record its measured hop bytes/token), plus the shared BENCH_MODEL,
    BENCH_DTYPE and BENCH_REPEATS."""
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.serve.decode import generate

    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    prompt = int(os.environ.get("BENCH_DECODE_PROMPT", "128"))
    new_tokens = int(os.environ.get("BENCH_DECODE_TOKENS", "128"))
    batch = int(os.environ.get("BENCH_DECODE_BATCH", "8"))
    repeats = max(int(os.environ.get("BENCH_REPEATS", "2")), 1)
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]
    codec_name = os.environ.get("BENCH_DECODE_CODEC", "int8_per_token")
    capacity = prompt + new_tokens

    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, prompt)))

    warm: dict = {}
    generate(cfg, params, ids, new_tokens, capacity=capacity,
             compute_dtype=dtype, stats=warm)  # compile prefill + step
    passes = []
    prefill_s = []
    for _ in range(repeats):
        st: dict = {}
        generate(cfg, params, ids, new_tokens, capacity=capacity,
                 compute_dtype=dtype, stats=st)
        passes.append(st["decode_tokens_per_s"])
        prefill_s.append(st["prefill_s"])
    tokens_per_s = max(passes)  # full precision; rounded only for display

    # SLO leg: the same passes with the LatencyObserver attached — TTFT +
    # per-token latency percentiles for the headline, and the measured
    # instrumented-vs-clean throughput delta (the regression test holds this
    # under 3%; the artifact records the number it enforces)
    from edgellm_tpu.obs.latency import LatencyObserver

    observe = LatencyObserver()
    obs_passes = []
    for _ in range(repeats):
        st = {}
        generate(cfg, params, ids, new_tokens, capacity=capacity,
                 compute_dtype=dtype, stats=st, observe=observe)
        obs_passes.append(st["decode_tokens_per_s"])
    slo = observe.summary()
    obs_overhead = max(0.0, 1.0 - max(obs_passes) / tokens_per_s)

    # what a split deployment would move per decode step at this batch: the
    # (B, 1, D) boundary activation through the configured wire codec
    from edgellm_tpu.codecs.packing import get_wire_codec

    codec = get_wire_codec(codec_name)
    hop_bytes_per_token = codec.payload_bytes((batch, 1, cfg.hidden_size)) / batch

    detail = {
        "decode": {
            "prompt": prompt, "new_tokens": new_tokens, "batch": batch,
            "capacity": capacity,
            "passes_tokens_per_s": [round(p, 2) for p in passes],
            "prefill_s": [round(p, 4) for p in prefill_s],
            "decode_step_cache_misses_warm": warm["decode_step_cache_misses"],
            "split_hop_codec": codec_name,
            "split_hop_bytes_per_token": hop_bytes_per_token,
            "observed_passes_tokens_per_s": [round(p, 2) for p in obs_passes],
            "obs_overhead_frac": round(obs_overhead, 4),
            "slo": {k: round(v, 6) for k, v in slo.items()},
        },
    }

    if (os.environ.get("BENCH_DECODE_SPLIT", "0") == "1"
            and len(jax.devices()) >= 2):
        from edgellm_tpu.parallel.split import (SplitConfig, SplitRuntime,
                                                make_stage_mesh)

        cut = cfg.num_layers // 2 - 1
        rt = SplitRuntime(cfg, SplitConfig(cuts=(cut,),
                                           hop_codecs=(codec_name,)),
                          make_stage_mesh(2))
        placed = rt.place_params(params)
        logits, cache = rt.prefill_decode(placed, ids, capacity)
        tok = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
        logits, cache = rt.decode_step(placed, cache, tok)  # compile step
        jax.block_until_ready(logits)
        t0 = time.monotonic()
        for _ in range(new_tokens - 1):
            tok = jnp.argmax(logits, -1).astype(jnp.int32)
            logits, cache = rt.decode_step(placed, cache, tok)
        jax.block_until_ready(logits)
        split_s = time.monotonic() - t0
        detail["decode"]["split"] = {
            "cut": cut,
            "tokens_per_s": round(batch * (new_tokens - 1) / split_s, 2),
            "measured_hop_bytes_per_step": rt.decode_hop_bytes(batch),
            "hop_bytes_per_token": [b / batch
                                    for b in rt.decode_hop_bytes(batch)],
        }

    line = {
        "metric": (f"{model_name} greedy decode throughput "
                   f"(prompt {prompt} +{new_tokens} tokens, batch {batch})"),
        "value": round(tokens_per_s, 1),
        "unit": "decode tokens/s",
        "vs_baseline": None,  # the reference has no autoregressive workload
        "tokens_per_s": round(tokens_per_s, 1),
        "prefill_s": round(min(prefill_s), 4),
        "batch": batch,
        "decode_step_cache_misses": warm["decode_step_cache_misses"],
    }
    # the SLO block is the acceptance surface: TTFT + per-token p50/p95/p99
    # ride the headline (None only if an SLO leg recorded nothing, which a
    # >= 2-token pass never does)
    for k in ("ttft_s", "token_latency_p50_s", "token_latency_p95_s",
              "token_latency_p99_s"):
        v = slo.get(k)
        line[k] = round(v, 6) if v is not None else None
    _emit(line, detail)


def faults_main():
    """BENCH_FAULTS=1: split-boundary robustness under seeded wire faults.

    One :func:`run_fault_sweep` over the real split runtime (rate 0 first —
    the exact fault-free baseline point), then, when >= 2 devices are visible,
    a clean-vs-faulty KV-cached split decode throughput comparison via
    ``serve.generate_split``. The headline value is the PPL at the worst
    swept rate; ``ppl_clean`` / ``ppl_ratio`` and the summed per-rate fault
    counters make the degradation (and the integrity layer's recovery work)
    auditable from the sidecar."""
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.codecs.faults import FaultConfig, LinkPolicy
    from edgellm_tpu.eval.split_eval import run_fault_sweep

    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]
    rates = sorted(float(r) for r in os.environ.get(
        "BENCH_FAULT_RATES", "0,0.05,0.2").split(","))
    knob = os.environ.get("BENCH_FAULT_KNOB", "drop_rate")
    retries = int(os.environ.get("BENCH_FAULT_RETRIES", "2"))
    codec = os.environ.get("BENCH_FAULT_CODEC", "int8_per_token")
    n_chunks = int(os.environ.get("BENCH_FAULT_CHUNKS", "16"))
    seed = int(os.environ.get("BENCH_FAULT_SEED", "0"))
    max_length = int(os.environ.get("BENCH_MAX_LENGTH", "512"))
    stride = int(os.environ.get("BENCH_STRIDE", "256"))
    cut = min(11, cfg.num_layers // 2)

    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    rng = np.random.default_rng(0)
    corpus = rng.integers(0, cfg.vocab_size,
                          max_length + stride * (n_chunks + 2))

    policy = LinkPolicy(max_retries=retries)
    sweep = run_fault_sweep(
        cfg, params, corpus, rates=rates, knob=knob, seed=seed,
        link_policy=policy, cuts=(cut,), hop_codecs=[codec],
        max_length=max_length, stride=stride, max_chunks=n_chunks,
        time_hops=False)
    rows = [{
        "rate": r["fault_rate"], "ppl": round(r["ppl"], 4),
        "tokens_per_s": round(r["tokens_per_s"], 1),
        "link_counters": r.get("link_counters"),
    } for r in sweep]
    ppl_clean, ppl_worst = sweep[0]["ppl"], sweep[-1]["ppl"]
    worst_counters = sweep[-1].get("link_counters", {})

    detail = {"faults": {
        "knob": knob, "rates": rates, "retries": retries, "codec": codec,
        "cut": cut, "seed": seed, "chunks": n_chunks,
        "max_length": max_length, "stride": stride, "sweep": rows,
    }}

    # decode leg: same split, clean vs worst-rate faulty wire
    if len(jax.devices()) >= 2 and max(rates) > 0:
        from edgellm_tpu.parallel.split import (SplitConfig, SplitRuntime,
                                                make_stage_mesh)
        from edgellm_tpu.serve.decode import generate_split

        split = SplitConfig(cuts=(cut,), hop_codecs=(codec,))
        mesh = make_stage_mesh(2)
        prompt, new_tokens, batch = 64, 64, 4
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, prompt)))
        decode = {}
        for label, fc in (
                ("clean", None),
                ("faulty", FaultConfig(**{knob: max(rates)}, seed=seed))):
            rt = SplitRuntime(cfg, split, mesh, faults=fc, policy=policy)
            placed = rt.place_params(params)
            generate_split(rt, placed, ids, new_tokens)  # compile
            st: dict = {}
            generate_split(rt, placed, ids, new_tokens, stats=st)
            decode[label] = {
                "decode_tokens_per_s": round(st["decode_tokens_per_s"], 2)}
            if "link_counters" in st:
                decode[label]["link_counters"] = st["link_counters"]
        detail["faults"]["decode"] = decode

    line = {
        "metric": (f"{model_name} split PPL under {knob}={max(rates)} "
                   f"(cut {cut}, {codec}, retries {retries})"),
        "value": round(ppl_worst, 4),
        "unit": "ppl",
        "vs_baseline": None,  # the reference models a lossless boundary
        "ppl_clean": round(ppl_clean, 4),
        "ppl_ratio": round(ppl_worst / ppl_clean, 4),
        "detected": sum(worst_counters.get("detected", [])),
        "recovered": sum(worst_counters.get("recovered", [])),
        "substituted": sum(worst_counters.get("substituted", [])),
    }
    dec = detail["faults"].get("decode")
    if dec:
        line["decode_tokens_per_s_clean"] = dec["clean"]["decode_tokens_per_s"]
        line["decode_tokens_per_s_faulty"] = dec["faulty"]["decode_tokens_per_s"]
    _emit(line, detail)


def fec_main():
    """BENCH_FEC=1: the self-healing link under seeded wire faults.

    Same fault-rate sweep as ``faults_main`` but with the full PR 5 ladder
    armed — FEC parity repair, hedged routes, and the burn-rate LinkHealth
    tracker — so the headline splits the recovery work into repaired-in-band
    (zero extra hops) vs retried (a full retransmission each). The declared
    wire overhead of the parity scheme rides along so the PPL/throughput
    numbers can be judged against what the redundancy costs on the wire.
    Knobs: BENCH_FEC_RATES (default "0,1e-06,1e-05" — per-BYTE flip rates;
    the forward payload is ~payload_bytes trials per transmission, and parity
    repairs at most one chunk per group, so the interesting regime is ~1-3
    flipped bytes per hop), BENCH_FEC_KNOB (default bitflip_rate — the regime
    parity repair exists for), BENCH_FEC_GROUP_SIZE
    / BENCH_FEC_GROUPS (parity geometry: overhead ~= 1/group_size),
    BENCH_FEC_ROUTES (hedged routes, 0/1 disables hedging),
    BENCH_FEC_DECODE_RATE (decode-leg fault rate — the per-step payload is
    far smaller, so it gets its own flips-per-hop calibration), plus the
    shared BENCH_FAULT_RETRIES/CODEC/CHUNKS/SEED and BENCH_MAX_LENGTH/STRIDE."""
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.codecs.faults import FaultConfig, LinkPolicy
    from edgellm_tpu.codecs.fec import FECConfig, HedgeConfig
    from edgellm_tpu.codecs.packing import get_wire_codec
    from edgellm_tpu.eval.split_eval import run_fault_sweep

    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]
    rates = sorted(float(r) for r in os.environ.get(
        "BENCH_FEC_RATES", "0,1e-06,1e-05").split(","))
    knob = os.environ.get("BENCH_FEC_KNOB", "bitflip_rate")
    retries = int(os.environ.get("BENCH_FAULT_RETRIES", "2"))
    codec = os.environ.get("BENCH_FAULT_CODEC", "int8_per_token")
    n_chunks = int(os.environ.get("BENCH_FAULT_CHUNKS", "16"))
    seed = int(os.environ.get("BENCH_FAULT_SEED", "0"))
    max_length = int(os.environ.get("BENCH_MAX_LENGTH", "512"))
    stride = int(os.environ.get("BENCH_STRIDE", "256"))
    group_size = int(os.environ.get("BENCH_FEC_GROUP_SIZE", "4"))
    n_groups = int(os.environ.get("BENCH_FEC_GROUPS", "4"))
    routes = int(os.environ.get("BENCH_FEC_ROUTES", "2"))
    cut = min(11, cfg.num_layers // 2)

    fec = FECConfig(group_size=group_size, n_groups=n_groups)
    hedge = HedgeConfig(routes=routes) if routes >= 2 else None

    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    rng = np.random.default_rng(0)
    corpus = rng.integers(0, cfg.vocab_size,
                          max_length + stride * (n_chunks + 2))

    # declared wire cost of the redundancy, from the codec's own abstract
    # payload accounting: sealed hop = packed payload + 8-byte integrity
    # sidecar, FEC = interleaved chunks + one parity chunk per group + a
    # uint32 canary word per chunk (all per route, per attempt)
    sealed = get_wire_codec(codec).payload_bytes(
        (1, max_length, cfg.hidden_size)) + 8
    wire_overhead = fec.overhead(sealed)

    policy = LinkPolicy(max_retries=retries)
    sweep = run_fault_sweep(
        cfg, params, corpus, rates=rates, knob=knob, seed=seed,
        link_policy=policy, cuts=(cut,), hop_codecs=[codec],
        max_length=max_length, stride=stride, max_chunks=n_chunks,
        fec=fec, hedge=hedge, time_hops=False)
    rows = [{
        "rate": r["fault_rate"], "ppl": round(r["ppl"], 4),
        "tokens_per_s": round(r["tokens_per_s"], 1),
        "link_counters": r.get("link_counters"),
    } for r in sweep]
    ppl_clean, ppl_worst = sweep[0]["ppl"], sweep[-1]["ppl"]
    worst = sweep[-1].get("link_counters", {})

    detail = {"fec": {
        "knob": knob, "rates": rates, "retries": retries, "codec": codec,
        "cut": cut, "seed": seed, "chunks": n_chunks,
        "max_length": max_length, "stride": stride,
        "group_size": group_size, "n_groups": n_groups, "routes": routes,
        "sealed_hop_bytes": sealed,
        "fec_wire_bytes": fec.wire_nbytes(sealed),
        "wire_overhead": round(wire_overhead, 4),
        "sweep": rows,
    }}

    # decode leg: clean vs faulty wire, both the FEC-armed path and the
    # retry-only PR 2 ladder at the same rate for the repair-vs-retry
    # throughput delta. The per-step payload is ~3 orders smaller than the
    # forward one, so the per-byte rate that gives ~1 flip/hop is its own
    # knob (BENCH_FEC_DECODE_RATE, default 0.002)
    if len(jax.devices()) >= 2 and max(rates) > 0:
        from edgellm_tpu.parallel.split import (SplitConfig, SplitRuntime,
                                                make_stage_mesh)
        from edgellm_tpu.serve.decode import generate_split

        split = SplitConfig(cuts=(cut,), hop_codecs=(codec,))
        mesh = make_stage_mesh(2)
        prompt, new_tokens, batch = 64, 64, 4
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, prompt)))
        decode_rate = float(os.environ.get("BENCH_FEC_DECODE_RATE", "0.002"))
        worst_fc = FaultConfig(**{knob: decode_rate}, seed=seed)
        decode = {}
        for label, fc, kw in (
                ("clean", None, {}),
                ("faulty_retry_only", worst_fc, {}),
                ("faulty_fec", worst_fc, {"fec": fec, "hedge": hedge})):
            rt = SplitRuntime(cfg, split, mesh, faults=fc, policy=policy,
                              **kw)
            placed = rt.place_params(params)
            generate_split(rt, placed, ids, new_tokens)  # compile
            st: dict = {}
            generate_split(rt, placed, ids, new_tokens, stats=st)
            decode[label] = {
                "decode_tokens_per_s": round(st["decode_tokens_per_s"], 2)}
            if "link_counters" in st:
                decode[label]["link_counters"] = st["link_counters"]
        decode["fault_rate"] = decode_rate
        detail["fec"]["decode"] = decode

    line = {
        "metric": (f"{model_name} split PPL under {knob}={max(rates)} with "
                   f"FEC g{group_size}x{n_groups}"
                   + (f" + {routes}-route hedge" if hedge else "")
                   + f" (cut {cut}, {codec}, retries {retries})"),
        "value": round(ppl_worst, 4),
        "unit": "ppl",
        "vs_baseline": None,  # the reference models a lossless boundary
        "ppl_clean": round(ppl_clean, 4),
        "ppl_ratio": round(ppl_worst / ppl_clean, 4),
        "wire_overhead": round(wire_overhead, 4),
        "detected": sum(worst.get("detected", [])),
        "repaired": sum(worst.get("repaired", [])),
        "retried": sum(worst.get("retried", [])),
        "hedge_wins": sum(worst.get("hedge_wins", [])),
        "substituted": sum(worst.get("substituted", [])),
    }
    dec = detail["fec"].get("decode")
    if dec:
        line["decode_tokens_per_s_clean"] = dec["clean"]["decode_tokens_per_s"]
        line["decode_tokens_per_s_faulty"] = (
            dec["faulty_fec"]["decode_tokens_per_s"])
    _emit(line, detail)


def recovery_main():
    """BENCH_RECOVERY=1: survivable split decode — checkpoint/resume latency
    and stage-failover throughput vs the clean split.

    Three legs over ``serve.generate_split``: (1) the clean 2-stage split
    decode (the baseline tokens/s); (2) halt-at-mid-decode with a
    :class:`DecodeCheckpoint` write, then a timed :func:`resume_split` of the
    tail (resume latency + checkpoint size); (3) a stage loss injected at
    mid-decode with failover re-planning onto the survivors (3 stages when
    >= 3 devices are visible, else 2 -> single-device fallback) — the
    headline is the failover run's end-to-end tokens/s, with the clean
    end-to-end rate and their ratio alongside. Knobs: BENCH_RECOVERY_PROMPT
    (default 64), BENCH_RECOVERY_TOKENS (default 64), BENCH_RECOVERY_BATCH
    (default 4), BENCH_RECOVERY_CODEC (default int8_per_token), plus the
    shared BENCH_MODEL / BENCH_DTYPE. With < 2 devices the split legs are
    skipped and the checkpoint/resume leg runs on the single-device loop."""
    import tempfile

    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.serve import RecoveryConfig, StageFailure
    from edgellm_tpu.serve.decode import generate, generate_split, resume_split

    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]
    prompt = int(os.environ.get("BENCH_RECOVERY_PROMPT", "64"))
    new_tokens = int(os.environ.get("BENCH_RECOVERY_TOKENS", "64"))
    batch = int(os.environ.get("BENCH_RECOVERY_BATCH", "4"))
    codec = os.environ.get("BENCH_RECOVERY_CODEC", "int8_per_token")
    capacity = prompt + new_tokens
    halt = new_tokens // 2

    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, prompt)))
    n_dev = len(jax.devices())
    ckpt = os.path.join(tempfile.mkdtemp(prefix="bench_recovery_"), "gen.ckpt")
    detail = {"recovery": {
        "prompt": prompt, "new_tokens": new_tokens, "batch": batch,
        "codec": codec, "halt_at_step": halt, "devices": n_dev,
    }}

    if n_dev < 2:
        # no split to cut: time checkpoint + resume on the single-device loop
        generate(cfg, params, ids, new_tokens, capacity=capacity,
                 compute_dtype=dtype)  # compile
        st_halt: dict = {}
        generate(cfg, params, ids, new_tokens, capacity=capacity,
                 compute_dtype=dtype, stats=st_halt,
                 recovery=RecoveryConfig(checkpoint_path=ckpt,
                                         halt_at_step=halt))
        from edgellm_tpu.serve import LocalRuntime

        rt = LocalRuntime(cfg, dtype)
        t0 = time.monotonic()
        st_res: dict = {}
        resume_split(rt, params, ckpt, stats=st_res)
        resume_wall = time.monotonic() - t0
        resumed_steps = new_tokens - 1 - halt
        tps = batch * resumed_steps / max(resume_wall, 1e-9)
        detail["recovery"]["resume"] = {
            "checkpoint_bytes": os.path.getsize(ckpt),
            "resume_wall_s": round(resume_wall, 4),
            "resumed_steps": resumed_steps,
            "counters": st_res.get("recovery_counters"),
        }
        _emit({
            "metric": (f"{model_name} resume decode throughput after a "
                       f"mid-generation checkpoint (single device; split "
                       f"legs skipped)"),
            "value": round(tps, 1),
            "unit": "resumed decode tokens/s",
            "vs_baseline": None,  # the reference has no restartable state
            "resume_wall_s": round(resume_wall, 4),
            "checkpoint_bytes": os.path.getsize(ckpt),
        }, detail)
        return

    from edgellm_tpu.parallel.split import (SplitConfig, SplitRuntime,
                                            make_stage_mesh)

    cut = min(11, cfg.num_layers // 2)
    split = SplitConfig(cuts=(cut,), hop_codecs=(codec,))
    rt = SplitRuntime(cfg, split, make_stage_mesh(2))
    placed = rt.place_params(params)
    generate_split(rt, placed, ids, new_tokens, capacity=capacity)  # compile
    st_clean: dict = {}
    generate_split(rt, placed, ids, new_tokens, capacity=capacity,
                   stats=st_clean)
    clean_tps = st_clean["decode_tokens_per_s"]
    clean_wall = st_clean["prefill_s"] + st_clean["decode_s"]
    clean_e2e = batch * new_tokens / max(clean_wall, 1e-9)
    detail["recovery"]["clean"] = {
        "cut": cut, "decode_tokens_per_s": round(clean_tps, 2),
        "end_to_end_tokens_per_s": round(clean_e2e, 2),
    }

    # leg 2: halt mid-decode with a checkpoint, then time the resumed tail
    st_halt = {}
    generate_split(rt, placed, ids, new_tokens, capacity=capacity,
                   recovery=RecoveryConfig(checkpoint_path=ckpt,
                                           halt_at_step=halt),
                   raw_params=params, stats=st_halt)
    t0 = time.monotonic()
    st_res = {}
    resume_split(rt, placed, ckpt, stats=st_res, raw_params=params)
    resume_wall = time.monotonic() - t0
    resumed_steps = new_tokens - 1 - halt
    detail["recovery"]["resume"] = {
        "checkpoint_bytes": os.path.getsize(ckpt),
        "resume_wall_s": round(resume_wall, 4),
        "resumed_steps": resumed_steps,
        "resumed_tokens_per_s": round(
            batch * resumed_steps / max(resume_wall, 1e-9), 2),
        "counters": st_res.get("recovery_counters"),
    }

    # leg 3: stage loss at mid-decode; failover re-plans onto the survivors
    # (the wall clock deliberately includes the re-plan, re-place, and
    # prefix-recompute cost — that IS the failover hit)
    if n_dev >= 3:
        cuts3 = tuple(round(i * cfg.num_layers / 3) - 1 for i in (1, 2))
        frt = SplitRuntime(cfg, SplitConfig(cuts=cuts3,
                                            hop_codecs=(codec, codec)),
                           make_stage_mesh(3))
        lost = 2
    else:
        frt = SplitRuntime(cfg, split, make_stage_mesh(2))
        lost = 1
    fplaced = frt.place_params(params)
    st_fail: dict = {}
    t0 = time.monotonic()
    generate_split(frt, fplaced, ids, new_tokens, capacity=capacity,
                   recovery=RecoveryConfig(
                       stage_failure=StageFailure(stage=lost, at_step=halt)),
                   raw_params=params, stats=st_fail)
    fail_wall = time.monotonic() - t0
    failover_tps = batch * new_tokens / max(fail_wall, 1e-9)
    detail["recovery"]["failover"] = {
        "stages": frt.split.n_stages, "lost_stage": lost, "at_step": halt,
        "end_to_end_tokens_per_s": round(failover_tps, 2),
        "wall_s": round(fail_wall, 4),
        "counters": st_fail.get("recovery_counters"),
    }

    line = {
        "metric": (f"{model_name} split decode throughput across a stage "
                   f"loss at step {halt} ({frt.split.n_stages} stages, "
                   f"{codec})"),
        "value": round(failover_tps, 1),
        "unit": "failover tokens/s (end to end)",
        "vs_baseline": None,  # the reference has no failure model at all
        "clean_tokens_per_s": round(clean_e2e, 1),
        "failover_ratio": round(failover_tps / max(clean_e2e, 1e-9), 4),
        "resume_wall_s": round(resume_wall, 4),
        "checkpoint_bytes": os.path.getsize(ckpt),
        "failovers": st_fail.get("recovery_counters", {}).get("failovers"),
    }
    _emit(line, detail)


def spec_main():
    """BENCH_SPEC=1: speculative split decode — stage-0 draft, one k-token
    batched verify hop per burst, vs the vanilla one-hop-per-token loop.

    Two legs over the same 2-stage quantized boundary: (1) vanilla
    ``generate_split`` — exactly one boundary round trip per emitted token
    (the baseline decode tokens/s); (2) ``generate_split(...,
    speculative=SpecConfig(k))`` — the truncated-layer stage-0 draft proposes
    k tokens and ONE verify hop carries the (1, k, D) activation block
    through the same codec ladder, so accepted tokens amortize the hop.
    Greedy token parity between the legs is asserted every run (the spec
    loop's lossless-acceptance contract), and the headline carries
    hops-per-token alongside the tokens/s ratio — the wire-amortization
    claim stays checkable even when a CPU runner's compute dominates the
    clock. Knobs: BENCH_SPEC_PROMPT (default 32), BENCH_SPEC_TOKENS
    (default 64), BENCH_SPEC_K (headline k, default 4), BENCH_SPEC_KS
    (detail sweep, default "1,2,4,8"), BENCH_SPEC_CODEC (default
    int8_per_token), BENCH_SPEC_CUT (boundary layer, default
    min(11, num_layers // 2); a deeper cut gives the stage-0 draft more of
    the model and a higher acceptance rate), BENCH_SPEC_DRAFT_LAYERS
    (default: the full stage-0 depth), plus the shared BENCH_MODEL /
    BENCH_DTYPE / BENCH_REPEATS. Needs >= 2 devices."""
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.serve.decode import generate_split
    from edgellm_tpu.serve.speculative import SpecConfig, spec_capacity

    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]
    prompt = int(os.environ.get("BENCH_SPEC_PROMPT", "32"))
    new_tokens = int(os.environ.get("BENCH_SPEC_TOKENS", "64"))
    k_head = int(os.environ.get("BENCH_SPEC_K", "4"))
    ks = sorted({int(x) for x in os.environ.get(
        "BENCH_SPEC_KS", "1,2,4,8").split(",")} | {k_head})
    codec = os.environ.get("BENCH_SPEC_CODEC", "int8_per_token")
    draft_layers = os.environ.get("BENCH_SPEC_DRAFT_LAYERS")
    draft_layers = int(draft_layers) if draft_layers else None
    repeats = max(int(os.environ.get("BENCH_REPEATS", "2")), 1)

    if len(jax.devices()) < 2:
        line = {"metric": f"{model_name} speculative split decode",
                "value": None, "unit": None,
                "vs_baseline": None, "status": "needs_2_devices",
                "section": "spec"}
        _emit(line, {"status": "needs_2_devices", "section": "spec"})
        return

    from edgellm_tpu.parallel.split import (SplitConfig, SplitRuntime,
                                            make_stage_mesh)

    cut = int(os.environ.get("BENCH_SPEC_CUT",
                             str(min(11, cfg.num_layers // 2))))
    rt = SplitRuntime(cfg, SplitConfig(cuts=(cut,), hop_codecs=(codec,)),
                      make_stage_mesh(2))
    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    placed = rt.place_params(params)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, prompt)))
    capacity = prompt + new_tokens

    def best_of(fn):
        best = None
        for _ in range(repeats):
            st: dict = {}
            toks = np.asarray(fn(st))
            if best is None or st["decode_tokens_per_s"] > \
                    best[1]["decode_tokens_per_s"]:
                best = (toks, st)
        return best

    generate_split(rt, placed, ids, new_tokens, capacity=capacity)  # compile
    van_toks, van_st = best_of(lambda st: generate_split(
        rt, placed, ids, new_tokens, capacity=capacity, stats=st))
    van_tps = van_st["decode_tokens_per_s"]

    detail = {"spec": {
        "prompt": prompt, "new_tokens": new_tokens, "codec": codec,
        "cut": cut, "draft_layers": draft_layers,
        "vanilla_tokens_per_s": round(van_tps, 2),
        "vanilla_hops_per_token": 1.0, "legs": {},
    }}
    head = None
    for k in ks:
        spec = SpecConfig(k=k, draft_layers=draft_layers)
        cap_k = spec_capacity(prompt, new_tokens, k)
        kw = dict(capacity=cap_k, speculative=spec, raw_params=params)
        generate_split(rt, placed, ids, new_tokens, **kw)  # compile
        toks, st = best_of(lambda st: generate_split(
            rt, placed, ids, new_tokens, stats=st, **kw))
        sp = st["speculative"]
        parity = bool(np.array_equal(toks, van_toks))
        leg = {
            "tokens_per_s": round(st["decode_tokens_per_s"], 2),
            "speedup_vs_vanilla": round(
                st["decode_tokens_per_s"] / max(van_tps, 1e-9), 4),
            "hops_per_token": round(sp["hops_per_token"], 4),
            "acceptance_rate": round(sp["acceptance_rate"], 4),
            "bursts": sp["bursts"],
            "token_parity": parity,
        }
        detail["spec"]["legs"][str(k)] = leg
        if k == k_head:
            head = leg
        if not parity:
            # the lossless-acceptance contract is broken: surface it in the
            # headline rather than burying a corrupt speedup number
            break

    line = {
        "metric": (f"{model_name} speculative split decode (k={k_head}, "
                   f"stage-0 draft, {codec} boundary)"),
        "value": None if head is None else head["tokens_per_s"],
        "unit": "decode tokens/s",
        "vs_baseline": None,  # the reference decodes one token per forward
        "k": k_head,
        "vanilla_tokens_per_s": round(van_tps, 1),
        "speedup_vs_vanilla": None if head is None
        else head["speedup_vs_vanilla"],
        "hops_per_token": None if head is None else head["hops_per_token"],
        "acceptance_rate": None if head is None else head["acceptance_rate"],
        "token_parity": all(leg["token_parity"]
                            for leg in detail["spec"]["legs"].values()),
    }
    _emit(line, detail)


def pipe_main():
    """BENCH_PIPE=1: micro-batch pipelined split decode vs the sequential
    schedule at n_stages in BENCH_PIPE_STAGES (default "2,3,4").

    For every stage count with enough devices: build the SAME boundary twice
    — once sequential, once with ``PipelineConfig(BENCH_PIPE_MICRO)`` µ-batches
    — run greedy ``generate_split`` through both, and ALWAYS assert token
    parity (the schedule is a latency optimization, never a numerics change).
    When the backend is a real accelerator (or BENCH_PIPE_TIME=1 forces it)
    the legs are timed and the row carries the measured steady-state bubble
    fraction, 1 - t_seq / (n_stages * t_pipe): 0 is a perfectly full
    pipeline, (n_stages-1)/n_stages means the schedule bought nothing over
    sequential. Off-accelerator the rows carry ``timing_skipped`` (every
    spoofed CPU "stage" shares one physical core, so overlap is
    unmeasurable) but still record parity and the analytic schedule bubble
    (n_stages-1)/(M+n_stages-1). Knobs: BENCH_PIPE_STAGES, BENCH_PIPE_MICRO
    (default 4), BENCH_PIPE_PROMPT (default 16), BENCH_PIPE_TOKENS (default
    32), BENCH_PIPE_CODEC (default int8_per_token), BENCH_PIPE_BATCH
    (default max(4, µ-batches)), plus the shared BENCH_MODEL / BENCH_DTYPE /
    BENCH_REPEATS. Needs >= 2 devices."""
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.obs.metrics import get_registry, record_pipeline_stats
    from edgellm_tpu.serve.decode import generate_split

    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]
    prompt = int(os.environ.get("BENCH_PIPE_PROMPT", "16"))
    new_tokens = int(os.environ.get("BENCH_PIPE_TOKENS", "32"))
    micro = int(os.environ.get("BENCH_PIPE_MICRO", "4"))
    codec = os.environ.get("BENCH_PIPE_CODEC", "int8_per_token")
    batch = int(os.environ.get("BENCH_PIPE_BATCH", str(max(4, micro))))
    stage_counts = sorted({int(x) for x in os.environ.get(
        "BENCH_PIPE_STAGES", "2,3,4").split(",")})
    repeats = max(int(os.environ.get("BENCH_REPEATS", "2")), 1)
    if batch % micro:
        batch += micro - batch % micro  # round up to a whole µ-batch grid
    n_dev = len(jax.devices())
    timed = (jax.default_backend() != "cpu"
             or os.environ.get("BENCH_PIPE_TIME") == "1")

    if n_dev < 2:
        line = {"metric": f"{model_name} pipelined split decode",
                "value": None, "unit": None,
                "vs_baseline": None, "status": "needs_2_devices",
                "section": "pipe"}
        _emit(line, {"status": "needs_2_devices", "section": "pipe"})
        return

    from edgellm_tpu.parallel.split import (PipelineConfig, SplitConfig,
                                            make_stage_mesh, SplitRuntime)

    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, prompt)))
    capacity = prompt + new_tokens

    def best_tps(rt, placed, kw):
        generate_split(rt, placed, ids, new_tokens, **kw)  # compile
        best = None
        for _ in range(repeats):
            st: dict = {}
            toks = np.asarray(generate_split(rt, placed, ids, new_tokens,
                                             stats=st, **kw))
            if best is None or st["decode_tokens_per_s"] > best[1]:
                best = (toks, st["decode_tokens_per_s"])
        return best

    detail = {"pipe": {"prompt": prompt, "new_tokens": new_tokens,
                       "batch": batch, "codec": codec,
                       "num_microbatches": micro, "timed": timed,
                       "legs": {}}}
    head = None
    all_parity = True
    for n in stage_counts:
        if n_dev < n:
            detail["pipe"]["legs"][str(n)] = {
                "status": f"needs_{n}_devices_found_{n_dev}"}
            continue
        # evenly spaced cuts keep per-stage compute (and thus the bubble
        # accounting) uniform across the pipeline
        cuts = tuple(round(i * cfg.num_layers / n) for i in range(1, n))
        split = SplitConfig(cuts=cuts, hop_codecs=(codec,) * (n - 1))
        mesh = make_stage_mesh(n)
        rt_seq = SplitRuntime(cfg, split, mesh)
        rt_pipe = SplitRuntime(cfg, split, mesh,
                               pipeline=PipelineConfig(num_microbatches=micro))
        placed = rt_seq.place_params(params)  # codec/schedule-independent
        kw = dict(capacity=capacity)
        seq_toks, seq_tps = best_tps(rt_seq, placed, kw)
        pipe_toks, pipe_tps = best_tps(rt_pipe, placed, kw)
        parity = bool(np.array_equal(seq_toks, pipe_toks))
        all_parity &= parity
        summary = rt_pipe.pipeline_summary()
        leg = {
            "cuts": list(cuts), "token_parity": parity,
            "bubble_fraction_schedule": round(
                summary["bubble_fraction_schedule"], 4),
            "bubble_fraction_sequential": round(
                summary["bubble_fraction_sequential"], 4),
            "stage_occupancy": [round(o, 4)
                                for o in summary["stage_occupancy"]],
        }
        if timed:
            # per-token times for the same token count: t_seq/t_pipe
            # proportionality collapses to a tokens/s ratio
            measured = 1.0 - pipe_tps / (n * seq_tps)
            leg.update({
                "sequential_tokens_per_s": round(seq_tps, 2),
                "pipelined_tokens_per_s": round(pipe_tps, 2),
                "speedup_vs_sequential": round(pipe_tps / max(seq_tps, 1e-9),
                                               4),
                "bubble_fraction_measured": round(measured, 4),
                "bubble_below_sequential_bound": bool(
                    measured < summary["bubble_fraction_sequential"]),
            })
            if get_registry().enabled:
                record_pipeline_stats(
                    {**summary, "bubble_fraction_measured": measured})
        else:
            leg["timing_skipped"] = (
                f"backend {jax.default_backend()!r}: spoofed stages share "
                f"one core, pipeline overlap is unmeasurable")
        detail["pipe"]["legs"][str(n)] = leg
        head = leg  # the deepest tested pipeline carries the headline
        if not parity:
            break  # a numerics break invalidates every deeper leg

    line = {
        "metric": (f"{model_name} pipelined split decode "
                   f"(M={micro} µ-batches, {codec} boundary, "
                   f"n_stages {stage_counts})"),
        "value": (None if head is None
                  else head.get("pipelined_tokens_per_s")),
        "unit": "decode tokens/s",
        "vs_baseline": None,  # the reference never splits, nothing to pipeline
        "token_parity": all_parity,
        "timed": timed,
        "bubble_fraction_measured": (None if head is None
                                     else head.get("bubble_fraction_measured")),
        "bubble_fraction_schedule": (None if head is None
                                     else head.get("bubble_fraction_schedule")),
    }
    _emit(line, detail)


def obs_main():
    """BENCH_OBS=1: observability smoke — arm the full obs stack (metrics
    registry + span tracer + latency SLOs), run a short instrumented decode
    (single-device, plus the 2-stage split when >= 2 devices are visible),
    and write the two artifacts the runbook promises: a metrics snapshot
    (BENCH_OBS_METRICS_PATH, default BENCH_OBS_METRICS.json; .prom/.txt
    suffix switches to Prometheus text format) and a Perfetto-loadable
    Chrome trace (BENCH_OBS_TRACE_PATH, default BENCH_OBS_TRACE.json). The
    headline is the instrumented decode tokens/s with the SLO percentiles
    and span/metric counts alongside; the registry snapshot rides the detail
    sidecar via ``_emit``'s enabled-registry hook."""
    import jax
    import jax.numpy as jnp
    from edgellm_tpu import obs
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.serve.decode import generate, generate_split

    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]
    prompt = int(os.environ.get("BENCH_OBS_PROMPT", "32"))
    new_tokens = int(os.environ.get("BENCH_OBS_TOKENS", "32"))
    batch = int(os.environ.get("BENCH_OBS_BATCH", "2"))
    capacity = prompt + new_tokens
    metrics_path = os.environ.get("BENCH_OBS_METRICS_PATH",
                                  "BENCH_OBS_METRICS.json")
    trace_path = os.environ.get("BENCH_OBS_TRACE_PATH", "BENCH_OBS_TRACE.json")

    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, prompt)))

    obs.enable(obs.ObservabilityConfig())
    # a clean slate: the smoke's artifacts must reflect THIS run, not metrics
    # or spans a prior section/test left in the process-global state
    obs.get_registry().clear()
    obs.get_tracer().clear()
    try:
        observe = obs.LatencyObserver()
        generate(cfg, params, ids, new_tokens, capacity=capacity,
                 compute_dtype=dtype)  # compile
        st: dict = {}
        generate(cfg, params, ids, new_tokens, capacity=capacity,
                 compute_dtype=dtype, stats=st, observe=observe)
        tokens_per_s = st["decode_tokens_per_s"]

        detail = {"obs": {
            "prompt": prompt, "new_tokens": new_tokens, "batch": batch,
            "slo": {k: round(v, 6) for k, v in observe.summary().items()},
        }}
        if len(jax.devices()) >= 2:
            from edgellm_tpu.parallel.split import (SplitConfig, SplitRuntime,
                                                    make_stage_mesh)

            cut = cfg.num_layers // 2 - 1
            rt = SplitRuntime(
                cfg, SplitConfig(cuts=(cut,),
                                 hop_codecs=("int8_per_token",)),
                make_stage_mesh(2))
            placed = rt.place_params(params)
            generate_split(rt, placed, ids, new_tokens,
                           capacity=capacity)  # compile
            st_split: dict = {}
            generate_split(rt, placed, ids, new_tokens, capacity=capacity,
                           stats=st_split, observe=obs.LatencyObserver())
            detail["obs"]["split"] = {
                "cut": cut,
                "decode_tokens_per_s": round(
                    st_split["decode_tokens_per_s"], 2),
            }

        # generate() already published the observers' histograms into the
        # enabled registry; export both artifact shapes from the live state
        reg = obs.get_registry()
        tracer = obs.get_tracer()
        if metrics_path.endswith((".prom", ".txt")):
            body = reg.to_prometheus()
        else:
            body = reg.to_json()
        with open(metrics_path, "w") as f:
            f.write(body)
        tracer.export(trace_path)
        n_spans = len(tracer.to_chrome_trace()["traceEvents"])
        print(f"metrics snapshot -> {metrics_path}")
        print(f"chrome trace -> {trace_path}")

        line = {
            "metric": (f"{model_name} obs-instrumented decode smoke "
                       f"(prompt {prompt} +{new_tokens} tokens, "
                       f"batch {batch})"),
            "value": round(tokens_per_s, 1),
            "unit": "decode tokens/s (obs on)",
            "vs_baseline": None,  # the reference has no telemetry at all
            "n_metrics": len(reg.names()),
            "n_spans": n_spans,
        }
        for k in ("ttft_s", "token_latency_p50_s", "token_latency_p95_s",
                  "token_latency_p99_s"):
            line[k] = detail["obs"]["slo"].get(k)
        _emit(line, detail)
    finally:
        obs.disable()


def obs_live_main():
    """BENCH_OBS_LIVE=1: the live-telemetry chaos smoke.

    BENCH_OBS exercises the exporters offline; this section exercises the
    tracing plane's *live* surfaces under failure. The full obs stack plus
    the flight recorder is armed, a :class:`ServeFront` over the 2-stage
    split runtime binds the telemetry endpoint to an OS-assigned port, and
    the chaos soak (scheduled mid-soak stage kill) runs on a background
    thread while the foreground scrapes ``/metrics`` and ``/healthz``
    mid-flight; the final scrape of each is written to
    BENCH_OBS_LIVE_METRICS_PATH (default BENCH_OBS_LIVE_METRICS.prom) and
    BENCH_OBS_LIVE_HEALTH_PATH (default BENCH_OBS_LIVE_HEALTH.json). After
    the soak the section asserts the failure contract: the injected stage
    kill produced EXACTLY ONE flight-recorder artifact (CRC-verified by
    reading it back), written under BENCH_OBS_LIVE_FLIGHT_DIR (default
    BENCH_OBS_FLIGHT). Needs >= 2 visible devices for the split kill;
    below that it emits a skip line. Knobs: BENCH_OBS_LIVE_REQUESTS
    (default 24), BENCH_OBS_LIVE_RATE (default 2.0), plus the shared
    BENCH_MODEL / BENCH_DTYPE."""
    import threading
    import urllib.request

    import jax
    import jax.numpy as jnp
    from edgellm_tpu import obs
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.obs.flight import load_flight
    from edgellm_tpu.serve.frontend import ServeFront
    from edgellm_tpu.serve.soak import SoakConfig, run_soak
    from edgellm_tpu.utils.clock import FakeClock

    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]
    n_requests = int(os.environ.get("BENCH_OBS_LIVE_REQUESTS", "24"))
    rate = float(os.environ.get("BENCH_OBS_LIVE_RATE", "2.0"))
    flight_dir = os.environ.get("BENCH_OBS_LIVE_FLIGHT_DIR",
                                "BENCH_OBS_FLIGHT")
    metrics_path = os.environ.get("BENCH_OBS_LIVE_METRICS_PATH",
                                  "BENCH_OBS_LIVE_METRICS.prom")
    health_path = os.environ.get("BENCH_OBS_LIVE_HEALTH_PATH",
                                 "BENCH_OBS_LIVE_HEALTH.json")

    n_dev = len(jax.devices())
    if n_dev < 2:
        # the failure contract needs a stage to kill; no split, no contract
        line = {"metric": "obs-live chaos smoke", "value": None,
                "unit": None, "vs_baseline": None,
                "status": f"skipped_needs_2_devices (found {n_dev})"}
        _emit(line, {"status": "skipped", "devices": n_dev})
        return

    from edgellm_tpu.parallel.split import (SplitConfig, SplitRuntime,
                                            make_stage_mesh)
    from edgellm_tpu.serve.decode import generate, generate_split

    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    clock = FakeClock()
    cut = cfg.num_layers // 2 - 1
    rt = SplitRuntime(cfg, SplitConfig(cuts=(cut,),
                                       hop_codecs=("int8_per_token",)),
                      make_stage_mesh(2))

    # flight recorder must be armed BEFORE the front exists — the front
    # installs its live-state contributor at construction
    obs.enable(obs.ObservabilityConfig(flight_recorder=flight_dir))
    obs.get_registry().clear()
    obs.get_tracer().clear()
    front = ServeFront(cfg, params, split_runtime=rt,
                       compute_dtype=dtype, clock=clock)
    port = front.start_obs_server(0)
    base = f"http://127.0.0.1:{port}"
    print(f"obs endpoint -> {base}")
    try:
        # warm every route the soak can take (split, post-kill local) so
        # compile time never lands on the virtual service clock
        prompt_len, new_tokens = 8, 8
        capacity = -(-(prompt_len + new_tokens) // 16) * 16
        warm_ids = jnp.asarray(np.zeros((1, prompt_len), np.int32))
        warm_kw = dict(capacity=capacity, temperature=0.7,
                       rng_key=jax.random.key(0))
        generate(cfg, params, warm_ids, new_tokens, compute_dtype=dtype,
                 **warm_kw)
        generate_split(rt, rt.place_params(params), warm_ids, new_tokens,
                       **warm_kw)

        soak = SoakConfig(n_requests=n_requests, arrival_rate=rate,
                          prompt_len=prompt_len, max_new_tokens=new_tokens,
                          kill_stage=1)
        result: dict = {}

        def _drive() -> None:
            try:
                result["artifact"] = run_soak(front, soak, clock=clock)
            except BaseException as e:  # surfaced after join
                result["error"] = e

        t = threading.Thread(target=_drive, name="obs-live-soak")
        t.start()
        scrapes = {"metrics": b"", "healthz": b"", "mid_soak": 0}

        def _scrape() -> None:
            scrapes["metrics"] = urllib.request.urlopen(
                base + "/metrics", timeout=2).read()
            scrapes["healthz"] = urllib.request.urlopen(
                base + "/healthz", timeout=2).read()

        while t.is_alive():
            try:
                _scrape()
                scrapes["mid_soak"] += 1
            except OSError:
                pass  # server warming up / request raced the soak's end
            time.sleep(0.02)
        t.join()
        if "error" in result:
            raise result["error"]
        _scrape()  # end-state scrape so the files reflect the whole soak

        artifact = result["artifact"]
        dumps = list(artifact.get("flight_dumps") or [])
        if len(dumps) != 1:
            raise AssertionError(
                f"stage kill must produce exactly one flight artifact, "
                f"got {len(dumps)}: {dumps}")
        payload = load_flight(dumps[0])  # CRC + framing verified here
        with open(metrics_path, "wb") as f:
            f.write(scrapes["metrics"])
        with open(health_path, "wb") as f:
            f.write(scrapes["healthz"])
        print(f"live /metrics scrape -> {metrics_path}")
        print(f"live /healthz scrape -> {health_path}")
        print(f"flight artifact -> {dumps[0]}")

        outcomes = artifact["outcomes"]
        line = {
            "metric": (f"{model_name} obs-live chaos smoke ({n_requests} "
                       f"reqs, stage kill @1, endpoint scraped live)"),
            "value": round(artifact["goodput_tokens_per_s"], 2),
            "unit": "goodput tokens/s (virtual, obs+flight on)",
            "vs_baseline": None,  # the reference has no telemetry at all
            "completed": outcomes.get("completed", 0),
            "failed_over": outcomes.get("failed_over", 0),
            "mid_soak_scrapes": scrapes["mid_soak"],
            "flight_artifact": dumps[0],
            "flight_spans": len(payload.get("spans", [])),
        }
        _emit(line, {"obs_live": {
            "artifact": artifact, "flight_failure": payload.get("failure"),
            "healthz": json.loads(scrapes["healthz"] or b"{}"),
        }})
    finally:
        front.stop_obs_server()
        obs.disable()


def serve_main():
    """BENCH_SERVE=1: continuous batching vs static batching, same load.

    One seeded Poisson arrival trace, two servers, one virtual clock that
    advances by each step's measured device wall time:

    - **continuous**: every arrival at or before virtual-now is submitted to
      the :class:`ContinuousBatcher`; each ``step()`` admits what fits,
      advances every running slot one ragged position, and frees slots the
      moment a stream finishes.
    - **static**: requests queue until ``BENCH_SERVE_SLOTS`` of them exist
      (or arrivals are exhausted), every prompt pads to the batch max,
      every row decodes to the batch-max new tokens at the batch-max
      capacity, and the whole batch occupies its worst-case reservation
      until the LAST row finishes.

    Cache-slot occupancy is live tokens / RESERVED tokens for both servers
    — the same metric, different reservation policies. Static reserves
    batch x worst-case capacity up front for the batch's whole run, so its
    reservation carries padding and rows that finished early. The paged
    server reserves only allocated pages (``alloc_util_mean`` from the
    batcher's own per-step samples), so its waste is bounded by one
    partial page per stream. The pool-level ratio (live / whole pool) is
    kept in the detail sidecar as ``pool_occupancy_mean``."""
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.serve.batching import BatchingConfig, ContinuousBatcher
    from edgellm_tpu.serve.decode import generate

    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]
    n_requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "24"))
    rate = float(os.environ.get("BENCH_SERVE_RATE", "2.0"))
    prompt_max = int(os.environ.get("BENCH_SERVE_PROMPT", "16"))
    tokens_max = int(os.environ.get("BENCH_SERVE_TOKENS", "16"))
    slots = int(os.environ.get("BENCH_SERVE_SLOTS", "8"))
    page_size = int(os.environ.get("BENCH_SERVE_PAGE_SIZE", "8"))
    seed = int(os.environ.get("BENCH_SERVE_SEED", "0"))

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_requests))
    prompts = [rng.integers(1, cfg.vocab_size,
                            size=int(rng.integers(max(prompt_max // 2, 1),
                                                  prompt_max + 1))
                            ).astype(np.int32)
               for _ in range(n_requests)]
    new_tokens = [int(rng.integers(max(tokens_max // 2, 1), tokens_max + 1))
                  for _ in range(n_requests)]

    span = prompt_max + tokens_max            # worst-case positions per slot
    pages_per_slot = -(-span // page_size)
    num_pages = int(os.environ.get(
        "BENCH_SERVE_PAGES", str(1 + slots * pages_per_slot)))
    params = init_params(cfg, jax.random.key(0), dtype=dtype)

    # ---- continuous batching ------------------------------------------
    bat = ContinuousBatcher(cfg, params, BatchingConfig(
        page_size=page_size, num_pages=num_pages, max_slots=slots,
        pages_per_slot=pages_per_slot, compute_dtype=dtype))
    # warm every executable on a throwaway geometry twin so compile time
    # never lands on the virtual timeline (shapes, not values, key the jit)
    warm = ContinuousBatcher(cfg, params, bat.bcfg)
    for s, m in {(len(p), 1) for p in prompts}:  # one prefill per length
        warm.submit(np.ones((s,), np.int32), m)
    warm.submit(np.ones((prompts[0].size,), np.int32), 2)
    warm.run()

    sid_of = {}
    t_submit, t_first, t_done = {}, {}, {}
    token_stamps = {i: [] for i in range(n_requests)}
    now, nxt = 0.0, 0
    while len(t_done) < n_requests:
        while nxt < n_requests and arrivals[nxt] <= now:
            sid = bat.submit(prompts[nxt], new_tokens[nxt],
                             rng_seed=seed + nxt)
            sid_of[sid] = nxt
            t_submit[nxt] = arrivals[nxt]
            nxt += 1
        counts = {sid: len(bat._streams[sid].tokens) for sid in sid_of}
        t0 = time.monotonic()
        advanced = bat.step()
        dt = time.monotonic() - t0
        if advanced == 0:
            if nxt >= n_requests:
                raise RuntimeError("batcher wedged with no future arrivals")
            now = max(now, arrivals[nxt])  # idle: jump to the next arrival
            continue
        now += dt
        for sid, i in sid_of.items():
            got = len(bat._streams[sid].tokens)
            for _ in range(got - counts.get(sid, 0)):
                token_stamps[i].append(now)
            if got and i not in t_first:
                t_first[i] = now
            if bat._streams[sid].status == "finished" and i not in t_done:
                t_done[i] = now
    cont_rep = bat.report()
    cont = _open_loop_summary(arrivals, t_submit, t_first, t_done,
                              token_stamps, new_tokens)
    cont["occupancy_mean"] = cont_rep["alloc_util_mean"]
    cont["pool_occupancy_mean"] = cont_rep["occupancy_mean"]
    cont["jit_misses"] = cont_rep["jit_misses"]
    cont["evicted"] = cont_rep["evicted"]

    # ---- static batching: same trace, padded fixed batches ------------
    batches = [list(range(i, min(i + slots, n_requests)))
               for i in range(0, n_requests, slots)]
    for group in batches:  # pre-warm each (b, s_max, cap, steps) executable
        s_max = max(prompts[i].size for i in group)
        m_max = max(new_tokens[i] for i in group)
        cap = -(-(s_max + m_max) // 16) * 16
        generate(cfg, params, np.ones((len(group), s_max), np.int32), m_max,
                 capacity=cap, compute_dtype=dtype,
                 rng_key=jax.random.key(0))
    now = 0.0
    t_submit2, t_first2, t_done2 = {}, {}, {}
    token_stamps2 = {i: [] for i in range(n_requests)}
    occ2 = []
    for group in batches:
        now = max(now, arrivals[group[-1]])   # batch forms at last arrival
        for i in group:
            t_submit2[i] = arrivals[i]
        s_max = max(prompts[i].size for i in group)
        m_max = max(new_tokens[i] for i in group)
        cap = -(-(s_max + m_max) // 16) * 16
        padded = np.zeros((len(group), s_max), np.int32)
        for r, i in enumerate(group):
            padded[r, :prompts[i].size] = prompts[i]
        t0 = time.monotonic()
        generate(cfg, params, padded, m_max, capacity=cap,
                 compute_dtype=dtype, rng_key=jax.random.key(seed))
        dt = time.monotonic() - t0
        # attribute wall time uniformly over the m_max lockstep positions;
        # each request's tokens arrive at its own first new_tokens[i] of them
        for t in range(1, m_max + 1):
            stamp = now + dt * t / m_max
            live = sum(min(prompts[i].size + t, prompts[i].size
                           + new_tokens[i]) for i in group)
            occ2.append(live / (len(group) * cap))
            for i in group:
                if t <= new_tokens[i]:
                    token_stamps2[i].append(stamp)
                    t_first2.setdefault(i, stamp)
        now += dt
        for i in group:   # padded rows hold their reservation to batch end
            t_done2[i] = now
    stat = _open_loop_summary(arrivals, t_submit2, t_first2, t_done2,
                              token_stamps2, new_tokens)
    stat["occupancy_mean"] = float(np.mean(occ2)) if occ2 else 0.0

    detail = {
        "requests": n_requests, "rate": rate, "seed": seed,
        "prompt_max": prompt_max, "tokens_max": tokens_max,
        "slots": slots, "page_size": page_size, "num_pages": num_pages,
        "pages_per_slot": pages_per_slot,
        "continuous": cont, "static": stat,
        "batcher_report": cont_rep,
    }
    line = {
        "metric": (f"{model_name} continuous batching ({n_requests} reqs at "
                   f"{rate}/s virtual, {slots} slots, page {page_size})"),
        "value": round(cont["tokens_per_s"], 2),
        "unit": "sustained tokens/s (virtual)",
        "vs_baseline": None,  # the reference has no serving layer at all
        "static_tokens_per_s": round(stat["tokens_per_s"], 2),
        "p50_token_latency_s": cont["p50_token_latency_s"],
        "p99_token_latency_s": cont["p99_token_latency_s"],
        "p50_ttft_s": cont["p50_ttft_s"],
        "p99_ttft_s": cont["p99_ttft_s"],
        "occupancy_mean": round(cont["occupancy_mean"], 4),
        "static_occupancy_mean": round(stat["occupancy_mean"], 4),
        "occupancy_gain": round(cont["occupancy_mean"]
                                - stat["occupancy_mean"], 4),
        "jit_misses": cont["jit_misses"],
    }
    _emit(line, detail)


def prefix_main():
    """BENCH_PREFIX=1: prefix-sharing paged KV cache, same load off vs on.

    ONE seeded Poisson arrival trace where every prompt opens with the same
    ``BENCH_PREFIX_SHARED``-token system prompt, served twice through the
    continuous batcher at the SAME fixed pool geometry: once with the prefix
    cache disabled (every admit prefills its whole prompt) and once enabled
    (matched pages map in from the radix index, only the suffix prefills,
    first decode writes fork copy-on-write). Reports:

    - **token parity**: every request's tokens must be identical across the
      two runs — sharing is a memory/compute optimization, never a numerics
      change (the CI gate asserts this unconditionally);
    - **prefill tokens saved**: positions the enabled run never prefilled
      (the pool's ``saved_tokens`` counter), absolute and as a fraction of
      all submitted prompt tokens;
    - **admitted capacity**: peak concurrently-running streams per run. The
      pool is sized so exclusive prompts bound concurrency; shared pages
      cover k streams with one physical copy, so the enabled run must peak
      strictly higher at the same page budget.

    Knobs: BENCH_PREFIX_REQUESTS (default 24), BENCH_PREFIX_RATE (virtual
    arrivals/s, default 8.0 — saturating, so peak concurrency is pool-bound
    rather than arrival-bound), BENCH_PREFIX_PROMPT (total prompt tokens,
    default 24), BENCH_PREFIX_SHARED (shared opening block, default 16),
    BENCH_PREFIX_TOKENS (new tokens per request, default 8),
    BENCH_PREFIX_SLOTS (default 6), BENCH_PREFIX_PAGE_SIZE (default 8),
    BENCH_PREFIX_PAGES (default sizes the pool to HALF the slots' exclusive
    reservation, the contended regime sharing relieves), BENCH_PREFIX_SEED,
    plus the shared BENCH_MODEL / BENCH_DTYPE."""
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.models.paged_kv import PrefixCacheConfig
    from edgellm_tpu.serve.batching import BatchingConfig, ContinuousBatcher

    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]
    n_requests = int(os.environ.get("BENCH_PREFIX_REQUESTS", "24"))
    rate = float(os.environ.get("BENCH_PREFIX_RATE", "8.0"))
    prompt_len = int(os.environ.get("BENCH_PREFIX_PROMPT", "24"))
    shared_len = int(os.environ.get("BENCH_PREFIX_SHARED", "16"))
    tokens = int(os.environ.get("BENCH_PREFIX_TOKENS", "8"))
    slots = int(os.environ.get("BENCH_PREFIX_SLOTS", "6"))
    page_size = int(os.environ.get("BENCH_PREFIX_PAGE_SIZE", "8"))
    seed = int(os.environ.get("BENCH_PREFIX_SEED", "0"))
    if not 0 < shared_len < prompt_len:
        raise SystemExit("BENCH_PREFIX_SHARED must be in (0, BENCH_PREFIX_"
                         f"PROMPT={prompt_len}), got {shared_len}")

    span = prompt_len + tokens
    pages_per_slot = -(-span // page_size)
    # default pool: half the slots' worst-case exclusive reservation — tight
    # enough that exclusive prompts can't all be live at once, which is
    # exactly the regime shared pages relieve
    num_pages = int(os.environ.get(
        "BENCH_PREFIX_PAGES", str(1 + (slots * pages_per_slot) // 2)))
    params = init_params(cfg, jax.random.key(0), dtype=dtype)

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_requests))
    shared = rng.integers(1, cfg.vocab_size, size=shared_len)
    prompts = []
    for _ in range(n_requests):
        p = rng.integers(1, cfg.vocab_size, size=prompt_len).astype(np.int32)
        p[:shared_len] = shared
        prompts.append(p)

    def drive(prefix_cache):
        bat = ContinuousBatcher(cfg, params, BatchingConfig(
            page_size=page_size, num_pages=num_pages, max_slots=slots,
            pages_per_slot=pages_per_slot, compute_dtype=dtype,
            prefix_cache=prefix_cache))
        # warm every executable on a throwaway geometry twin: the full
        # prefill, the ragged step, and (enabled run) the suffix prefill the
        # second warm stream's index hit compiles
        warm = ContinuousBatcher(cfg, params, bat.bcfg)
        for w in range(2):
            wp = np.ones((prompt_len,), np.int32)
            wp[shared_len:] += w  # distinct suffixes, identical prefix
            warm.submit(wp, 2, rng_seed=w)
        warm.run()
        sid_of: dict = {}
        now, nxt, peak = 0.0, 0, 0
        while nxt < n_requests or bat._slot_to_sid or bat._waiting:
            while nxt < n_requests and arrivals[nxt] <= now:
                sid = bat.submit(prompts[nxt], tokens, rng_seed=seed + nxt)
                sid_of[sid] = nxt
                nxt += 1
            t0 = time.monotonic()
            advanced = bat.step()
            dt = time.monotonic() - t0
            if advanced == 0:
                if nxt >= n_requests:
                    raise RuntimeError(
                        "batcher wedged with no future arrivals")
                now = max(now, arrivals[nxt])  # idle: jump to next arrival
                continue
            now += dt
            peak = max(peak, len(bat._slot_to_sid))
        bat.pool.check_invariants()
        toks = {i: bat.results[sid].tolist() for sid, i in sid_of.items()}
        return toks, bat.report(), peak

    base_toks, base_rep, base_peak = drive(None)
    got_toks, rep, peak = drive(PrefixCacheConfig(
        enabled=True, min_shared_block=page_size))
    parity = all(got_toks[i] == base_toks[i] for i in range(n_requests))
    pf = rep["prefix"]
    total_prompt_tokens = n_requests * prompt_len

    detail = {
        "requests": n_requests, "rate": rate, "seed": seed,
        "prompt_len": prompt_len, "shared_len": shared_len,
        "tokens": tokens, "slots": slots, "page_size": page_size,
        "num_pages": num_pages, "pages_per_slot": pages_per_slot,
        "token_parity": parity,
        "prefix": pf,
        "peak_concurrent": {"off": base_peak, "on": peak},
        "batcher_report": rep, "batcher_report_off": base_rep,
    }
    line = {
        "metric": (f"{model_name} prefix sharing ({n_requests} reqs, "
                   f"{shared_len}/{prompt_len} shared prompt tokens, "
                   f"{num_pages} pages)"),
        "value": pf["saved_tokens"],
        "unit": "prefill token positions saved",
        "vs_baseline": None,  # the reference has no serving layer at all
        "token_parity": parity,
        "prefill_tokens_saved": pf["saved_tokens"],
        "saved_fraction": round(pf["saved_tokens"] / total_prompt_tokens, 4),
        "prefix_hit_rate": round(pf["hit_rate"], 4),
        "cow_forks": pf["cow_forks"],
        "peak_concurrent_off": base_peak,
        "peak_concurrent_on": peak,
        "jit_misses": rep["jit_misses"],
    }
    _emit(line, detail)


def kvq_main():
    """BENCH_KVQ=1: KV-at-rest quantized pages, same trace per tier at a
    FIXED pool byte budget.

    ONE seeded Poisson arrival trace (the BENCH_PREFIX workload shape, no
    prefix sharing so capacity attribution is purely the page tier), served
    through the continuous batcher once per KV tier — ``fp``,
    ``int8_per_channel``, ``int4_per_channel`` — with the pool sized to the
    SAME HBM byte budget each time (``num_pages_for_bytes``: quantized rows
    are smaller, so the same bytes hold more pages). Reports per tier:

    - **peak admitted concurrency**: the capacity multiplier compression
      buys at fixed memory (the CI gate requires int4 >= 2x fp);
    - **PPL** via :func:`run_kv_tier_eval` on a seeded corpus — quality is
      measured through the exact serving data path, never assumed (the CI
      gate requires the int8 delta vs fp <= 1%);
    - **jit_misses**: every tier must hold the jit-miss-free steady state;
    - **pool bytes + live-tokens-per-HBM-byte**: the tracked capacity
      numbers behind the multiplier claim (detail sidecar).

    Knobs: BENCH_KVQ_REQUESTS (default 24), BENCH_KVQ_RATE (default 8.0,
    saturating), BENCH_KVQ_PROMPT (default 24), BENCH_KVQ_TOKENS (default
    8), BENCH_KVQ_SLOTS (default 6), BENCH_KVQ_PAGE_SIZE (default 8),
    BENCH_KVQ_POOL_BYTES (default: the bytes of an fp pool holding HALF the
    slots' exclusive reservation — the contended regime), BENCH_KVQ_PPL_*
    (WINDOW default 96, STRIDE 48, CHUNKS 3, BATCH 3), BENCH_KVQ_SEED, plus
    the shared BENCH_MODEL / BENCH_DTYPE."""
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.eval.split_eval import run_kv_tier_eval
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.models.paged_kv import (kv_page_bytes,
                                             num_pages_for_bytes)
    from edgellm_tpu.serve.batching import BatchingConfig, ContinuousBatcher

    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]
    n_requests = int(os.environ.get("BENCH_KVQ_REQUESTS", "24"))
    rate = float(os.environ.get("BENCH_KVQ_RATE", "8.0"))
    prompt_len = int(os.environ.get("BENCH_KVQ_PROMPT", "24"))
    tokens = int(os.environ.get("BENCH_KVQ_TOKENS", "8"))
    slots = int(os.environ.get("BENCH_KVQ_SLOTS", "6"))
    page_size = int(os.environ.get("BENCH_KVQ_PAGE_SIZE", "8"))
    seed = int(os.environ.get("BENCH_KVQ_SEED", "0"))
    ppl_window = int(os.environ.get("BENCH_KVQ_PPL_WINDOW", "96"))
    ppl_stride = int(os.environ.get("BENCH_KVQ_PPL_STRIDE", "48"))
    ppl_chunks = int(os.environ.get("BENCH_KVQ_PPL_CHUNKS", "3"))
    ppl_batch = int(os.environ.get("BENCH_KVQ_PPL_BATCH", "3"))
    tiers = ("fp", "int8_per_channel", "int4_per_channel")

    span = prompt_len + tokens
    pages_per_slot = -(-span // page_size)
    # the KV cache is stored at the pool's cache_dtype (float32 default),
    # independent of the compute dtype — size the byte budget off THAT
    cache_dtype = jnp.float32
    fp_page = kv_page_bytes(cfg, page_size, dtype=cache_dtype)
    pool_bytes = int(os.environ.get(
        "BENCH_KVQ_POOL_BYTES",
        str((1 + (slots * pages_per_slot) // 2) * fp_page)))

    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_requests))
    prompts = [rng.integers(1, cfg.vocab_size,
                            size=prompt_len).astype(np.int32)
               for _ in range(n_requests)]
    ppl_corpus = rng.integers(
        1, cfg.vocab_size,
        size=ppl_window + ppl_stride * (ppl_chunks + 1)).astype(np.int32)

    def drive(kv_codec, num_pages):
        bat = ContinuousBatcher(cfg, params, BatchingConfig(
            page_size=page_size, num_pages=num_pages, max_slots=slots,
            pages_per_slot=pages_per_slot, compute_dtype=dtype,
            kv_codec=kv_codec))
        # warm every executable on a throwaway geometry twin so the traced
        # run's jit_misses isolates steady-state recompiles
        warm = ContinuousBatcher(cfg, params, bat.bcfg)
        warm.submit(np.ones((prompt_len,), np.int32), 2, rng_seed=0)
        warm.run()
        sid_of: dict = {}
        now, nxt, peak, peak_live = 0.0, 0, 0, 0
        while nxt < n_requests or bat._slot_to_sid or bat._waiting:
            while nxt < n_requests and arrivals[nxt] <= now:
                sid = bat.submit(prompts[nxt], tokens, rng_seed=seed + nxt)
                sid_of[sid] = nxt
                nxt += 1
            t0 = time.monotonic()
            advanced = bat.step()
            dt = time.monotonic() - t0
            if advanced == 0:
                if nxt >= n_requests:
                    raise RuntimeError(
                        "batcher wedged with no future arrivals")
                now = max(now, arrivals[nxt])  # idle: jump to next arrival
                continue
            now += dt
            peak = max(peak, len(bat._slot_to_sid))
            peak_live = max(peak_live, sum(
                int(bat.pool.lengths[s]) for s in bat._slot_to_sid))
        bat.pool.check_invariants()
        toks = {i: bat.results[sid].tolist() for sid, i in sid_of.items()}
        return toks, bat.report(), peak, peak_live

    rows = []
    fp_toks = None
    for tier in tiers:
        tier_page = kv_page_bytes(cfg, page_size, kv_codec=tier,
                                  dtype=cache_dtype)
        num_pages = num_pages_for_bytes(cfg, pool_bytes, page_size,
                                        kv_codec=tier, dtype=cache_dtype)
        toks, rep, peak, peak_live = drive(tier, num_pages)
        if tier == "fp":
            fp_toks = toks
        ppl = run_kv_tier_eval(cfg, params, ppl_corpus, kv_codec=tier,
                               max_length=ppl_window, stride=ppl_stride,
                               page_size=page_size, window_batch=ppl_batch,
                               max_chunks=ppl_chunks, compute_dtype=dtype)
        used_bytes = num_pages * tier_page
        rows.append({
            "kv_codec": tier,
            "num_pages": num_pages,
            "page_bytes": tier_page,
            "pool_bytes": used_bytes,
            "pool_bytes_budget": pool_bytes,
            "capacity_tokens": (num_pages - 1) * page_size,
            # the tracked capacity number: decode-live token rows the SAME
            # byte budget can hold at this tier
            "live_tokens_per_hbm_byte": ((num_pages - 1) * page_size
                                         / used_bytes),
            "peak_concurrent": peak,
            "peak_live_tokens": peak_live,
            "finished": rep["finished"],
            "evicted": rep["evicted"],
            "jit_misses": rep["jit_misses"],
            "ppl": ppl["ppl"],
            "ppl_n_tokens": ppl["n_tokens"],
        })

    base = rows[0]
    for r in rows:
        r["ppl_delta_vs_fp"] = (r["ppl"] - base["ppl"]) / base["ppl"]
        r["concurrency_vs_fp"] = (r["peak_concurrent"]
                                  / max(base["peak_concurrent"], 1))
    # fp-tier tokens must match a second fp run bit-for-bit? stronger: the
    # fp tier IS the pre-quantization path (graphlint pins that); here we
    # record that every stream finished everywhere instead
    int4 = rows[-1]
    int8 = rows[1]
    detail = {
        "section": "kvq", "requests": n_requests, "rate": rate,
        "seed": seed, "prompt_len": prompt_len, "tokens": tokens,
        "slots": slots, "page_size": page_size,
        "pages_per_slot": pages_per_slot,
        "pool_bytes_budget": pool_bytes,
        "ppl_eval": {"window": ppl_window, "stride": ppl_stride,
                     "chunks": ppl_chunks, "window_batch": ppl_batch},
        "tiers": rows,
    }
    line = {
        "metric": (f"{model_name} KV-at-rest int4 capacity multiplier "
                   f"({n_requests} reqs, {pool_bytes} pool bytes)"),
        "value": round(int4["concurrency_vs_fp"], 2),
        "unit": "x peak admitted concurrency vs fp",
        "vs_baseline": None,  # the reference serves nothing — no KV pool
        "peak_concurrent_fp": base["peak_concurrent"],
        "peak_concurrent_int8": int8["peak_concurrent"],
        "peak_concurrent_int4": int4["peak_concurrent"],
        "ppl_fp": round(base["ppl"], 4),
        "ppl_delta_int8": round(int8["ppl_delta_vs_fp"], 6),
        "ppl_delta_int4": round(int4["ppl_delta_vs_fp"], 6),
        "jit_misses": max(r["jit_misses"] for r in rows),
        "all_finished": all(r["finished"] == n_requests for r in rows),
    }
    _emit(line, detail)


def _open_loop_summary(arrivals, t_submit, t_first, t_done, token_stamps,
                       new_tokens) -> dict:
    """Shared latency/throughput rollup for one serve run on the virtual
    clock: sustained tok/s over the busy span, TTFT and inter-token
    percentiles."""
    emitted = sum(len(v) for v in token_stamps.values())
    span = (max(t_done.values()) - float(arrivals[0])) if t_done else 0.0
    ttfts = [t_first[i] - t_submit[i] for i in t_first]
    gaps = []
    for i, stamps in token_stamps.items():
        if not stamps:
            continue
        prev = t_submit[i]
        for s in stamps:
            gaps.append(s - prev)
            prev = s

    def pct(xs, q):
        return float(np.percentile(np.asarray(xs, np.float64), q)) if xs \
            else None

    return {
        "tokens_out": emitted,
        "span_s": span,
        "tokens_per_s": (emitted / span) if span > 0 else 0.0,
        "p50_ttft_s": pct(ttfts, 50), "p99_ttft_s": pct(ttfts, 99),
        "p50_token_latency_s": pct(gaps, 50),
        "p99_token_latency_s": pct(gaps, 99),
    }


def soak_main():
    """BENCH_SOAK=1: deterministic chaos soak over the serving front.

    Builds a :class:`ServeFront` on a virtual clock over the real split
    runtime (3 stages when >= 3 devices are visible, 2 with 2, local-only
    below that), with a low ambient drop rate on the boundary wire, then
    runs :func:`run_soak`: seeded Poisson arrivals, a whole-stage kill at
    the midpoint arrival, and a corruption-burst runtime (same topology,
    BENCH_SOAK_CORRUPT per-attempt drop rate) swapped in over the burst
    arrival window. The headline is goodput tokens/s over the virtual span;
    SLO attainment, reject/shed rates, p99 TTFT, post-kill recovery time,
    the retry-budget audit, and the completed-request token-identity audit
    ride alongside (the last two are pass/fail acceptance surfaces). The
    full soak artifact goes to the detail sidecar."""
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.codecs.faults import FaultConfig, LinkPolicy
    from edgellm_tpu.serve.frontend import ServeFront
    from edgellm_tpu.serve.soak import SoakConfig, run_soak
    from edgellm_tpu.utils.clock import FakeClock

    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]
    n_requests = int(os.environ.get("BENCH_SOAK_REQUESTS", "24"))
    # default arrival rate sits below the tiny models' ~0.6 req/s service
    # rate so arrivals interleave with drains and the burst window spans
    # actually-served requests; push it above service rate to drive the
    # overload (backlog/brownout/reject) regime instead
    rate = float(os.environ.get("BENCH_SOAK_RATE", "0.5"))
    prompt_len = int(os.environ.get("BENCH_SOAK_PROMPT", "8"))
    new_tokens = int(os.environ.get("BENCH_SOAK_TOKENS", "8"))
    deadline_s = float(os.environ.get("BENCH_SOAK_DEADLINE_S", "60"))
    corrupt = float(os.environ.get("BENCH_SOAK_CORRUPT", "0.2"))
    seed = int(os.environ.get("BENCH_SOAK_SEED", "0"))

    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    n_dev = len(jax.devices())
    clock = FakeClock()

    # the boundary wire: a low ambient per-ATTEMPT drop rate that the
    # unrolled retries recover (drop, unlike per-byte bitflips, gives each
    # retry an independent 1-rate success chance — the regime where retries
    # work and completed stays token-identical), bursting to BENCH_SOAK_CORRUPT
    # over the burst window
    policy = LinkPolicy(max_retries=4)
    ambient = FaultConfig(drop_rate=0.02, seed=seed)
    burst_fc = FaultConfig(drop_rate=corrupt, seed=seed)

    burst_rt = None
    kill_stage = None
    if n_dev >= 2:
        from edgellm_tpu.parallel.split import (SplitConfig, SplitRuntime,
                                                make_stage_mesh)

        n_stages = 3 if n_dev >= 3 else 2
        cuts = tuple(round(i * cfg.num_layers / n_stages) - 1
                     for i in range(1, n_stages))
        split = SplitConfig(cuts=cuts,
                            hop_codecs=("int8_per_token",) * len(cuts))
        mesh = make_stage_mesh(n_stages)
        rt = SplitRuntime(cfg, split, mesh, faults=ambient, policy=policy)
        burst_rt = SplitRuntime(cfg, split, mesh, faults=burst_fc,
                                policy=policy)
        # with 3+ stages the kill exercises the front's replan-onto-survivors
        # failover; with exactly 2 it exercises the local-fallback route
        kill_stage = 1
        front = ServeFront(cfg, params, split_runtime=rt,
                           compute_dtype=dtype, clock=clock)
    else:
        front = ServeFront(cfg, params, compute_dtype=dtype, clock=clock)

    # pre-warm the jit caches for every route the soak can take (ambient
    # split, burst split, local fallback): the first request's service time
    # advances the VIRTUAL clock, so an uncompiled path would fold ~tens of
    # compile-seconds into the timeline and collapse all later arrivals
    # (and the burst window) into one instant
    from edgellm_tpu.serve.decode import generate, generate_split

    capacity = -(-(prompt_len + new_tokens) // 16) * 16
    warm_ids = jnp.asarray(
        np.zeros((1, prompt_len), np.int32))
    warm_kw = dict(capacity=capacity, temperature=0.7,
                   rng_key=jax.random.key(0))
    generate(cfg, params, warm_ids, new_tokens, compute_dtype=dtype,
             **warm_kw)
    if n_dev >= 2:
        for wrt in (rt, burst_rt):
            generate_split(wrt, wrt.place_params(params), warm_ids,
                           new_tokens, **warm_kw)

    soak = SoakConfig(
        n_requests=n_requests, arrival_rate=rate, seed=seed,
        prompt_len=prompt_len, max_new_tokens=new_tokens,
        deadline_s=deadline_s, kill_stage=kill_stage)
    artifact = run_soak(front, soak, clock=clock, burst_runtime=burst_rt)

    detail = {"soak": artifact, "devices": n_dev,
              "ambient_drop_rate": 0.02, "burst_drop_rate": corrupt,
              "retries": policy.max_retries}
    outcomes = artifact["outcomes"]
    identity = artifact["token_identity"]
    kill = artifact["kill"]
    line = {
        "metric": (f"{model_name} chaos-soak goodput ({n_requests} reqs at "
                   f"{rate}/s virtual, stage kill"
                   + (f" @{kill_stage}" if kill_stage is not None else " off")
                   + f", burst drop {corrupt})"),
        "value": round(artifact["goodput_tokens_per_s"], 2),
        "unit": "goodput tokens/s (virtual)",
        "vs_baseline": None,  # the reference has no serving layer at all
        "completed": outcomes.get("completed", 0),
        "failed_over": outcomes.get("failed_over", 0),
        "slo_attainment": artifact["slo_attainment"],
        "reject_rate": round(artifact["reject_rate"], 4),
        "shed_rate": round(artifact["shed_rate"], 4),
        "p99_ttft_s": artifact["p99_ttft_s"],
        "recovery_s": None if kill is None else kill["recovery_s"],
        "retry_budget_ok": artifact["retry_budget"]["within_budget"],
        "token_identity_ok": None if identity is None else identity["ok"],
    }
    _emit(line, detail)


def cluster_main():
    """BENCH_CLUSTER=1: replica-router acceptance — real-model mini fleet
    with a mid-workload kill, then the million-request simulated chaos
    soak with its no-fault and equal-capacity-single-replica controls.

    Every gate the CI job enforces is computed here and carried in the
    headline line: chaos-run token identity vs the fault-free same-plan
    replay, zero accepted loss, exactly one flight dump per induced kill,
    zero decode-step jit misses on the real fleet, outage-window goodput
    >= 90% of the no-fault run, and no-fault fleet goodput/SLO no worse
    than a single replica at equal total capacity."""
    import dataclasses
    import tempfile

    import numpy as np
    from edgellm_tpu.obs.metrics import record_cluster_stats
    from edgellm_tpu.serve.cluster import (ClusterConfig, ClusterFront,
                                           RespawnConfig, SimReplicaConfig,
                                           SimReplicaFront)
    from edgellm_tpu.serve.frontend import Request
    from edgellm_tpu.serve.soak import ClusterSoakConfig, run_cluster_soak
    from edgellm_tpu.utils.clock import FakeClock

    seed = int(os.environ.get("BENCH_CLUSTER_SEED", "0"))
    tmpdir = tempfile.mkdtemp(prefix="bench_cluster_")

    # -- leg (a): real-model 2-replica fleet, mid-workload kill ------------

    def real_leg() -> dict:
        import jax
        import jax.numpy as jnp
        from edgellm_tpu.models import PRESETS, init_params
        from edgellm_tpu.serve.batching import BatchingConfig, ContinuousBatcher
        from edgellm_tpu.serve.frontend import ServeFront

        model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
        cfg = PRESETS[model_name]
        dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
            os.environ.get("BENCH_DTYPE", "bfloat16")]
        n = int(os.environ.get("BENCH_CLUSTER_REAL_REQUESTS", "12"))
        prompt_len, new_tokens, shared_len = 16, 8, 8
        params = init_params(cfg, jax.random.key(0), dtype=dtype)

        page_size = 8
        pages_per_slot = -(-(prompt_len + new_tokens) // page_size)
        max_slots = 4
        bcfg = BatchingConfig(page_size=page_size, max_slots=max_slots,
                              num_pages=1 + max_slots * pages_per_slot,
                              pages_per_slot=pages_per_slot,
                              compute_dtype=dtype)
        # one warm run heats the process-global batched-step jit cache for
        # the whole fleet: every replica (including post-kill respawns)
        # reuses the same executables, so the steady-state gate is ZERO
        # misses on every record
        warm = ContinuousBatcher(cfg, params, bcfg)
        warm.submit(np.ones((prompt_len,), np.int32), 2, temperature=0.7)
        warm.run()

        rng = np.random.default_rng(seed)
        shared_pfx = rng.integers(1, cfg.vocab_size,
                                  size=shared_len).astype(np.int32)
        prompts = []
        for _ in range(n):
            p = rng.integers(1, cfg.vocab_size,
                             size=prompt_len).astype(np.int32)
            p[:shared_len] = shared_pfx
            prompts.append(p)
        gaps = rng.exponential(0.5, size=n)

        def make_req(i: int) -> Request:
            # half greedy, half sampled through the recorded seed — the
            # identity gate must hold at temperature > 0 too
            sampled = i % 2 == 1
            return Request(prompt_ids=prompts[i].copy(),
                           max_new_tokens=new_tokens,
                           temperature=0.7 if sampled else 0.0,
                           rng_seed=100 + i if sampled else 0,
                           deadline_s=600.0)

        def run_fleet(n_replicas: int, kill_at) -> tuple:
            clock = FakeClock()

            def factory(rid, gen):
                return ServeFront(cfg, params, clock=clock,
                                  batcher=ContinuousBatcher(cfg, params,
                                                            bcfg))

            cluster = ClusterFront(
                factory,
                ClusterConfig(
                    num_replicas=n_replicas, min_affinity_tokens=shared_len,
                    flight_dir=os.path.join(
                        tmpdir, f"real_{n_replicas}r_{kill_at}"),
                    respawn=RespawnConfig(backoff_base_s=0.5,
                                          jitter_frac=0.0)),
                clock=clock)
            by_req: dict = {}
            records = []
            for i in range(n):
                if kill_at is not None and i == kill_at:
                    # queues have built up (no drain yet): the kill must
                    # re-admit replica 0's queued work elsewhere with zero
                    # accepted loss
                    cluster.kill_replica(0, "chaos")
                clock.advance(float(gaps[i]))
                by_req[cluster.submit(make_req(i))] = i
            while True:
                recs = cluster.drain()
                if recs:
                    records.extend(recs)
                    continue
                if not cluster.pending:
                    break
                ev = cluster.next_event_s()
                if ev is None:
                    break
                clock.set_time(max(ev, clock.now))
            assert cluster.pending == 0, (
                f"real fleet lost {cluster.pending} accepted request(s)")
            return records, by_req, cluster

        chaos_recs, chaos_map, chaos_cluster = run_fleet(2, kill_at=n // 2)
        ref_recs, ref_map, _ = run_fleet(1, kill_at=None)

        def toks(r) -> list:
            return (np.asarray(r.tokens).reshape(-1).tolist()
                    if r.tokens is not None else None)

        ref_tokens = {ref_map[r.request_id]: toks(r) for r in ref_recs}
        completed = sum(1 for r in chaos_recs if r.outcome == "completed")
        mismatched = [
            chaos_map[r.request_id] for r in chaos_recs
            if r.outcome == "completed"
            and toks(r) != ref_tokens.get(chaos_map[r.request_id])]
        jit_max = max((r.jit_misses or 0) for r in chaos_recs)
        dumps = chaos_cluster.flight_dumps()
        rep = chaos_cluster.report()
        outcomes: dict = {}
        for r in chaos_recs:
            outcomes[r.outcome] = outcomes.get(r.outcome, 0) + 1
        return {
            "model": model_name, "requests": n,
            "completed": completed,
            "outcomes": outcomes,
            "identity_ok": completed == n and not mismatched,
            "mismatched": mismatched,
            "jit_misses_max": jit_max,
            "flight_dumps": len(dumps),
            "readmitted": rep["totals"]["readmitted"],
            "report": rep,
        }

    real = None
    if os.environ.get("BENCH_CLUSTER_REAL", "1") == "1":
        real = real_leg()

    # -- leg (b): simulated chaos soak + controls --------------------------

    n_sim = int(os.environ.get("BENCH_CLUSTER_REQUESTS", "1000000"))
    replicas = int(os.environ.get("BENCH_CLUSTER_REPLICAS", "4"))
    rate = float(os.environ.get("BENCH_CLUSTER_RATE", "80.0"))
    outage_s = float(os.environ.get("BENCH_CLUSTER_OUTAGE_S", "10.0"))
    soak = ClusterSoakConfig(
        n_requests=n_sim, arrival_rate=rate, seed=seed,
        prompt_len=16, shared_prefix_len=8, num_prefix_groups=32,
        max_new_tokens=16, deadline_s=120.0,
        sampled_frac=0.5, sample_temperature=0.7,
        kills=((0.3, 0), (0.6, 1)),
        burst_start_frac=0.45, burst_end_frac=0.55,
        burst_corrupt_rate=0.05)

    def sim_run(n_replicas: int, scfg: SimReplicaConfig,
                soak_cfg: ClusterSoakConfig, tag: str) -> dict:
        clock = FakeClock()

        def factory(rid, gen):
            return SimReplicaFront(scfg, clock=clock, replica_id=rid)

        cluster = ClusterFront(
            factory,
            ClusterConfig(num_replicas=n_replicas,
                          flight_dir=os.path.join(tmpdir, f"sim_{tag}"),
                          respawn=RespawnConfig(backoff_base_s=0.5,
                                                jitter_seed=seed)),
            clock=clock)
        return run_cluster_soak(cluster, soak_cfg, clock=clock)

    base_sim = SimReplicaConfig()
    calm = dataclasses.replace(soak, kills=(), burst_start_frac=0.0,
                               burst_end_frac=0.0, burst_corrupt_rate=0.0,
                               verify_identity=False)
    chaos = sim_run(replicas, base_sim, soak, "chaos")
    nofault = sim_run(replicas, base_sim, calm, "nofault")
    # the single-replica control at equal TOTAL capacity: one front whose
    # per-token service times are the fleet's divided by N and whose queue
    # holds the fleet's combined depth — the router must not cost goodput
    # or SLO relative to it
    single_cfg = dataclasses.replace(
        base_sim,
        prefill_s_per_token=base_sim.prefill_s_per_token / replicas,
        decode_s_per_token=base_sim.decode_s_per_token / replicas,
        max_queue_depth=base_sim.max_queue_depth * replicas)
    baseline = sim_run(1, single_cfg, calm, "baseline")
    record_cluster_stats(chaos["report"])

    width = float(chaos["goodput_buckets"]["width_s"])

    def window_tokens(art: dict, t0: float, t1: float) -> int:
        toks = art["goodput_buckets"]["tokens"]
        b0, b1 = int(t0 / width), int(t1 / width)
        return sum(v for b, v in toks.items() if b0 <= int(b) <= b1)

    # per-kill outage window: chaos goodput over [kill, kill + outage_s]
    # vs the SAME virtual window of the no-fault run of the same arrival
    # plan; the gate is the worst kill's fraction
    outage = []
    for ev in chaos["kills"]:
        t0 = float(ev["at_s"])
        lost = window_tokens(chaos, t0, t0 + outage_s)
        ref = window_tokens(nofault, t0, t0 + outage_s)
        outage.append({"replica": ev["replica"], "at_s": t0,
                       "chaos_tokens": lost, "nofault_tokens": ref,
                       "frac": (lost / ref) if ref else None})
    outage_frac = min((o["frac"] for o in outage if o["frac"] is not None),
                      default=None)

    goodput_vs_single = (nofault["goodput_tokens_per_s"]
                         / max(baseline["goodput_tokens_per_s"], 1e-9))
    slo_vs_single = ((nofault["slo_attainment"] or 0.0)
                     - (baseline["slo_attainment"] or 0.0))
    identity = chaos["token_identity"]
    gates = {
        "token_identity_ok": bool(identity["ok"] and identity["checked"]),
        "zero_accepted_loss": sum(chaos["outcomes"].values()) == n_sim,
        "flight_dumps_exactly_once":
            len(chaos["flight_dumps"]) == len(soak.kills),
        "respawned_through_probes": chaos["respawns"] == len(soak.kills),
        "outage_goodput_ge_90pct":
            outage_frac is not None and outage_frac >= 0.9,
        "goodput_ge_single_replica": goodput_vs_single >= 0.95,
        "slo_ge_single_replica": slo_vs_single >= -0.01,
    }
    if real is not None:
        gates["real_identity_ok"] = bool(real["identity_ok"])
        gates["real_jit_misses_zero"] = real["jit_misses_max"] == 0
        gates["real_flight_dumps_exactly_once"] = real["flight_dumps"] == 1

    detail = {
        "chaos": chaos, "nofault": nofault, "baseline_single": baseline,
        "outage_windows": outage, "outage_window_s": outage_s,
        "real": real, "gates": gates,
    }
    line = {
        "metric": (f"{replicas}-replica cluster chaos soak goodput "
                   f"({n_sim} reqs at {rate}/s virtual, "
                   f"{len(soak.kills)} kills, burst "
                   f"{soak.burst_corrupt_rate})"),
        "value": round(chaos["goodput_tokens_per_s"], 2),
        "unit": "goodput tokens/s (virtual)",
        "vs_baseline": round(goodput_vs_single, 4),
        "slo_attainment": chaos["slo_attainment"],
        "outage_goodput_frac": (None if outage_frac is None
                                else round(outage_frac, 4)),
        "token_identity_ok": gates["token_identity_ok"],
        "identity_checked": identity["checked"],
        "flight_dumps": len(chaos["flight_dumps"]),
        "kills": len(soak.kills),
        "respawns": chaos["respawns"],
        "readmitted": chaos["readmitted"],
        "recompute_tokens": chaos["recompute_tokens"],
        "real_identity_ok": None if real is None else real["identity_ok"],
        "real_jit_misses_max": (None if real is None
                                else real["jit_misses_max"]),
        "real_flight_dumps": None if real is None else real["flight_dumps"],
        "gates_ok": all(gates.values()),
    }
    _emit(line, detail)
    if not all(gates.values()):
        failed = sorted(k for k, v in gates.items() if not v)
        raise SystemExit(f"cluster bench gates failed: {failed}")


def gray_main():
    """BENCH_GRAY=1: gray-failure acceptance — a 3-replica simulated fleet
    where one replica silently degrades 20x MID-RUN (after the prefix-
    affinity map has captured most groups onto it, the case queue-depth
    routing cannot dodge), run three ways: gray plane armed (straggler
    demotion + hedging + deadline propagation), gray disabled, and a
    no-slowdown control of the same arrival plan.

    Gates carried in the headline line: hedged-fleet SLO goodput >= 1.5x
    the unhedged slowed fleet AND >= 0.9x the no-slowdown fleet, hedge
    overhead bounded by max_hedge_fraction, token identity on every
    completed request of the hedged run, zero accepted loss, and zero
    FAILED outcomes. SLO goodput is deadlines-met / ALL requests — a
    timed-out request counts as a miss instead of escaping the
    attainment denominator."""
    import dataclasses

    from edgellm_tpu.obs.metrics import record_cluster_stats
    from edgellm_tpu.serve.cluster import (ClusterConfig, ClusterFront,
                                           GrayConfig, SimReplicaConfig,
                                           SimReplicaFront)
    from edgellm_tpu.serve.soak import ClusterSoakConfig, run_cluster_soak
    from edgellm_tpu.utils.clock import FakeClock

    n = int(os.environ.get("BENCH_GRAY_REQUESTS", "600"))
    rate = float(os.environ.get("BENCH_GRAY_RATE", "30.0"))
    seed = int(os.environ.get("BENCH_GRAY_SEED", "7"))
    replicas = int(os.environ.get("BENCH_GRAY_REPLICAS", "3"))
    slow_mult = float(os.environ.get("BENCH_GRAY_SLOW_MULT", "20.0"))
    slow_at = float(os.environ.get("BENCH_GRAY_SLOW_AT", "0.3"))
    deadline_s = float(os.environ.get("BENCH_GRAY_DEADLINE_S", "0.5"))

    armed = GrayConfig(enabled=True, min_dwell_s=0.5, min_samples=8,
                       window_s=30.0, max_hedge_fraction=0.4)
    slowdowns = ((slow_at, 0, slow_mult),)

    def run(gray: GrayConfig, slow: tuple, tag: str) -> tuple:
        clock = FakeClock()
        # deadline propagation rides the gray switch: the disabled control
        # is the PR-19 fleet bit-for-bit
        scfg = SimReplicaConfig(deadline_propagation=gray.enabled)
        cluster = ClusterFront(
            lambda rid, gen: SimReplicaFront(scfg, clock=clock,
                                             replica_id=rid),
            ClusterConfig(num_replicas=replicas, gray=gray), clock=clock)
        art = run_cluster_soak(cluster, ClusterSoakConfig(
            n_requests=n, arrival_rate=rate, seed=seed,
            deadline_s=deadline_s, slowdowns=slow), clock=clock)
        art["pending"] = cluster.pending
        return art, cluster

    hedged, hedged_cl = run(armed, slowdowns, "hedged")
    unhedged, _ = run(GrayConfig(), slowdowns, "unhedged")
    nofault, _ = run(GrayConfig(), (), "nofault")
    record_cluster_stats(hedged["report"])

    vs_unhedged = (hedged["slo_goodput"]
                   / max(unhedged["slo_goodput"], 1e-9))
    vs_nofault = hedged["slo_goodput"] / max(nofault["slo_goodput"], 1e-9)
    identity = hedged["token_identity"]
    gates = {
        "slo_ge_1p5x_unhedged": vs_unhedged >= 1.5,
        "slo_ge_0p9x_nofault": vs_nofault >= 0.9,
        "hedge_fraction_bounded":
            hedged["hedge_fraction"] <= armed.max_hedge_fraction,
        "token_identity_ok": bool(identity["ok"] and identity["checked"]),
        "zero_accepted_loss": (sum(hedged["outcomes"].values()) == n
                               and hedged["pending"] == 0),
        "zero_failed": hedged["outcomes"].get("failed", 0) == 0,
    }
    detail = {
        "hedged": hedged, "unhedged": unhedged, "nofault": nofault,
        "gray_config": dataclasses.asdict(armed),
        "slowdowns": list(slowdowns), "gates": gates,
    }
    line = {
        "metric": (f"{replicas}-replica gray-failure soak SLO goodput "
                   f"({n} reqs at {rate}/s virtual, replica 0 slowed "
                   f"{slow_mult}x at {slow_at:.0%} of arrivals)"),
        "value": round(hedged["slo_goodput"], 4),
        "unit": "SLO goodput (deadlines met / all requests)",
        "vs_unhedged": round(vs_unhedged, 4),
        "vs_nofault": round(vs_nofault, 4),
        "unhedged_slo_goodput": round(unhedged["slo_goodput"], 4),
        "nofault_slo_goodput": round(nofault["slo_goodput"], 4),
        "hedges": hedged["hedges"],
        "hedge_wins": hedged["hedge_wins"],
        "hedge_fraction": round(hedged["hedge_fraction"], 4),
        "deadline_expired": hedged["deadline_expired"],
        "stragglers_flagged": (hedged["gray"] or {}).get("flagged"),
        "token_identity_ok": gates["token_identity_ok"],
        "identity_checked": identity["checked"],
        "gates_ok": all(gates.values()),
    }
    _emit(line, detail)
    if not all(gates.values()):
        failed = sorted(k for k, v in gates.items() if not v)
        raise SystemExit(f"gray bench gates failed: {failed}")


def disagg_main():
    """BENCH_DISAGG=1: disaggregated prefill/decode acceptance — a mixed
    long/short Poisson workload served by the DisaggServer vs the colocated
    batcher (token identity asserted, TTFT + tok/s compared), then the
    chaos leg: mid-migration prefill-worker kill, decode-worker kill, and a
    link-corruption burst with zero accepted loss."""
    import dataclasses
    import tempfile
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from edgellm_tpu.codecs.fec import FECConfig
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.serve.batching import BatchingConfig, ContinuousBatcher
    from edgellm_tpu.serve.disagg import DisaggConfig, DisaggServer
    from edgellm_tpu.serve.soak import DisaggSoakConfig, run_disagg_soak

    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]
    seed = int(os.environ.get("BENCH_DISAGG_SEED", "0"))
    n = int(os.environ.get("BENCH_DISAGG_REQUESTS", "16"))
    long_len = int(os.environ.get("BENCH_DISAGG_LONG", "48"))
    short_len = int(os.environ.get("BENCH_DISAGG_SHORT", "8"))
    new_tokens = int(os.environ.get("BENCH_DISAGG_TOKENS", "8"))
    corrupt = float(os.environ.get("BENCH_DISAGG_CORRUPT", "0.01"))
    tmpdir = tempfile.mkdtemp(prefix="bench_disagg_")

    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    page_size = 8
    pages_per_slot = -(-(long_len + new_tokens) // page_size)
    max_slots = 4
    bcfg = BatchingConfig(page_size=page_size, max_slots=max_slots,
                          num_pages=1 + max_slots * pages_per_slot,
                          pages_per_slot=pages_per_slot,
                          kv_codec="int8_per_channel",
                          compute_dtype=dtype)
    dcfg = DisaggConfig(num_prefill_workers=2, prefill_batch=2,
                        fec=FECConfig(enabled=True))

    # one warm disagg run compiles every executable both legs reuse: the
    # staging workers' prefill plan AND the decode plan (identical to the
    # colocated batcher's — same geometry, same kv codec), so compile time
    # never lands inside a timed leg
    warm = DisaggServer(cfg, params, bcfg, dcfg)
    warm.submit(np.ones((long_len,), np.int32), 2, temperature=0.7,
                rng_seed=1)
    warm.submit(np.ones((short_len,), np.int32), 2)
    warm.run()

    # -- leg (a): perf — mixed long/short Poisson, disagg vs colocated -----

    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        plen = long_len if i % 2 == 0 else short_len
        sampled = i % 2 == 1
        reqs.append((rng.integers(1, cfg.vocab_size,
                                  size=plen).astype(np.int32),
                     new_tokens,
                     0.7 if sampled else 0.0,
                     100 + i if sampled else 0))
    arrive_steps = rng.poisson(1.0, size=n)

    def first_token_ready(server, sid) -> bool:
        if sid in server.results:
            return True
        if hasattr(server, "handoffs"):      # DisaggServer
            if sid in server.handoffs:       # token 0 migrated, queued
                return True
            dsid = server._to_decode.get(sid)
            if dsid is None:
                return False
            st = server.decode._streams.get(dsid)
            return bool(st is not None and st.tokens)
        st = server._streams.get(sid)
        return bool(st is not None and st.tokens)

    def drive(server) -> dict:
        sids: list = []
        ttft: dict = {}
        t0 = time.perf_counter()

        def scan() -> None:
            now = time.perf_counter() - t0
            for i, s in enumerate(sids):
                if i not in ttft and first_token_ready(server, s):
                    ttft[i] = now

        for i, (p, mnt, temp, rs) in enumerate(reqs):
            sids.append(server.submit(p, mnt, temperature=temp,
                                      rng_seed=rs))
            for _ in range(int(arrive_steps[i]) + 1):
                server.step()
            scan()
        guard = 0
        while len(server.results) < n:
            server.step()
            scan()
            guard += 1
            assert guard < 100_000, "drive(): server stalled"
        wall = time.perf_counter() - t0
        results = [np.asarray(server.results[s]).tolist() for s in sids]
        tokens_out = sum(len(r) for r in results)
        tt = sorted(ttft.values())
        return {"wall_s": wall, "tokens_out": tokens_out,
                "tokens_per_s": tokens_out / max(wall, 1e-9),
                "ttft_mean_s": float(np.mean(tt)),
                "ttft_p50_s": float(tt[len(tt) // 2]),
                "results": results}

    srv = DisaggServer(cfg, params, bcfg, dcfg)
    disagg = drive(srv)
    disagg_rep = srv.report()["disagg"]
    colo = drive(ContinuousBatcher(cfg, params, bcfg))
    mismatched = [i for i in range(n)
                  if disagg["results"][i] != colo["results"][i]]

    # -- leg (b): chaos — worker kills + corruption burst, zero loss -------

    chaos_dcfg = DisaggConfig(num_prefill_workers=3, prefill_batch=2,
                              queue_bound=4, degrade_after=50,
                              fec=FECConfig(enabled=True))
    chaos_bcfg = dataclasses.replace(bcfg, checkpoint_dir=tmpdir)
    chaos_soak = DisaggSoakConfig(
        n_requests=n, seed=seed + 1, vocab_size=cfg.vocab_size,
        min_prompt_len=short_len, max_prompt_len=long_len,
        max_new_tokens=new_tokens, sampled_frac=0.5,
        sample_temperature=0.7,
        kills=((0.25, "prefill"), (0.7, "decode")),
        burst_start_frac=0.4, burst_end_frac=0.6,
        burst_bitflip_rate=corrupt)
    chaos_srv = DisaggServer(cfg, params, chaos_bcfg, chaos_dcfg)
    chaos = run_disagg_soak(
        chaos_srv, chaos_soak,
        reference_factory=lambda: ContinuousBatcher(cfg, params, bcfg))

    identity = chaos["token_identity"]
    gates = {
        "perf_identity_ok": not mismatched,
        "perf_not_degraded": not disagg_rep["degraded"],
        "perf_all_migrated": disagg_rep["migrations"] == n,
        "chaos_zero_accepted_loss": chaos["accepted_lost"] == 0
            and chaos["completed"] == n,
        "chaos_identity_ok": bool(identity["ok"]
                                  and identity["checked"] == n),
        "chaos_kills_fired": len(chaos["kills"]) >= len(chaos_soak.kills),
        "chaos_not_degraded": not chaos["disagg"]["degraded"],
    }
    detail = {
        "model": model_name, "requests": n,
        "long_len": long_len, "short_len": short_len,
        "disagg": {k: v for k, v in disagg.items() if k != "results"},
        "colocated": {k: v for k, v in colo.items() if k != "results"},
        "mismatched": mismatched,
        "disagg_report": disagg_rep,
        "chaos": chaos,
        "gates": gates,
    }
    line = {
        "metric": (f"disagg vs colocated serve ({n} reqs, "
                   f"{long_len}/{short_len} mixed prompts, int8 KV pages "
                   f"over FEC link)"),
        "value": round(disagg["tokens_per_s"], 2),
        "unit": "decode tokens/s (disagg)",
        "vs_baseline": round(disagg["tokens_per_s"]
                             / max(colo["tokens_per_s"], 1e-9), 4),
        "ttft_disagg_s": round(disagg["ttft_mean_s"], 4),
        "ttft_colocated_s": round(colo["ttft_mean_s"], 4),
        "token_identity_ok": gates["perf_identity_ok"],
        "migrations": disagg_rep["migrations"],
        "migrated_pages": disagg_rep["migrated_pages"],
        "wire_bytes": disagg_rep["wire_bytes"],
        "chaos_completed": chaos["completed"],
        "chaos_identity_ok": gates["chaos_identity_ok"],
        "chaos_kills": len(chaos["kills"]),
        "chaos_redriven_pages": chaos["disagg"]["redriven_pages"],
        "chaos_recompute_tokens": chaos["disagg"]["recompute_tokens"],
        "chaos_link_repaired": chaos["disagg"]["link"]["repaired"],
        "gates_ok": all(gates.values()),
    }
    _emit(line, detail)
    if not all(gates.values()):
        failed = sorted(k for k, v in gates.items() if not v)
        raise SystemExit(f"disagg bench gates failed: {failed}")


def _backend_unavailable(exc: BaseException) -> bool:
    """True when the error is an accelerator-backend outage (the tunneled
    TPU plugin failing to come up), not a code bug in the bench."""
    msg = str(exc)
    return ("nable to initialize backend" in msg
            or "UNAVAILABLE" in msg
            or "No visible device" in msg)


def _run_section(section: str, fn):
    """Run one bench section with a backend preflight: an accelerator outage
    emits a partial artifact with an explicit per-section status and returns
    success, instead of dying rc=1 with no artifact at all (round 5 lost its
    whole BENCH.json to ``Unable to initialize backend 'axon'``)."""
    try:
        import jax

        jax.devices()  # preflight: force backend init before any workload
        return fn()
    except RuntimeError as e:
        if not _backend_unavailable(e):
            raise
        err = " ".join(str(e).split())[:300]
        line = {
            "metric": f"bench section {section!r}",
            "value": None,
            "unit": None,
            "vs_baseline": None,
            "status": "backend_unavailable",
            "section": section,
        }
        _emit(line, {"status": "backend_unavailable", "section": section,
                     "error": err})
        return 0


def main():
    if os.environ.get("BENCH_LINT") == "1":
        # pre-flight the bench build through graphlint (REPRODUCING §8):
        # refuse to burn accelerator time on a build whose decode/split
        # graphs violate their declared contracts
        from edgellm_tpu.lint.__main__ import main as lint_main

        raise SystemExit(lint_main(["--no-mypy"]))
    if os.environ.get("BENCH_OBS") == "1":
        return _run_section("obs", obs_main)
    if os.environ.get("BENCH_OBS_LIVE") == "1":
        return _run_section("obs_live", obs_live_main)
    if os.environ.get("BENCH_RECOVERY") == "1":
        return _run_section("recovery", recovery_main)
    if os.environ.get("BENCH_DECODE") == "1":
        return _run_section("decode", decode_main)
    if os.environ.get("BENCH_FAULTS") == "1":
        return _run_section("faults", faults_main)
    if os.environ.get("BENCH_FEC") == "1":
        return _run_section("fec", fec_main)
    if os.environ.get("BENCH_SOAK") == "1":
        return _run_section("soak", soak_main)
    if os.environ.get("BENCH_CLUSTER") == "1":
        return _run_section("cluster", cluster_main)
    if os.environ.get("BENCH_GRAY") == "1":
        return _run_section("gray", gray_main)
    if os.environ.get("BENCH_DISAGG") == "1":
        return _run_section("disagg", disagg_main)
    if os.environ.get("BENCH_SERVE") == "1":
        return _run_section("serve", serve_main)
    if os.environ.get("BENCH_PREFIX") == "1":
        return _run_section("prefix", prefix_main)
    if os.environ.get("BENCH_KVQ") == "1":
        return _run_section("kvq", kvq_main)
    if os.environ.get("BENCH_WIRE") == "1":
        return _run_section("wire", wire_main)
    if os.environ.get("BENCH_SPEC") == "1":
        return _run_section("spec", spec_main)
    if os.environ.get("BENCH_PIPE") == "1":
        return _run_section("pipe", pipe_main)
    return _run_section("sweep", sweep_main)


def wire_main():
    """BENCH_WIRE=1: the fused boundary-hop workload.

    For every FUSED_CAPABLE base codec, cross a real 2-stage boundary both
    ways — the fused wire hop (encode -> seal -> ONE flat uint8 ppermute ->
    verify -> decode, ``codecs.pallas_kernels.fused_wire_hop``) and the
    separate encode/per-leaf-ppermute/decode ladder the pre-fusion runtime
    traces — and assert the receiver's activations are BIT-identical
    (``fused_equals_fallback``; the wire format adds an 8-byte seal, never a
    different value). On TPU the roundtrips are timed (pre-warmed jits,
    interleaved) and the fused-vs-fallback ratio lands in the probe cache
    under ``fused_hop:<base>`` — the measurement :func:`fused_hop_plan`'s
    default gate requires before it ever fuses a hop. Off-TPU the rows carry
    ``timing_skipped`` (hop timing off-chip is noise) but still record the
    parity verdict, ``default_substituted``, and the current probe-cache
    decision, so every artifact documents WHY the default path did or did
    not fuse. Knobs: BENCH_WIRE_BATCH/SEQ/DIM (default 8x512x896),
    BENCH_WIRE_ITERS (default 20)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from edgellm_tpu.codecs import probe_cache
    from edgellm_tpu.codecs.packing import get_wire_codec
    from edgellm_tpu.codecs.pallas_kernels import (FUSED_CAPABLE,
                                                  REMOTE_CAPABLE,
                                                  default_substituted,
                                                  fused_hop_plan,
                                                  fused_wire_hop)
    from edgellm_tpu.codecs.wire_format import WireFormat
    from edgellm_tpu.parallel import make_stage_mesh
    from edgellm_tpu.utils.jax_compat import shard_map
    from edgellm_tpu.utils.profiling import timed

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    batch = int(os.environ.get("BENCH_WIRE_BATCH", "8"))
    seq = int(os.environ.get("BENCH_WIRE_SEQ", "512"))
    dim = int(os.environ.get("BENCH_WIRE_DIM", "896"))
    iters = int(os.environ.get("BENCH_WIRE_ITERS", "20"))

    if len(jax.devices()) < 2:
        line = {"metric": "fused boundary hop", "value": None, "unit": None,
                "vs_baseline": None, "status": "needs_2_devices",
                "section": "wire"}
        _emit(line, {"status": "needs_2_devices", "section": "wire"})
        return 0

    mesh = make_stage_mesh(2)
    rng = np.random.default_rng(0)
    hidden = jnp.asarray(rng.standard_normal((batch, seq, dim)),
                         jnp.float32)
    stacked = jnp.broadcast_to(hidden[None], (2,) + hidden.shape)

    def hop_fns(codec):
        """(fused, fallback) jitted 0->1 hops over the 2-stage mesh; both
        return the stacked per-stage rows so nothing is DCE'd."""
        def fused_body(h):
            idx = jax.lax.axis_index("stage")
            return fused_wire_hop(codec, h[0], 0, "stage", idx)[None]

        def plain_body(h):
            idx = jax.lax.axis_index("stage")
            mine = h[0]
            payload = codec.encode(mine)
            moved = jax.tree_util.tree_map(
                lambda a: jax.lax.ppermute(a, "stage", [(0, 1)]), payload)
            dec = codec.decode(moved).astype(mine.dtype)
            return jnp.where(idx == 1, dec, mine)[None]

        mk = lambda body: jax.jit(shard_map(
            body, mesh=mesh, in_specs=P("stage"), out_specs=P("stage"),
            check_vma=False))
        return mk(fused_body), mk(plain_body)

    rows, cache_rows = [], []
    for base in sorted(FUSED_CAPABLE):
        codec = get_wire_codec(base)
        wf = WireFormat.for_codec(codec, hidden.shape, hidden.dtype)
        fused_fn, plain_fn = hop_fns(codec)
        # pre-warm BOTH jits before any timing (the BENCH_SOAK trick: the
        # first call pays compile, and a compile inside a timed window would
        # gift the other side a phantom speedup)
        out_f = np.asarray(jax.block_until_ready(fused_fn(stacked)))
        out_p = np.asarray(jax.block_until_ready(plain_fn(stacked)))
        row = {
            "codec": base,
            "backend": backend,
            "shape": [batch, seq, dim],
            "wire_bytes": wf.wire_nbytes,
            "payload_bytes": wf.payload_nbytes,
            "default_substituted": default_substituted(base),
            "remote_capable": base in REMOTE_CAPABLE,
            "fused_equals_fallback": bool(np.array_equal(out_f, out_p)),
        }
        plan = fused_hop_plan(codec)
        row["fused_plan"] = (None if plan is None
                             else {"mode": plan.mode, "reason": plan.reason})
        if on_tpu:
            sec_f, _ = timed(fused_fn, stacked, warmup=2, iters=iters)
            sec_p, _ = timed(plain_fn, stacked, warmup=2, iters=iters)
            ratio = sec_p / sec_f
            row["fused_us"] = round(sec_f * 1e6, 1)
            row["fallback_us"] = round(sec_p * 1e6, 1)
            row["roundtrip_speedup_vs_jnp"] = round(ratio, 2)
            # unrounded: WIN_MARGIN hysteresis must never see a rounded value
            row["roundtrip_speedup_vs_jnp_raw"] = ratio
            cache_rows.append({"codec": f"fused_hop:{base}",
                               "roundtrip_speedup_vs_jnp_raw": ratio})
        else:
            row["timing_skipped"] = (f"backend {backend!r}: hop timing is "
                                     "only meaningful on TPU")
        rows.append(row)

    cache_path = probe_cache.record(cache_rows) if cache_rows else None
    for row in rows:
        # the decision the NEXT runtime build will read for this codec: the
        # win/loss verdict (post-record, so a fresh TPU measurement is
        # reflected) plus the margin it was judged against
        row["probe_decision"] = {
            "measured_win": probe_cache.measured_win(
                f"fused_hop:{row['codec']}"),
            "win_margin": probe_cache.WIN_MARGIN,
        }

    n_parity = sum(r["fused_equals_fallback"] for r in rows)
    speedups = [r["roundtrip_speedup_vs_jnp_raw"] for r in rows
                if "roundtrip_speedup_vs_jnp_raw" in r]
    # the kernel family must earn its keep: a codec the default path WOULD
    # substitute (frozen win set or probed win) that times slower than its
    # jnp ladder is a regression — demote it (drop it from the win set or
    # let the probe cache record the loss) before serving reuses the kernel
    slow_defaults = [r["codec"] for r in rows
                     if r.get("default_substituted")
                     and r.get("roundtrip_speedup_vs_jnp_raw", 1.0) < 1.0]
    detail = {"section": "wire", "backend": backend, "codecs": rows,
              "probe_cache_path": cache_path}
    if speedups:
        line = {"metric": "fused hop min speedup vs separate ladder",
                "value": round(min(speedups), 3), "unit": "x",
                "vs_baseline": None, "section": "wire",
                "parity": f"{n_parity}/{len(rows)}",
                "slow_default_codecs": slow_defaults}
    else:
        line = {"metric": "fused hop parity (timing skipped off-TPU)",
                "value": n_parity, "unit": f"of {len(rows)} codecs",
                "vs_baseline": None, "section": "wire",
                "slow_default_codecs": slow_defaults}
    _emit(line, detail)
    assert n_parity == len(rows), \
        [r["codec"] for r in rows if not r["fused_equals_fallback"]]
    assert not slow_defaults, \
        (f"default-substituted codec(s) timed slower than the jnp ladder: "
         f"{slow_defaults} — demote before serving reuses the kernel")
    return 0


def sweep_main():
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import PRESETS, init_params
    from edgellm_tpu.eval import run_token_sweep
    from edgellm_tpu.utils.flops import token_sweep_flops_per_chunk

    # BENCH_MODEL switches the swept model (e.g. qwen2-1.5b); the reference's
    # 16 s/chunk anchor is its Qwen2-0.5B run, so vs_baseline is only emitted
    # for the default model
    model_name = os.environ.get("BENCH_MODEL", "qwen2-0.5b")
    cfg = PRESETS[model_name]
    n_chunks = int(os.environ.get("BENCH_CHUNKS", "96"))
    window_batch = int(os.environ.get("BENCH_WINDOW_BATCH", "64"))
    peak_tflops = float(os.environ.get("BENCH_PEAK_TFLOPS", "197"))
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]

    # BENCH_MAX_LENGTH=2048 reproduces the reference's own Pythia evaluation
    # window (Experiments/Pythia-70M/initial_exp.py:86 — both Pythia
    # experiments evaluate at window = max_position_embeddings = 2048),
    # served by the round-5 query-blocked attention kernel
    max_length = int(os.environ.get("BENCH_MAX_LENGTH", "512"))
    stride = int(os.environ.get("BENCH_STRIDE", "32"))
    methods = ["regular_importance", "weighted_importance", "last_row", "aggregate_till"]
    # the reference's headline split layer (11) where it exists; mid-stack for
    # shallower presets so any BENCH_MODEL runs
    layers_of_interest = [min(11, cfg.num_layers // 2)]
    ratios = [0.0, 0.25, 0.5, 0.75, 1.0]

    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    rng = np.random.default_rng(0)
    # corpus long enough for n_chunks full 512-token windows at stride 32 + warmup
    corpus = rng.integers(0, cfg.vocab_size, max_length + stride * (n_chunks + 2))
    head_weights = rng.random((cfg.num_layers, cfg.num_heads)).astype(np.float32)
    head_weights /= head_weights.sum(axis=1, keepdims=True)

    codec = "int4_token_select"  # the reference's boundary scheme
    kw = dict(
        methods=methods, layers_of_interest=layers_of_interest, ratios=ratios,
        max_length=max_length, stride=stride, head_weights=head_weights,
        codec=codec,
    )

    from edgellm_tpu.eval.harness import run_with_oom_backoff

    requested_wb = window_batch
    if jax.default_backend() == "tpu":
        # pick the largest window batch that FITS before touching device
        # memory: a real TPU OOM poisons the process allocator, so the
        # preflight AOT-compiles the sweep executables and reads XLA's memory
        # analysis (no allocation) instead of trying-and-backing-off
        from edgellm_tpu.tools.wb_preflight import preflight_token_sweep_batch

        window_batch = preflight_token_sweep_batch(
            cfg, window_batch, max_length=max_length, stride=stride,
            layers_of_interest=layers_of_interest, ratios=ratios,
            dtype=dtype, codec=codec)
        # warmup: one full untimed pass over the same chunk schedule, so every
        # executable the timed run needs (chunk-0 group, steady groups, the
        # final partial group) is compiled and cached before the clock starts
        run_token_sweep(cfg, params, corpus, max_chunks=n_chunks,
                        window_batch=window_batch, **kw)
    else:
        # non-TPU backends recover from OOM in-process: warmup under the
        # halving backoff, then time at the surviving batch
        _, window_batch = run_with_oom_backoff(
            lambda wb: run_token_sweep(cfg, params, corpus, max_chunks=n_chunks,
                                       window_batch=wb, **kw),
            window_batch)

    # best sustained of BENCH_REPEATS timed passes: the tunneled backend's
    # fixed per-call cost drifts by phase (observed 0.030 -> 0.045 s/chunk
    # for IDENTICAL code an hour apart while the differential-scan kernel
    # rate held steady), and a single pass inherits whatever phase it lands in
    repeats = int(os.environ.get("BENCH_REPEATS", "2"))
    sweep_passes = []  # EVERY timed pass, so the best-of-N headline is
    # auditable against the documented tunnel drift (VERDICT r4 weak #5)
    for _ in range(max(repeats, 1)):
        t0 = time.monotonic()
        result = run_token_sweep(cfg, params, corpus, max_chunks=n_chunks,
                                 window_batch=window_batch, **kw)
        elapsed = time.monotonic() - t0
        sweep_passes.append(elapsed / result.chunks)
    s_per_chunk = min(sweep_passes)  # full precision; rounded only for display

    # analytic FLOPs for a steady-state chunk (stride-token scoring tail);
    # counts executed work only (the fp-baseline column is deduped across
    # methods by the harness exactly when the codec is in DEDUP_ZERO_CODECS)
    from edgellm_tpu.eval.harness import DEDUP_ZERO_CODECS

    n_zero = (sum(1 for r in ratios if float(r) == 0.0)
              if codec in DEDUP_ZERO_CODECS else 0)
    chunk_flops = token_sweep_flops_per_chunk(
        cfg, max_length, tail=stride, n_methods=len(methods),
        layers_of_interest=layers_of_interest, n_ratios=len(ratios),
        n_zero_ratios=n_zero)
    tflops_per_s = chunk_flops / s_per_chunk / 1e12

    line = {
        "metric": (f"{model_name} sweep time per {stride}-token chunk "
                   f"(4 methods x 1 layer x 5 ratios, window {max_length})"),
        "value": round(s_per_chunk, 4),
        "unit": "s/chunk",
        # the 16 s/chunk anchor is the reference's Qwen2-0.5B run at ITS
        # workload shape (window 512, stride 32) — other models or windows
        # have no anchor to compare against
        "vs_baseline": (round(REFERENCE_S_PER_CHUNK / s_per_chunk, 2)
                        if (model_name, max_length, stride) ==
                        ("qwen2-0.5b", 512, 32) else None),
        "tokens_per_s": round(stride / s_per_chunk, 1),
        "window_batch": window_batch,
        "model_tflops_per_s": round(tflops_per_s, 2),
        "mfu": round(tflops_per_s / peak_tflops, 4),
    }
    # verbose blocks (pallas probe, relevance detail, flop accounting) go to a
    # sidecar + an EARLIER stdout line: the driver's tail capture must always
    # land on the compact headline as the FINAL line (round-3's artifact lost
    # its headline to a single giant JSON line)
    detail = {
        "requested_window_batch": requested_wb,
        "sweep_passes_s_per_chunk": [round(p, 4) for p in sweep_passes],
        "model_tflops_per_chunk": round(chunk_flops / 1e12, 3),
        "assumed_peak_tflops": peak_tflops,
    }
    if model_name == "qwen2-0.5b":
        # STATIC documentation of a one-off round-5 trace, not a product of
        # this run (tracing every bench would distort the timings it exists
        # to explain): device-time attribution of THE flagship sweep
        # (jax.profiler on the tunneled v5e, wb=64, 96 chunks; XLA-Modules
        # occupancy was 100% — the sweep is device-bound, not host-bound)
        detail["profile_trace_r5_static"] = {
            "static_record": True,
            "applies_to": "qwen2-0.5b sweep, wb=64, v5e, round-5 code",
            "device_fraction": {
                "matmul_fusions": 0.79, "attention_kernels": 0.106,
                "rotary_slice_negate": 0.025, "layout_copies": 0.021,
                "softmax_ce_reduce": 0.012, "other": 0.046},
            "matmul_fusion_tflops": 157,
            "fix": "flat-batch suffix (_suffix_sweep): the nested ratio x "
                   "window vmaps carried 5-D activations whose non-default "
                   "layouts forced ~117 MB physical-no-op copies around "
                   "every attention custom-call and a per-vocab-block "
                   "logits retile in the streamed unembed; flattening to "
                   "(R*W, S, D) cut copies 6.8% -> 2.1% of device time "
                   "(0.0295 -> 0.0273 s/chunk measured)",
        }

    on_tpu = jax.default_backend() == "tpu"

    # the chip's ACHIEVABLE bf16 matmul ceiling, so MFU is honest across
    # rounds (the spec 197 TF/s is ~30% above what this tunneled chip gives)
    if on_tpu and os.environ.get("BENCH_MEASURE_PEAK", "1") != "0":
        from edgellm_tpu.utils.profiling import measure_peak_tflops

        measured = measure_peak_tflops(cap=peak_tflops)
        if measured is not None:  # None = noise swallowed every differential
            line["measured_peak_tflops"] = round(measured, 1)
            line["mfu_vs_measured"] = round(tflops_per_s / measured, 4)

    # LRP head-relevance extraction throughput (reference: 2.1 it/s on its
    # GPU for the same Qwen2-0.5B/512-token workload, BASELINE.md)
    if on_tpu and os.environ.get("BENCH_RELEVANCE", "1") != "0":
        from edgellm_tpu.importance.relevance import run_relevance_extraction

        from edgellm_tpu.tools.wb_preflight import largest_fitting_relevance_batch

        rel_chunks = int(os.environ.get("BENCH_REL_CHUNKS", "24"))
        rel_kw = dict(max_length=max_length, stride=stride, max_chunks=rel_chunks)
        rel_wb = largest_fitting_relevance_batch(
            cfg, int(os.environ.get("BENCH_REL_WINDOW_BATCH", "16")),
            max_length=max_length, dtype=dtype)
        run_relevance_extraction(cfg, params, corpus, window_batch=rel_wb,
                                 **rel_kw)  # warmup
        rel_stats: dict = {}
        run_relevance_extraction(cfg, params, corpus, window_batch=rel_wb,
                                 stats=rel_stats, **rel_kw)
        line["relevance_it_per_s"] = round(rel_stats["it_per_s"], 2)
        detail["relevance_window_batch"] = rel_wb
        # the 2.1 it/s anchor is the reference's Qwen2-0.5B relevance run at
        # ITS workload shape — same guard as vs_baseline above
        if (model_name, max_length, stride) == ("qwen2-0.5b", 512, 32):
            line["relevance_vs_baseline"] = round(rel_stats["it_per_s"] / 2.1, 2)

    # on-silicon proof of the Pallas codec substitution path (VERDICT r2 #1):
    # every *_pallas wire codec executed on the real backend, parity + GB/s
    if on_tpu and os.environ.get("BENCH_PALLAS", "1") != "0":
        from edgellm_tpu.tools.pallas_probe import probe_all

        detail["pallas"] = probe_all()

    # silicon record of the attention-kernel wins at the envelope-extension
    # shapes (VERDICT r4 #1): the reference's own Pythia window (S=2048) and
    # llama-1b's wide packed row, neither covered by the whole-S kernel
    if on_tpu and os.environ.get("BENCH_ATTN", "1") != "0":
        from edgellm_tpu.tools.attn_probe import SHAPES, probe_shape

        names = os.environ.get(
            "BENCH_ATTN_SHAPES", "pythia-70m_s2048,llama-3.2-1b_s512").split(",")
        # reps >= 3: the interleaved-pair estimator is a MEDIAN of per-pair
        # ratios — at reps=2 it degenerates to a midpoint and the phase-drift
        # rejection it exists for never engages (ADVICE r5 #4)
        detail["attn_kernel"] = [probe_shape(*t, reps=3)
                                 for t in SHAPES if t[0] in names]

    _emit(line, detail)


if __name__ == "__main__":
    main()
