"""Benchmark: Qwen2-0.5B importance-guided quantization sweep throughput.

Reproduces the reference's headline workload — the Qwen2-0.5B sweep of
``Experiments/Qwen2-0.5B/main.py``: per 32-token stride over a 512-token window,
importance scoring for 4 methods from a full attention pass, then
4 methods x 1 split layer x 5 ratios quantized evaluations. The reference runs
1 eager + 20 quantized FULL forwards per chunk at ~16.0 s/chunk on its Colab GPU
(``Notebooks/qwen2-0.5B_experiment.ipynb`` cell 12, BASELINE.md). Here the same
sweep is one stats forward + vmapped layer suffixes.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline > 1 means faster than the reference's s/chunk on its hardware.

Env knobs: BENCH_CHUNKS (default 8), BENCH_DTYPE (float32|bfloat16, default
bfloat16 — TPU MXU native; fp32 PPL parity is the CPU test suite's job).
"""
import json
import os
import time

import numpy as np

REFERENCE_S_PER_CHUNK = 16.0  # qwen2-0.5B_experiment.ipynb cell 12 (BASELINE.md)


def main():
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import QWEN2_0_5B as cfg, init_params
    from edgellm_tpu.eval import run_token_sweep

    n_chunks = int(os.environ.get("BENCH_CHUNKS", "8"))
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]

    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    rng = np.random.default_rng(0)
    # corpus long enough for n_chunks full 512-token windows at stride 32 + warmup
    corpus = rng.integers(0, cfg.vocab_size, 512 + 32 * (n_chunks + 2))
    head_weights = rng.random((cfg.num_layers, cfg.num_heads)).astype(np.float32)
    head_weights /= head_weights.sum(axis=1, keepdims=True)

    kw = dict(
        methods=["regular_importance", "weighted_importance", "last_row", "aggregate_till"],
        layers_of_interest=[11],
        ratios=[0.0, 0.25, 0.5, 0.75, 1.0],
        max_length=512, stride=32, head_weights=head_weights,
    )

    # warmup: compile both chunk shapes out of band
    run_token_sweep(cfg, params, corpus, max_chunks=1, **kw)

    t0 = time.monotonic()
    result = run_token_sweep(cfg, params, corpus, max_chunks=n_chunks, **kw)
    elapsed = time.monotonic() - t0
    s_per_chunk = elapsed / result.chunks

    print(json.dumps({
        "metric": "qwen2-0.5b sweep time per 32-token chunk (4 methods x 1 layer x 5 ratios)",
        "value": round(s_per_chunk, 4),
        "unit": "s/chunk",
        "vs_baseline": round(REFERENCE_S_PER_CHUNK / s_per_chunk, 2),
    }))


if __name__ == "__main__":
    main()
