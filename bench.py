"""Benchmark: Qwen2-0.5B importance-guided quantization sweep throughput.

Reproduces the reference's headline workload — the Qwen2-0.5B sweep of
``Experiments/Qwen2-0.5B/main.py``: per 32-token stride over a 512-token window,
importance scoring for 4 methods from a full attention pass, then
4 methods x 1 split layer x 5 ratios quantized evaluations. The reference runs
1 eager + 20 quantized FULL forwards per chunk at ~16.0 s/chunk on its Colab GPU
(``Notebooks/qwen2-0.5B_experiment.ipynb`` cell 12, BASELINE.md). Here the same
sweep is one stats forward + window-batched vmapped layer suffixes with the
full-vocab unembed restricted to the scored tail positions.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline > 1 means faster than the reference's s/chunk on its hardware,
plus observability fields: tokens_per_s (scored tokens), model_tflops_per_s and
mfu (analytic sweep FLOPs vs the chip's assumed bf16 peak).

Env knobs: BENCH_CHUNKS (default 96), BENCH_WINDOW_BATCH (default 64 — batches
evaluation windows into one executable to feed the MXU), BENCH_DTYPE
(float32|bfloat16, default bfloat16), BENCH_PEAK_TFLOPS (assumed bf16 peak for
the MFU denominator, default 197 = TPU v5e).
"""
import json
import os
import time

import numpy as np

REFERENCE_S_PER_CHUNK = 16.0  # qwen2-0.5B_experiment.ipynb cell 12 (BASELINE.md)


def main():
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import QWEN2_0_5B as cfg, init_params
    from edgellm_tpu.eval import run_token_sweep
    from edgellm_tpu.utils.flops import token_sweep_flops_per_chunk

    n_chunks = int(os.environ.get("BENCH_CHUNKS", "96"))
    window_batch = int(os.environ.get("BENCH_WINDOW_BATCH", "64"))
    peak_tflops = float(os.environ.get("BENCH_PEAK_TFLOPS", "197"))
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        os.environ.get("BENCH_DTYPE", "bfloat16")]

    max_length, stride = 512, 32
    methods = ["regular_importance", "weighted_importance", "last_row", "aggregate_till"]
    layers_of_interest = [11]
    ratios = [0.0, 0.25, 0.5, 0.75, 1.0]

    params = init_params(cfg, jax.random.key(0), dtype=dtype)
    rng = np.random.default_rng(0)
    # corpus long enough for n_chunks full 512-token windows at stride 32 + warmup
    corpus = rng.integers(0, cfg.vocab_size, max_length + stride * (n_chunks + 2))
    head_weights = rng.random((cfg.num_layers, cfg.num_heads)).astype(np.float32)
    head_weights /= head_weights.sum(axis=1, keepdims=True)

    codec = "int4_token_select"  # the reference's boundary scheme
    kw = dict(
        methods=methods, layers_of_interest=layers_of_interest, ratios=ratios,
        max_length=max_length, stride=stride, head_weights=head_weights,
        window_batch=window_batch, codec=codec,
    )

    # warmup: one full untimed pass over the same chunk schedule, so every
    # executable the timed run needs (chunk-0 group, steady groups, the final
    # partial group) is compiled and cached before the clock starts
    run_token_sweep(cfg, params, corpus, max_chunks=n_chunks, **kw)

    t0 = time.monotonic()
    result = run_token_sweep(cfg, params, corpus, max_chunks=n_chunks, **kw)
    elapsed = time.monotonic() - t0
    s_per_chunk = elapsed / result.chunks

    # analytic FLOPs for a steady-state chunk (stride-token scoring tail);
    # counts executed work only (the fp-baseline column is deduped across
    # methods by the harness exactly when the codec is in DEDUP_ZERO_CODECS)
    from edgellm_tpu.eval.harness import DEDUP_ZERO_CODECS

    n_zero = (sum(1 for r in ratios if float(r) == 0.0)
              if codec in DEDUP_ZERO_CODECS else 0)
    chunk_flops = token_sweep_flops_per_chunk(
        cfg, max_length, tail=stride, n_methods=len(methods),
        layers_of_interest=layers_of_interest, n_ratios=len(ratios),
        n_zero_ratios=n_zero)
    tflops_per_s = chunk_flops / s_per_chunk / 1e12

    print(json.dumps({
        "metric": "qwen2-0.5b sweep time per 32-token chunk (4 methods x 1 layer x 5 ratios)",
        "value": round(s_per_chunk, 4),
        "unit": "s/chunk",
        "vs_baseline": round(REFERENCE_S_PER_CHUNK / s_per_chunk, 2),
        "tokens_per_s": round(stride / s_per_chunk, 1),
        "window_batch": window_batch,
        "model_tflops_per_chunk": round(chunk_flops / 1e12, 3),
        "model_tflops_per_s": round(tflops_per_s, 2),
        "mfu": round(tflops_per_s / peak_tflops, 4),
        "assumed_peak_tflops": peak_tflops,
    }))


if __name__ == "__main__":
    main()
