"""Deterministic-schedule race harness: threadlint's dynamic side.

The static rules (EG101-EG104) prove lock discipline; this module
*executes* the discovered critical sections under controlled thread
interleavings, so a racy schedule is a replayable artifact instead of a
once-a-month CI flake.

How it works: scenario threads run under a cooperative scheduler that
allows exactly ONE thread to run at a time.  Locks are replaced
(``instrument(sched, obj, "_lock")``) with :class:`SchedLock`, whose
``acquire``/``release`` yield control back to the scheduler at every
boundary — each yield is a *choice point* where any runnable thread may
be scheduled next.  A schedule is the sequence of choices, so:

- **Replay**: ``run_schedule(make_scenario, decisions=[1, 0, ...])``
  replays one exact interleaving (decisions index the runnable set at
  each choice point).
- **Deadlock as a value**: when no thread is runnable but some are
  blocked on locks, the run returns ``Outcome(deadlocked=True)`` with
  the tid -> lock wait map — no timeouts, no hangs.
- **Exhaustive bounded search**: :func:`explore` enumerates schedules by
  iterative context bounding (branch on every choice point, bounding the
  number of *preemptions* — switches away from a still-runnable
  thread), the Musuvathi/Qadeer CHESS result that most real races and
  deadlocks show up within 2 preemptions.

The EG102 ``Histogram.merge_from`` cross-merge deadlock is reachable
here in a 2-thread, 2-preemption search pre-fix, and provably absent
from the full bounded interleaving set post-fix (see
``tests/test_threadlint.py``).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, List, Optional, Sequence, Set,
                    Tuple, Union)

__all__ = ["SchedLock", "Scheduler", "Outcome", "run_schedule", "explore",
           "instrument"]

#: harness self-defence: a cv.wait longer than this means the harness
#: itself (not the scenario) wedged — raise instead of hanging the test
_WAIT_S = 30.0
#: runaway-scenario guard: more yields than this in one run is a bug
_MAX_STEPS = 20_000

Scenario = Union[Sequence[Callable[[], None]],
                 Tuple[Sequence[Callable[[], None]], Callable[[], None]]]


class _Abandon(BaseException):
    """Raised inside parked workers to unwind them after a verdict."""


@dataclass
class Outcome:
    """Result of running one scenario under one schedule."""
    deadlocked: bool
    errors: List[Tuple[int, BaseException]]
    blocked: Dict[int, str]                      # tid -> lock name waited on
    schedule: List[int]                          # chosen tid per step
    choice_points: List[Tuple[Tuple[int, ...], int]]  # (runnable tids, idx)

    @property
    def ok(self) -> bool:
        return not self.deadlocked and not self.errors


class _Worker:
    __slots__ = ("tid", "fn", "thread", "state", "waiting_on", "error")

    def __init__(self, tid: int, fn: Callable[[], None]) -> None:
        self.tid = tid
        self.fn = fn
        self.thread: Optional[threading.Thread] = None
        self.state = "new"            # new|ready|running|blocked|done
        self.waiting_on: Optional["SchedLock"] = None
        self.error: Optional[BaseException] = None


class SchedLock:
    """Drop-in for ``threading.Lock`` whose acquire/release are scheduler
    yield points.  Non-reentrant, like the real thing.  Compatible with
    ``acquire_in_order`` (plain acquire/release, stable ``id()``)."""

    def __init__(self, sched: "Scheduler", name: str) -> None:
        self._sched = sched
        self.name = name
        self._owner: Optional[int] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._sched._lock_acquire(self)
        return True

    def release(self) -> None:
        self._sched._lock_release(self)

    def locked(self) -> bool:
        return self._owner is not None

    def __enter__(self) -> "SchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


class Scheduler:
    """Cooperative turn-passing scheduler: one runnable thread at a time,
    every lock boundary a choice point."""

    def __init__(self) -> None:
        self._cv = threading.Condition()
        self._workers: List[_Worker] = []
        self._turn: Optional[int] = None
        self._abandoned = False
        self._local = threading.local()

    # -- worker side --------------------------------------------------------

    def _current(self) -> Optional[_Worker]:
        tid = getattr(self._local, "tid", None)
        return self._workers[tid] if tid is not None else None

    def _park(self, me: _Worker, state: str,
              waiting_on: Optional[SchedLock] = None) -> None:
        """Give up the turn and wait to be scheduled again.  Caller must
        NOT hold self._cv."""
        with self._cv:
            me.state = state
            me.waiting_on = waiting_on
            self._turn = None
            self._cv.notify_all()
            while self._turn != me.tid:
                if self._abandoned:
                    raise _Abandon()
                if not self._cv.wait(_WAIT_S):
                    raise RuntimeError(
                        f"schedule harness wedged: worker {me.tid} waited "
                        f">{_WAIT_S}s for a turn")
            me.state = "running"
            me.waiting_on = None

    def _lock_acquire(self, lock: SchedLock) -> None:
        me = self._current()
        if me is None:
            # main thread touching an instrumented object outside run():
            # single-threaded by construction, just take the lock
            lock._owner = -1
            return
        self._park(me, "ready")          # pre-acquire preemption point
        while True:
            with self._cv:
                if lock._owner == me.tid:
                    raise RuntimeError(
                        f"self-deadlock: worker {me.tid} re-acquired "
                        f"non-reentrant {lock.name}")
                if lock._owner is None:
                    lock._owner = me.tid
                    return
            self._park(me, "blocked", waiting_on=lock)

    def _lock_release(self, lock: SchedLock) -> None:
        me = self._current()
        if me is None:
            lock._owner = None
            return
        with self._cv:
            lock._owner = None
        self._park(me, "ready")          # post-release preemption point

    def _worker_main(self, worker: _Worker) -> None:
        self._local.tid = worker.tid
        try:
            self._park(worker, "ready")  # all workers park before step 0
            worker.fn()
        except _Abandon:
            pass
        except BaseException as e:       # noqa: BLE001 - reported in Outcome
            worker.error = e
        finally:
            with self._cv:
                worker.state = "done"
                if self._turn == worker.tid:
                    self._turn = None
                self._cv.notify_all()

    # -- controller side ----------------------------------------------------

    def _runnable(self) -> List[int]:
        out = []
        for w in self._workers:
            if w.state == "ready":
                out.append(w.tid)
            elif (w.state == "blocked" and w.waiting_on is not None
                  and w.waiting_on._owner is None):
                out.append(w.tid)
        return out

    def run(self, fns: Sequence[Callable[[], None]],
            decisions: Sequence[int] = ()) -> Outcome:
        """Run ``fns`` as scheduler-controlled threads under one schedule.

        ``decisions[i]`` picks (by index into the sorted runnable set) the
        thread scheduled at choice point ``i``; once decisions run out the
        default policy keeps the previous thread running when it can
        (fewest preemptions first).
        """
        self._workers = [_Worker(tid, fn) for tid, fn in enumerate(fns)]
        for w in self._workers:
            w.thread = threading.Thread(
                target=self._worker_main, args=(w,),
                name=f"sched-worker-{w.tid}", daemon=True)
            w.thread.start()

        schedule: List[int] = []
        choice_points: List[Tuple[Tuple[int, ...], int]] = []
        deadlocked = False
        blocked: Dict[int, str] = {}
        prev: Optional[int] = None
        step = 0
        with self._cv:
            while True:
                while (self._turn is not None
                       or any(w.state in ("new", "running")
                              for w in self._workers)):
                    if not self._cv.wait(_WAIT_S):
                        raise RuntimeError(
                            "schedule harness wedged waiting for workers "
                            "to park")
                if all(w.state == "done" for w in self._workers):
                    break
                runnable = self._runnable()
                if not runnable:
                    deadlocked = True
                    blocked = {
                        w.tid: w.waiting_on.name
                        for w in self._workers
                        if w.state == "blocked" and w.waiting_on is not None}
                    break
                idx = decisions[step] if step < len(decisions) else None
                if idx is None or not (0 <= idx < len(runnable)):
                    idx = (runnable.index(prev) if prev in runnable else 0)
                chosen = runnable[idx]
                choice_points.append((tuple(runnable), idx))
                schedule.append(chosen)
                prev = chosen
                step += 1
                if step > _MAX_STEPS:
                    raise RuntimeError(
                        f"scenario exceeded {_MAX_STEPS} schedule steps")
                self._turn = chosen
                self._cv.notify_all()
            # verdict reached: unwind any parked workers
            self._abandoned = True
            self._cv.notify_all()
        for w in self._workers:
            assert w.thread is not None
            w.thread.join(timeout=_WAIT_S)
        errors = [(w.tid, w.error) for w in self._workers
                  if w.error is not None]
        return Outcome(deadlocked=deadlocked, errors=errors, blocked=blocked,
                       schedule=schedule, choice_points=choice_points)


def instrument(sched: Scheduler, obj: Any, attr: str = "_lock") -> Any:
    """Replace ``obj.<attr>`` with a scheduler-controlled lock."""
    setattr(obj, attr, SchedLock(sched, f"{type(obj).__name__}.{attr}"))
    return obj


def run_schedule(make_scenario: Callable[[Scheduler], Scenario],
                 decisions: Sequence[int] = ()) -> Outcome:
    """Build a fresh scenario and run it under one schedule.

    ``make_scenario(sched)`` must construct fresh objects, instrument
    their locks, and return either a list of thread bodies or a
    ``(bodies, verify)`` tuple; ``verify()`` runs after an ok schedule
    (raise/assert inside it to fail the test)."""
    sched = Scheduler()
    scenario = make_scenario(sched)
    verify: Optional[Callable[[], None]] = None
    if (isinstance(scenario, tuple) and len(scenario) == 2
            and callable(scenario[1])):
        fns, verify = scenario[0], scenario[1]
    else:
        fns = scenario  # type: ignore[assignment]
    outcome = sched.run(list(fns), decisions=decisions)
    if outcome.ok and verify is not None:
        verify()
    return outcome


def _preemptions(
        choice_points: Sequence[Tuple[Tuple[int, ...], int]]) -> int:
    count = 0
    prev: Optional[int] = None
    for runnable, idx in choice_points:
        chosen = runnable[idx]
        if prev is not None and prev != chosen and prev in runnable:
            count += 1
        prev = chosen
    return count


def explore(make_scenario: Callable[[Scheduler], Scenario],
            max_preemptions: int = 2,
            max_schedules: int = 2000) -> List[Outcome]:
    """Iterative-context-bounded exhaustive exploration.

    Runs the scenario under every schedule whose preemption count is
    <= ``max_preemptions`` (deduplicated by decision prefix), up to
    ``max_schedules`` runs.  Returns every Outcome; callers assert
    ``not any(o.deadlocked for o in outcomes)`` (or hunt for one).
    """
    results: List[Outcome] = []
    seen: Set[Tuple[int, ...]] = set()
    frontier: List[Tuple[int, ...]] = [()]
    while frontier and len(results) < max_schedules:
        prefix = frontier.pop()
        if prefix in seen:
            continue
        seen.add(prefix)
        out = run_schedule(make_scenario, decisions=prefix)
        results.append(out)
        base = [idx for _, idx in out.choice_points]
        for i in range(len(prefix), len(out.choice_points)):
            runnable, chosen_idx = out.choice_points[i]
            for alt in range(len(runnable)):
                if alt == chosen_idx:
                    continue
                cand = tuple(base[:i]) + (alt,)
                if cand in seen:
                    continue
                hypo = list(out.choice_points[:i]) + [(runnable, alt)]
                if _preemptions(hypo) <= max_preemptions:
                    frontier.append(cand)
    return results
