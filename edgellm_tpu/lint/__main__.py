"""graphlint CLI: ``python -m edgellm_tpu.lint``.

Exit 0 when every layer is clean, 1 when any finding survives. ``--json``
writes the merged machine-readable report (the CI artifact).

The graph layer traces real entry points over a 2-stage pipeline, so the
spoofed multi-device CPU topology must be configured BEFORE jax initializes
its backends — this module sets the env vars first and only then imports
anything that pulls in jax (same bootstrap as tests/conftest.py).
"""
from __future__ import annotations

import argparse
import os
import sys


def _bootstrap_jax() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    # sitecustomize may have imported jax already; backends are lazy, so
    # forcing the platform here still lands before first device use
    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m edgellm_tpu.lint",
        description="graphlint: AST footgun rules, thread/lock-discipline "
                    "rules (EG1xx) + jaxpr-level graph contracts for the "
                    "split-decode stack (REPRODUCING §8)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the merged JSON report here")
    ap.add_argument("--sarif", metavar="PATH", default=None,
                    help="also write the report as SARIF 2.1.0 (all layers)")
    ap.add_argument("--ast-only", action="store_true",
                    help="run only the AST rule layer (no jax import)")
    ap.add_argument("--graph-only", action="store_true",
                    help="run only the graph-contract layer")
    ap.add_argument("--thread-only", action="store_true",
                    help="run only the thread/lock-discipline layer "
                         "(EG1xx; no jax import)")
    ap.add_argument("--lattice-only", action="store_true",
                    help="run only the config-lattice verifier (latticelint:"
                         " AOT footprint + donation + pairwise compat)")
    ap.add_argument("--matrix", metavar="PATH", default=None,
                    help="where the lattice layer writes the capability "
                         "matrix (CI uploads capability_matrix.json)")
    ap.add_argument("--no-mypy", action="store_true",
                    help="skip the scoped mypy --strict layer")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="list every '# graphlint: disable=' marker with "
                         "file:line (audit trail for silenced findings)")
    ap.add_argument("paths", nargs="*",
                    help="AST/thread-lint these files instead of the package "
                         "(graph layer always targets the real package)")
    args = ap.parse_args(argv)
    only_flags = [args.ast_only, args.graph_only, args.thread_only,
                  args.lattice_only]
    if sum(only_flags) > 1:
        ap.error("--ast-only, --graph-only, --thread-only and "
                 "--lattice-only are mutually exclusive")
    if args.lattice_only and args.paths:
        ap.error("--lattice-only lints configs/, not source paths")

    from .report import LintReport, merge, to_sarif

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_root = os.path.dirname(pkg_root)
    findings_by_layer = []
    checked: list = []
    skipped: list = []

    if not (args.graph_only or args.thread_only or args.lattice_only):
        from .ast_rules import iter_package_files, lint_paths

        targets = args.paths or list(iter_package_files(pkg_root))
        findings_by_layer.append(lint_paths(targets))

        if not args.no_mypy and not args.paths:
            from .typecheck import run_typecheck

            ty_findings, ty_skips = run_typecheck(repo_root)
            findings_by_layer.append(ty_findings)
            skipped.extend(ty_skips)

    if not (args.ast_only or args.graph_only or args.lattice_only):
        # pure-AST layer like the EG00x rules: runs pre-jax-bootstrap
        from .threadlint import lint_files as thread_lint_files
        from .threadlint import lint_package as thread_lint_package

        if args.paths:
            findings_by_layer.append(thread_lint_files(args.paths))
        else:
            findings_by_layer.append(thread_lint_package(pkg_root))

    if not (args.ast_only or args.thread_only or args.lattice_only):
        _bootstrap_jax()
        from .entrypoints import run_graph_checks

        g_findings, g_checked, g_skips = run_graph_checks()
        findings_by_layer.append(g_findings)
        checked.extend(g_checked)
        skipped.extend(g_skips)

    if not (args.ast_only or args.thread_only or args.graph_only
            or args.paths):
        # layer 4: the config-lattice verifier (AOT footprints, donation
        # coverage, pairwise feature compat) + the capability-matrix artifact
        _bootstrap_jax()
        from .lattice import run_lattice_checks, write_matrix

        l_findings, l_checked, l_skips, matrix = run_lattice_checks()
        findings_by_layer.append(l_findings)
        checked.extend(l_checked)
        skipped.extend(l_skips)
        if args.matrix:
            write_matrix(matrix, args.matrix)

    if args.show_suppressed:
        from .ast_rules import collect_suppressions, iter_package_files

        sup_targets = args.paths or list(iter_package_files(pkg_root))
        marks = collect_suppressions(sup_targets)
        print(f"suppressions: {len(marks)} marker(s)")
        for path, line, rules in marks:
            what = "all rules" if rules is None else ",".join(sorted(rules))
            print(f"  {path}:{line}: disable={what}")

    report = LintReport(findings=merge(*findings_by_layer),
                        checked_contracts=checked, skipped=skipped)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            f.write(report.to_json() + "\n")
    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as f:
            f.write(to_sarif(report) + "\n")
    print(report.summary())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
