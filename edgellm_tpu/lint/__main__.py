"""graphlint CLI: ``python -m edgellm_tpu.lint``.

Exit 0 when every layer is clean, 1 when any finding survives. ``--json``
writes the merged machine-readable report (the CI artifact).

The graph layer traces real entry points over a 2-stage pipeline, so the
spoofed multi-device CPU topology must be configured BEFORE jax initializes
its backends — this module sets the env vars first and only then imports
anything that pulls in jax (same bootstrap as tests/conftest.py).
"""
from __future__ import annotations

import argparse
import os
import sys


def _bootstrap_jax() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    # sitecustomize may have imported jax already; backends are lazy, so
    # forcing the platform here still lands before first device use
    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m edgellm_tpu.lint",
        description="graphlint: AST footgun rules + jaxpr-level graph "
                    "contracts for the split-decode stack (REPRODUCING §8)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the merged JSON report here")
    ap.add_argument("--ast-only", action="store_true",
                    help="run only the AST rule layer (no jax import)")
    ap.add_argument("--graph-only", action="store_true",
                    help="run only the graph-contract layer")
    ap.add_argument("--no-mypy", action="store_true",
                    help="skip the scoped mypy --strict layer")
    ap.add_argument("paths", nargs="*",
                    help="AST-lint these files instead of the package "
                         "(graph layer always targets the real package)")
    args = ap.parse_args(argv)
    if args.ast_only and args.graph_only:
        ap.error("--ast-only and --graph-only are mutually exclusive")

    from .report import LintReport, merge

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_root = os.path.dirname(pkg_root)
    findings_by_layer = []
    checked: list = []
    skipped: list = []

    if not args.graph_only:
        from .ast_rules import iter_package_files, lint_paths

        targets = args.paths or list(iter_package_files(pkg_root))
        findings_by_layer.append(lint_paths(targets))

        if not args.no_mypy and not args.paths:
            from .typecheck import run_typecheck

            ty_findings, ty_skips = run_typecheck(repo_root)
            findings_by_layer.append(ty_findings)
            skipped.extend(ty_skips)

    if not args.ast_only:
        _bootstrap_jax()
        from .entrypoints import run_graph_checks

        g_findings, g_checked, g_skips = run_graph_checks()
        findings_by_layer.append(g_findings)
        checked.extend(g_checked)
        skipped.extend(g_skips)

    report = LintReport(findings=merge(*findings_by_layer),
                        checked_contracts=checked, skipped=skipped)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            f.write(report.to_json() + "\n")
    print(report.summary())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
