"""Graph-contract registry and the jaxpr-level checkers behind it.

The stack's load-bearing invariants — "only quantized bytes cross the
boundary hop", "the hop plan adds exactly N collectives", "no f64, no host
callbacks, donated KV buffers", "a disabled feature builds the identical
graph" — were each proven ad hoc in one test and enforced nowhere else.
This module promotes them to *declared contracts*: a subsystem opts in by
decorating its entry point with :func:`graph_contract`, and the lint CLI
traces the real function (``jax.make_jaxpr`` / ``.lower()``) and verifies
the declaration against the actual graph.

Contract fields may be plain values or callables taking a ``ctx`` dict —
the driver (``lint.entrypoints``) supplies measured facts (payload leaf
counts, hop byte totals) so a declaration like ``collectives=lambda ctx:
{"ppermute": ctx["n_hops"] * ctx["payload_leaves"], "psum": 1}`` states the
*invariant* while the numbers come from the codec registry, not from a
hand-maintained constant that rots.

Checkers are pure jaxpr/HLO walks — nothing here executes model code.
"""
from __future__ import annotations

import dataclasses
import hashlib
from collections import Counter
from typing import Any, Callable, Iterator, Mapping, Optional, Union

import jax

from .report import Finding

#: communication primitives counted by the collective-count contract;
#: a silently-added collective is exactly what this check exists to catch
COLLECTIVE_PRIMS = frozenset({
    "ppermute", "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "reduce_scatter", "psum_scatter", "pgather",
})

#: primitives that re-enter the host from inside a jitted graph — forbidden
#: on every decode/forward hot path (each one is a device->host sync)
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_callback", "infeed", "outfeed",
})

#: dtypes the "no f64" contract rejects: double precision anywhere in a
#: traced graph means a silent promotion slipped in (TPUs emulate f64 at a
#: catastrophic slowdown; the paper's wire formats are int4/int8/bf16)
F64_DTYPES = frozenset({"float64", "complex128"})

_DEFAULT_FORBID = ("f64", "host_callback")


@dataclasses.dataclass(frozen=True)
class GraphContract:
    """A declared graph-level contract for one traced entry point.

    Every field except ``name``/``fn`` may be a plain value or a
    ``callable(ctx) -> value`` resolved at check time (see module
    docstring). ``None`` disables that particular check.

    collectives: exact {primitive name: count} over the whole traced graph
        (scan/shard_map bodies count once — these are static graph counts).
    wire_dtypes: allowed dtype names for every operand of every ``ppermute``
        (the boundary-hop wire). Anything else crossing a cut is a leak.
    wire_bytes: exact total payload bytes moved by all ``ppermute`` eqns.
    forbid: subset of {"f64", "host_callback"}.
    donate: minimum number of donated (input->output aliased) buffers the
        *lowered* entry point must carry — 0 disables the check.
    """

    name: str
    fn: Optional[Callable] = None
    collectives: Union[None, Mapping[str, int], Callable] = None
    wire_dtypes: Union[None, frozenset, Callable] = None
    wire_bytes: Union[None, int, Callable] = None
    forbid: tuple = _DEFAULT_FORBID
    donate: Union[int, Callable] = 0

    def resolve(self, field: str, ctx: Optional[dict]) -> Any:
        val = getattr(self, field)
        return val(ctx or {}) if callable(val) else val


#: the in-code registry ``@graph_contract`` populates; the lint CLI's graph
#: layer iterates it (drivers in ``lint.entrypoints`` know how to build
#: example inputs for each name)
GRAPH_CONTRACTS: dict = {}


def graph_contract(name: Optional[str] = None, *,
                   collectives: Union[None, Mapping[str, int], Callable] = None,
                   wire_dtypes: Union[None, frozenset, Callable] = None,
                   wire_bytes: Union[None, int, Callable] = None,
                   forbid: tuple = _DEFAULT_FORBID,
                   donate: Union[int, Callable] = 0) -> Callable:
    """Declare a graph contract on an entry point (decorator, zero runtime
    cost — it only records the spec and returns the function unchanged).

    Usage::

        @graph_contract("split.forward",
                        collectives=lambda ctx: {"ppermute": ctx["hop_eqns"],
                                                 "psum": 1},
                        wire_bytes=lambda ctx: ctx["wire_bytes"])
        def forward(self, ...): ...

    A new subsystem opts in with one decorator plus a driver in
    ``lint.entrypoints`` that builds example inputs (see REPRODUCING §8).
    """
    unknown = set(forbid) - {"f64", "host_callback"}
    if unknown:
        raise ValueError(f"unknown forbid entries {sorted(unknown)}; "
                         f"supported: 'f64', 'host_callback'")

    def deco(fn: Callable) -> Callable:
        cname = name or fn.__qualname__
        GRAPH_CONTRACTS[cname] = GraphContract(
            name=cname, fn=fn, collectives=collectives,
            wire_dtypes=wire_dtypes, wire_bytes=wire_bytes, forbid=forbid,
            donate=donate)
        fn.__graph_contract__ = cname  # type: ignore[attr-defined]
        return fn

    return deco


# ---------------------------------------------------------------------------
# jaxpr walks
# ---------------------------------------------------------------------------


def _sub_jaxprs(params: Mapping) -> Iterator:
    """Yield every Jaxpr/ClosedJaxpr nested in an equation's params
    (pjit/scan/while/cond/shard_map/custom_* all stash theirs differently)."""
    for v in params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if isinstance(x, jax.core.ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, jax.core.Jaxpr):
                yield x


def iter_eqns(jaxpr) -> Iterator:
    """Depth-first over every equation of a (Closed)Jaxpr, including all
    nested sub-jaxprs. Bodies of scan/shard_map are visited ONCE — contract
    counts are static graph counts, not runtime trip counts."""
    if isinstance(jaxpr, jax.core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def count_collectives(jaxpr) -> Counter:
    """Static {collective primitive: equation count} over the whole graph."""
    c: Counter = Counter()
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            c[eqn.primitive.name] += 1
    return c


def ppermute_traffic(jaxpr) -> list:
    """[(dtype name, shape, nbytes)] for every ``ppermute`` operand — the
    bytes that actually cross a boundary hop, read off the traced graph."""
    out = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "ppermute":
            continue
        for v in eqn.invars:
            aval = v.aval
            nbytes = int(aval.size) * aval.dtype.itemsize
            out.append((aval.dtype.name, tuple(aval.shape), nbytes))
    return out


def _all_avals(jaxpr) -> Iterator:
    if isinstance(jaxpr, jax.core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for v in list(jaxpr.invars) + list(jaxpr.constvars) + list(jaxpr.outvars):
        yield v.aval
    for eqn in iter_eqns(jaxpr):
        for v in list(eqn.invars) + list(eqn.outvars):
            yield v.aval


def find_f64(jaxpr) -> list:
    """Dtype-name list of every f64/c128 aval anywhere in the graph."""
    hits = []
    for aval in _all_avals(jaxpr):
        dt = getattr(aval, "dtype", None)
        if dt is not None and dt.name in F64_DTYPES:
            hits.append(dt.name)
    return hits


def find_callbacks(jaxpr) -> list:
    """Primitive names of every host re-entry inside the graph."""
    return [eqn.primitive.name for eqn in iter_eqns(jaxpr)
            if eqn.primitive.name in CALLBACK_PRIMS]


def donated_input_count(jitted_fn: Callable, *args: Any, **kwargs: Any) -> int:
    """Number of input buffers the entry point donates — the static form of
    "the KV cache is updated in place, not copied every step".

    Counted two ways and reconciled with max(): ``donated_invars`` on the
    traced pjit equation (the jit-level declaration, robust on every
    backend), and ``tf.aliasing_output`` annotations in the lowered
    StableHLO (present where the backend actually implements aliasing —
    single-device paths here; the multi-device CPU grid drops them even
    though the declaration stands)."""
    declared = 0
    try:
        jaxpr = jax.make_jaxpr(jitted_fn)(*args, **kwargs)
        for eqn in jaxpr.jaxpr.eqns:
            di = eqn.params.get("donated_invars")
            if di:
                declared += sum(1 for d in di if d)
    except Exception:  # noqa: BLE001 — fall through to the lowering count
        pass
    lowered = jitted_fn.lower(*args, **kwargs)
    return max(declared, lowered.as_text().count("tf.aliasing_output"))


def graph_fingerprint(fn: Callable, *args: Any, **kwargs: Any) -> str:
    """sha256 over the pretty-printed jaxpr of ``fn(*args)`` — two builds
    with the same fingerprint compile the same graph. This is PR 2/3's
    "disabled config is bit-identical to the pre-feature graph" test turned
    into a reusable checker."""
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    return hashlib.sha256(jaxpr.pretty_print().encode()).hexdigest()


# ---------------------------------------------------------------------------
# the contract checker
# ---------------------------------------------------------------------------


def check_traced(contract: GraphContract, traced_fn: Callable, args: tuple,
                 ctx: Optional[dict] = None,
                 lowerable: Optional[Callable] = None,
                 lower_args: Optional[tuple] = None) -> list:
    """Verify one contract against the real traced graph.

    ``traced_fn``/``args`` build the jaxpr (the driver's example inputs);
    ``lowerable``/``lower_args``, when given, are the *jitted* entry point
    the donation check lowers. Returns a list of :class:`Finding` (empty =
    contract holds)."""
    findings = []

    def fail(rule: str, msg: str) -> None:
        findings.append(Finding(layer="graph", rule=rule, where=contract.name,
                                line=0, message=msg))

    try:
        jaxpr = jax.make_jaxpr(traced_fn)(*args)
    except Exception as e:  # noqa: BLE001 — a contract that cannot trace IS a finding
        fail("GC-trace", f"entry point failed to trace: {type(e).__name__}: {e}")
        return findings

    if "f64" in contract.forbid:
        hits = find_f64(jaxpr)
        if hits:
            fail("GC-f64",
                 f"{len(hits)} double-precision aval(s) in the traced graph "
                 f"({sorted(set(hits))}); a silent f32->f64 promotion slipped "
                 f"into the jitted path")
    if "host_callback" in contract.forbid:
        cbs = find_callbacks(jaxpr)
        if cbs:
            fail("GC-callback",
                 f"host callback(s) {sorted(set(cbs))} inside the jitted "
                 f"graph; each one is a device->host sync on the hot path")

    want = contract.resolve("collectives", ctx)
    if want is not None:
        got = count_collectives(jaxpr)
        want_c = Counter({k: v for k, v in dict(want).items() if v})
        if got != want_c:
            fail("GC-collectives",
                 f"collective count mismatch: declared {dict(want_c)}, traced "
                 f"graph has {dict(got)} — a collective was silently added or "
                 f"removed")

    dtypes = contract.resolve("wire_dtypes", ctx)
    nbytes = contract.resolve("wire_bytes", ctx)
    if dtypes is not None or nbytes is not None:
        traffic = ppermute_traffic(jaxpr)
        if dtypes is not None:
            allowed = frozenset(dtypes)
            bad = sorted({d for d, _, _ in traffic} - allowed)
            if bad:
                fail("GC-wire-dtype",
                     f"dtypes {bad} cross the boundary hop but the codec's "
                     f"declared wire format is {sorted(allowed)} — "
                     f"unquantized data is leaking across the cut")
        if nbytes is not None:
            total = sum(b for _, _, b in traffic)
            if total != int(nbytes):
                fail("GC-wire-bytes",
                     f"boundary hops move {total} bytes, codec declares "
                     f"{int(nbytes)} — payload width drifted from the wire "
                     f"contract")

    min_donate = contract.resolve("donate", ctx) or 0
    if min_donate:
        target = lowerable if lowerable is not None else traced_fn
        targs = lower_args if lower_args is not None else args
        try:
            n = donated_input_count(target, *targs)
        except Exception as e:  # noqa: BLE001
            fail("GC-donate", f"donation check failed to lower: "
                              f"{type(e).__name__}: {e}")
        else:
            if n < int(min_donate):
                fail("GC-donate",
                     f"only {n} input buffer(s) are donated "
                     f"(input->output aliased) in the lowered executable, "
                     f"contract requires >= {int(min_donate)} — the KV cache "
                     f"is being copied every step instead of updated in "
                     f"place")
    return findings


def check_identity(name: str, fn_a: Callable, args_a: tuple,
                   fn_b: Callable, args_b: tuple,
                   what: str = "disabled-config graph") -> list:
    """The reusable disabled-config-identity checker: both builds must hash
    to the identical jaxpr. Returns [] or one Finding."""
    try:
        fp_a = graph_fingerprint(fn_a, *args_a)
        fp_b = graph_fingerprint(fn_b, *args_b)
    except Exception as e:  # noqa: BLE001
        return [Finding(layer="graph", rule="GC-identity", where=name, line=0,
                        message=f"identity check failed to trace: "
                                f"{type(e).__name__}: {e}")]
    if fp_a != fp_b:
        return [Finding(
            layer="graph", rule="GC-identity", where=name, line=0,
            message=f"{what} is NOT identical to the pre-feature graph "
                    f"({fp_a[:12]} != {fp_b[:12]}); the disabled feature "
                    f"leaks machinery into the compiled executable")]
    return []
