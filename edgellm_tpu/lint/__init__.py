"""graphlint: static analysis for the split-decode stack.

Four layers behind one CLI (``python -m edgellm_tpu.lint``, REPRODUCING §8):

- **AST rules** (:mod:`.ast_rules`): JAX footguns ruff can't see — traced
  branches, host I/O under jit, numpy-on-tracer, missing static_argnames,
  per-token host syncs in decode loops, trace-time container mutation.
- **Thread/lock discipline** (:mod:`.threadlint`): EG1xx rules for the
  host-side serving stack — locks around shared batcher/pool state, no
  blocking calls under a lock, condition-variable hygiene.
- **Graph contracts** (:mod:`.contracts` + :mod:`.entrypoints`): production
  entry points declare their compiled-graph invariants with
  :func:`graph_contract`; the lint CLI traces the real functions and
  verifies collective counts, wire dtypes/bytes, no-f64, no-host-callback,
  KV-cache donation, and disabled-config graph identity.
- **Config lattice** (:mod:`.lattice`, REPRODUCING §22): every
  ``configs/*.json`` must validate, AOT-lower its entry points under its
  ``"budget"`` block with donation intact, and keep a README row; the
  feature lattice is fuzzed pairwise against the typed-refusal oracle and
  the result lands in ``capability_matrix.json``.

This ``__init__`` stays import-light on purpose: production modules import
:func:`graph_contract` from here at module import time, so pulling drivers
or jax-heavy machinery in here would create cycles.
"""
from .contracts import GRAPH_CONTRACTS, GraphContract, graph_contract
from .report import Finding, LintReport

__all__ = ["GRAPH_CONTRACTS", "GraphContract", "graph_contract", "Finding",
           "LintReport"]
