"""graphlint: static analysis for the split-decode stack.

Two layers behind one CLI (``python -m edgellm_tpu.lint``, REPRODUCING §8):

- **AST rules** (:mod:`.ast_rules`): JAX footguns ruff can't see — traced
  branches, host I/O under jit, numpy-on-tracer, missing static_argnames,
  per-token host syncs in decode loops, trace-time container mutation.
- **Graph contracts** (:mod:`.contracts` + :mod:`.entrypoints`): production
  entry points declare their compiled-graph invariants with
  :func:`graph_contract`; the lint CLI traces the real functions and
  verifies collective counts, wire dtypes/bytes, no-f64, no-host-callback,
  KV-cache donation, and disabled-config graph identity.

This ``__init__`` stays import-light on purpose: production modules import
:func:`graph_contract` from here at module import time, so pulling drivers
or jax-heavy machinery in here would create cycles.
"""
from .contracts import GRAPH_CONTRACTS, GraphContract, graph_contract
from .report import Finding, LintReport

__all__ = ["GRAPH_CONTRACTS", "GraphContract", "graph_contract", "Finding",
           "LintReport"]
