"""Layer 1: AST lint — JAX footguns ruff has no rules for.

Rules (stable ids, suppress per line with ``# graphlint: disable=EG00x`` or
``# graphlint: disable`` for all):

EG001  Python ``if``/``while``/``assert`` on a likely-traced value inside a
       jit-reachable function (``jnp``/``lax`` call or ``.any()``/``.all()``
       in the test) — raises ``TracerBoolConversionError`` at trace time or,
       worse, silently bakes one branch into the compiled graph.
EG002  Host I/O reachable from a jitted function (``print``, ``open``,
       ``time.time``/``perf_counter``/``sleep``, ``subprocess``, ...) —
       runs at *trace* time, not run time, and is a classic "why does my
       timer report 0ms" / "why did it print once" footgun.
EG003  ``numpy`` math applied to a likely-traced array inside a
       jit-reachable function — forces a host transfer + constant-folds the
       tracer, or crashes; ``jnp`` is the traced-world spelling.
EG004  ``jax.jit`` wrapping a function with config-like parameters
       (``cfg``, ``mesh``, ``capacity``, ...) that are not listed in
       ``static_argnames``/``static_argnums`` — every distinct config then
       either fails to hash or retraces silently.
EG005  Host coercion (``.item()``, ``float(...)``/``int(...)`` of computed
       values, ``jax.device_get``) inside a decode/generate hot loop — a
       device sync per token.
EG006  Mutation of a captured container (``append``/``update``/subscript
       assignment) inside a function nested under a jit-reachable one —
       the mutation happens once at trace time, not per call.
EG007  A literal metric name (``registry.counter/gauge/histogram("...")``,
       direct ``Counter``/``Gauge``/``Histogram`` construction) or span name
       (``span("...")``/``obs_span("...")``) that is not in the registered
       vocabulary (``obs/names.py``) — a typo'd name silently creates a
       series no dashboard ever scrapes. f-string names lint as wildcard
       patterns against the registered templates; fully dynamic names (a
       variable) are out of scope.

Reachability: a function is *jit-reachable* when it is (a) decorated with
``jax.jit`` (directly or via ``partial``), (b) wrapped by a module-level
``NAME = jax.jit(fn, ...)``, (c) passed to a tracing wrapper
(``shard_map``, ``lax.scan``, ``vmap``, ``checkpoint``, ``cond``, ...), or
(d) called by name from a jit-reachable function (intra-module closure,
nested defs included). Host-side orchestration code is deliberately out of
scope — these rules only fire where tracing semantics apply.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .report import Finding

# -- rule vocabulary --------------------------------------------------------

#: parameters that are config-like (hashable python objects, not arrays):
#: passing one through jit without static_argnames is EG004
CONFIG_LIKE_PARAMS = frozenset({
    "cfg", "config", "mesh", "capacity", "codec", "codecs", "hop_codecs",
    "split", "split_cfg", "n_stages", "compute_dtype", "dtype", "temperature",
    "plan", "policy", "family",
})

#: callables that trace their function argument (make it jit-reachable)
TRACE_WRAPPERS = frozenset({
    "jit", "shard_map", "scan", "vmap", "pmap", "pjit", "checkpoint",
    "remat", "cond", "while_loop", "fori_loop", "switch", "grad",
    "value_and_grad", "custom_jvp", "custom_vjp", "eval_shape", "make_jaxpr",
})

#: host-I/O builtins / attribute paths flagged by EG002 inside traced code
HOST_IO_BUILTINS = frozenset({"print", "input", "open", "breakpoint"})
HOST_IO_MODULES = {
    "time": {"time", "monotonic", "perf_counter", "perf_counter_ns",
             "process_time", "sleep", "time_ns"},
    "subprocess": None,  # any attribute
    "os": {"system", "popen", "remove", "unlink", "makedirs", "mkdir"},
}

#: numpy namespaces whose math ops must not touch tracers (EG003); pure
#: metadata helpers are exempt below
NUMPY_ALIASES = frozenset({"np", "numpy", "onp"})
NUMPY_METADATA_FNS = frozenset({
    "dtype", "shape", "ndim", "issubdtype", "result_type", "promote_types",
    "finfo", "iinfo", "can_cast", "prod",  # np.prod(shape) is host math
})

#: container-mutating method names for EG006
MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "update", "add", "pop", "popitem",
    "remove", "clear", "setdefault", "discard",
})

#: EG007 vocabulary: registry factory methods, direct metric constructors,
#: and the span entry points whose first argument is THE name
METRIC_FACTORY_METHODS = frozenset({"counter", "gauge", "histogram"})
METRIC_CLASSES = frozenset({"Counter", "Gauge", "Histogram"})
SPAN_CALLEES = frozenset({"span", "obs_span"})

_DISABLE_RE = re.compile(r"#\s*graphlint:\s*disable(?:=([A-Z0-9, ]+))?")


# -- per-file analysis ------------------------------------------------------


class _FnInfo:
    """One function (or method / nested def) in the module."""

    __slots__ = ("node", "name", "params", "calls", "is_root", "static_names")

    def __init__(self, node: ast.AST, name: str) -> None:
        self.node = node
        self.name = name
        args = node.args
        self.params = [a.arg for a in
                       args.posonlyargs + args.args + args.kwonlyargs]
        self.calls: Set[str] = set()
        self.is_root = False
        self.static_names: Set[str] = set()


def _call_target_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute chains, 'jit' for Names, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jax_jit(node: ast.AST) -> bool:
    d = _dotted(node)
    return d in ("jax.jit", "jit", "pjit", "jax.pjit")


def _static_names_from_call(call: ast.Call) -> Optional[Set[str]]:
    """static_argnames from a jax.jit(...) call, or None when the value is
    not statically resolvable (a variable) — the check then stands down."""
    for kw in call.keywords:
        if kw.arg != "static_argnames":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)):
            names = set()
            for elt in v.elts:
                if not (isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str)):
                    return None
                names.add(elt.value)
            return names
        return None
    return set()


def _jit_wrapping_call(call: ast.Call) -> Optional[ast.Call]:
    """The jax.jit(...) call inside ``partial(jax.jit, ...)`` / plain jit."""
    if _is_jax_jit(call.func):
        return call
    if _dotted(call.func) in ("partial", "functools.partial") and call.args:
        if _is_jax_jit(call.args[0]):
            return call
    return None


class _ModuleIndex(ast.NodeVisitor):
    """Collect every function def, jit roots, and the by-name call graph."""

    def __init__(self) -> None:
        self.fns: List[_FnInfo] = []
        self.by_name: Dict[str, List[_FnInfo]] = {}
        self._stack: List[_FnInfo] = []
        #: Name -> static_argnames for `X = jax.jit(f, static_argnames=...)`
        self.wrapped_static: Dict[str, Optional[Set[str]]] = {}

    def _add(self, node) -> _FnInfo:
        info = _FnInfo(node, node.name)
        self.fns.append(info)
        self.by_name.setdefault(node.name, []).append(info)
        return info

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        info = self._add(node)
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if _is_jax_jit(target):
                info.is_root = True
                if isinstance(dec, ast.Call):
                    jc = _jit_wrapping_call(dec)
                    if jc is not None:
                        info.static_names = _static_names_from_call(jc) or set()
            elif isinstance(dec, ast.Call):
                jc = _jit_wrapping_call(dec)
                if jc is not None:
                    info.is_root = True
                    info.static_names = _static_names_from_call(jc) or set()
        self._stack.append(info)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        if self._stack:
            tgt = _call_target_name(node.func)
            if tgt:
                self._stack[-1].calls.add(tgt)
        # fn passed to a tracing wrapper becomes a root: shard_map(body, ...)
        fname = _call_target_name(node.func)
        if fname in TRACE_WRAPPERS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    for info in self.by_name.get(arg.id, []):
                        info.is_root = True
            jc = _jit_wrapping_call(node) if fname in ("jit", "pjit") else None
            if jc is not None and jc.args and isinstance(jc.args[0], ast.Name):
                inner = jc.args[0].id
                self.wrapped_static[inner] = _static_names_from_call(jc)
                for info in self.by_name.get(inner, []):
                    info.is_root = True
                    info.static_names |= (self.wrapped_static[inner] or set())
        self.generic_visit(node)


def _reachable(index: _ModuleIndex) -> Set[int]:
    """Closure of jit roots over the by-simple-name call graph."""
    reach: Set[int] = set()
    frontier = [f for f in index.fns if f.is_root]
    while frontier:
        f = frontier.pop()
        if id(f) in reach:
            continue
        reach.add(id(f))
        for callee_name in f.calls:
            for callee in index.by_name.get(callee_name, []):
                if id(callee) not in reach:
                    frontier.append(callee)
        # nested defs trace when called from the traced body
        for sub in ast.walk(f.node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not f.node:
                for info in index.by_name.get(sub.name, []):
                    if info.node is sub and id(info) not in reach:
                        frontier.append(info)
    return reach


# -- rule visitors ----------------------------------------------------------


def _test_looks_traced(test: ast.AST) -> bool:
    """EG001 trigger: the branch condition computes on arrays — a jnp/lax
    call, or .any()/.all() on something."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Call):
            d = _dotted(sub.func)
            root = d.split(".", 1)[0]
            if root in ("jnp", "lax") or d.startswith("jax.numpy") \
                    or d.startswith("jax.lax"):
                return True
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in ("any", "all") \
                    and not isinstance(sub.func.value, ast.Call):
                return True
    return False


def _host_io_call(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name) and f.id in HOST_IO_BUILTINS:
        return f.id
    d = _dotted(f)
    if "." in d:
        mod, attr = d.split(".", 1)
        allowed = HOST_IO_MODULES.get(mod)
        if mod in HOST_IO_MODULES and (allowed is None or attr in allowed):
            return d
        if d in ("sys.stdout.write", "sys.stderr.write"):
            return d
    return None


def _numpy_math_call(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id in NUMPY_ALIASES \
            and f.attr not in NUMPY_METADATA_FNS:
        return f"{f.value.id}.{f.attr}"
    return None


def _maybe_traced_names(info: _FnInfo) -> Set[str]:
    """Parameters plausibly holding tracers: everything except self/cls,
    declared-static names, and config-like python objects."""
    out = set()
    for p in info.params:
        if p in ("self", "cls"):
            continue
        if p in info.static_names or p in CONFIG_LIKE_PARAMS:
            continue
        out.add(p)
    return out


def _arg_touches(node: ast.AST, names: Set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in names:
            return True
        if isinstance(sub, ast.Attribute):
            # x.shape / x.dtype are host metadata, not array math
            if sub.attr in ("shape", "dtype", "ndim", "size"):
                return False
    return False


def _local_names(fn: ast.AST) -> Set[str]:
    """Names bound inside ``fn`` (params, assignments, loop targets,
    comprehensions, nested defs) — everything NOT captured."""
    names: Set[str] = set()
    args = fn.args
    for a in args.posonlyargs + args.args + args.kwonlyargs:
        names.add(a.arg)
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            names.add(sub.id)
        elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and sub is not fn:
            names.add(sub.name)
        elif isinstance(sub, ast.comprehension):
            for t in ast.walk(sub.target):
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _check_traced_fn(info: _FnInfo, path: str, emit) -> None:
    """EG001 / EG002 / EG003 / EG006 over one jit-reachable function."""
    traced = _maybe_traced_names(info)
    own_nested = [n for n in ast.walk(info.node)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and n is not info.node]
    nested_ids = {id(n) for n in own_nested}

    for node in ast.walk(info.node):
        # skip statements living inside nested defs for the branch rules —
        # the nested def is its own reachable unit
        if isinstance(node, (ast.If, ast.While)) \
                and _test_looks_traced(node.test):
            emit("EG001", node.lineno,
                 "Python branch on a traced value inside a jit-reachable "
                 "function; use lax.cond/jnp.where or hoist the check to "
                 "host code")
        elif isinstance(node, ast.Assert) and _test_looks_traced(node.test):
            emit("EG001", node.lineno,
                 "assert on a traced value inside a jit-reachable function; "
                 "it evaluates once at trace time — use "
                 "checkify or a host-side check")
        elif isinstance(node, ast.Call):
            io = _host_io_call(node)
            if io is not None:
                emit("EG002", node.lineno,
                     f"host I/O `{io}(...)` reachable from a jitted "
                     f"function; it runs at trace time, not per call — "
                     f"use jax.debug.print or move it to host code")
            npcall = _numpy_math_call(node)
            if npcall is not None and any(
                    _arg_touches(a, traced) for a in node.args):
                emit("EG003", node.lineno,
                     f"`{npcall}` applied to a likely-traced array; numpy "
                     f"forces a host transfer under trace — use the jnp "
                     f"equivalent")

    # EG006: nested defs mutating captured containers
    for nested in own_nested:
        locals_ = _local_names(nested)
        for node in ast.walk(nested):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in MUTATING_METHODS \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id not in locals_:
                emit("EG006", node.lineno,
                     f"`{node.func.value.id}.{node.func.attr}(...)` mutates "
                     f"a container captured from the enclosing scope inside "
                     f"traced code; the mutation happens once at trace time "
                     f"— return the value instead")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id not in locals_:
                        emit("EG006", t.lineno,
                             f"subscript assignment into captured "
                             f"`{t.value.id}` inside traced code; the write "
                             f"happens once at trace time")
    _ = nested_ids  # (kept for clarity of intent above)


def _check_jit_static(index: _ModuleIndex, tree: ast.Module, emit) -> None:
    """EG004 over every jax.jit site whose wrapped signature is resolvable."""

    def check(params: List[str], static: Optional[Set[str]], line: int,
              fname: str) -> None:
        if static is None:  # static_argnames not statically resolvable
            return
        missing = [p for p in params
                   if p in CONFIG_LIKE_PARAMS and p not in static]
        if missing:
            emit("EG004", line,
                 f"jax.jit on `{fname}` takes config-like parameter(s) "
                 f"{missing} not listed in static_argnames; each distinct "
                 f"config will fail to trace or silently retrace")

    for info in index.fns:
        node = info.node
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if _is_jax_jit(target):
                static = (_static_names_from_call(_jit_wrapping_call(dec))
                          if isinstance(dec, ast.Call) else set())
                check(info.params, static, node.lineno, info.name)
            elif isinstance(dec, ast.Call):
                jc = _jit_wrapping_call(dec)
                if jc is not None:
                    check(info.params, _static_names_from_call(jc),
                          node.lineno, info.name)

    for call in ast.walk(tree):
        if not isinstance(call, ast.Call):
            continue
        jc = _jit_wrapping_call(call)
        if jc is None or not jc.args or not isinstance(jc.args[0], ast.Name):
            continue
        inner = jc.args[0].id
        for info in index.by_name.get(inner, []):
            check(info.params, _static_names_from_call(jc), call.lineno,
                  inner)
            break  # one resolution is enough


def _is_host_numpy_expr(node: ast.AST) -> bool:
    """True when the expression is plain-numpy host math (np.prod(shape) in a
    checkpoint parser, say) — coercing THAT to int is not a device sync."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            d = _dotted(sub.func)
            if d.split(".", 1)[0] in NUMPY_ALIASES:
                return True
    return False


def _check_decode_loops(index: _ModuleIndex, path: str, emit) -> None:
    """EG005: per-token host syncs inside decode/generate loops."""
    in_serve = f"{os.sep}serve{os.sep}" in path
    for info in index.fns:
        name_l = info.name.lower()
        if not (in_serve or "generate" in name_l or "decode" in name_l):
            continue
        for loop in ast.walk(info.node):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "item" \
                        and not node.args:
                    emit("EG005", node.lineno,
                         "`.item()` inside a decode loop forces a device "
                         "sync per token; accumulate on device and sync "
                         "once after the loop")
                elif _dotted(f) in ("jax.device_get", "device_get"):
                    emit("EG005", node.lineno,
                         "`jax.device_get` inside a decode loop forces a "
                         "device sync per token; sync once after the loop")
                elif isinstance(f, ast.Name) and f.id in ("float", "int") \
                        and node.args \
                        and isinstance(node.args[0],
                                       (ast.Call, ast.Subscript)) \
                        and not _is_host_numpy_expr(node.args[0]):
                    emit("EG005", node.lineno,
                         f"`{f.id}(...)` of a computed value inside a "
                         f"decode loop is a per-token host sync; keep the "
                         f"value on device")


def _literal_name_pattern(node: ast.AST) -> Optional[str]:
    """The statically-known name of a metric/span call's first argument:
    a string constant verbatim, an f-string with its holes as ``*``, or
    None (dynamic — EG007 stands down)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for piece in node.values:
            if isinstance(piece, ast.Constant) and isinstance(piece.value,
                                                              str):
                parts.append(piece.value)
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _check_registered_names(tree: ast.Module, emit) -> None:
    """EG007 over every metric/span call site with a literal name."""
    try:
        from ..obs import names as obs_names
    except ImportError:  # pragma: no cover - standalone lint of one file
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        is_metric = (
            (isinstance(f, ast.Attribute)
             and f.attr in METRIC_FACTORY_METHODS)
            or (isinstance(f, ast.Name) and f.id in METRIC_CLASSES))
        is_span = ((isinstance(f, ast.Name) and f.id in SPAN_CALLEES)
                   or (isinstance(f, ast.Attribute)
                       and f.attr in SPAN_CALLEES))
        if not (is_metric or is_span):
            continue
        pattern = _literal_name_pattern(node.args[0])
        if pattern is None:
            continue  # dynamic name: not statically checkable
        if is_metric and not obs_names.metric_registered(pattern):
            emit("EG007", node.lineno,
                 f"metric name {pattern!r} is not in the registered "
                 f"vocabulary (obs/names.py); register it there or fix "
                 f"the typo — an unregistered series is never scraped")
        elif is_span and not obs_names.span_registered(pattern):
            emit("EG007", node.lineno,
                 f"span name {pattern!r} is not in the registered "
                 f"vocabulary (obs/names.py); register it there or fix "
                 f"the typo")


# -- driver -----------------------------------------------------------------


def _suppressed_lines(source: str) -> Dict[int, Optional[Set[str]]]:
    """line -> set of suppressed rule ids (None = all rules)."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _DISABLE_RE.search(line)
        if m:
            rules = m.group(1)
            out[i] = ({r.strip() for r in rules.split(",") if r.strip()}
                      if rules else None)
    return out


def lint_source(source: str, path: str) -> List[Finding]:
    """All AST findings for one module's source text."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(layer="ast", rule="EG000", where=path,
                        line=e.lineno or 0,
                        message=f"syntax error: {e.msg}")]

    index = _ModuleIndex()
    index.visit(tree)
    reach = _reachable(index)
    suppressed = _suppressed_lines(source)
    raw: List[Tuple[str, int, str]] = []

    def emit(rule: str, line: int, message: str) -> None:
        sup = suppressed.get(line)
        if line in suppressed and (sup is None or rule in sup):
            return
        raw.append((rule, line, message))

    for info in index.fns:
        if id(info) in reach:
            _check_traced_fn(info, path, emit)
    _check_jit_static(index, tree, emit)
    _check_decode_loops(index, path, emit)
    _check_registered_names(tree, emit)

    seen: Set[Tuple[str, int, str]] = set()
    findings = []
    for rule, line, message in raw:
        key = (rule, line, message)
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(layer="ast", rule=rule, where=path,
                                line=line, message=message))
    return findings


def lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        return lint_source(f.read(), path)


def iter_package_files(root: str) -> Iterator[str]:
    """Every .py under ``root``, skipping caches and the lint pkg itself
    (its fixture-shaped docstrings and rule tables would self-trip)."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git", ".jax_cache")]
        if os.path.basename(dirpath) == "lint":
            dirnames[:] = []
            continue
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_paths(paths) -> List[Finding]:
    out: List[Finding] = []
    for p in paths:
        out.extend(lint_file(p))
    return out


def collect_suppressions(paths) -> List[Tuple[str, int, Optional[Set[str]]]]:
    """Every ``# graphlint: disable=`` marker across ``paths``.

    Returns ``(path, line, rules)`` triples sorted by location; ``rules``
    is None for a bare ``disable`` (all rules) or the set of rule ids a
    comma-separated marker names. Feeds the CLI's ``--show-suppressed``
    audit so silenced lines stay reviewable instead of invisible.
    """
    out: List[Tuple[str, int, Optional[Set[str]]]] = []
    for p in paths:
        try:
            with open(p, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        for line, rules in sorted(_suppressed_lines(source).items()):
            out.append((p, line, rules))
    return sorted(out, key=lambda t: (t[0], t[1]))
