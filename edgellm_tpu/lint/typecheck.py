"""Optional layer: scoped ``mypy --strict`` over the typed public surfaces.

mypy is not a runtime dependency of the package — when it isn't importable
(the pinned runtime image ships without it) the layer records a skip note
instead of failing, and CI installs it so the gate actually runs there.
Scope and strictness flags live in ``pyproject.toml`` (``[tool.mypy]``);
this module only shells out and converts the output to findings.
"""
from __future__ import annotations

import importlib.util
import os
import re
import subprocess
import sys
from typing import List, Tuple

from .report import Finding

#: the modules whose public APIs carry full type hints (satellite: serve/,
#: parallel/split.py, codecs/faults.py) — strictness is scoped here so the
#: gate can be strict without annotating the whole package at once
TYPED_MODULES = (
    "edgellm_tpu/serve/decode.py",
    "edgellm_tpu/serve/recovery.py",
    "edgellm_tpu/parallel/split.py",
    "edgellm_tpu/codecs/faults.py",
    "edgellm_tpu/obs/metrics.py",
)

_LINE_RE = re.compile(r"^(?P<path>[^:]+):(?P<line>\d+):(?:\d+:)?\s*"
                      r"error:\s*(?P<msg>.*)$")


def mypy_available() -> bool:
    return importlib.util.find_spec("mypy") is not None


def run_typecheck(repo_root: str) -> Tuple[List[Finding], List[str]]:
    """(findings, skip notes). Runs ``python -m mypy`` on TYPED_MODULES with
    the pyproject config; absent mypy degrades to a recorded skip."""
    if not mypy_available():
        return [], ["typecheck: mypy not installed (pip install mypy to "
                    "enable; CI runs it)"]
    targets = [os.path.join(repo_root, m) for m in TYPED_MODULES]
    missing = [t for t in targets if not os.path.exists(t)]
    if missing:
        return [], [f"typecheck: missing targets {missing}"]
    proc = subprocess.run(
        [sys.executable, "-m", "mypy", "--no-error-summary", *targets],
        cwd=repo_root, capture_output=True, text=True, timeout=600)
    findings: List[Finding] = []
    for line in proc.stdout.splitlines():
        m = _LINE_RE.match(line.strip())
        if m:
            findings.append(Finding(
                layer="typecheck", rule="MYPY", where=m.group("path"),
                line=int(m.group("line")), message=m.group("msg")))
    if proc.returncode not in (0, 1):  # 1 = type errors; anything else broke
        findings.append(Finding(
            layer="typecheck", rule="MYPY", where="mypy", line=0,
            message=f"mypy crashed (exit {proc.returncode}): "
                    f"{proc.stderr.strip()[:500]}"))
    return findings, []
