"""Layer 4: latticelint — the AOT config-lattice verifier.

The other three graphlint layers check code (AST footguns, thread/lock
discipline, traced graph contracts). This layer checks the CONFIG lattice:
every ``configs/*.json`` must (1) pass run.py's params validator, (2)
AOT-lower its serve/eval entry points at tiny geometry — reusing the
``analysis.aot`` lower/compile/``memory_analysis()`` driver the window-batch
preflight ships on — with the measured peak held against the config's own
``"budget"`` block, (3) keep its KV/pool buffers donated in the lowered
executables (a dropped ``donate_argnums`` is a finding here, not a silent
2x HBM cost in production), and (4) have a ``configs/README.md`` table row.

On top of the shipped configs, the layer fuzzes the feature lattice
pairwise: every two-block combination of the serve/split feature set must
either validate AND lower, or be refused with the exact typed error
:data:`PAIR_ORACLE` pins — so a validator rule nobody tests ("refuses
spec + batching") cannot silently drift from what the builders actually
accept, in either direction.

Everything is static: ``.lower()`` traces, ``.compile()`` builds the
executable, ``memory_analysis()`` is a read — no model math executes and
no device memory is allocated (the same property that makes the preflight
safe on the tunneled TPU backend). The whole sweep shares one compile
cache keyed by plan geometry, so the 27 configs plus ~80 fuzzed combos
resolve to a couple dozen distinct compiles.

The machine-readable side product is ``capability_matrix.json``
(:data:`MATRIX_SCHEMA`): per-config features, lowered entry points with
argument/output/temp bytes, donation map, and refusal reasons — the input
ROADMAP's boundary auto-planner consumes instead of deployment-time
profiling (MCAP in PAPERS.md measures at runtime; this is a lint
artifact).

Findings use the shared :class:`~edgellm_tpu.lint.report.Finding` shape
(rules ``LL-*``) so they merge into the same JSON/SARIF reports as the
other layers.
"""
from __future__ import annotations

import itertools
import json
import os
import re
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from .report import Finding

#: schema tag stamped into capability_matrix.json — bump on layout change
MATRIX_SCHEMA = "edgellm.capability_matrix/v1"

RULE_VALIDATE = "LL-validate"   # configs/*.json fails run.py validation
RULE_README = "LL-readme"       # configs/README.md drift (row <-> file)
RULE_LOWER = "LL-lower"         # an entry point fails to lower/compile
RULE_BUDGET = "LL-budget"       # missing budget block or peak over budget
RULE_DONATE = "LL-donate"       # lowered executable dropped a donation
RULE_COMPAT = "LL-compat"       # pairwise fuzz: validator/builder drift

#: lint-scale geometry, identical to the graph layer's (entrypoints.py) so
#: the two layers compile against the same tiny model
BATCH, SEQ, CAPACITY = 1, 8, 16
SPEC_K = 4                      # speculative verify window
SWEEP_W, SWEEP_S, SWEEP_TAIL = 2, 32, 9  # eval-sweep window batch/len/tail
MAX_TINY_PAGES = 64             # pool-page cap at lint scale (note on clamp)

# ---------------------------------------------------------------------------
# pairwise feature-composition oracle
# ---------------------------------------------------------------------------

_MSG_SPEC_BATCH = (
    "speculative runs the one-stream spec loop; the batcher's ragged step "
    "verifies one token per slot — drop 'speculative' or 'batching'")
_MSG_FUSED_LINK = (
    "fused_hops: an active faults/fec/hedge link owns the hop protocol — "
    "fusion is refused at runtime; set fused_hops: 'off' or drop the link "
    "config")
_MSG_PIPE_SPEC = (
    "pipeline + speculative: the spec loop verifies one stream at a time "
    "(B == 1), leaving nothing to micro-batch — drop one of the two blocks")
_MSG_KVQ_PIPE = (
    "kv_at_rest + pipeline: quantized paged decode composes with the "
    "unpipelined split runtime only — drop 'pipeline' or use codec 'fp'")

#: every pair of feature blocks run.py REFUSES, with the exact message its
#: validator claims (``params.json: `` prefix stripped). Absent pairs must
#: validate AND lower. A validator edit that changes either direction
#: without updating this table is an LL-compat finding — that is the point.
PAIR_ORACLE: Dict[Tuple[str, str], str] = {
    ("batching", "speculative"): _MSG_SPEC_BATCH,
    ("cluster", "speculative"): _MSG_SPEC_BATCH,
    ("disagg", "speculative"): _MSG_SPEC_BATCH,
    ("gray", "speculative"): _MSG_SPEC_BATCH,
    ("kv_at_rest", "speculative"): _MSG_SPEC_BATCH,
    ("prefix_cache", "speculative"): _MSG_SPEC_BATCH,
    ("faults", "fused_hops"): _MSG_FUSED_LINK,
    ("fec", "fused_hops"): _MSG_FUSED_LINK,
    ("fused_hops", "hedge"): _MSG_FUSED_LINK,
    ("pipeline", "speculative"): _MSG_PIPE_SPEC,
    ("kv_at_rest", "pipeline"): _MSG_KVQ_PIPE,
}

#: minimal valid params block per feature, composed onto a bare serve config
FUZZ_BLOCKS: Dict[str, dict] = {
    "cuts": {"cuts": [2], "hop_codecs": ["int8_per_token"]},
    "faults": {"faults": {"drop_rate": 0.05, "seed": 0}},
    "fec": {"fec": {"enabled": True}},
    "hedge": {"hedge": {"routes": 2}},
    "fused_hops": {"fused_hops": "wire"},
    "pipeline": {"pipeline": {"num_microbatches": 2}},
    "speculative": {"speculative": {"k": 4}},
    "batching": {"batching": {"page_size": 8, "num_pages": 10,
                              "max_slots": 2, "pages_per_slot": 2}},
    "prefix_cache": {"prefix_cache": {"enabled": True}},
    "kv_at_rest": {"kv_at_rest": {"codec": "int8_per_channel"}},
    "cluster": {"cluster": {"num_replicas": 2}},
    "disagg": {"disagg": {"num_prefill_workers": 1}},
    "gray": {"gray": {"p95_multiple": 3.0}},
}

#: structural prerequisites a feature block cannot validate without —
#: pulled in silently when composing a combo (they are scaffolding, not
#: part of the pair under test)
FUZZ_DEPS: Dict[str, Tuple[str, ...]] = {
    "fec": ("faults",), "hedge": ("faults",),
    "pipeline": ("cuts",), "speculative": ("cuts",), "fused_hops": ("cuts",),
    "prefix_cache": ("batching",), "kv_at_rest": ("batching",),
    "cluster": ("batching",), "disagg": ("batching",),
    # dep expansion is one level deep, so gray names cluster's own
    # scaffolding explicitly
    "gray": ("cluster", "batching"),
}

FUZZ_BASE = {"experiment": "serve", "serving": {}}

#: params keys that count as composable features in the matrix
FEATURE_KEYS = (
    "cuts", "faults", "link_policy", "fec", "hedge", "link_health",
    "fused_hops", "pipeline", "speculative", "serving", "batching",
    "prefix_cache", "kv_at_rest", "cluster", "disagg", "gray", "deadline",
    "stage_failure", "recovery", "n_seq")


def compose_combo(names: Tuple[str, ...]) -> dict:
    """Minimal serve params exercising exactly the feature blocks in
    ``names`` (plus their :data:`FUZZ_DEPS` scaffolding)."""
    p = dict(FUZZ_BASE)
    want = list(names)
    for n in names:
        for d in FUZZ_DEPS.get(n, ()):
            if d not in want:
                want.append(d)
    for n in want:
        for k, v in FUZZ_BLOCKS[n].items():
            p.setdefault(k, v)
    return p


def default_configs_dir() -> Path:
    """``<repo>/configs`` next to the installed package."""
    return Path(__file__).resolve().parents[2] / "configs"


def config_features(p: dict) -> List[str]:
    """The feature blocks a params dict composes, for the matrix."""
    return sorted(k for k in FEATURE_KEYS if k in p)


def _validate(p: dict) -> Optional[str]:
    """run.py's params validation -> None (ok) or the refusal message with
    the ``params.json: `` prefix stripped."""
    from ..run import _validate_params_json

    try:
        _validate_params_json(p)
        return None
    except SystemExit as e:
        msg = str(e)
        return msg[len("params.json: "):] if msg.startswith(
            "params.json: ") else msg


# ---------------------------------------------------------------------------
# README parity
# ---------------------------------------------------------------------------

def readme_parity_findings(configs_dir: Path) -> List[Finding]:
    """Every ``configs/*.json`` needs a README table row and vice versa."""
    readme = configs_dir / "README.md"
    where = str(readme)
    if not readme.exists():
        return [Finding(layer="lattice", rule=RULE_README, where=where,
                        line=0, message="configs/README.md is missing")]
    text = readme.read_text(encoding="utf-8")
    # only the first column of a TABLE row registers a config — prose and
    # description cells may mention produced artifacts or upstream files
    # ("attention_head_weights.json", "params.json") that are not configs
    cells = [ln.split("|")[1] for ln in text.splitlines()
             if ln.lstrip().startswith("|") and ln.count("|") >= 2]
    mentioned = set(re.findall(r"`([\w.\-]+\.json)`", "\n".join(cells)))
    present = {f.name for f in configs_dir.glob("*.json")}
    findings = []
    for name in sorted(present - mentioned):
        findings.append(Finding(
            layer="lattice", rule=RULE_README, where=where, line=0,
            message=f"configs/{name} has no README table row"))
    for name in sorted(mentioned - present):
        findings.append(Finding(
            layer="lattice", rule=RULE_README, where=where, line=0,
            message=f"README mentions `{name}` but configs/{name} does not "
                    f"exist"))
    return findings


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------

def donation_findings(jitted_fn: Callable, args: tuple, required: int,
                      where: str) -> List[Finding]:
    """LL-donate findings for one lowered entry point: the executable must
    declare at least ``required`` donated input buffers (its KV cache /
    page-pool arrays). Unit-tested directly against a donation-stripped jit
    twin — the seeded missing-donation fixture."""
    from .contracts import donated_input_count

    donated = donated_input_count(jitted_fn, *args)
    if donated >= required:
        return []
    return [Finding(
        layer="lattice", rule=RULE_DONATE, where=where, line=0,
        message=f"lowered executable donates {donated} input buffer(s), "
                f"needs >= {required} (KV/pool buffers must alias their "
                f"outputs — a dropped donate_argnums doubles HBM)")]


# ---------------------------------------------------------------------------
# entry-point planning + AOT evaluation
# ---------------------------------------------------------------------------

class _Entry:
    """One lowerable entry point of a config's plan."""

    def __init__(self, name: str, key: str, build: Callable[[], dict]):
        self.name = name
        self.key = key      # compile-cache key (plan geometry signature)
        self.build = build  # -> {"cost": AOTCost|None, "donated", "required"}


class _Lattice:
    """Shared tiny-geometry world + compile cache for the whole sweep."""

    def __init__(self) -> None:
        import jax
        import jax.numpy as jnp

        from ..models import transformer
        from ..models.configs import tiny_config

        self.jax, self.jnp = jax, jnp
        self.cfg = tiny_config("qwen2", num_layers=4, hidden_size=32,
                               num_heads=4, vocab_size=128)
        self.params = transformer.init_params(self.cfg, jax.random.key(0))
        self.cache: Dict[str, dict] = {}

    # -- cache -------------------------------------------------------------

    def evaluate(self, entry: _Entry) -> dict:
        """Build (or fetch) one entry's AOT result. Errors are captured per
        entry — one broken config must not abort the sweep."""
        if entry.key in self.cache:
            return self.cache[entry.key]
        try:
            res = entry.build()
        except Exception as e:  # noqa: BLE001 — surfaced as LL-lower
            res = {"error": f"{type(e).__name__}: {e}"}
        self.cache[entry.key] = res
        return res

    def _result(self, lowered: Any, jitted: Optional[Callable],
                args: tuple, required: int) -> dict:
        from ..analysis.aot import lowered_cost
        from .contracts import donated_input_count

        cost = lowered_cost(lowered)
        donated = (donated_input_count(jitted, *args)
                   if jitted is not None and required > 0 else None)
        return {"cost": cost, "donated": donated, "required": required}

    # -- tiny-geometry mapping ----------------------------------------------

    def tiny_cuts(self, n: int) -> Tuple[int, ...]:
        """Map a config's cut count onto the 4-layer lint model (valid cut
        positions 0..2): the stage COUNT is what shapes the lowered graph."""
        return {1: (2,), 2: (1, 2)}.get(min(n, 3), (0, 1, 2))

    def tiny_layers(self, layers: Any) -> Tuple[int, ...]:
        """Clamp real-model layer indices into the tiny model's range."""
        out = sorted({min(max(int(l), 0), self.cfg.num_layers - 1)
                      for l in layers
                      if isinstance(l, int) and not isinstance(l, bool)})
        return tuple(out) or (1,)

    # -- local (single-device) serve entries ---------------------------------

    def entry_decode(self) -> List[_Entry]:
        jax, jnp = self.jax, self.jnp
        cfg, params = self.cfg, self.params

        def build_prefill():
            from ..serve import decode as serve_decode

            ids = jnp.zeros((BATCH, SEQ), jnp.int32)
            args = (cfg, params, ids, CAPACITY, None)
            return self._result(serve_decode._prefill_jit.lower(*args),
                                None, args, 0)

        def build_step():
            from ..models import transformer
            from ..serve import decode as serve_decode

            cache = transformer.init_cache(cfg, BATCH, CAPACITY)
            tok = jnp.zeros((BATCH,), jnp.int32)
            args = (cfg, params, cache, tok, jax.random.key(0), 0.0, None)
            return self._result(serve_decode._step_jit.lower(*args),
                                serve_decode._step_jit, args, 2)

        return [_Entry("decode.prefill", "local:prefill", build_prefill),
                _Entry("decode.step", "local:step", build_step)]

    def entry_prefill_suffix(self) -> List[_Entry]:
        jnp = self.jnp
        cfg, params = self.cfg, self.params

        def build():
            from ..models import transformer
            from ..serve import decode as serve_decode

            cache = transformer.init_cache(cfg, BATCH, CAPACITY)
            suffix = jnp.zeros((BATCH, 4), jnp.int32)
            args = (cfg, params, suffix, cache, None)
            return self._result(serve_decode._prefill_suffix_jit.lower(*args),
                                serve_decode._prefill_suffix_jit, args, 2)

        return [_Entry("decode.prefill_suffix", "local:prefill_suffix",
                       build)]

    def _pool_geom(self, p: dict, notes: List[str]) -> Tuple[int, int, int,
                                                             int, str]:
        """(max_slots, pages_per_slot, page_size, num_pages, kv_codec) at
        lint scale, derived the way run.py derives them — including the
        ``kv_at_rest.pool_bytes`` -> page-count conversion — then clamped."""
        b = p.get("batching", {})
        ms = int(b.get("max_slots", 4))
        pps = int(b.get("pages_per_slot", 8))
        pgs = int(b.get("page_size", 16))
        npg = int(b.get("num_pages", 65))
        kq = p.get("kv_at_rest", {})
        codec = kq.get("codec", "fp")
        if "pool_bytes" in kq:
            from ..models.paged_kv import num_pages_for_bytes

            npg = num_pages_for_bytes(self.cfg, kq["pool_bytes"], pgs,
                                      kv_codec=codec)
        if npg > MAX_TINY_PAGES:
            notes.append(f"pool clamped to {MAX_TINY_PAGES} pages at lint "
                         f"geometry (config asks for {npg})")
            npg = MAX_TINY_PAGES
        ms, pps, pgs = min(ms, 8), min(pps, 8), min(pgs, 16)
        return ms, pps, pgs, max(npg, 2), codec

    def entry_batched(self, p: dict, notes: List[str]) -> List[_Entry]:
        jax, jnp = self.jax, self.jnp
        cfg, params = self.cfg, self.params
        ms, pps, pgs, npg, codec = self._pool_geom(p, notes)
        key = f"batched:{ms}:{pps}:{pgs}:{npg}:{codec}"

        def build():
            from ..models import paged_kv
            from ..serve import batching

            tab = jnp.zeros((ms, pps), jnp.int32)
            lens = jnp.zeros((ms,), jnp.int32)
            toks = jnp.zeros((ms,), jnp.int32)
            keys = jnp.stack([jax.random.key(0)] * ms)
            steps = jnp.zeros((ms,), jnp.int32)
            temps = jnp.zeros((ms,), jnp.float32)
            if codec == "fp":
                pool = paged_kv.init_pool(cfg, npg, pgs)
                args = (cfg, params, pool.k, pool.v, tab, lens, toks, keys,
                        steps, temps, None)
                return self._result(batching._batched_step_jit.lower(*args),
                                    batching._batched_step_jit, args, 2)
            pool = paged_kv.init_quant_pool(cfg, npg, pgs, codec)
            args = (cfg, params, pool.k, pool.v, pool.k_scale, pool.v_scale,
                    tab, lens, toks, keys, steps, temps, codec, None)
            return self._result(batching._batched_step_quant_jit.lower(*args),
                                batching._batched_step_quant_jit, args, 4)

        name = "batched.step" if codec == "fp" else "batched.step_quant"
        return [_Entry(name, key, build)]

    # -- split runtime entries ----------------------------------------------

    def _split_notes(self, p: dict, notes: List[str]) -> None:
        """Plan-time notes about how a split config maps to lint geometry
        (the builders run behind the compile cache, so notes cannot come
        from them)."""
        from ..eval.split_eval import parse_hop_codec

        cuts = self.tiny_cuts(len(p["cuts"]))
        for spec in list(p["hop_codecs"])[:len(cuts)]:
            try:
                parse_hop_codec(spec, 1)
            except (ValueError, KeyError):
                notes.append(f"hop codec {spec!r} has no n_seq=1 form at "
                             f"lint geometry; lowered as int8_per_token")
        if p.get("n_seq", 1) > 1:
            notes.append(f"stage x seq ring (n_seq={p['n_seq']}) lowered as "
                         f"its n_seq=1 twin")
        if p.get("fused_hops") == "remote":
            notes.append("fused_hops 'remote' lowered as 'wire' (remote "
                         "fusion needs the TPU backend)")
        elif p.get("fused_hops") == "auto":
            notes.append("fused_hops 'auto' resolved off at lint time "
                         "(plan probes would execute)")

    def _split_runtime(self, p: dict):
        """Tiny-geometry :class:`SplitRuntime` mirroring the config's plan:
        same stage count, codec family, link ladder and µ-batch schedule."""
        from ..codecs.faults import FaultConfig, LinkPolicy
        from ..eval.split_eval import parse_hop_codec
        from ..parallel.split import (PipelineConfig, SplitConfig,
                                      SplitRuntime, make_stage_mesh)

        cuts = self.tiny_cuts(len(p["cuts"]))
        codecs = []
        for spec in list(p["hop_codecs"])[:len(cuts)]:
            try:
                codecs.append(parse_hop_codec(spec, 1))
            except (ValueError, KeyError):
                codecs.append("int8_per_token")
        while len(codecs) < len(cuts):
            codecs.append(codecs[-1] if codecs else "int8_per_token")
        lp = p.get("link_policy")
        n_micro = 0
        if "pipeline" in p:
            n_micro = min(int(p["pipeline"].get("num_microbatches", 2)), 2)
        fused = p.get("fused_hops", "off")
        saved = os.environ.get("EDGELLM_FUSED_HOP")
        try:
            if fused in ("wire", "remote"):
                os.environ["EDGELLM_FUSED_HOP"] = "wire"
            elif fused == "auto":
                os.environ["EDGELLM_FUSED_HOP"] = "0"
            rt = SplitRuntime(
                self.cfg,
                SplitConfig(cuts=cuts, hop_codecs=tuple(codecs)),
                make_stage_mesh(len(cuts) + 1),
                faults=(FaultConfig(**p["faults"])
                        if "faults" in p else None),
                policy=(LinkPolicy(**{**lp, "tiers": tuple(lp.get("tiers",
                                                                  ()))})
                        if lp else None),
                fec=(self._fec(p) if "fec" in p else None),
                hedge=(self._hedge(p) if "hedge" in p else None),
                pipeline=(PipelineConfig(num_microbatches=n_micro)
                          if n_micro else None))
        finally:
            if saved is None:
                os.environ.pop("EDGELLM_FUSED_HOP", None)
            else:
                os.environ["EDGELLM_FUSED_HOP"] = saved
        return rt, n_micro

    def _fec(self, p: dict):
        from ..codecs.fec import FECConfig

        return FECConfig(**p["fec"])

    def _hedge(self, p: dict):
        from ..codecs.fec import HedgeConfig

        return HedgeConfig(**p["hedge"])

    def _split_key(self, p: dict, what: str) -> str:
        sig = {k: p[k] for k in ("cuts", "hop_codecs", "faults",
                                 "link_policy", "fec", "hedge", "pipeline",
                                 "fused_hops", "n_seq", "batching",
                                 "kv_at_rest", "speculative") if k in p}
        return f"split:{what}:{json.dumps(sig, sort_keys=True)}"

    def entry_split_eval(self, p: dict, notes: List[str]) -> List[_Entry]:
        """experiment "split": the boundary-sweep forward."""
        jnp = self.jnp

        def build():
            rt, n_micro = self._split_runtime(p)
            bat = max(BATCH, n_micro)
            ids = jnp.zeros((bat, SEQ), jnp.int32)
            imps = jnp.zeros((len(rt.codecs), SEQ), jnp.float32)
            args = ((rt.place_params(self.params), ids, imps)
                    if rt._link is None else
                    (rt.place_params(self.params), ids, imps,
                     jnp.asarray(0, jnp.int32)))
            return self._result(rt._forward.lower(*args), None, args, 0)

        return [_Entry("split.forward", self._split_key(p, "forward"),
                       build)]

    def entry_split_decode(self, p: dict, notes: List[str],
                           speculative: bool) -> List[_Entry]:
        """Serve-path split pipeline: prefill + donated decode step, plus the
        k-token verify burst when the config speculates."""
        jnp = self.jnp
        entries = []

        def mk_state(rt, n_micro):
            bat = max(BATCH, n_micro)
            kv_shape = (rt.split.n_stages, rt.stage_size, bat, CAPACITY,
                        self.cfg.num_kv_heads, self.cfg.head_dim)
            placed = rt.place_params(self.params)
            return (placed, jnp.zeros(kv_shape, jnp.float32),
                    jnp.zeros(kv_shape, jnp.float32),
                    jnp.asarray(SEQ, jnp.int32),
                    jnp.zeros((bat,), jnp.int32))

        def build_prefill():
            rt, n_micro = self._split_runtime(p)
            prefill_fn, _ = rt._decode_fns(CAPACITY)
            ids = jnp.zeros((max(BATCH, n_micro), SEQ), jnp.int32)
            placed = rt.place_params(self.params)
            args = ((placed, ids) if rt._link is None
                    else (placed, ids, jnp.asarray(0, jnp.int32)))
            return self._result(prefill_fn.lower(*args), None, args, 0)

        def build_step():
            rt, n_micro = self._split_runtime(p)
            _, step_fn = rt._decode_fns(CAPACITY)
            args = mk_state(rt, n_micro)
            return self._result(step_fn.lower(*args), step_fn, args, 2)

        entries.append(_Entry("split.prefill",
                              self._split_key(p, "prefill"), build_prefill))
        entries.append(_Entry("split.decode_step",
                              self._split_key(p, "step"), build_step))
        if speculative:
            def build_verify():
                rt, n_micro = self._split_runtime(p)
                verify_fn = rt._verify_fns(CAPACITY, SPEC_K)
                placed, k_c, v_c, length, _ = mk_state(rt, 0)
                vtoks = jnp.zeros((BATCH, SPEC_K), jnp.int32)
                args = (placed, k_c, v_c, length, vtoks)
                return self._result(verify_fn.lower(*args), verify_fn,
                                    args, 2)

            entries.append(_Entry("split.verify_step",
                                  self._split_key(p, "verify"),
                                  build_verify))
        return entries

    def entry_split_paged(self, p: dict, notes: List[str]) -> List[_Entry]:
        """Serve-path split pipeline behind the continuous batcher: the
        ragged paged decode step over per-stage pools."""
        jnp = self.jnp
        ms, pps, pgs, npg, codec = self._pool_geom(p, notes)

        def build():
            rt, n_micro = self._split_runtime(p)
            pstep = rt._paged_decode_fns(npg, pgs, kv_codec=codec)
            pool = rt.init_paged_pool(npg, pgs, kv_codec=codec)
            placed = rt.place_params(self.params)
            tab = jnp.zeros((ms, pps), jnp.int32)
            lens = jnp.zeros((ms,), jnp.int32)
            toks = jnp.zeros((ms,), jnp.int32)
            if codec == "fp":
                args = (placed, pool["k"], pool["v"], tab, lens, toks)
                required = 2
            else:
                args = (placed, pool["k"], pool["v"], pool["k_scale"],
                        pool["v_scale"], tab, lens, toks)
                required = 4
            return self._result(pstep.lower(*args), pstep, args, required)

        return [_Entry("split.decode_step_paged",
                       self._split_key(p, f"paged:{ms}:{pps}:{pgs}:{npg}"),
                       build)]

    # -- eval-sweep entries ---------------------------------------------------

    def entry_sweep(self, p: dict, notes: List[str]) -> List[_Entry]:
        """Token/channel/initial/last_row sweeps: the stats forward + the
        ratio-vmapped suffix sweep — the same two executables the window-
        batch preflight sizes, at lint geometry."""
        jax, jnp = self.jax, self.jnp
        cfg = self.cfg
        layers = self.tiny_layers(p.get("layers_of_interest", (1,)))
        ratios = [r for r in p.get("ratios", []) or [0.25]]
        codec = "int4_token_select"
        key_base = f"sweep:{layers}:{len(ratios)}"

        def params_shape():
            from ..models import init_params

            return jax.eval_shape(
                lambda k: init_params(cfg, k, dtype=jnp.float32),
                jax.random.key(0))

        def build_stats():
            from ..eval.harness import DEDUP_ZERO_CODECS, _stats_forward

            ids = jax.ShapeDtypeStruct((SWEEP_W, SWEEP_S), jnp.int32)
            lowered = _stats_forward(
                cfg, layers,
                want_final=codec in DEDUP_ZERO_CODECS).lower(params_shape(),
                                                             ids)
            return self._result(lowered, None, (), 0)

        def build_suffix():
            from ..eval.harness import DEDUP_ZERO_CODECS, _suffix_sweep

            n_ratios = (max(1, sum(1 for r in ratios if float(r) != 0.0))
                        if codec in DEDUP_ZERO_CODECS
                        else max(1, len(ratios)))
            hidden = jax.ShapeDtypeStruct((SWEEP_W, SWEEP_S,
                                           cfg.hidden_size), jnp.float32)
            targets = jax.ShapeDtypeStruct((SWEEP_W, SWEEP_S), jnp.int32)
            imp = jax.ShapeDtypeStruct((SWEEP_W, SWEEP_S), jnp.float32)
            rr = jax.ShapeDtypeStruct((n_ratios,), jnp.float32)
            ks = jax.ShapeDtypeStruct((n_ratios,), jnp.int32)
            lowered = _suffix_sweep(cfg, min(layers), codec,
                                    SWEEP_TAIL).lower(
                params_shape(), hidden, targets, imp, rr, ks)
            return self._result(lowered, None, (), 0)

        return [_Entry("eval.stats_forward", key_base + ":stats",
                       build_stats),
                _Entry("eval.suffix_sweep", key_base + ":suffix",
                       build_suffix)]

    def entry_relevance(self) -> List[_Entry]:
        jax, jnp = self.jax, self.jnp
        cfg = self.cfg

        def build():
            from ..importance.relevance import _chunk_relevance
            from ..models import init_params

            ps = jax.eval_shape(
                lambda k: init_params(cfg, k, dtype=jnp.float32),
                jax.random.key(0))
            ids = jax.ShapeDtypeStruct((SWEEP_W, SWEEP_S), jnp.int32)
            return self._result(_chunk_relevance(cfg).lower(ps, ids),
                                None, (), 0)

        return [_Entry("eval.relevance", "relevance", build)]

    # -- the plan -------------------------------------------------------------

    def plan(self, p: dict) -> Tuple[List[_Entry], List[str]]:
        """Entry points a validated params dict would compile, at lint
        geometry. Mirrors run.py's serve/eval dispatch."""
        notes: List[str] = []
        exp = p.get("experiment", "")
        if exp == "serve":
            entries: List[_Entry] = []
            has_cuts, has_batch = "cuts" in p, "batching" in p
            spec = "speculative" in p and p["speculative"].get("enabled",
                                                               True)
            if "faults" in p and not has_cuts:
                notes.append("faults/link config without cuts: the local "
                             "decode path has no boundary link to fault")
            if has_cuts:
                self._split_notes(p, notes)
            if has_cuts and has_batch:
                entries += self.entry_split_paged(p, notes)
            elif has_cuts:
                entries += self.entry_split_decode(p, notes, spec)
            elif has_batch:
                entries += self.entry_batched(p, notes)
                entries += self.entry_decode()
                if "prefix_cache" in p:
                    entries += self.entry_prefill_suffix()
            else:
                entries += self.entry_decode()
            for host_side in ("cluster", "disagg", "gray"):
                if host_side in p:
                    notes.append(f"{host_side} is host-side orchestration: "
                                 f"its replicas/workers compile the entry "
                                 f"points above")
            return entries, notes
        if exp == "split":
            self._split_notes(p, notes)
            return self.entry_split_eval(p, notes), notes
        if exp == "relevance":
            return self.entry_relevance(), notes
        if exp == "distances":
            notes.append("distances sweeps compile per replan candidate; "
                         "no fixed entry point to pin at lint geometry")
            return [], notes
        # "", "initial", "last_row": the token/channel sweep family
        return self.entry_sweep(p, notes), notes


# ---------------------------------------------------------------------------
# the sweep
# ---------------------------------------------------------------------------

def _config_record(world: _Lattice, name: str, p: dict,
                   findings: List[Finding], where: str,
                   budget_required: bool) -> dict:
    """Verify one config (lower + budget + donation) and build its matrix
    row, appending findings in place."""
    record: Dict[str, Any] = {
        "features": config_features(p),
        "experiment": p.get("experiment", "") or "token_sweep",
        "valid": True, "refusal": None,
        "entrypoints": {}, "donation": {}, "notes": [],
        "peak_bytes": None, "budget_bytes": None,
    }
    entries, notes = world.plan(p)
    record["notes"] = notes
    peak = 0
    for entry in entries:
        res = world.evaluate(entry)
        if "error" in res:
            findings.append(Finding(
                layer="lattice", rule=RULE_LOWER, where=where, line=0,
                message=f"{entry.name}: failed to lower/compile at lint "
                        f"geometry: {res['error']}"))
            record["entrypoints"][entry.name] = {"error": res["error"]}
            continue
        cost = res["cost"]
        if cost is None:
            findings.append(Finding(
                layer="lattice", rule=RULE_LOWER, where=where, line=0,
                message=f"{entry.name}: compiler proved the program "
                        f"over-HBM at lint geometry"))
            record["entrypoints"][entry.name] = {"over_hbm": True}
            continue
        record["entrypoints"][entry.name] = cost.as_dict()
        peak = max(peak, cost.total)
        if res["required"]:
            record["donation"][entry.name] = {
                "donated": res["donated"], "required": res["required"]}
            if res["donated"] < res["required"]:
                findings.append(Finding(
                    layer="lattice", rule=RULE_DONATE, where=where, line=0,
                    message=f"{entry.name}: lowered executable donates "
                            f"{res['donated']} input buffer(s), needs >= "
                            f"{res['required']} (KV/pool buffers must alias "
                            f"their outputs)"))
    record["peak_bytes"] = peak if entries else None
    budget = p.get("budget")
    if budget is None:
        if budget_required:
            findings.append(Finding(
                layer="lattice", rule=RULE_BUDGET, where=where, line=0,
                message='missing "budget" block: every shipped config pins '
                        'its lint-geometry AOT peak ({"aot_peak_bytes": N})'))
    else:
        record["budget_bytes"] = budget["aot_peak_bytes"]
        if entries and peak > budget["aot_peak_bytes"]:
            findings.append(Finding(
                layer="lattice", rule=RULE_BUDGET, where=where, line=0,
                message=f"AOT peak {peak} bytes exceeds the config's budget "
                        f"of {budget['aot_peak_bytes']} bytes at lint "
                        f"geometry"))
    return record


def _pair_sweep(world: _Lattice, findings: List[Finding],
                pair_oracle: Dict[Tuple[str, str], str]) -> dict:
    """Pairwise feature-composition fuzz against :data:`PAIR_ORACLE`."""
    names = sorted(FUZZ_BLOCKS)
    combos = ([(n,) for n in names]
              + list(itertools.combinations(names, 2)))
    pairs: Dict[str, Any] = {}
    where = "lint/lattice.py:pairwise"
    for combo in combos:
        label = "+".join(combo)
        p = compose_combo(combo)
        got = _validate(p)
        want = pair_oracle.get(tuple(combo))
        pairs[label] = {"ok": got is None, "refusal": got}
        if got != want:
            if want is None:
                msg = (f"combo {label} should validate but run.py refuses "
                       f"it: {got}")
            elif got is None:
                msg = (f"combo {label} should be refused ({want!r}) but "
                       f"run.py accepts it")
            else:
                msg = (f"combo {label} is refused with a different message "
                       f"than the oracle pins: got {got!r}, want {want!r}")
            findings.append(Finding(layer="lattice", rule=RULE_COMPAT,
                                    where=where, line=0, message=msg))
            continue
        if got is not None:
            continue
        # accepted combos must also BUILD and LOWER — the builder half of
        # validator/builder drift (a validator that waves through what the
        # runtime constructors refuse)
        entries, _ = world.plan(p)
        for entry in entries:
            res = world.evaluate(entry)
            if "error" in res:
                findings.append(Finding(
                    layer="lattice", rule=RULE_COMPAT, where=where, line=0,
                    message=f"combo {label} validates but {entry.name} "
                            f"refuses to build/lower: {res['error']}"))
                pairs[label]["ok"] = False
                pairs[label]["build_error"] = res["error"]
                break
    return pairs


def run_lattice_checks(
        configs_dir: Optional[Path] = None,
        pair_oracle: Optional[Dict[Tuple[str, str], str]] = None,
        budget_required: bool = True,
        pairwise: bool = True,
) -> Tuple[List[Finding], List[str], List[str], dict]:
    """Run the whole lattice sweep.

    Returns ``(findings, checked, skipped, capability_matrix)`` — the first
    three in the shape the other layers use, the fourth the
    :data:`MATRIX_SCHEMA` document for ``capability_matrix.json``.

    ``configs_dir``/``pair_oracle``/``budget_required`` exist for the
    seeded-fixture tests; production callers take the defaults.
    """
    configs_dir = Path(configs_dir) if configs_dir else default_configs_dir()
    pair_oracle = PAIR_ORACLE if pair_oracle is None else pair_oracle
    findings: List[Finding] = []
    checked: List[str] = []
    skipped: List[str] = []

    world = _Lattice()
    if len(world.jax.devices()) < 4:
        skipped.append("lattice split-runtime entries: needs >= 4 devices "
                       "(set XLA_FLAGS=--xla_force_host_platform_device_"
                       "count=8)")

    readme = readme_parity_findings(configs_dir)
    findings.extend(readme)
    if not readme:
        checked.append("lattice.readme-parity")

    matrix: Dict[str, Any] = {
        "schema": MATRIX_SCHEMA,
        "tiny_geometry": {
            "model": "qwen2-tiny", "num_layers": world.cfg.num_layers,
            "hidden_size": world.cfg.hidden_size,
            "num_heads": world.cfg.num_heads,
            "num_kv_heads": world.cfg.num_kv_heads,
            "vocab_size": world.cfg.vocab_size,
            "batch": BATCH, "seq": SEQ, "capacity": CAPACITY,
            "sweep_window": [SWEEP_W, SWEEP_S],
        },
        "configs": {}, "pairs": {},
    }

    for path in sorted(configs_dir.glob("*.json")):
        where = str(path)
        try:
            p = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, ValueError) as e:
            findings.append(Finding(
                layer="lattice", rule=RULE_VALIDATE, where=where, line=0,
                message=f"unreadable config: {e}"))
            continue
        refusal = _validate(p)
        if refusal is not None:
            findings.append(Finding(
                layer="lattice", rule=RULE_VALIDATE, where=where, line=0,
                message=f"run.py refuses this config: {refusal}"))
            matrix["configs"][path.stem] = {
                "features": config_features(p), "valid": False,
                "refusal": refusal, "entrypoints": {}, "donation": {},
                "notes": [], "peak_bytes": None, "budget_bytes": None,
                "experiment": p.get("experiment", "") or "token_sweep",
            }
            continue
        before = len(findings)
        matrix["configs"][path.stem] = _config_record(
            world, path.stem, p, findings, where, budget_required)
        if len(findings) == before:
            checked.append(f"lattice.config:{path.stem}")

    if pairwise:
        before = len(findings)
        matrix["pairs"] = _pair_sweep(world, findings, pair_oracle)
        if len(findings) == before:
            checked.append("lattice.pairwise-compat")

    return findings, checked, skipped, matrix


def write_matrix(matrix: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(matrix, f, indent=1, sort_keys=True)
        f.write("\n")
