"""threadlint: lock-discipline static analysis for the host serve plane.

Third graphlint layer (beside the AST rules and the jaxpr contracts),
covering the code the tracer never sees: the threads.  The serve plane
(ServeFront.submit, ContinuousBatcher, the obs HTTP daemon scraping
/metrics mid-decode, the flight-recorder ring) rests on hand-placed
``threading.Lock`` sites; these rules make that discipline checkable.

Rule family EG1xx ("thread" layer):

- **EG101** — write to a guarded field outside the owning lock.  A class
  declares its contract with ``@guarded_by("_lock", fields=[...])``
  (``edgellm_tpu.utils.concurrency``), or is auto-discovered: any field
  a class writes under ``with self.<lock>`` is inferred guarded, and
  every *other* write to it must also hold the lock.  ``__init__`` and
  ``*_locked`` helper methods (caller-holds-lock convention) are exempt.
- **EG102** — inconsistent multi-lock acquisition order: acquiring two
  locks of the same shape (``self._lock`` then ``other._lock``) in
  source order deadlocks when two instances merge into each other
  concurrently (the ``Histogram.merge_from`` bug).  Also fires on
  re-acquiring a held non-reentrant lock, and on cross-class A→B / B→A
  order cycles seen anywhere in the linted set.  The fix —
  ``with acquire_in_order(a._lock, b._lock):`` — is recognised as one
  atomic, globally-ordered acquisition and never flagged.
- **EG103** — blocking call while holding a lock: jax dispatch,
  ``.block_until_ready()``, file I/O (``open``/``os.replace``/fsync),
  ``time.sleep``, subprocess/socket/HTTP work.  Critical sections on the
  scrape path must be O(memcpy); stage the slow work outside the lock
  (see ``FlightRecorder.dump``).
- **EG104** — ``contextvars`` token discipline: a token returned by
  ``cv.set(...)`` must be ``cv.reset(token)`` in the same frame that set
  it (the TraceContext bind/unbind invariant).  Storing the token on
  ``self``, discarding it, resetting a foreign token, or leaking it
  without a reset all fire.

Like the other layers, ``# graphlint: disable=EG10x`` on the offending
line suppresses a finding.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .ast_rules import _suppressed_lines, iter_package_files  # noqa: F401
from .report import Finding

LAYER = "thread"

#: spellings that create a lock object
_LOCK_FACTORIES = {"Lock", "RLock", "threading.Lock", "threading.RLock"}
#: ``with <helper>(lockA, lockB):`` — atomic globally-ordered acquisition
_ORDERED_HELPERS = {"acquire_in_order", "ordered_locks"}
#: method names exempt from EG101 (single-threaded construction, or the
#: ``*_locked`` caller-holds-lock convention)
_EXEMPT_METHODS = {"__init__", "__post_init__", "__new__", "__del__",
                   "__getstate__", "__setstate__", "__copy__", "__deepcopy__"}
#: container mutators: ``self.field.append(x)`` is a write to ``field``
_MUTATORS = {"append", "extend", "insert", "update", "add", "pop", "popitem",
             "remove", "clear", "setdefault", "discard", "appendleft",
             "popleft", "sort", "reverse"}
_HEAP_FNS = {"heappush", "heappop", "heappushpop", "heapreplace", "heapify"}

# EG103 vocabulary ----------------------------------------------------------
_BLOCKING_PREFIXES = ("jax.", "jnp.", "subprocess.", "requests.", "urllib.",
                      "socket.", "shutil.", "http.")
_BLOCKING_EXACT = {"time.sleep", "os.replace", "os.fsync", "os.makedirs",
                   "os.mkdir", "os.rename", "os.remove", "os.unlink",
                   "os.system", "os.popen"}
_BLOCKING_ATTRS = {"block_until_ready", "serve_forever", "urlopen"}


def _dotted(expr: ast.expr) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _lockish(attr: str) -> bool:
    return "lock" in attr.lower()


def _self_root(expr: ast.expr) -> Optional[str]:
    """Field name F for stores through ``self.F`` / ``self.F[...]`` /
    ``self.F.x`` — the attribute hanging directly off ``self``."""
    if isinstance(expr, ast.Attribute):
        if isinstance(expr.value, ast.Name) and expr.value.id == "self":
            return expr.attr
        return _self_root(expr.value)
    if isinstance(expr, ast.Subscript):
        return _self_root(expr.value)
    if isinstance(expr, (ast.Starred,)):
        return _self_root(expr.value)
    return None


def _written_fields(stmt: ast.stmt) -> List[Tuple[str, int]]:
    """(field, line) for every ``self.<field>`` write this statement makes."""
    out: List[Tuple[str, int]] = []

    def add_target(t: ast.expr) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                add_target(elt)
            return
        root = _self_root(t)
        if root is not None:
            out.append((root, t.lineno))

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            add_target(t)
    elif isinstance(stmt, ast.AugAssign):
        add_target(stmt.target)
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        add_target(stmt.target)
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            add_target(t)
    return out


def _call_writes(call: ast.Call) -> List[Tuple[str, int]]:
    """``self.<field>`` writes made by one call expression, wherever it
    sits (statement, assign value, condition): container mutators like
    ``self.q.append(x)`` and the in-place heapq free functions."""
    out: List[Tuple[str, int]] = []
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
        root = _self_root(f.value)
        if root is not None:
            out.append((root, call.lineno))
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    if name in _HEAP_FNS and call.args:
        root = _self_root(call.args[0])
        if root is not None:
            out.append((root, call.lineno))
    return out


# -- per-class contracts ----------------------------------------------------


@dataclass
class _ClassInfo:
    name: str
    node: ast.ClassDef
    lock_attrs: Set[str] = field(default_factory=set)
    declared_lock: Optional[str] = None
    declared_fields: Set[str] = field(default_factory=set)
    guarded: Set[str] = field(default_factory=set)


def _parse_guarded_by(dec: ast.expr) -> Optional[Tuple[str, Set[str]]]:
    if not isinstance(dec, ast.Call):
        return None
    name = dec.func.attr if isinstance(dec.func, ast.Attribute) else (
        dec.func.id if isinstance(dec.func, ast.Name) else None)
    if name != "guarded_by" or not dec.args:
        return None
    lock = dec.args[0]
    if not (isinstance(lock, ast.Constant) and isinstance(lock.value, str)):
        return None
    fields: Set[str] = set()
    for kw in dec.keywords:
        if kw.arg == "fields" and isinstance(kw.value, (ast.List, ast.Tuple)):
            for elt in kw.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    fields.add(elt.value)
    return lock.value, fields


def _collect_class(node: ast.ClassDef) -> _ClassInfo:
    info = _ClassInfo(name=node.name, node=node)
    for dec in node.decorator_list:
        parsed = _parse_guarded_by(dec)
        if parsed:
            info.declared_lock, info.declared_fields = parsed
    for sub in ast.walk(node):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
            dotted = _dotted(sub.value.func)
            if dotted in _LOCK_FACTORIES:
                for t in sub.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        info.lock_attrs.add(t.attr)
    if info.declared_lock:
        info.lock_attrs.add(info.declared_lock)
    return info


# -- lock-region walker -----------------------------------------------------


@dataclass
class _Acq:
    """One ``with``-item lock acquisition (possibly several locks when
    taken through an ordered helper)."""
    tokens: List[Tuple[str, str]]        # (owner class | "?" | "<module>", attr)
    expr_keys: List[str]                 # source spelling per token
    guards_self: bool
    ordered: bool
    line: int
    display: str


@dataclass
class _FileState:
    path: str
    emit: "object"                       # callable(rule, line, msg)
    edges: List[Tuple[Tuple[str, str], Tuple[str, str], int]] = \
        field(default_factory=list)


def _ann_name(ann: Optional[ast.expr]) -> Optional[str]:
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.strip('"\'')
    if isinstance(ann, ast.Attribute):
        return ann.attr
    return None


class _FnWalker:
    """Walks one function body tracking the held-lock stack; fires
    EG101 (check mode) / collects guarded fields (collect mode), EG102
    inline, and EG103."""

    def __init__(self, st: _FileState, cls: Optional[_ClassInfo],
                 fn: ast.AST, collect_only: bool,
                 discovered: Optional[Set[str]] = None) -> None:
        self.st = st
        self.cls = cls
        self.fn = fn
        self.collect_only = collect_only
        self.discovered = discovered if discovered is not None else set()
        self.stack: List[_Acq] = []
        self.param_types: Dict[str, str] = {}
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = fn.args
            for a in (list(args.posonlyargs) + list(args.args)
                      + list(args.kwonlyargs)):
                t = _ann_name(a.annotation)
                if t:
                    self.param_types[a.arg] = t

    # lock classification ---------------------------------------------------

    def _owner_of(self, base: ast.expr) -> str:
        if isinstance(base, ast.Name):
            if base.id == "self":
                return self.cls.name if self.cls else "?"
            return self.param_types.get(base.id, "?")
        return "?"

    def _classify(self, expr: ast.expr) -> Optional[_Acq]:
        # with acquire_in_order(a._lock, b._lock):
        if isinstance(expr, ast.Call):
            name = expr.func.attr if isinstance(expr.func, ast.Attribute) \
                else (expr.func.id if isinstance(expr.func, ast.Name) else None)
            if name in _ORDERED_HELPERS:
                tokens, keys, guards_self = [], [], False
                for arg in expr.args:
                    if isinstance(arg, ast.Attribute) and _lockish(arg.attr):
                        tokens.append((self._owner_of(arg.value), arg.attr))
                        keys.append(ast.unparse(arg))
                        if (isinstance(arg.value, ast.Name)
                                and arg.value.id == "self"):
                            guards_self = True
                return _Acq(tokens=tokens, expr_keys=keys,
                            guards_self=guards_self, ordered=True,
                            line=expr.lineno, display=ast.unparse(expr))
            return None
        # with self._lock:   /   with other._lock:
        if isinstance(expr, ast.Attribute):
            known = self.cls.lock_attrs if self.cls else set()
            if expr.attr in known or _lockish(expr.attr):
                owner = self._owner_of(expr.value)
                guards_self = (isinstance(expr.value, ast.Name)
                               and expr.value.id == "self")
                return _Acq(tokens=[(owner, expr.attr)],
                            expr_keys=[ast.unparse(expr)],
                            guards_self=guards_self, ordered=False,
                            line=expr.lineno, display=ast.unparse(expr))
        # with _lock:   (module-level lock)
        if isinstance(expr, ast.Name) and _lockish(expr.id):
            return _Acq(tokens=[("<module>", expr.id)], expr_keys=[expr.id],
                        guards_self=False, ordered=False,
                        line=expr.lineno, display=expr.id)
        return None

    def _check_order(self, acq: _Acq) -> None:
        """EG102 inline + record cross-class edges."""
        for held in self.stack:
            for (h_owner, h_attr), h_key in zip(held.tokens, held.expr_keys):
                for (n_owner, n_attr), n_key in zip(acq.tokens, acq.expr_keys):
                    if not acq.ordered and n_key == h_key:
                        self.st.emit(
                            "EG102", acq.line,
                            f"re-acquiring `{n_key}` while already holding it "
                            f"(line {held.line}); threading.Lock is not "
                            f"reentrant — this self-deadlocks")
                        continue
                    same_shape = (n_attr == h_attr
                                  and (n_owner == h_owner
                                       or "?" in (n_owner, h_owner)))
                    if not acq.ordered and same_shape:
                        self.st.emit(
                            "EG102", acq.line,
                            f"acquiring `{n_key}` while holding `{h_key}` "
                            f"(line {held.line}): two instances of the same "
                            f"lock taken in source order deadlock when the "
                            f"roles reverse concurrently; use "
                            f"acquire_in_order({h_key}, {n_key})")
                        continue
                    if ("?" not in (n_owner, h_owner)
                            and (h_owner, h_attr) != (n_owner, n_attr)):
                        self.st.edges.append(
                            ((h_owner, h_attr), (n_owner, n_attr), acq.line))

    # traversal -------------------------------------------------------------

    def _held_self(self) -> bool:
        return any(a.guards_self for a in self.stack)

    def _innermost(self) -> str:
        return self.stack[-1].display if self.stack else "?"

    def _check_write(self, fieldname: str, line: int) -> None:
        if self.cls is None:
            return
        if fieldname in self.cls.lock_attrs:
            return
        if self.collect_only:
            if self._held_self():
                self.discovered.add(fieldname)
            return
        if fieldname in self.cls.guarded and not self._held_self():
            lock = self.cls.declared_lock or next(
                iter(sorted(self.cls.lock_attrs)), "_lock")
            self.st.emit(
                "EG101", line,
                f"write to guarded field `{self.cls.name}.{fieldname}` "
                f"outside `with self.{lock}`; every other writer holds the "
                f"lock, so this write can race or be torn")

    def _check_blocking(self, call: ast.Call) -> None:
        if self.collect_only or not self.stack:
            return
        dotted = _dotted(call.func)
        label: Optional[str] = None
        if dotted is not None:
            if dotted in _BLOCKING_EXACT:
                label = dotted
            elif dotted.startswith(_BLOCKING_PREFIXES):
                label = dotted
            elif dotted == "open":
                label = "open"
        if label is None and isinstance(call.func, ast.Attribute) \
                and call.func.attr in _BLOCKING_ATTRS:
            label = f".{call.func.attr}"
        if label is not None:
            self.st.emit(
                "EG103", call.lineno,
                f"blocking call `{label}(...)` while holding "
                f"`{self._innermost()}`; critical sections on the serve/"
                f"scrape path must stay O(memcpy) — stage the slow work "
                f"outside the lock")

    def walk(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        for f, line in _written_fields(stmt):
            self._check_write(f, line)
        for call in self._calls_in(stmt):
            self._check_blocking(call)
            for f, line in _call_writes(call):
                self._check_write(f, line)

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in stmt.items:
                acq = self._classify(item.context_expr)
                if acq is not None:
                    self._check_order(acq)
                    self.stack.append(acq)
                    pushed += 1
            self.walk(stmt.body)
            for _ in range(pushed):
                self.stack.pop()
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: analyzed with the lock state at its def site
            self.walk(stmt.body)
            return
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, ast.stmt):
                self._stmt(sub)
            elif isinstance(sub, ast.ExceptHandler):
                self.walk(sub.body)

    def _calls_in(self, stmt: ast.stmt) -> Iterable[ast.Call]:
        """Calls made directly by this statement (not inside nested defs
        or nested ``with`` bodies, which get their own visit)."""
        skip_bodies = isinstance(stmt, (ast.With, ast.AsyncWith, ast.If,
                                        ast.For, ast.AsyncFor, ast.While,
                                        ast.Try, ast.FunctionDef,
                                        ast.AsyncFunctionDef))
        roots: List[ast.AST] = []
        if skip_bodies:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                roots.extend(i.context_expr for i in stmt.items)
            elif isinstance(stmt, (ast.If, ast.While)):
                roots.append(stmt.test)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                roots.append(stmt.iter)
            # Try: nothing at statement level
        else:
            roots.append(stmt)
        for root in roots:
            for node in ast.walk(root):
                if isinstance(node, ast.Call):
                    yield node


# -- EG104: contextvars token discipline ------------------------------------


def _contextvar_names(tree: ast.Module) -> Set[str]:
    names: Set[str] = set()
    for stmt in tree.body:
        value = None
        targets: List[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            value, targets = stmt.value, stmt.targets
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            value, targets = stmt.value, [stmt.target]
        if not isinstance(value, ast.Call):
            continue
        dotted = _dotted(value.func)
        if dotted in ("contextvars.ContextVar", "ContextVar"):
            for t in targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _frame_stmts(fn: ast.AST) -> Iterable[ast.stmt]:
    """Statements of this function frame, not descending into nested
    function/class frames (a token crossing frames is exactly the bug)."""
    stack: List[ast.stmt] = list(getattr(fn, "body", []))
    while stack:
        stmt = stack.pop()
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                stack.append(child)
            elif isinstance(child, ast.ExceptHandler):
                stack.extend(child.body)


def _cv_call(node: ast.expr, cv_names: Set[str],
             method: str) -> Optional[ast.Call]:
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == method):
        base = node.func.value
        base_name = base.attr if isinstance(base, ast.Attribute) else (
            base.id if isinstance(base, ast.Name) else None)
        if base_name in cv_names:
            return node
    return None


def _check_contextvars(tree: ast.Module, emit) -> None:
    cv_names = _contextvar_names(tree)
    if not cv_names:
        return
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        tokens: Dict[str, int] = {}          # local token name -> set line
        handled: Set[int] = set()            # id() of set-calls accounted for
        resets_of: Set[str] = set()
        stmts = list(_frame_stmts(fn))
        for stmt in stmts:
            if isinstance(stmt, ast.Assign):
                call = _cv_call(stmt.value, cv_names, "set")
                if call is not None:
                    handled.add(id(call))
                    if (len(stmt.targets) == 1
                            and isinstance(stmt.targets[0], ast.Name)):
                        tokens[stmt.targets[0].id] = stmt.lineno
                    else:
                        emit("EG104", stmt.lineno,
                             "contextvar token stored outside this frame "
                             "(e.g. on self); tokens must be reset by the "
                             "frame that called .set() — a foreign-frame "
                             "reset raises or silently corrupts the context")
            elif isinstance(stmt, ast.Expr):
                call = _cv_call(stmt.value, cv_names, "set")
                if call is not None:
                    handled.add(id(call))
                    emit("EG104", stmt.lineno,
                         "contextvar .set() token discarded; without the "
                         "token this frame can never .reset(), leaking the "
                         "binding into unrelated requests on this thread")
        for stmt in stmts:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.expr):
                    continue
                call = _cv_call(sub, cv_names, "set")
                if call is not None and id(call) not in handled:
                    handled.add(id(call))
                    emit("EG104", call.lineno,
                         "contextvar .set() in an expression position; bind "
                         "the token to a local and reset it in a finally")
                rcall = _cv_call(sub, cv_names, "reset")
                if rcall is not None:
                    arg = rcall.args[0] if rcall.args else None
                    if isinstance(arg, ast.Name) and arg.id in tokens:
                        resets_of.add(arg.id)
                    else:
                        emit("EG104", rcall.lineno,
                             "contextvar .reset() with a token not created "
                             "in this frame; set and reset must pair within "
                             "one frame (the TraceContext bind/unbind "
                             "invariant)")
        for name, line in tokens.items():
            if name not in resets_of:
                emit("EG104", line,
                     f"contextvar token `{name}` is never reset in the frame "
                     f"that set it; wrap the body in try/finally and call "
                     f".reset({name})")


# -- driver -----------------------------------------------------------------


def _analyze(tree: ast.Module, st: _FileState) -> None:
    for cls_node in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        info = _collect_class(cls_node)
        if not info.lock_attrs:
            continue
        methods = [m for m in cls_node.body
                   if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))]
        discovered: Set[str] = set()
        for m in methods:
            w = _FnWalker(st, info, m, collect_only=True,
                          discovered=discovered)
            w.walk(m.body)
        info.guarded = discovered | info.declared_fields
        for m in methods:
            if m.name in _EXEMPT_METHODS or m.name.endswith("_locked"):
                # still track EG102/EG103 inside, but skip EG101 via
                # collect_only=False with guarded cleared for this method
                saved = info.guarded
                info.guarded = set()
                w = _FnWalker(st, info, m, collect_only=False)
                w.walk(m.body)
                info.guarded = saved
                continue
            w = _FnWalker(st, info, m, collect_only=False)
            w.walk(m.body)
    # module-level functions: EG102/EG103 against module locks
    for fn in tree.body:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            w = _FnWalker(st, None, fn, collect_only=False)
            w.walk(fn.body)
    _check_contextvars(tree, st.emit)


def _cycle_findings(
        edges: List[Tuple[Tuple[str, str], Tuple[str, str], str, int]],
) -> List[Finding]:
    """Global pass: A→B at one site and B→A at another is an order cycle."""
    by_pair: Dict[Tuple[Tuple[str, str], Tuple[str, str]],
                  List[Tuple[str, int]]] = {}
    for a, b, path, line in edges:
        by_pair.setdefault((a, b), []).append((path, line))
    out: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for (a, b), sites in by_pair.items():
        rev = by_pair.get((b, a))
        if not rev or a >= b:  # report each unordered pair once, from a<b
            continue
        for path, line in sites + rev:
            if (path, line) in seen:
                continue
            seen.add((path, line))
            out.append(Finding(
                layer=LAYER, rule="EG102", where=path, line=line,
                message=(f"lock-order cycle: `{a[0]}.{a[1]}` -> "
                         f"`{b[0]}.{b[1]}` here, but the reverse order is "
                         f"taken elsewhere in the package; pick one global "
                         f"order or use acquire_in_order")))
    return out


def lint_source(source: str, path: str) -> List[Finding]:
    """All thread-layer findings for one module (including the local
    lock-order cycle pass)."""
    findings, edges = _lint_one(source, path)
    findings.extend(_cycle_findings(edges))
    return sort_unique(findings)


def _lint_one(
        source: str, path: str,
) -> Tuple[List[Finding],
           List[Tuple[Tuple[str, str], Tuple[str, str], str, int]]]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return ([Finding(layer=LAYER, rule="EG000", where=path,
                         line=e.lineno or 0,
                         message=f"syntax error: {e.msg}")], [])
    suppressed = _suppressed_lines(source)
    raw: List[Finding] = []

    def emit(rule: str, line: int, message: str) -> None:
        sup = suppressed.get(line, ...)
        if sup is None or (sup is not ... and rule in sup):
            return
        raw.append(Finding(layer=LAYER, rule=rule, where=path, line=line,
                           message=message))

    st = _FileState(path=path, emit=emit)
    _analyze(tree, st)
    edges = [(a, b, path, line) for a, b, line in st.edges
             if not _edge_suppressed(suppressed, line)]
    return raw, edges


def _edge_suppressed(suppressed: Dict[int, Optional[Set[str]]],
                     line: int) -> bool:
    if line not in suppressed:
        return False
    sup = suppressed[line]
    return sup is None or "EG102" in sup


def sort_unique(findings: List[Finding]) -> List[Finding]:
    seen: Set[Tuple[str, str, int, str]] = set()
    out: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.where, f.line, f.rule)):
        key = (f.rule, f.where, f.line, f.message)
        if key in seen:
            continue
        seen.add(key)
        out.append(f)
    return out


def lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        return lint_source(f.read(), path)


def lint_files(paths: Iterable[str]) -> List[Finding]:
    """Thread-layer findings across ``paths``, with the lock-order cycle
    pass run over the whole set (cross-file A→B / B→A is visible here)."""
    findings: List[Finding] = []
    edges: List[Tuple[Tuple[str, str], Tuple[str, str], str, int]] = []
    for p in paths:
        try:
            with open(p, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        fs, es = _lint_one(source, p)
        findings.extend(fs)
        edges.extend(es)
    findings.extend(_cycle_findings(edges))
    return sort_unique(findings)


def lint_package(root: Optional[str] = None) -> List[Finding]:
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return lint_files(iter_package_files(root))
