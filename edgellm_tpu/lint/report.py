"""Shared finding type + report serialization for both lint layers.

Every rule — AST, graph contract, or typecheck — reports the same flat
:class:`Finding` record, so the CLI can merge the layers into one JSON
report and one exit code, and CI can archive a single artifact.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Optional


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint violation.

    layer: "ast" | "graph" | "typecheck".
    rule: stable rule id (``EG00x`` for AST rules, ``GC-*`` for graph
        contracts, ``MYPY`` for the typechecker).
    where: file path (AST/typecheck) or contract name (graph layer).
    line: 1-based source line, or 0 when the finding has no source anchor
        (graph contracts point at traced jaxprs, not lines).
    message: human-readable description of the violation.
    """

    layer: str
    rule: str
    where: str
    line: int
    message: str

    def format(self) -> str:
        anchor = f"{self.where}:{self.line}" if self.line else self.where
        return f"[{self.rule}] {anchor}: {self.message}"


@dataclasses.dataclass
class LintReport:
    """The merged result of every layer the CLI ran."""

    findings: list  # list[Finding]
    checked_contracts: list  # contract names that were verified clean
    skipped: list  # layer-level skips, e.g. "typecheck: mypy not installed"

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> str:
        return json.dumps(
            {
                "ok": self.ok,
                "findings": [dataclasses.asdict(f) for f in self.findings],
                "checked_contracts": list(self.checked_contracts),
                "skipped": list(self.skipped),
            },
            indent=2, sort_keys=True)

    def summary(self) -> str:
        lines = [f.format() for f in self.findings]
        lines.append(
            f"graphlint: {len(self.findings)} violation(s), "
            f"{len(self.checked_contracts)} graph contract(s) clean"
            + (f", skipped: {'; '.join(self.skipped)}" if self.skipped else ""))
        return "\n".join(lines)


#: SARIF severity per layer — everything graphlint emits is a build-breaker
_SARIF_LEVEL = "error"


def to_sarif(report: "LintReport") -> str:
    """Render the merged report as SARIF 2.1.0 (one run, driver=graphlint).

    Covers every layer: AST rules, thread rules, graph contracts and the
    scoped typechecker all share the flat :class:`Finding` shape, so each
    becomes one SARIF ``result``. Findings with ``line == 0`` (graph
    contracts anchor to traced jaxprs, not source lines) omit the
    ``region`` block but keep the artifact URI.
    """
    rule_ids = sorted({f.rule for f in report.findings})
    results = []
    for f in report.findings:
        loc: dict = {
            "physicalLocation": {
                "artifactLocation": {"uri": f.where},
            },
        }
        if f.line:
            loc["physicalLocation"]["region"] = {"startLine": f.line}
        results.append({
            "ruleId": f.rule,
            "level": _SARIF_LEVEL,
            "message": {"text": f.message},
            "locations": [loc],
            "properties": {"layer": f.layer},
        })
    doc = {
        "$schema": ("https://json.schemastore.org/sarif-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "graphlint",
                    "rules": [{"id": rid} for rid in rule_ids],
                },
            },
            "results": results,
            "properties": {
                "checked_contracts": list(report.checked_contracts),
                "skipped": list(report.skipped),
            },
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True)


def sort_findings(findings: list) -> list:
    return sorted(findings, key=lambda f: (f.layer, f.where, f.line, f.rule))


def merge(*finding_lists: list) -> list:
    out: list = []
    for fl in finding_lists:
        out.extend(fl)
    return sort_findings(out)


def load_report(path: str) -> Optional[dict]:
    """Parse a previously-written JSON report (CI tooling convenience)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
