"""Layer 2 drivers: trace the REAL entry points and verify their declared
graph contracts.

Each driver builds test-scale example inputs (tiny qwen2 config, 2-stage
mesh on the spoofed CPU device grid), abstract-evals the production
function with ``jax.make_jaxpr``/``.lower()``, and hands the traced graph
to :mod:`edgellm_tpu.lint.contracts`. Nothing here executes model math —
tracing and lowering only, so the whole layer runs in seconds under
``JAX_PLATFORMS=cpu``.

The *declarations* live on the production code (``@graph_contract`` in
``models/transformer.py``, ``serve/decode.py``, ``parallel/split.py``,
``codecs/faults.py``); this module only knows how to build inputs and the
measured ``ctx`` facts (payload leaf counts, hop byte totals from the codec
registry) that parameterize them.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np

from .contracts import GRAPH_CONTRACTS, check_identity, check_traced
from .report import Finding

#: example-input scale: big enough to exercise GQA + a real cut, small
#: enough that tracing every contract stays in seconds
BATCH, SEQ, CAPACITY = 1, 8, 16


def _missing(name: str) -> Finding:
    return Finding(layer="graph", rule="GC-missing", where=name, line=0,
                   message="entry point has no @graph_contract registration "
                           "(decorator removed or module not imported)")


def _driver_error(name: str, exc: Exception) -> Finding:
    return Finding(layer="graph", rule="GC-driver", where=name, line=0,
                   message=f"contract driver failed: "
                           f"{type(exc).__name__}: {exc}")


def _payload_info(codec, shape) -> Tuple[int, set, int]:
    """(leaf count, dtype names, total bytes) of one hop's wire payload,
    measured abstractly from the codec itself."""
    import jax
    import jax.numpy as jnp

    spec = jax.eval_shape(codec.encode, jax.ShapeDtypeStruct(shape,
                                                             jnp.float32))
    leaves = jax.tree_util.tree_leaves(spec)
    nbytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize for a in leaves)
    return len(leaves), {a.dtype.name for a in leaves}, nbytes


def run_graph_checks() -> Tuple[List[Finding], List[str], List[str]]:
    """Run every registered graph contract against real traced graphs.

    Returns (findings, names of contracts verified clean, skip notes)."""
    import jax
    import jax.numpy as jnp

    # importing the production modules is what populates GRAPH_CONTRACTS
    from ..codecs.faults import COUNTER_KEYS, FaultConfig, LinkPolicy
    from ..models import transformer
    from ..models.configs import tiny_config
    from ..parallel.split import (PipelineConfig, SplitConfig, SplitRuntime,
                                  make_stage_mesh)
    from ..serve import decode as serve_decode
    from ..serve import recovery

    findings: List[Finding] = []
    checked: List[str] = []
    skipped: List[str] = []

    def run_one(name: str, traced: Callable, args: tuple,
                ctx: Optional[dict] = None, lowerable: Optional[Callable] = None,
                lower_args: Optional[tuple] = None) -> None:
        contract = GRAPH_CONTRACTS.get(name)
        if contract is None:
            findings.append(_missing(name))
            return
        try:
            found = check_traced(contract, traced, args, ctx,
                                 lowerable=lowerable, lower_args=lower_args)
        except Exception as e:  # noqa: BLE001 — a crashed driver must be loud
            findings.append(_driver_error(name, e))
            return
        if found:
            findings.extend(found)
        else:
            checked.append(name)

    cfg = tiny_config("qwen2", num_layers=4, hidden_size=32, num_heads=4,
                      vocab_size=128)
    params = transformer.init_params(cfg, jax.random.key(0))
    ids = jnp.zeros((BATCH, SEQ), jnp.int32)
    tok = jnp.zeros((BATCH,), jnp.int32)

    # ---- transformer core: prefill / decode_step (collective-free, no f64,
    # ---- no host callbacks) --------------------------------------------
    run_one("transformer.prefill",
            lambda p, i: transformer.prefill(cfg, p, i, CAPACITY),
            (params, ids))
    cache = transformer.init_cache(cfg, BATCH, CAPACITY)
    run_one("transformer.decode_step",
            lambda p, c, t: transformer.decode_step(cfg, p, c, t),
            (params, cache, tok))

    # ---- serve layer: the jitted generate() internals; the step contract
    # ---- also requires the KV cache to be donated in the lowered
    # ---- executable -----------------------------------------------------
    key = jax.random.key(0)
    run_one("decode.prefill",
            lambda p, i: serve_decode._prefill_impl(cfg, p, i, CAPACITY, None),
            (params, ids))
    run_one("decode.step",
            lambda p, c, t, k: serve_decode._step_impl(cfg, p, c, t, k, 0.0,
                                                       None),
            (params, cache, tok, key),
            ctx={"donate_min": 2},
            lowerable=serve_decode._step_jit,
            lower_args=(cfg, params, cache, tok, key, 0.0, None))

    # recovery must add NOTHING to the decode graph: the LocalRuntime step is
    # the raw transformer decode_step, bit-identical
    ident = check_identity(
        "decode.recovery-identity",
        lambda p, c, t: recovery._local_step.__wrapped__(cfg, p, c, t, None),
        (params, cache, tok),
        lambda p, c, t: transformer.decode_step(cfg, p, c, t,
                                                compute_dtype=None),
        (params, cache, tok),
        what="LocalRuntime (recovery failover) decode graph")
    (findings.extend(ident) if ident
     else checked.append("decode.recovery-identity"))

    # the serving front must add NOTHING either: a default-config ServeFront
    # routes admitted requests through the direct generate() loop, so the
    # decode step it traces — with the front's own bucketed capacity and
    # static args — is byte-identical to calling generate directly
    from ..serve.frontend import ServeFront

    front = ServeFront(cfg, params)
    spec = front.step_trace_spec(BATCH, SEQ, max_new_tokens=CAPACITY - SEQ)
    if spec["uses_survivable_loop"]:
        findings.append(Finding(
            layer="graph", rule="GC-identity",
            where="frontend.decode-step-identity", line=0,
            message="default-config ServeFront routes decode through the "
                    "survivable loop instead of the direct generate path"))
    front_cache = transformer.init_cache(cfg, BATCH, spec["capacity"])
    ident = check_identity(
        "frontend.decode-step-identity",
        lambda p, c, t, k: serve_decode._step_impl(
            cfg, p, c, t, k, spec["temperature"], spec["compute_dtype"]),
        (params, front_cache, tok, key),
        lambda p, c, t, k: serve_decode._step_impl(cfg, p, c, t, k, 0.0,
                                                   None),
        (params, front_cache, tok, key),
        what="default-config ServeFront decode-step graph")
    (findings.extend(ident) if ident
     else checked.append("frontend.decode-step-identity"))

    # ---- paged KV: the ragged continuous-batching step (collective-free;
    # ---- the pool buffers must stay donated in the lowered executable) --
    from ..models import paged_kv
    from ..serve import batching

    MS, PPS, PGS, NPG = 2, 2, 8, 5  # slots, pages/slot, page size, pages
    ppool = paged_kv.init_pool(cfg, NPG, PGS)
    ptab = jnp.zeros((MS, PPS), jnp.int32)
    plens = jnp.zeros((MS,), jnp.int32)
    ptoks = jnp.zeros((MS,), jnp.int32)
    pkeys = jnp.stack([jax.random.key(0)] * MS)
    psteps = jnp.zeros((MS,), jnp.int32)
    ptemps = jnp.zeros((MS,), jnp.float32)
    run_one("paged.decode_step",
            lambda p, pk, pv, pt, ln, t: paged_kv.paged_decode_step(
                cfg, p, pk, pv, pt, ln, t),
            (params, ppool.k, ppool.v, ptab, plens, ptoks),
            ctx={"donate_min": 2},
            lowerable=batching._batched_step_jit,
            lower_args=(cfg, params, ppool.k, ppool.v, ptab, plens, ptoks,
                        pkeys, psteps, ptemps, None))

    # ---- continuous batching: a single-request paged decode must emit
    # ---- token-for-token what direct generate() emits. This is the one
    # ---- driver that EXECUTES (tiny model, a handful of steps) — token
    # ---- identity is a value property no jaxpr hash can witness ---------
    try:
        bat = batching.ContinuousBatcher(
            cfg, params, batching.BatchingConfig(
                page_size=PGS, num_pages=NPG, max_slots=MS,
                pages_per_slot=PPS))
        bprompt = np.arange(1, 1 + SEQ, dtype=np.int32)
        sid = bat.submit(bprompt, 6, temperature=0.0, rng_seed=0)
        got = bat.run()[sid]
        ref = np.asarray(serve_decode.generate(
            cfg, params, bprompt[None], 6, capacity=CAPACITY,
            rng_key=jax.random.key(0)))[0]
        if not np.array_equal(got, ref):
            findings.append(Finding(
                layer="graph", rule="GC-identity",
                where="batching.decode-step-identity", line=0,
                message=f"single-request paged decode diverged from direct "
                        f"generate: {got.tolist()} != {ref.tolist()}"))
        else:
            checked.append("batching.decode-step-identity")
    except Exception as e:  # noqa: BLE001 — a crashed driver must be loud
        findings.append(_driver_error("batching.decode-step-identity", e))

    # ---- prefix-sharing paged KV: the suffix prefill that backfills only
    # ---- the unmatched prompt tail (collective-free, donated cache) -----
    suffix_cache = transformer.init_cache(cfg, BATCH, CAPACITY)
    suffix_ids = jnp.zeros((BATCH, 4), jnp.int32)
    run_one("decode.prefill_suffix",
            lambda p, i, c: serve_decode._prefill_suffix_impl(
                cfg, p, i, c, None),
            (params, suffix_ids, suffix_cache),
            ctx={"donate_min": 2},
            lowerable=serve_decode._prefill_suffix_jit,
            lower_args=(cfg, params, suffix_ids, suffix_cache, None))

    # prefix sharing is host-side bookkeeping ONLY: a prefix-enabled batcher
    # whose pool really holds shared (refcount > 1) pages must feed the
    # byte-identical ragged step graph as the zero-table trace — sharing may
    # change the table DATA, never the traced GRAPH
    try:
        pbat = batching.ContinuousBatcher(
            cfg, params, batching.BatchingConfig(
                page_size=PGS, num_pages=NPG, max_slots=MS,
                pages_per_slot=PPS,
                prefix_cache=paged_kv.PrefixCacheConfig(enabled=True)))
        pshared = np.arange(1, 1 + PGS, dtype=np.int32)  # one full page
        pbat.submit(np.concatenate([pshared, [99]]).astype(np.int32), 4,
                    temperature=0.0, rng_seed=0)
        pbat.submit(np.concatenate([pshared, [98]]).astype(np.int32), 4,
                    temperature=0.0, rng_seed=1)
        pbat.step()  # admit both: the shared page is live under two slots
        if pbat.pool.shared_pages < 1:
            raise AssertionError("driver bug: no page ended up shared")
        live_tab, live_lens = pbat.pool.device_tables()
        live_toks = jnp.zeros((MS,), jnp.int32)
        ident = check_identity(
            "batching.prefix-disabled-identity",
            lambda p, pk, pv, pt, ln, t: paged_kv.paged_decode_step(
                cfg, p, pk, pv, pt, ln, t),
            (params, pbat.pool.pool.k, pbat.pool.pool.v, live_tab,
             live_lens, live_toks),
            lambda p, pk, pv, pt, ln, t: paged_kv.paged_decode_step(
                cfg, p, pk, pv, pt, ln, t),
            (params, ppool.k, ppool.v, ptab, plens, ptoks),
            what="prefix-enabled batcher's ragged decode-step graph")
        (findings.extend(ident) if ident
         else checked.append("batching.prefix-disabled-identity"))
    except Exception as e:  # noqa: BLE001 — a crashed driver must be loud
        findings.append(_driver_error("batching.prefix-disabled-identity", e))

    # ---- prefix token identity: a mixed trace (two prompts sharing a
    # ---- prefix + one disjoint, mixed temperatures) must emit token-for-
    # ---- token what the prefix-DISABLED batcher emits — the EXECUTED half
    # ---- of the contract (suffix prefill + COW are value properties no
    # ---- jaxpr hash can witness) ----------------------------------------
    try:
        prng = np.random.default_rng(7)
        pfx = prng.integers(1, 128, size=PGS).astype(np.int32)
        pprompts = [
            np.concatenate([pfx, prng.integers(1, 128, size=3)]),
            np.concatenate([pfx, prng.integers(1, 128, size=2)]),
            prng.integers(1, 128, size=6).astype(np.int32),
        ]
        ptemps = [0.0, 0.8, 0.0]

        def _trace(prefix_cache):
            b = batching.ContinuousBatcher(
                cfg, params, batching.BatchingConfig(
                    page_size=PGS, num_pages=NPG, max_slots=MS,
                    pages_per_slot=PPS, prefix_cache=prefix_cache))
            sids = [b.submit(pp.astype(np.int32), 3, temperature=t,
                             rng_seed=i)
                    for i, (pp, t) in enumerate(zip(pprompts, ptemps))]
            out = b.run()
            b.pool.check_invariants()
            return [out[s].tolist() for s in sids], b.pool.prefix_counters

        base_toks, _ = _trace(None)
        got_toks, pc = _trace(paged_kv.PrefixCacheConfig(enabled=True))
        if got_toks != base_toks:
            findings.append(Finding(
                layer="graph", rule="GC-identity",
                where="batching.prefix-token-identity", line=0,
                message=f"prefix-enabled batched decode diverged from the "
                        f"non-shared path: {got_toks} != {base_toks}"))
        elif pc["hits"] < 1 or pc["saved_tokens"] < 1:
            findings.append(Finding(
                layer="graph", rule="GC-identity",
                where="batching.prefix-token-identity", line=0,
                message=f"prefix trace never hit the index (hits="
                        f"{pc['hits']}, saved={pc['saved_tokens']}): the "
                        f"parity check proved nothing"))
        else:
            checked.append("batching.prefix-token-identity")
    except Exception as e:  # noqa: BLE001 — a crashed driver must be loud
        findings.append(_driver_error("batching.prefix-token-identity", e))

    # ---- KV-at-rest quantization: the quant ragged step (collective-free;
    # ---- all FOUR pool buffers — codes AND scales — stay donated in the
    # ---- lowered executable) --------------------------------------------
    qpool = paged_kv.init_quant_pool(cfg, NPG, PGS, "int8_per_channel")
    qkeys = jnp.stack([jax.random.key(0)] * MS)
    qsteps = jnp.zeros((MS,), jnp.int32)
    qtemps = jnp.zeros((MS,), jnp.float32)
    run_one("paged.decode_step_quant",
            lambda p, pk, pv, ks, vs, pt, ln, t:
                paged_kv.paged_decode_step_quant(
                    cfg, p, pk, pv, ks, vs, pt, ln, t,
                    kv_codec="int8_per_channel"),
            (params, qpool.k, qpool.v, qpool.k_scale, qpool.v_scale, ptab,
             plens, ptoks),
            ctx={"donate_min": 4},
            lowerable=batching._batched_step_quant_jit,
            lower_args=(cfg, params, qpool.k, qpool.v, qpool.k_scale,
                        qpool.v_scale, ptab, plens, ptoks, qkeys, qsteps,
                        qtemps, "int8_per_channel", None))

    # the fp tier must be a NO-OP: a kv_codec="fp" batcher with live state
    # feeds the byte-identical ragged step graph the pre-quantization
    # batcher traces — the disabled-build jaxpr fingerprint half of the
    # KV-at-rest contract
    try:
        fbat = batching.ContinuousBatcher(
            cfg, params, batching.BatchingConfig(
                page_size=PGS, num_pages=NPG, max_slots=MS,
                pages_per_slot=PPS, kv_codec="fp"))
        fbat.submit(np.arange(1, 1 + SEQ, dtype=np.int32), 4,
                    temperature=0.0, rng_seed=0)
        fbat.step()
        ftab, flens = fbat.pool.device_tables()
        ftoks = jnp.zeros((MS,), jnp.int32)
        ident = check_identity(
            "batching.kvq-disabled-identity",
            lambda p, pk, pv, pt, ln, t: paged_kv.paged_decode_step(
                cfg, p, pk, pv, pt, ln, t),
            (params, fbat.pool.pool.k, fbat.pool.pool.v, ftab, flens, ftoks),
            lambda p, pk, pv, pt, ln, t: paged_kv.paged_decode_step(
                cfg, p, pk, pv, pt, ln, t),
            (params, ppool.k, ppool.v, ptab, plens, ptoks),
            what="kv_codec=\"fp\" batcher's ragged decode-step graph")
        (findings.extend(ident) if ident
         else checked.append("batching.kvq-disabled-identity"))
    except Exception as e:  # noqa: BLE001 — a crashed driver must be loud
        findings.append(_driver_error("batching.kvq-disabled-identity", e))

    # ---- fp-tier token identity: an explicit kv_codec="fp" batcher must
    # ---- emit token-for-token what direct generate() emits — the EXECUTED
    # ---- half (quantize-on-append must never touch the fp path) ---------
    try:
        kbat = batching.ContinuousBatcher(
            cfg, params, batching.BatchingConfig(
                page_size=PGS, num_pages=NPG, max_slots=MS,
                pages_per_slot=PPS, kv_codec="fp"))
        kprompt = np.arange(1, 1 + SEQ, dtype=np.int32)
        ksid = kbat.submit(kprompt, 6, temperature=0.0, rng_seed=0)
        kgot = kbat.run()[ksid]
        kref = np.asarray(serve_decode.generate(
            cfg, params, kprompt[None], 6, capacity=CAPACITY,
            rng_key=jax.random.key(0)))[0]
        if not np.array_equal(kgot, kref):
            findings.append(Finding(
                layer="graph", rule="GC-identity",
                where="batching.kvq-fp-token-identity", line=0,
                message=f"fp-tier paged decode diverged from direct "
                        f"generate: {kgot.tolist()} != {kref.tolist()}"))
        else:
            checked.append("batching.kvq-fp-token-identity")
    except Exception as e:  # noqa: BLE001 — a crashed driver must be loud
        findings.append(_driver_error("batching.kvq-fp-token-identity", e))

    # ---- quant decode fallback: the XLA page-table-gather path must equal
    # ---- quantize->dequantize + plain decode attention EXACTLY (same op
    # ---- order, no extra rounding) — executed on random packed pools ----
    try:
        from ..models import flash_attention as fa

        eq_rng = np.random.default_rng(3)
        for tier in ("int8_per_channel", "int4_per_channel"):
            tpool = paged_kv.init_quant_pool(cfg, NPG, PGS, tier)
            kq, ks = fa.quantize_kv_rows(jnp.asarray(
                eq_rng.standard_normal((cfg.num_layers, NPG * PGS,
                                        cfg.num_kv_heads, cfg.head_dim),
                                       np.float32)), tier)
            vq, vs = fa.quantize_kv_rows(jnp.asarray(
                eq_rng.standard_normal((cfg.num_layers, NPG * PGS,
                                        cfg.num_kv_heads, cfg.head_dim),
                                       np.float32)), tier)
            shp = tpool.k.shape
            kq = kq.reshape(shp)
            vq = vq.reshape(shp)
            ks = ks.reshape(shp[:-1])
            vs = vs.reshape(shp[:-1])
            q = jnp.asarray(eq_rng.standard_normal(
                (MS, 1, cfg.num_heads, cfg.head_dim), np.float32))
            etab = jnp.asarray(
                eq_rng.permutation(np.arange(1, NPG))[:MS * PPS]
                .reshape(MS, PPS).astype(np.int32))
            elens = jnp.asarray([PGS + 3, PGS - 2], jnp.int32)
            got = fa.paged_decode_attention_quant(
                q, kq[0], vq[0], ks[0], vs[0], etab, elens, kv_codec=tier)
            # reference: dequantize the WHOLE pool, then the plain fp path
            kf = fa.dequantize_kv_rows(
                kq[0].reshape(NPG * PGS, cfg.num_kv_heads, -1),
                ks[0].reshape(NPG * PGS, cfg.num_kv_heads), tier)
            vf = fa.dequantize_kv_rows(
                vq[0].reshape(NPG * PGS, cfg.num_kv_heads, -1),
                vs[0].reshape(NPG * PGS, cfg.num_kv_heads), tier)
            ref = fa.paged_decode_attention(
                q, kf.reshape(NPG, PGS, cfg.num_kv_heads, cfg.head_dim),
                vf.reshape(NPG, PGS, cfg.num_kv_heads, cfg.head_dim),
                etab, elens)
            if not np.array_equal(np.asarray(got), np.asarray(ref)):
                d = float(np.abs(np.asarray(got) - np.asarray(ref)).max())
                findings.append(Finding(
                    layer="graph", rule="GC-identity",
                    where="paged.quant-fallback-equivalence", line=0,
                    message=f"{tier} XLA fallback diverged from quantize->"
                            f"dequantize decode attention (max |d|={d:g})"))
                break
        else:
            checked.append("paged.quant-fallback-equivalence")
    except Exception as e:  # noqa: BLE001 — a crashed driver must be loud
        findings.append(_driver_error("paged.quant-fallback-equivalence", e))

    # ---- disaggregated prefill/decode: pure host-side orchestration.
    # ---- A DisaggServer's decode batcher — after a REAL migration landed
    # ---- (prefill on a staging worker, pages over the link, resume adopt)
    # ---- — must feed the byte-identical ragged step graph the pre-disagg
    # ---- batcher traces: migration moves page DATA, never the GRAPH ------
    try:
        from ..serve import disagg as serve_disagg

        dsrv = serve_disagg.DisaggServer(
            cfg, params, batching.BatchingConfig(
                page_size=PGS, num_pages=NPG, max_slots=MS,
                pages_per_slot=PPS),
            serve_disagg.DisaggConfig(num_prefill_workers=1,
                                      prefill_batch=1))
        dsid = dsrv.submit(np.arange(1, 1 + SEQ, dtype=np.int32), 4,
                           temperature=0.0, rng_seed=0)
        dsrv.step()  # prefill + migrate + adopt: decode holds migrated pages
        if dsrv.report()["disagg"]["migrations"] < 1:
            raise AssertionError("driver bug: no migration happened")
        dtab, dlens = dsrv.pool.device_tables()
        dtoks = jnp.zeros((MS,), jnp.int32)
        ident = check_identity(
            "disagg.disabled-identity",
            lambda p, pk, pv, pt, ln, t: paged_kv.paged_decode_step(
                cfg, p, pk, pv, pt, ln, t),
            (params, dsrv.pool.pool.k, dsrv.pool.pool.v, dtab, dlens,
             dtoks),
            lambda p, pk, pv, pt, ln, t: paged_kv.paged_decode_step(
                cfg, p, pk, pv, pt, ln, t),
            (params, ppool.k, ppool.v, ptab, plens, ptoks),
            what="disagg decode batcher's ragged decode-step graph (with "
                 "migrated pages live)")
        (findings.extend(ident) if ident
         else checked.append("disagg.disabled-identity"))
        dsrv.run()
        dsrv.pop_result(dsid)
    except Exception as e:  # noqa: BLE001 — a crashed driver must be loud
        findings.append(_driver_error("disagg.disabled-identity", e))

    # ---- disagg migration wire bytes: every page transfer's built wire
    # ---- tree must measure exactly migration_wire_nbytes(payload) — the
    # ---- sealed form (payload + 8 B sidecar) and the FEC frame (parity
    # ---- chunks + per-chunk checksum words). A drifting frame layout is a
    # ---- silent protocol break between prefill and decode builds ---------
    try:
        from ..codecs import fec as codecs_fec
        from ..codecs import wire_format as codecs_wire

        wbat = batching.ContinuousBatcher(
            cfg, params, batching.BatchingConfig(
                page_size=PGS, num_pages=NPG, max_slots=MS,
                pages_per_slot=PPS))
        wsid = wbat.submit(np.arange(1, 1 + SEQ, dtype=np.int32), 2,
                           temperature=0.0, rng_seed=0)
        wst = wbat.prefill_hold(wsid)
        chunk = wbat.gather_rows(wst.slot, 0, PGS)
        payload = jax.tree_util.tree_map(jnp.asarray, chunk)
        sealed = codecs_wire.seal_payload(payload)
        bad = []
        measured = codecs_wire.tree_nbytes(sealed)
        declared = serve_disagg.migration_wire_nbytes(
            codecs_wire.tree_nbytes(payload), None)
        if measured != declared:
            bad.append(f"sealed frame measures {measured} B, "
                       f"declared {declared} B")
        fcfg = codecs_fec.FECConfig(enabled=True)
        fmeasured = codecs_wire.tree_nbytes(
            codecs_fec.fec_encode(sealed, fcfg))
        fdeclared = serve_disagg.migration_wire_nbytes(
            codecs_wire.tree_nbytes(payload), fcfg)
        if fmeasured != fdeclared:
            bad.append(f"FEC frame measures {fmeasured} B, "
                       f"declared {fdeclared} B")
        wbat.release_handoff(wsid)
        if bad:
            findings.append(Finding(
                layer="graph", rule="GC-identity",
                where="disagg.migration-wire-bytes", line=0,
                message="migration wire-byte contract violated: "
                        + "; ".join(bad)))
        else:
            checked.append("disagg.migration-wire-bytes")
    except Exception as e:  # noqa: BLE001 — a crashed driver must be loud
        findings.append(_driver_error("disagg.migration-wire-bytes", e))

    # ---- gray-failure hedging: pure host-side orchestration. A cluster
    # ---- whose gray plane REALLY fired (straggler samples observed, a
    # ---- hedge leg dispatched and settled first-finisher-wins) must leave
    # ---- every replica batcher feeding the byte-identical ragged step
    # ---- graph as the zero-table trace — hedging re-places REQUESTS,
    # ---- never touches the compiled decode graph ------------------------
    try:
        from ..serve.cluster import ClusterConfig, ClusterFront, GrayConfig
        from ..serve.frontend import Request, ServeFront
        from ..serve.overload import COMPLETED
        from ..utils.clock import FakeClock

        hck = FakeClock()
        hfronts = {}

        def _hedge_factory(rid, gen):
            f = ServeFront(cfg, params, clock=hck,
                           batcher=batching.ContinuousBatcher(
                               cfg, params, batching.BatchingConfig(
                                   page_size=PGS, num_pages=NPG,
                                   max_slots=MS, pages_per_slot=PPS)))
            hfronts[rid] = f
            return f

        hclu = ClusterFront(_hedge_factory, ClusterConfig(
            num_replicas=2, probe_prefix=False,
            gray=GrayConfig(enabled=True, p95_multiple=1.5,
                            hedge_delay_quantile=0.5, min_dwell_s=0.0,
                            max_hedge_fraction=1.0, min_samples=1)),
            clock=hck)
        hprompt = np.arange(1, 1 + SEQ, dtype=np.int32)
        # two seed requests give the detector per-replica latency samples
        # (FakeClock latencies are 0, so the hedge delay collapses to 0)
        for i in range(2):
            hclu.submit(Request(prompt_ids=hprompt, max_new_tokens=3,
                                temperature=0.0, rng_seed=i))
            while hclu.drain():
                pass
        hcrid = hclu.submit(Request(prompt_ids=hprompt, max_new_tokens=3,
                                    temperature=0.0, rng_seed=7))
        hck.advance(0.5)   # older than the 0-second hedge delay
        hrecs = []
        while True:
            got = hclu.drain()
            if not got:
                break
            hrecs.extend(got)
        if hclu.totals["hedges"] < 1:
            raise AssertionError("driver bug: no hedge leg fired")
        if hclu.pending:
            raise AssertionError(
                f"hedge settlement lost work: {hclu.pending} pending")
        hrec = next(r for r in hrecs if r.request_id == hcrid)
        href = np.asarray(serve_decode.generate(
            cfg, params, hprompt[None], 3, capacity=CAPACITY,
            rng_key=jax.random.key(7)))[0]
        htoks_got = (None if hrec.tokens is None
                     else np.asarray(hrec.tokens).reshape(-1))
        if hrec.outcome != COMPLETED or not np.array_equal(htoks_got, href):
            findings.append(Finding(
                layer="graph", rule="GC-identity",
                where="cluster.hedge-disabled-identity", line=0,
                message=f"hedged request diverged from direct generate: "
                        f"outcome={hrec.outcome} tokens={htoks_got} "
                        f"!= {href.tolist()}"))
        else:
            hpool = hfronts[0].batcher.pool
            htab, hlens = hpool.device_tables()
            htoks = jnp.zeros((MS,), jnp.int32)
            ident = check_identity(
                "cluster.hedge-disabled-identity",
                lambda p, pk, pv, pt, ln, t: paged_kv.paged_decode_step(
                    cfg, p, pk, pv, pt, ln, t),
                (params, hpool.pool.k, hpool.pool.v, htab, hlens, htoks),
                lambda p, pk, pv, pt, ln, t: paged_kv.paged_decode_step(
                    cfg, p, pk, pv, pt, ln, t),
                (params, ppool.k, ppool.v, ptab, plens, ptoks),
                what="gray-hedged replica's ragged decode-step graph")
            (findings.extend(ident) if ident
             else checked.append("cluster.hedge-disabled-identity"))
    except Exception as e:  # noqa: BLE001 — a crashed driver must be loud
        findings.append(_driver_error("cluster.hedge-disabled-identity", e))

    # ---- split pipeline: boundary hops over a real 2-stage mesh ---------
    if len(jax.devices()) < 2:
        skipped.append("split/fault contracts: needs >= 2 devices "
                       "(set XLA_FLAGS=--xla_force_host_platform_device_count=8)")
        return findings, checked, skipped

    mesh = make_stage_mesh(2)
    split = SplitConfig(cuts=(2,), hop_codecs=("int8_per_token",))
    rt = SplitRuntime(cfg, split, mesh)
    placed = rt.place_params(params)
    n_hops = len(rt.codecs)

    fwd_shape = (BATCH, SEQ, cfg.hidden_size)
    leaves_f, dtypes_f, bytes_f = _payload_info(rt.codecs[0], fwd_shape)
    imps = jnp.zeros((n_hops, SEQ), jnp.float32)  # blank importance stack
    fwd_ctx = {
        "hop_eqns": n_hops * leaves_f,
        "wire_dtypes": frozenset(dtypes_f),
        "wire_bytes": sum(rt.hop_bytes(BATCH, SEQ)),
    }
    run_one("split.forward", rt._forward, (placed, ids, imps), fwd_ctx)

    step_shape = (BATCH, 1, cfg.hidden_size)
    leaves_s, dtypes_s, _ = _payload_info(rt.codecs[0], step_shape)
    prefill_fn, step_fn = rt._decode_fns(CAPACITY)
    kv_shape = (split.n_stages, rt.stage_size, BATCH, CAPACITY,
                cfg.num_kv_heads, cfg.head_dim)
    k_cache = jnp.zeros(kv_shape, jnp.float32)
    v_cache = jnp.zeros(kv_shape, jnp.float32)
    length = jnp.asarray(SEQ, jnp.int32)
    step_ctx = {
        "hop_eqns": n_hops * leaves_s,
        "wire_dtypes": frozenset(dtypes_s),
        "wire_bytes": sum(rt.decode_hop_bytes(BATCH)),
        "donate_min": 2,  # k_cache + v_cache buffers update in place
    }
    run_one("split.decode_step", step_fn,
            (placed, k_cache, v_cache, length, tok), step_ctx,
            lowerable=step_fn,
            lower_args=(placed, k_cache, v_cache, length, tok))

    # ---- paged split: the ragged twin of split.decode_step — every cut
    # ---- still quantizes a (max_slots, 1, D) boundary activation, the
    # ---- per-stage page pools stay donated ------------------------------
    spool = rt.init_paged_pool(NPG, PGS)
    paged_step_shape = (MS, 1, cfg.hidden_size)
    leaves_p, dtypes_p, _ = _payload_info(rt.codecs[0], paged_step_shape)
    pstep_fn = rt._paged_decode_fns(NPG, PGS)
    paged_ctx = {
        "hop_eqns": n_hops * leaves_p,
        "wire_dtypes": frozenset(dtypes_p),
        "wire_bytes": sum(rt.decode_hop_bytes(MS)),
        "donate_min": 2,  # the per-stage page pools update in place
    }
    run_one("split.decode_step_paged", pstep_fn,
            (placed, spool["k"], spool["v"], ptab, plens, ptoks), paged_ctx,
            lowerable=pstep_fn,
            lower_args=(placed, spool["k"], spool["v"], ptab, plens, ptoks))

    # ---- k-token verify: the speculative burst's ONE boundary round-trip —
    # ---- every cut quantizes a single (B, K, D) activation block instead of
    # ---- K single-token payloads, KV donation discipline unchanged ---------
    K = 4  # verify window; any k traces the same contract shape
    verify_shape = (BATCH, K, cfg.hidden_size)
    leaves_v, dtypes_v, _ = _payload_info(rt.codecs[0], verify_shape)
    verify_fn = rt._verify_fns(CAPACITY, K)
    vtoks = jnp.zeros((BATCH, K), jnp.int32)
    verify_ctx = {
        "hop_eqns": n_hops * leaves_v,
        "wire_dtypes": frozenset(dtypes_v),
        "wire_bytes": sum(rt.verify_hop_bytes(BATCH, K)),
        "donate_min": 2,  # the burst updates both KV caches in place
    }
    run_one("split.verify_step", verify_fn,
            (placed, k_cache, v_cache, length, vtoks), verify_ctx,
            lowerable=verify_fn,
            lower_args=(placed, k_cache, v_cache, length, vtoks))

    # a disabled SpecConfig is pure host-side dispatch: a runtime whose
    # verify executables HAVE been built must still trace the byte-identical
    # vanilla decode step (the pre-spec graph) — this is the fingerprint
    # half of the ISSUE's disabled-spec contract; run.py's validator and the
    # serve loop's dispatch guard are the other half
    rt_prespec = SplitRuntime(cfg, split, mesh)
    _, step_fn_prespec = rt_prespec._decode_fns(CAPACITY)
    ident = check_identity(
        "split.decode_step.spec-disabled-identity",
        step_fn, (placed, k_cache, v_cache, length, tok),
        step_fn_prespec, (placed, k_cache, v_cache, length, tok),
        what="spec-aware build's vanilla decode-step graph")
    (findings.extend(ident) if ident
     else checked.append("split.decode_step.spec-disabled-identity"))

    # ---- micro-batch pipelined schedule: same wire protocol per hop, but
    # ---- every cut now moves M payloads of (B/M, ...) — hop_eqns and wire
    # ---- bytes scale by M, replication still collapses to ONE stacked psum,
    # ---- and the KV/pool donation discipline survives the schedule --------
    PBATCH, PM = 2, 2  # batch and µ-batch count; µ-batch rows = PBATCH // PM
    rt_pipe = SplitRuntime(cfg, split, mesh,
                           pipeline=PipelineConfig(num_microbatches=PM))
    pipe_ids = jnp.zeros((PBATCH, SEQ), jnp.int32)
    pipe_fwd_ctx = {
        "hop_eqns": PM * n_hops * leaves_f,
        "wire_dtypes": frozenset(dtypes_f),
        "wire_bytes": PM * sum(rt_pipe.hop_bytes(PBATCH // PM, SEQ)),
    }
    run_one("split.forward.pipelined", rt_pipe._forward,
            (placed, pipe_ids, imps), pipe_fwd_ctx)

    pipe_kv_shape = (split.n_stages, rt.stage_size, PBATCH, CAPACITY,
                     cfg.num_kv_heads, cfg.head_dim)
    pipe_k = jnp.zeros(pipe_kv_shape, jnp.float32)
    pipe_v = jnp.zeros(pipe_kv_shape, jnp.float32)
    pipe_tok = jnp.zeros((PBATCH,), jnp.int32)
    _, pipe_step_fn = rt_pipe._decode_fns(CAPACITY)
    pipe_step_ctx = {
        "hop_eqns": PM * n_hops * leaves_s,
        "wire_dtypes": frozenset(dtypes_s),
        "wire_bytes": sum(rt_pipe.pipelined_decode_hop_bytes(PBATCH)),
        "donate_min": 2,
    }
    run_one("split.decode_step.pipelined", pipe_step_fn,
            (placed, pipe_k, pipe_v, length, pipe_tok), pipe_step_ctx,
            lowerable=pipe_step_fn,
            lower_args=(placed, pipe_k, pipe_v, length, pipe_tok))

    # MS slots split into PM µ-batches of MS // PM ragged rows each
    pipe_pstep_fn = rt_pipe._paged_decode_fns(NPG, PGS)
    pipe_paged_ctx = {
        "hop_eqns": PM * n_hops * leaves_p,
        "wire_dtypes": frozenset(dtypes_p),
        "wire_bytes": sum(rt_pipe.pipelined_decode_hop_bytes(MS)),
        "donate_min": 2,
    }
    run_one("split.decode_step_paged.pipelined", pipe_pstep_fn,
            (placed, spool["k"], spool["v"], ptab, plens, ptoks),
            pipe_paged_ctx,
            lowerable=pipe_pstep_fn,
            lower_args=(placed, spool["k"], spool["v"], ptab, plens, ptoks))

    # num_microbatches=1 must trace the ORIGINAL sequential schedule byte for
    # byte — the fingerprint half of the ISSUE's disabled-pipeline contract
    # (run.py's validator and the runtime's n_micro dispatch are the other
    # half); pinned for forward AND decode so neither schedule can drift
    rt_m1 = SplitRuntime(cfg, split, mesh,
                         pipeline=PipelineConfig(num_microbatches=1))
    ident = check_identity(
        "split.forward.pipeline-disabled-identity",
        rt._forward, (placed, ids, imps),
        rt_m1._forward, (placed, ids, imps),
        what="num_microbatches=1 build's forward graph")
    (findings.extend(ident) if ident
     else checked.append("split.forward.pipeline-disabled-identity"))
    _, step_fn_m1 = rt_m1._decode_fns(CAPACITY)
    ident = check_identity(
        "split.decode_step.pipeline-disabled-identity",
        step_fn, (placed, k_cache, v_cache, length, tok),
        step_fn_m1, (placed, k_cache, v_cache, length, tok),
        what="num_microbatches=1 build's decode-step graph")
    (findings.extend(ident) if ident
     else checked.append("split.decode_step.pipeline-disabled-identity"))

    # ---- faulty link: sealed payloads, statically-unrolled retries ------
    attempts = 2  # 1 try + 1 retry, statically unrolled in the graph
    rt_fault = SplitRuntime(cfg, split, mesh,
                            faults=FaultConfig(bitflip_rate=0.01, seed=0),
                            policy=LinkPolicy(max_retries=attempts - 1))
    sealed_leaves = leaves_f + 2  # + canary + crc sidecars
    fault_ctx = {
        "hop_eqns": n_hops * sealed_leaves * attempts,
        "n_psum": 1 + len(COUNTER_KEYS),  # output + replicated counters
        "wire_dtypes": frozenset(dtypes_f) | {"uint32"},
        # every attempt retransmits payload + 8-byte integrity sidecar
        "wire_bytes": attempts * (bytes_f + 8) * n_hops,
    }
    fault_step = jnp.asarray(0, jnp.int32)
    run_one("faults.hop", rt_fault._forward,
            (placed, ids, imps, fault_step), fault_ctx)

    # ---- self-healing link: FEC parity + hedged routes ------------------
    from ..codecs.fec import FECConfig, HedgeConfig

    fec_cfg = FECConfig(group_size=4, n_groups=4)
    hedge_cfg = HedgeConfig(routes=2)
    rt_fec = SplitRuntime(cfg, split, mesh,
                          faults=FaultConfig(bitflip_rate=0.01, seed=0),
                          policy=LinkPolicy(max_retries=attempts - 1),
                          fec=fec_cfg, hedge=hedge_cfg)
    transmissions = attempts * hedge_cfg.routes  # retries x staggered routes
    fec_ctx = {
        # 2 wire leaves per transmission: the chunk matrix + the word vector
        "hop_eqns": n_hops * 2 * transmissions,
        "n_psum": 1 + len(rt_fec._link.counter_keys),
        "wire_dtypes": frozenset({"uint8", "uint32"}),
        # ppermute traffic = declared payload + parity overhead, per route
        "wire_bytes": transmissions * fec_cfg.wire_nbytes(bytes_f + 8)
        * n_hops,
    }
    run_one("fec.hop", rt_fec._forward,
            (placed, ids, imps, fault_step), fec_ctx)

    # a faulted build with FEC and hedging *disabled* must trace the exact
    # PR 2 hop — same fingerprint as a build that never heard of fec.py
    rt_fec_off = SplitRuntime(cfg, split, mesh,
                              faults=FaultConfig(bitflip_rate=0.01, seed=0),
                              policy=LinkPolicy(max_retries=attempts - 1),
                              fec=FECConfig(enabled=False),
                              hedge=HedgeConfig(enabled=False))
    ident = check_identity(
        "split.forward.fec-disabled-identity",
        rt_fault._forward, (placed, ids, imps, fault_step),
        rt_fec_off._forward, (placed, ids, imps, fault_step),
        what="disabled-FEC faulted forward graph")
    (findings.extend(ident) if ident
     else checked.append("split.forward.fec-disabled-identity"))

    # ---- disabled-config identity: a zero-rate fault config and an absent
    # ---- one must compile the SAME executable -----------------------------
    rt_zero = SplitRuntime(cfg, split, mesh, faults=FaultConfig())
    ident = check_identity(
        "split.forward.zero-fault-identity",
        rt._forward, (placed, ids, imps),
        rt_zero._forward, (placed, ids, imps),
        what="zero-rate FaultConfig forward graph")
    (findings.extend(ident) if ident
     else checked.append("split.forward.zero-fault-identity"))

    _, step_fn_zero = rt_zero._decode_fns(CAPACITY)
    ident = check_identity(
        "split.decode_step.zero-fault-identity",
        step_fn, (placed, k_cache, v_cache, length, tok),
        step_fn_zero, (placed, k_cache, v_cache, length, tok),
        what="zero-rate FaultConfig decode-step graph")
    (findings.extend(ident) if ident
     else checked.append("split.decode_step.zero-fault-identity"))

    # ---- fused boundary hops: a forced-wire build must cross each cut as
    # ---- ONE flat sealed uint8 buffer carrying exactly hop_bytes + the
    # ---- 8-byte canary/crc seal; a fused-DISABLED build must trace the
    # ---- byte-identical pre-fusion graph (the FaultyLink refactor's whole
    # ---- point: fusion changes scheduling, never what bytes cross) --------
    import os

    saved_env = os.environ.get("EDGELLM_FUSED_HOP")
    try:
        # plans resolve at runtime construction, so the env must be set first
        os.environ["EDGELLM_FUSED_HOP"] = "wire"
        rt_fused = SplitRuntime(cfg, split, mesh)
        os.environ["EDGELLM_FUSED_HOP"] = "0"
        rt_unfused = SplitRuntime(cfg, split, mesh)
    finally:
        if saved_env is None:
            os.environ.pop("EDGELLM_FUSED_HOP", None)
        else:
            os.environ["EDGELLM_FUSED_HOP"] = saved_env

    if any(p is None for p in rt_fused.fused_plans):
        findings.append(Finding(
            layer="graph", rule="GC-driver", where="split.forward.fused",
            line=0, message="EDGELLM_FUSED_HOP=wire build refused a fused "
                            f"plan: {rt_fused.fused_plans}"))
    else:
        fused_fwd_ctx = {
            "hop_eqns": n_hops,  # one flat buffer ppermute per cut
            "wire_dtypes": frozenset({"uint8"}),
            "wire_bytes": sum(rt_fused.hop_bytes(BATCH, SEQ)) + 8 * n_hops,
        }
        run_one("split.forward.fused", rt_fused._forward,
                (placed, ids, imps), fused_fwd_ctx)

        _, step_fn_fused = rt_fused._decode_fns(CAPACITY)
        fused_step_ctx = {
            "hop_eqns": n_hops,
            "wire_dtypes": frozenset({"uint8"}),
            "wire_bytes": sum(rt_fused.decode_hop_bytes(BATCH)) + 8 * n_hops,
            "donate_min": 2,  # KV donation discipline survives fusion
        }
        run_one("split.decode_step.fused", step_fn_fused,
                (placed, k_cache, v_cache, length, tok), fused_step_ctx,
                lowerable=step_fn_fused,
                lower_args=(placed, k_cache, v_cache, length, tok))

        # verify-shape twin: the whole (B, K, D) burst block crosses each cut
        # as ONE flat sealed buffer — K x hop_bytes payload + the 8-byte seal
        verify_fn_fused = rt_fused._verify_fns(CAPACITY, K)
        fused_verify_ctx = {
            "hop_eqns": n_hops,
            "wire_dtypes": frozenset({"uint8"}),
            "wire_bytes": sum(rt_fused.verify_hop_bytes(BATCH, K))
            + 8 * n_hops,
            "donate_min": 2,
        }
        run_one("split.verify_step.fused", verify_fn_fused,
                (placed, k_cache, v_cache, length, vtoks), fused_verify_ctx,
                lowerable=verify_fn_fused,
                lower_args=(placed, k_cache, v_cache, length, vtoks))

    ident = check_identity(
        "split.forward.fused-disabled-identity",
        rt._forward, (placed, ids, imps),
        rt_unfused._forward, (placed, ids, imps),
        what="EDGELLM_FUSED_HOP=0 forward graph vs pre-fusion default")
    (findings.extend(ident) if ident
     else checked.append("split.forward.fused-disabled-identity"))

    _, step_fn_unfused = rt_unfused._decode_fns(CAPACITY)
    ident = check_identity(
        "split.decode_step.fused-disabled-identity",
        step_fn, (placed, k_cache, v_cache, length, tok),
        step_fn_unfused, (placed, k_cache, v_cache, length, tok),
        what="EDGELLM_FUSED_HOP=0 decode-step graph vs pre-fusion default")
    (findings.extend(ident) if ident
     else checked.append("split.decode_step.fused-disabled-identity"))

    # ---- observability identity: ARMING the obs stack (registry + tracer
    # ---- on, a span open on this thread) must not change a single jaxpr
    # ---- byte — every instrument is host-side, at sample boundaries, never
    # ---- inside the compiled graph ---------------------------------------
    from .. import obs

    def _armed(fn: Callable) -> Callable:
        """Trace ``fn`` with the full obs stack enabled and an open span, so
        any graph residue (a host callback, a metric op) flips the hash."""
        def traced(*args):
            obs.enable(obs.ObservabilityConfig())
            try:
                with obs.span("lint.obs-identity-probe"):
                    return fn(*args)
            finally:
                obs.disable()
        return traced

    ident = check_identity(
        "split.forward.obs-enabled-identity",
        rt._forward, (placed, ids, imps),
        _armed(rt._forward), (placed, ids, imps),
        what="obs-enabled forward graph")
    (findings.extend(ident) if ident
     else checked.append("split.forward.obs-enabled-identity"))

    ident = check_identity(
        "split.decode_step.obs-enabled-identity",
        step_fn, (placed, k_cache, v_cache, length, tok),
        _armed(step_fn), (placed, k_cache, v_cache, length, tok),
        what="obs-enabled decode-step graph")
    (findings.extend(ident) if ident
     else checked.append("split.decode_step.obs-enabled-identity"))

    return findings, checked, skipped
