"""CLI sweep driver, compatible with the reference's ``params.json`` convention.

The reference drives each experiment with ``python main.py`` next to a flat
``params.json`` (keys: ``ratios``, ``layers_of_interest``, ``stride``,
``max_length``, ``experiment``, ``methods`` — ``Pythia-70M/main.py:23-32``,
``Qwen2-0.5B/main.py:107-119``). Here one entry point covers every experiment:

    python -m edgellm_tpu.run --params params.json --model qwen2-0.5b \
        --corpus corpus.npy [--weights ckpt.safetensors] [--output-dir out]

Dispatch mirrors the reference:
- ``experiment: "initial"``   -> Pythia initial sweep (affine-int8 rank / top-rho)
- ``experiment: "last_row"``  -> token-selective int4 sweep (Pythia defaults)
- ``experiment: "relevance"`` -> LRP head-relevance extraction
- ``experiment: "split"``     -> real mesh-split eval (ppermute boundary hops)
- ``experiment: "distances"`` -> layer-pair JS-divergence matrix + heatmap
  (the ``distributions_distance_across_layers.ipynb`` cell 16-18 analysis)
- ``experiment: "serve"``     -> deterministic soak through the overload-robust
  serving front (admission control, circuit breakers, brownout; ``"serving"``
  params block, ``--serve-report``)
- methods containing "channel" -> per-channel codec sweep (``main.py:118-119``)
- otherwise                   -> the Qwen-style token sweep

Corpus input is a ``.npy``/``.npz`` of token ids, or a raw ``.txt`` plus
``--tokenizer`` (a local HF tokenizer path; this environment has no network).
Weights: a local torch checkpoint via ``--weights`` (state_dict ``.pt`` or
HF directory), else random init (smoke/benchmark mode).
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

import numpy as np


def _load_corpus(args, vocab_size: int) -> np.ndarray:
    if args.corpus is None:
        rng = np.random.default_rng(args.seed)
        return rng.integers(0, vocab_size, args.synthetic_corpus_len)
    if args.corpus.endswith((".npy", ".npz")):
        data = np.load(args.corpus)
        if hasattr(data, "files"):
            data = data[data.files[0]]
        return np.asarray(data).reshape(-1)
    # raw text: reproduce the reference's corpus construction — documents joined
    # with "\n\n" (Qwen2-0.5B/main.py:122-124). A text file is assumed to already
    # be the joined corpus.
    if args.tokenizer is None:
        raise SystemExit("--tokenizer is required for raw-text corpora")
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(args.tokenizer)
    with open(args.corpus) as f:
        text = f.read()
    return np.asarray(tok(text, return_tensors="np").input_ids).reshape(-1)


def _load_model(args):
    import jax
    from .models import PRESETS, init_params, params_from_state_dict, config_from_hf

    if args.weights:
        # torch-free fast path: .safetensors file, or an HF directory laid out
        # with safetensors shards + config.json
        from .models.safetensors_io import load_checkpoint

        if args.weights.endswith(".safetensors"):
            if args.model not in PRESETS:
                raise SystemExit(f"--model must be one of {sorted(PRESETS)} with a "
                                 f"bare .safetensors file")
            return load_checkpoint(args.weights, PRESETS[args.model])
        if os.path.isdir(args.weights) and any(
                f.endswith(".safetensors") for f in os.listdir(args.weights)):
            return load_checkpoint(args.weights)

        import torch

        if os.path.isdir(args.weights):
            from transformers import AutoConfig, AutoModelForCausalLM

            hf_cfg = AutoConfig.from_pretrained(args.weights)
            cfg = config_from_hf(hf_cfg)
            model = AutoModelForCausalLM.from_pretrained(args.weights)
            sd = model.state_dict()
        else:
            if args.model not in PRESETS:
                raise SystemExit(f"--model must be one of {sorted(PRESETS)} with --weights file")
            cfg = PRESETS[args.model]
            sd = torch.load(args.weights, map_location="cpu")
        return cfg, params_from_state_dict(cfg, sd)
    cfg = PRESETS[args.model]
    return cfg, init_params(cfg, jax.random.key(args.seed))


#: every key any experiment reads, with the experiments that consume it —
#: unknown keys fail fast instead of being silently ignored (a typo'd
#: "hop_codec" used to run the whole eval with defaults)
_PARAM_KEYS = {
    "experiment": "all",
    "max_length": "all", "stride": "all",
    "methods": "token/channel sweeps",
    "layers_of_interest": "initial/token/channel sweeps",
    "ratios": "initial/token sweeps",
    "cuts": "split/serve", "hop_codecs": "split/serve",
    "fused_hops": "split/serve",
    "importance_method": "split",
    "n_seq": "split", "n_data": "split", "n_model": "split",
    "faults": "split/serve", "link_policy": "split/serve",
    "fec": "split/serve", "hedge": "split/serve",
    "link_health": "split/serve",
    "deadline": "split", "stage_failure": "split", "recovery": "split",
    "pipeline": "split/serve",
    "serving": "serve",
    "batching": "serve",
    "prefix_cache": "serve",
    "kv_at_rest": "serve",
    "speculative": "serve",
    "cluster": "serve",
    "disagg": "serve",
    "gray": "serve",
    "max_compiles": "distances",
    "observability": "all",
    "budget": "all (latticelint AOT peak)",
}
_EXPERIMENTS = ("", "initial", "last_row", "relevance", "split", "distances",
                "serve")
_REQUIRED = {"split": ("cuts", "hop_codecs"),
             "serve": ("serving",),
             "initial": ("layers_of_interest", "ratios")}


def _validate_params_json(p: dict) -> None:
    """Fail fast — naming the offending key — before any device work starts.

    Checks the key set, per-experiment required keys, basic value shapes, and
    resolves every codec name (hop codecs, fault-ladder tiers) and fault/policy
    field against the real constructors, so a typo'd params.json dies in
    milliseconds instead of after the model loads."""
    def die(msg):
        raise SystemExit(f"params.json: {msg}")

    if not isinstance(p, dict):
        die(f"expected a JSON object, got {type(p).__name__}")
    unknown = sorted(set(p) - set(_PARAM_KEYS))
    if unknown:
        die(f"unknown key(s) {unknown}; known keys: {sorted(_PARAM_KEYS)}")
    exp = p.get("experiment", "")
    if exp not in _EXPERIMENTS:
        die(f"unknown experiment {exp!r}; options: {list(_EXPERIMENTS)}")
    if "observability" in p:
        from .obs import ObservabilityConfig

        ob = p["observability"]
        if not isinstance(ob, dict):
            die(f"observability must be an object of ObservabilityConfig "
                f"fields, got {ob!r}")
        fields = {f.name for f in dataclasses.fields(ObservabilityConfig)}
        bad = sorted(set(ob) - fields)
        if bad:
            die(f"observability: unknown field(s) {bad}; "
                f"known: {sorted(fields)}")
        try:
            ObservabilityConfig(**ob)
        except (TypeError, ValueError) as e:
            die(f"observability: {e}")
    if "budget" in p:
        # the latticelint contract: a shipped config pins its lint-geometry
        # AOT peak so a graph change that balloons temp bytes is a finding
        b = p["budget"]
        if not isinstance(b, dict):
            die(f"budget must be an object with 'aot_peak_bytes' (and an "
                f"optional 'note'), got {b!r}")
        bad = sorted(set(b) - {"aot_peak_bytes", "note"})
        if bad:
            die(f"budget: unknown field(s) {bad}; "
                f"known: ['aot_peak_bytes', 'note']")
        if "aot_peak_bytes" not in b:
            die("budget needs 'aot_peak_bytes' (the latticelint AOT ceiling)")
        if (not isinstance(b["aot_peak_bytes"], int)
                or isinstance(b["aot_peak_bytes"], bool)
                or b["aot_peak_bytes"] < 1):
            die(f"budget.aot_peak_bytes must be a positive integer, "
                f"got {b['aot_peak_bytes']!r}")
        if "note" in b and not isinstance(b["note"], str):
            die(f"budget.note must be a string, got {b['note']!r}")
    if exp not in ("split", "serve") and (
            "faults" in p or "link_policy" in p or "fec" in p
            or "hedge" in p or "link_health" in p):
        die("faults/link_policy/fec/hedge/link_health only apply to "
            "experiments 'split' and 'serve'")
    if exp != "split" and ("deadline" in p or "stage_failure" in p
                           or "recovery" in p):
        die("deadline/stage_failure/recovery only apply to experiment 'split'")
    if exp != "serve" and "serving" in p:
        die("serving only applies to experiment 'serve'")
    if exp != "serve" and "batching" in p:
        die("batching only applies to experiment 'serve'")
    for k in _REQUIRED.get(exp, ()):
        if k not in p:
            die(f"experiment {exp!r} requires key {k!r}")
    if exp not in ("split", "serve", "initial", "relevance", "distances"):
        # token/channel sweeps (the default dispatch) sweep layers (x ratios
        # for the token sweep; the channel sweep has no ratio axis)
        methods = p.get("methods", [])
        need = ["layers_of_interest"]
        if not (methods and isinstance(methods[0], str)
                and "channel" in methods[0]):
            need.append("ratios")
        for k in need:
            if k not in p:
                die(f"experiment {exp or '(token sweep)'!r} requires key {k!r}")
    for k in ("max_length", "stride", "n_seq", "n_data", "n_model",
              "max_compiles"):
        if k in p and (not isinstance(p[k], int) or isinstance(p[k], bool)
                       or p[k] < 1):
            die(f"{k} must be a positive integer, got {p[k]!r}")
    for k in ("methods", "layers_of_interest", "ratios", "cuts", "hop_codecs"):
        if k in p and not isinstance(p[k], list):
            die(f"{k} must be a list, got {type(p[k]).__name__}")
    if exp == "serve" and ("cuts" in p) != ("hop_codecs" in p):
        die("serve: cuts and hop_codecs go together")
    if "fused_hops" in p:
        if exp not in ("split", "serve"):
            die("fused_hops only applies to experiments 'split' and 'serve'")
        if "cuts" not in p:
            die("fused_hops needs a pipeline to fuse — add 'cuts'/'hop_codecs'")
        fh = p["fused_hops"]
        if fh not in ("auto", "off", "wire", "remote"):
            die(f"fused_hops must be one of ['auto', 'off', 'wire', "
                f"'remote'], got {fh!r}")
        if fh != "off" and any(("faults" in p, "fec" in p, "hedge" in p)):
            # mirror of fused_hop_plan's link_active refusal: an active
            # FaultyLink owns the hop, so forcing fusion would silently lose
            die("fused_hops: an active faults/fec/hedge link owns the hop "
                "protocol — fusion is refused at runtime; set fused_hops: "
                "'off' or drop the link config")
    if exp in ("split", "serve") and "cuts" in p:
        if not p["cuts"] or not all(
                isinstance(c, int) and not isinstance(c, bool) and c >= 0
                for c in p["cuts"]):
            die(f"cuts must be a non-empty list of layer indices, "
                f"got {p['cuts']!r}")
        if len(p["hop_codecs"]) != len(p["cuts"]):
            die(f"hop_codecs has {len(p['hop_codecs'])} entries for "
                f"{len(p['cuts'])} cut(s)")
        from .codecs.packing import get_wire_codec
        from .eval.split_eval import parse_hop_codec

        for spec in p["hop_codecs"]:
            if not isinstance(spec, str):
                die(f"hop_codecs entries must be codec spec strings, "
                    f"got {spec!r}")
            try:
                resolved = parse_hop_codec(spec, p.get("n_seq", 1))
                if isinstance(resolved, str):
                    get_wire_codec(resolved)
            except (ValueError, KeyError) as e:
                die(f"bad hop codec {spec!r}: {e}")
    if exp in ("split", "serve"):
        from .codecs.faults import FaultConfig, LinkPolicy

        for key, cls in (("faults", FaultConfig), ("link_policy", LinkPolicy)):
            if key not in p:
                continue
            if not isinstance(p[key], dict):
                die(f"{key} must be an object of {cls.__name__} fields, "
                    f"got {p[key]!r}")
            fields = {f.name for f in dataclasses.fields(cls)}
            bad = sorted(set(p[key]) - fields)
            if bad:
                die(f"{key}: unknown field(s) {bad}; known: {sorted(fields)}")
            try:
                obj = cls(**{**p[key], "tiers": tuple(p[key].get("tiers", ()))}
                          if key == "link_policy" else p[key])
            except (TypeError, ValueError) as e:
                die(f"{key}: {e}")
            if key == "link_policy":
                for t in obj.tiers:
                    try:
                        get_wire_codec(t)
                    except ValueError as e:
                        die(f"link_policy.tiers: {e}")
        from .codecs.fec import FECConfig, HedgeConfig, LinkHealthConfig

        for key, cls in (("fec", FECConfig), ("hedge", HedgeConfig),
                         ("link_health", LinkHealthConfig)):
            if key not in p:
                continue
            if not isinstance(p[key], dict):
                die(f"{key} must be an object of {cls.__name__} fields, "
                    f"got {p[key]!r}")
            fields = {f.name for f in dataclasses.fields(cls)}
            bad = sorted(set(p[key]) - fields)
            if bad:
                die(f"{key}: unknown field(s) {bad}; known: {sorted(fields)}")
            try:
                cls(**p[key])
            except (TypeError, ValueError) as e:
                die(f"{key}: {e}")
            if "faults" not in p or not FaultConfig(**p["faults"]).enabled:
                die(f"{key} requires an enabled 'faults' config (the link "
                    f"machinery only exists in the graph when a fault can "
                    f"fire)")
        if "deadline" in p:
            d = p["deadline"]
            if isinstance(d, bool) or not isinstance(d, (int, float)) or d <= 0:
                die(f"deadline must be a positive number of seconds, got {d!r}")
        if "stage_failure" in p:
            from .serve.recovery import StageFailure

            sf = p["stage_failure"]
            if not isinstance(sf, dict):
                die(f"stage_failure must be an object of StageFailure fields, "
                    f"got {sf!r}")
            fields = {f.name for f in dataclasses.fields(StageFailure)}
            bad = sorted(set(sf) - fields)
            if bad:
                die(f"stage_failure: unknown field(s) {bad}; "
                    f"known: {sorted(fields)}")
            try:
                obj = StageFailure(**sf)
            except (TypeError, ValueError) as e:
                die(f"stage_failure: {e}")
            if obj.stage > len(p["cuts"]):
                die(f"stage_failure.stage {obj.stage} out of range for "
                    f"{len(p['cuts']) + 1} pipeline stage(s)")
            if p.get("n_seq", 1) > 1:
                die("stage_failure needs the plain split runtime (n_seq == 1)")
        if "recovery" in p:
            r = p["recovery"]
            if not isinstance(r, dict):
                die(f"recovery must be an object, got {r!r}")
            bad = sorted(set(r) - {"replan", "max_failovers"})
            if bad:
                die(f"recovery: unknown field(s) {bad}; "
                    f"known: ['max_failovers', 'replan']")
            if "replan" in r and not isinstance(r["replan"], bool):
                die(f"recovery.replan must be a boolean, got {r['replan']!r}")
            mf = r.get("max_failovers", 1)
            if isinstance(mf, bool) or not isinstance(mf, int) or mf < 1:
                die(f"recovery.max_failovers must be a positive integer, "
                    f"got {mf!r}")
    if "serving" in p:
        from .serve.frontend import ServeFrontConfig
        from .serve.overload import (AdmissionConfig, BreakerConfig,
                                     BrownoutConfig, RetryBudgetConfig)
        from .serve.soak import SoakConfig

        sv = p["serving"]
        if not isinstance(sv, dict):
            die(f"serving must be an object of ServeFrontConfig fields "
                f"(plus 'soak'), got {sv!r}")
        top = {f.name for f in dataclasses.fields(ServeFrontConfig)} | {"soak"}
        bad = sorted(set(sv) - top)
        if bad:
            die(f"serving: unknown field(s) {bad}; known: {sorted(top)}")
        for key, cls in (("admission", AdmissionConfig),
                         ("breaker", BreakerConfig),
                         ("brownout", BrownoutConfig),
                         ("retry_budget", RetryBudgetConfig),
                         ("soak", SoakConfig)):
            if key not in sv:
                continue
            if not isinstance(sv[key], dict):
                die(f"serving.{key} must be an object of {cls.__name__} "
                    f"fields, got {sv[key]!r}")
            fields = {f.name for f in dataclasses.fields(cls)}
            bad = sorted(set(sv[key]) - fields)
            if bad:
                die(f"serving.{key}: unknown field(s) {bad}; "
                    f"known: {sorted(fields)}")
            try:
                cls(**sv[key])
            except (TypeError, ValueError) as e:
                die(f"serving.{key}: {e}")
        try:
            _serve_front_config(sv)
        except (TypeError, ValueError) as e:
            die(f"serving: {e}")
        ks = (sv.get("soak") or {}).get("kill_stage")
        if ks is not None and "cuts" in p and ks > len(p["cuts"]):
            die(f"serving.soak.kill_stage {ks} out of range for "
                f"{len(p['cuts']) + 1} pipeline stage(s)")
    if "batching" in p:
        from .serve.batching import BatchingConfig

        b = p["batching"]
        if not isinstance(b, dict):
            die(f"batching must be an object of BatchingConfig fields, "
                f"got {b!r}")
        # dtype fields are runtime objects, not JSON — keep them out of the
        # schema so a typo'd key dies with the real field list; prefix_cache
        # and kv_codec have their own top-level params blocks
        fields = {f.name for f in dataclasses.fields(BatchingConfig)} \
            - {"compute_dtype", "cache_dtype", "prefix_cache", "kv_codec"}
        bad = sorted(set(b) - fields)
        if bad:
            die(f"batching: unknown field(s) {bad}; known: {sorted(fields)}")
        try:
            bcfg = BatchingConfig(**b)
        except (TypeError, ValueError) as e:
            die(f"batching: {e}")
        sk = (p.get("serving", {}).get("soak") or {})
        need = (sk.get("prompt_len", 8) + sk.get("max_new_tokens", 8) - 1)
        if need > bcfg.span:
            die(f"batching: soak requests need {need} cache positions > slot "
                f"span {bcfg.span} (pages_per_slot x page_size)")
    if "prefix_cache" in p:
        from .models.paged_kv import PrefixCacheConfig

        if exp != "serve":
            die("prefix_cache only applies to experiment 'serve'")
        if "batching" not in p:
            die("prefix_cache rides the continuous batcher's paged pool — "
                "add a 'batching' block")
        pc = p["prefix_cache"]
        if not isinstance(pc, dict):
            die(f"prefix_cache must be an object of PrefixCacheConfig "
                f"fields, got {pc!r}")
        fields = {f.name for f in dataclasses.fields(PrefixCacheConfig)}
        bad = sorted(set(pc) - fields)
        if bad:
            die(f"prefix_cache: unknown field(s) {bad}; "
                f"known: {sorted(fields)}")
        if "enabled" in pc and not isinstance(pc["enabled"], bool):
            die(f"prefix_cache.enabled must be a boolean, "
                f"got {pc['enabled']!r}")
        for k in ("min_shared_block", "max_index_pages"):
            if k in pc and (not isinstance(pc[k], int)
                            or isinstance(pc[k], bool) or pc[k] < 0):
                die(f"prefix_cache.{k} must be a non-negative integer, "
                    f"got {pc[k]!r}")
        try:
            PrefixCacheConfig(**pc)
        except (TypeError, ValueError) as e:
            die(f"prefix_cache: {e}")
    if "kv_at_rest" in p:
        from .models.paged_kv import KV_PAGE_CODECS, resolve_kv_codec

        if exp != "serve":
            die("kv_at_rest only applies to experiment 'serve'")
        if "batching" not in p:
            die("kv_at_rest compresses the continuous batcher's paged pool "
                "— add a 'batching' block")
        kq = p["kv_at_rest"]
        if not isinstance(kq, dict):
            die(f"kv_at_rest must be an object with a 'codec' tier (and "
                f"optional 'pool_bytes'), got {kq!r}")
        bad = sorted(set(kq) - {"codec", "pool_bytes"})
        if bad:
            die(f"kv_at_rest: unknown field(s) {bad}; "
                f"known: ['codec', 'pool_bytes']")
        if "codec" not in kq:
            die(f"kv_at_rest needs a 'codec' tier name; "
                f"options: {sorted(KV_PAGE_CODECS)}")
        try:
            resolve_kv_codec(kq["codec"])
        except (TypeError, ValueError) as e:
            die(f"kv_at_rest: {e}")
        if "pool_bytes" in kq and (not isinstance(kq["pool_bytes"], int)
                                   or isinstance(kq["pool_bytes"], bool)
                                   or kq["pool_bytes"] < 1):
            die(f"kv_at_rest.pool_bytes must be a positive integer, "
                f"got {kq['pool_bytes']!r}")
    if "pipeline" in p:
        from .parallel.split import PipelineConfig

        if exp not in ("split", "serve"):
            die("pipeline only applies to experiments 'split' and 'serve'")
        if "cuts" not in p:
            die("pipeline schedules micro-batches across the split boundary "
                "— add 'cuts'/'hop_codecs'")
        pl = p["pipeline"]
        if not isinstance(pl, dict):
            die(f"pipeline must be an object of PipelineConfig fields, "
                f"got {pl!r}")
        fields = {f.name for f in dataclasses.fields(PipelineConfig)}
        bad = sorted(set(pl) - fields)
        if bad:
            die(f"pipeline: unknown field(s) {bad}; known: {sorted(fields)}")
        try:
            pc = PipelineConfig(**pl)
        except (TypeError, ValueError) as e:
            die(f"pipeline: {e}")
        if pc.enabled and p.get("n_seq", 1) > 1:
            die("pipeline needs the plain split runtime (n_seq == 1); the "
                "stage x seq runtime overlaps hops with its ring rotation")
        if pc.enabled and "batching" in p:
            ms = p["batching"].get("max_slots", 4)
            if ms % pc.num_microbatches:
                die(f"batching.max_slots {ms} must be a multiple of "
                    f"pipeline.num_microbatches {pc.num_microbatches}")
        if pc.enabled and "speculative" in p:
            sp_on = p["speculative"].get("enabled", True)
            if sp_on:
                die("pipeline + speculative: the spec loop verifies one "
                    "stream at a time (B == 1), leaving nothing to "
                    "micro-batch — drop one of the two blocks")
        if pc.enabled and p.get("kv_at_rest", {}).get("codec", "fp") != "fp":
            # mirror of _paged_decode_fns_quant's refusal: the µ-batch
            # trash-page routing has no quant twin
            die("kv_at_rest + pipeline: quantized paged decode composes "
                "with the unpipelined split runtime only — drop 'pipeline' "
                "or use codec 'fp'")
    if "speculative" in p:
        from .serve.speculative import SpecConfig

        if exp != "serve":
            die("speculative only applies to experiment 'serve'")
        if "cuts" not in p:
            die("speculative decode verifies across the boundary — add "
                "'cuts'/'hop_codecs'")
        sp = p["speculative"]
        if not isinstance(sp, dict):
            die(f"speculative must be an object of SpecConfig fields, "
                f"got {sp!r}")
        fields = {f.name for f in dataclasses.fields(SpecConfig)}
        bad = sorted(set(sp) - fields)
        if bad:
            die(f"speculative: unknown field(s) {bad}; "
                f"known: {sorted(fields)}")
        try:
            sc = SpecConfig(**sp)
        except (TypeError, ValueError) as e:
            die(f"speculative: {e}")
        if sc.enabled and p.get("fused_hops") == "remote":
            # forcing remote fusion skips the probe; the k-token verify
            # shape has no measured win yet, so refuse until probed
            die("speculative + fused_hops 'remote': forced remote fusion is "
                "unprobed at the k-token verify shape — use 'auto' or 'off'")
        if sc.enabled and "batching" in p:
            die("speculative runs the one-stream spec loop; the batcher's "
                "ragged step verifies one token per slot — drop "
                "'speculative' or 'batching'")
    if "cluster" in p:
        from .serve.cluster import (AutoscalerConfig, ClusterConfig,
                                    RespawnConfig)
        from .serve.overload import BreakerConfig, RetryBudgetConfig

        if exp != "serve":
            die("cluster only applies to experiment 'serve'")
        if "batching" not in p:
            die("cluster replicas each run the continuous batcher — add a "
                "'batching' block")
        if "speculative" in p:
            die("cluster + speculative: the spec loop is single-stream with "
                "no replica routing story — drop one of the two blocks")
        cl = p["cluster"]
        if not isinstance(cl, dict):
            die(f"cluster must be an object of ClusterConfig fields, "
                f"got {cl!r}")
        top = {f.name for f in dataclasses.fields(ClusterConfig)}
        bad = sorted(set(cl) - top)
        if bad:
            die(f"cluster: unknown field(s) {bad}; known: {sorted(top)}")
        for key, cls in (("breaker", BreakerConfig),
                         ("retry_budget", RetryBudgetConfig),
                         ("respawn", RespawnConfig),
                         ("autoscaler", AutoscalerConfig)):
            if key not in cl:
                continue
            if not isinstance(cl[key], dict):
                die(f"cluster.{key} must be an object of {cls.__name__} "
                    f"fields, got {cl[key]!r}")
            fields = {f.name for f in dataclasses.fields(cls)}
            bad = sorted(set(cl[key]) - fields)
            if bad:
                die(f"cluster.{key}: unknown field(s) {bad}; "
                    f"known: {sorted(fields)}")
        try:
            ccfg = _cluster_config(cl)
        except (TypeError, ValueError) as e:
            die(f"cluster: {e}")
        if ccfg.num_replicas < 2:
            die(f"cluster.num_replicas must be >= 2 (a one-replica cluster "
                f"is the plain serve front — drop the 'cluster' block), "
                f"got {ccfg.num_replicas}")
        if (p.get("serving", {}).get("soak") or {}).get(
                "kill_stage") is not None:
            die("cluster + serving.soak.kill_stage: the stage kill is the "
                "single-front chaos hook — replica kills belong to the "
                "router (ClusterFront.kill_replica, exercised by the "
                "cluster tests/bench)")
    if "disagg" in p:
        from .codecs.faults import FaultConfig
        from .codecs.fec import FECConfig, HedgeConfig
        from .serve.disagg import DisaggConfig

        if exp != "serve":
            die("disagg only applies to experiment 'serve'")
        if "speculative" in p:
            die("disagg + speculative: the spec loop is single-stream with "
                "no prefill/decode split story — drop one of the two blocks")
        if "batching" not in p:
            die("disagg splits the continuous batcher into prefill and "
                "decode workers — add a 'batching' block")
        dg = p["disagg"]
        if not isinstance(dg, dict):
            die(f"disagg must be an object of DisaggConfig fields, "
                f"got {dg!r}")
        top = {f.name for f in dataclasses.fields(DisaggConfig)}
        bad = sorted(set(dg) - top)
        if bad:
            die(f"disagg: unknown field(s) {bad}; known: {sorted(top)}")
        for key, cls in (("fec", FECConfig), ("hedge", HedgeConfig),
                         ("faults", FaultConfig)):
            if dg.get(key) is None:
                continue
            if not isinstance(dg[key], dict):
                die(f"disagg.{key} must be an object of {cls.__name__} "
                    f"fields, got {dg[key]!r}")
            fields = {f.name for f in dataclasses.fields(cls)}
            bad = sorted(set(dg[key]) - fields)
            if bad:
                die(f"disagg.{key}: unknown field(s) {bad}; "
                    f"known: {sorted(fields)}")
        try:
            _disagg_config(dg)
        except (TypeError, ValueError) as e:
            die(f"disagg: {e}")
    if "gray" in p:
        from .serve.cluster import GrayConfig

        if exp != "serve":
            die("gray only applies to experiment 'serve'")
        if "cluster" not in p:
            die("gray hardening (straggler demotion, request hedging) is a "
                "router policy — add a 'cluster' block")
        gy = p["gray"]
        if not isinstance(gy, dict):
            die(f"gray must be an object of GrayConfig fields, got {gy!r}")
        top = {f.name for f in dataclasses.fields(GrayConfig)}
        bad = sorted(set(gy) - top)
        if bad:
            die(f"gray: unknown field(s) {bad}; known: {sorted(top)}")
        try:
            _gray_config(gy)
        except (TypeError, ValueError) as e:
            die(f"gray: {e}")


def _pipeline_config(p: dict):
    """Build the :class:`PipelineConfig` a ``"pipeline"`` params block
    describes (None when absent) — validated by :func:`_validate_params_json`
    before anything touches devices."""
    if "pipeline" not in p:
        return None
    from .parallel.split import PipelineConfig

    return PipelineConfig(**p["pipeline"])


def _serve_front_config(sv: dict):
    """Build the :class:`ServeFrontConfig` a ``"serving"`` params block
    describes: nested objects become the matching sub-configs, scalar keys
    pass through, and the soak definition (``"soak"``) is the harness's,
    not the front's. Raises ``TypeError``/``ValueError`` on bad fields —
    the validator turns those into field-naming ``die()``s."""
    from .serve.frontend import ServeFrontConfig
    from .serve.overload import (AdmissionConfig, BreakerConfig,
                                 BrownoutConfig, RetryBudgetConfig)

    kwargs = {k: v for k, v in sv.items() if k != "soak"}
    for key, cls in (("admission", AdmissionConfig),
                     ("breaker", BreakerConfig),
                     ("brownout", BrownoutConfig),
                     ("retry_budget", RetryBudgetConfig)):
        if key in kwargs:
            kwargs[key] = cls(**kwargs[key])
    return ServeFrontConfig(**kwargs)


def _cluster_config(cl: dict):
    """Build the :class:`ClusterConfig` a ``"cluster"`` params block
    describes — nested policy objects (breaker, retry budget, respawn
    backoff, autoscaler bounds) become the matching sub-configs. Raises
    ``TypeError``/``ValueError``/``ClusterConfigError`` on bad fields; the
    validator turns those into field-naming ``die()``s."""
    from .serve.cluster import AutoscalerConfig, ClusterConfig, RespawnConfig
    from .serve.overload import BreakerConfig, RetryBudgetConfig

    kwargs = dict(cl)
    for key, cls in (("breaker", BreakerConfig),
                     ("retry_budget", RetryBudgetConfig),
                     ("respawn", RespawnConfig),
                     ("autoscaler", AutoscalerConfig)):
        if key in kwargs:
            kwargs[key] = cls(**kwargs[key])
    return ClusterConfig(**kwargs)


def _disagg_config(dg: dict):
    """Build the :class:`DisaggConfig` a ``"disagg"`` params block
    describes — nested migration-ladder objects (``fec``, ``hedge``,
    ``faults``) become the matching codec configs. Raises
    ``TypeError``/``ValueError`` on bad fields; the validator turns those
    into field-naming ``die()``s."""
    from .codecs.faults import FaultConfig
    from .codecs.fec import FECConfig, HedgeConfig
    from .serve.disagg import DisaggConfig

    kwargs = dict(dg)
    for key, cls in (("fec", FECConfig), ("hedge", HedgeConfig),
                     ("faults", FaultConfig)):
        if kwargs.get(key) is not None:
            kwargs[key] = cls(**kwargs[key])
    return DisaggConfig(**kwargs)


def _gray_config(gy: dict):
    """Build the :class:`GrayConfig` a ``"gray"`` params block describes —
    flat scalar fields only (the straggler/hedge thresholds). Raises
    ``TypeError``/``ValueError``/``ClusterConfigError`` on bad fields; the
    validator turns those into field-naming ``die()``s. A params block that
    is present but does not say otherwise is armed: configs opt in by
    writing the block at all, so ``enabled`` defaults to True here (the
    dataclass default False serves programmatic construction)."""
    from .serve.cluster import GrayConfig

    kwargs = dict(gy)
    kwargs.setdefault("enabled", True)
    return GrayConfig(**kwargs)


def _attach_front_obs(front) -> None:
    """Point the live endpoint's ``/healthz`` at this serve front (breaker
    states, brownout level, queue depth) when ``--obs-port`` or the params
    ``obs_port`` armed one — the global server starts before the front
    exists, so the front attaches itself here."""
    from .obs.server import get_global

    srv = get_global()
    if srv is not None:
        srv.health_fn = front.health_summary


def _print_serve_report(report: dict) -> None:
    """Human-readable tail for ``--serve-report``: outcome counts,
    reject/shed reasons, per-breaker states, and the brownout/retry-budget
    posture after the soak."""
    print("serve report:")
    for k in sorted(report["outcomes"]):
        print(f"  outcome {k:<14} {report['outcomes'][k]}")
    for k in sorted(report.get("reasons", {})):
        print(f"  reason  {k:<28} {report['reasons'][k]}")
    for name, b in sorted(report["breakers"].items()):
        print(f"  breaker {name:<8} {b['state']:<9} opens={b['opens']} "
              f"failures={b['total_failures']}")
    bo = report["brownout"]
    print(f"  brownout level={bo['level']} mode={bo['mode']} "
          f"switches={bo['switches']} sheds={bo['sheds']}")
    rb = report["retry_budget"]
    print(f"  retry budget spent={rb['spent']} denied={rb['denied']} "
          f"available={rb['available']:.1f}")
    pf = report.get("prefix")
    if pf:
        print(f"  prefix  hits={pf['hits']} misses={pf['misses']} "
              f"hit_rate={pf['hit_rate']:.3f} "
              f"prefill_tokens_saved={pf['saved_tokens']}")
        print(f"  prefix  cow_forks={pf['cow_forks']} "
              f"shared_pages={pf['shared_pages']} "
              f"index_pages={pf['index_pages']} "
              f"evictions={pf['index_evictions']} "
              f"reclaimed={pf['reclaimed_pages']}")


def _print_fault_report(result: dict) -> None:
    """Human-readable tail for ``--fault-report``, routed through the obs
    metrics registry: link counters, link-health gauges, and recovery
    counters all land in one registry and print as ONE unified table
    (was three hand-formatted ones), plus the tier trail."""
    from .codecs.faults import flatten_counters
    from .obs.metrics import (MetricsRegistry, format_table,
                              record_link_counters, record_link_health,
                              record_recovery_counters)

    counters = result.get("link_counters")
    if not counters:
        print("fault report: no link counters recorded (faults were off)")
        return
    reg = MetricsRegistry(enabled=True)
    record_link_counters(counters, registry=reg)
    for k, total in flatten_counters(counters).items():
        reg.counter(f"edgellm_link_{k}_total").inc(total, hop="total")
    record_link_health(result.get("link_health"), registry=reg)
    record_recovery_counters((result.get("recovery") or {}).get("counters"),
                             registry=reg)
    print(format_table(reg, title="fault report (obs metrics registry)"))
    if result.get("tier_switches"):
        print(f"  tier switches: {result['tier_switches']} "
              f"(final tier {result.get('final_tier', 0)}, "
              f"{result.get('degraded_chunks', 0)} degraded chunk(s))")


def _print_trace_report(tracer) -> None:
    """Human-readable tail for ``--trace-report``: one block per request id
    showing the span tree the host tracer recorded — wall time, TTFT (first
    span start -> end of prefill), nested span durations, and every boundary
    hop's {cut, codec, wire bytes, ladder outcome} attribution line. Spans
    without a request id (warmup, eval sweeps) are counted but not listed."""
    events = tracer.to_chrome_trace()["traceEvents"]
    by_rid: dict = {}
    unattributed = 0
    for ev in events:
        rid = (ev.get("args") or {}).get("rid")
        if rid is None:
            unattributed += 1
        else:
            by_rid.setdefault(str(rid), []).append(ev)
    if not by_rid:
        print(f"trace report: no request-attributed spans "
              f"({unattributed} unattributed span(s); tracing off, or "
              f"nothing was submitted)")
        return

    def _order(rid: str):
        # "r12" sorts numerically, anything else lexically after
        tail = rid.lstrip("r")
        return (0, int(tail), rid) if tail.isdigit() else (1, 0, rid)

    print(f"trace report: {len(by_rid)} request(s), "
          f"{sum(len(v) for v in by_rid.values())} attributed span(s)"
          + (f", {unattributed} unattributed" if unattributed else ""))
    for rid in sorted(by_rid, key=_order):
        evs = sorted(by_rid[rid], key=lambda e: (e["ts"], -e["dur"]))
        t0 = min(e["ts"] for e in evs)
        wall_ms = (max(e["ts"] + e["dur"] for e in evs) - t0) / 1e3
        prefill = [e for e in evs if e["name"] == "generate.prefill"]
        head = f"  {rid}: {wall_ms:.2f} ms wall"
        if prefill:
            head += (f", ttft "
                     f"{(prefill[0]['ts'] + prefill[0]['dur'] - t0) / 1e3:.2f}"
                     f" ms")
        print(head)
        open_until: list = []  # end timestamps of still-open ancestors
        for e in evs:
            while open_until and e["ts"] >= open_until[-1]:
                open_until.pop()
            pad = "    " + "  " * len(open_until)
            a = dict(e.get("args") or {})
            a.pop("rid", None)
            if e["name"] == "split.hop":
                line = (f"hop {a.pop('hop', '?')}: "
                        f"cut={a.pop('cut', '?')} codec={a.pop('codec', '?')}"
                        f" wire_bytes={a.pop('wire_bytes', '?')} "
                        f"outcome={a.pop('outcome', '?')}")
            else:
                line = f"{e['name']} {e['dur'] / 1e3:.2f} ms"
            if a:
                line += " " + " ".join(f"{k}={a[k]}" for k in sorted(a))
            print(pad + line)
            open_until.append(e["ts"] + e["dur"])


def main(argv=None) -> int:
    # --lint short-circuits before the parser: the graphlint gate needs no
    # params.json, and running it first means a contract violation is caught
    # before any experiment spends accelerator time (REPRODUCING §8)
    if "--lint" in (sys.argv[1:] if argv is None else argv):
        from .lint.__main__ import main as lint_main

        return lint_main(["--no-mypy"])
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--params", required=True, help="reference-style params.json")
    from .models import PRESETS

    ap.add_argument("--model", default="qwen2-0.5b", choices=sorted(PRESETS),
                    help="model preset")
    ap.add_argument("--corpus", help=".npy/.npz token ids or raw .txt (with --tokenizer); "
                                     "omitted -> synthetic corpus (smoke mode)")
    ap.add_argument("--tokenizer", help="local HF tokenizer path for raw-text corpora")
    ap.add_argument("--weights", help="local torch state_dict (.pt) or HF model dir; "
                                      "omitted -> random init (smoke mode)")
    ap.add_argument("--head-weights", help="LRP head weights .json (L x H) for weighted_importance")
    ap.add_argument("--output-dir", default=".")
    ap.add_argument("--max-chunks", type=int, help="stop after N chunks (smoke/CI)")
    ap.add_argument("--window-batch", type=int, default=8,
                    help="evaluation windows batched per forward in the token, "
                         "initial, channel, and split experiments (identical "
                         "accumulation; feeds the MXU; for split with a data "
                         "mesh axis, must be a multiple of its size)")
    ap.add_argument("--profile", metavar="DIR",
                    help="capture an XLA profiler trace of the experiment into "
                         "DIR (view with TensorBoard/Perfetto; includes "
                         "ppermute hops and Pallas codec kernels)")
    ap.add_argument("--checkpoint-every", type=int, default=1000)
    ap.add_argument("--deadline-s", type=float,
                    help="split experiment: per-chunk watchdog deadline in "
                         "seconds — a stalled eval writes a best-effort resume "
                         "checkpoint and exits with a typed DecodeTimeout "
                         "instead of hanging (overrides params.json "
                         "\"deadline\")")
    ap.add_argument("--metrics-out", metavar="PATH",
                    help="enable the obs metrics registry and write its final "
                         "snapshot to PATH after the experiment — Prometheus "
                         "text format for .prom/.txt, JSON otherwise "
                         "(REPRODUCING §10)")
    ap.add_argument("--trace-out", metavar="PATH",
                    help="enable host-side span tracing and write the Chrome "
                         "trace-event JSON to PATH (load at ui.perfetto.dev); "
                         "composes with --profile's XLA capture")
    ap.add_argument("--obs-port", type=int, metavar="PORT",
                    help="serve the live telemetry endpoint on "
                         "127.0.0.1:PORT for the duration of the run "
                         "(/metrics Prometheus text, /healthz JSON, "
                         "/snapshot.json, /trace Chrome JSON); 0 binds an "
                         "OS-assigned port, printed at startup; overrides "
                         "params.json observability.obs_port "
                         "(REPRODUCING §17)")
    ap.add_argument("--trace-report", action="store_true",
                    help="after the experiment, pretty-print per-request "
                         "span trees from the host tracer — wall time, TTFT, "
                         "and every boundary hop's {cut, codec, wire bytes, "
                         "ladder outcome} attribution; implies tracing")
    ap.add_argument("--serve-report", action="store_true",
                    help="serve experiment: after the soak, pretty-print the "
                         "outcome counts, reject/shed reasons, breaker "
                         "states, and the brownout/retry-budget posture")
    ap.add_argument("--fault-report", action="store_true",
                    help="split experiment: after the sweep, pretty-print the "
                         "summed per-hop link counters (detected / repaired / "
                         "retried / hedge wins / substituted), the tier trail, "
                         "and the link-health budget burn")
    ap.add_argument("--distributed", action="store_true",
                    help="join a multi-host run via jax.distributed.initialize() "
                         "before touching devices; split meshes become "
                         "slice-aware (stage/seq/model axes pinned within a "
                         "slice, only the data axis crosses DCN)")
    ap.add_argument("--lint", action="store_true",
                    help="run the graphlint static-analysis gate (AST rules "
                         "+ jaxpr contracts, python -m edgellm_tpu.lint) and "
                         "exit — handled before any other flag is required")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--synthetic-corpus-len", type=int, default=4096)
    args = ap.parse_args(argv)

    if args.distributed:
        from .parallel import initialize_distributed

        n_proc = initialize_distributed()
        print(f"distributed: process {__import__('jax').process_index()} "
              f"of {n_proc}", flush=True)

    if args.params.lstrip().startswith("{"):  # inline JSON (REPRODUCING.md)
        params_json = json.loads(args.params)
    else:
        with open(args.params) as f:
            params_json = json.load(f)
    _validate_params_json(params_json)

    def load_head_weights():
        if not args.head_weights:
            return None
        with open(args.head_weights) as f:
            return np.asarray(json.load(f))

    cfg, params = _load_model(args)
    corpus = _load_corpus(args, cfg.vocab_size)
    if corpus.max() >= cfg.vocab_size or corpus.min() < 0:
        raise SystemExit(f"corpus token ids outside [0, {cfg.vocab_size}) — wrong tokenizer?")
    os.makedirs(args.output_dir, exist_ok=True)
    out = lambda name: os.path.join(args.output_dir, name)

    import contextlib

    from .obs.tracing import trace_capture

    profile_cm = (trace_capture(args.profile) if args.profile
                  else contextlib.nullcontext())

    # --metrics-out / --trace-out arm the obs subsystem; a params.json
    # "observability" object picks the pillars (flags force their own pillar
    # on — asking for an output file implies wanting its contents)
    from . import obs

    obs_params = params_json.get("observability")
    if (args.metrics_out or args.trace_out or args.trace_report
            or args.obs_port is not None or obs_params is not None):
        ob_cfg = obs.ObservabilityConfig(**(obs_params or {}))
        if args.metrics_out or args.trace_out or args.trace_report:
            ob_cfg = dataclasses.replace(
                ob_cfg,
                metrics=ob_cfg.metrics or bool(args.metrics_out),
                tracing=(ob_cfg.tracing or bool(args.trace_out)
                         or args.trace_report))
        if args.obs_port is not None:
            try:
                ob_cfg = dataclasses.replace(ob_cfg, obs_port=args.obs_port)
            except ValueError as e:
                raise SystemExit(f"--obs-port: {e}")
        if ob_cfg.flight_recorder is True:
            # unnamed recorder: keep the post-mortems with the run's other
            # artifacts instead of littering the cwd
            ob_cfg = dataclasses.replace(
                ob_cfg,
                flight_recorder=os.path.join(args.output_dir,
                                             "flight_recorder"))
        obs.enable(ob_cfg)
        if ob_cfg.obs_port is not None:
            from .obs.server import get_global

            srv = get_global()
            if srv is not None:
                print(f"obs endpoint -> {srv.url}  "
                      f"(/metrics /healthz /snapshot.json /trace)",
                      flush=True)

    def _export_observability() -> None:
        if args.metrics_out:
            reg = obs.get_registry()
            text = (reg.to_prometheus()
                    if args.metrics_out.endswith((".prom", ".txt"))
                    else reg.to_json(indent=1))
            with open(args.metrics_out, "w") as f:
                f.write(text)
            print(f"metrics snapshot -> {args.metrics_out}", flush=True)
        if args.trace_out:
            obs.get_tracer().export(args.trace_out)
            print(f"chrome trace -> {args.trace_out}", flush=True)

    def _dispatch() -> int:
        experiment = params_json.get("experiment", "")
        methods = params_json.get("methods", [])
        max_length = params_json.get("max_length", cfg.max_position_embeddings)
        stride = params_json.get("stride", 32)
        common = dict(
            max_length=max_length, stride=stride,
            checkpoint_path=out("sweep_checkpoint.json"),
            checkpoint_every=args.checkpoint_every,
            metrics_path=out("metrics.jsonl"),
            max_chunks=args.max_chunks,
            window_batch=max(args.window_batch, 1),
        )

        if experiment == "relevance":
            try:
                from .importance.relevance import run_relevance_extraction
            except ImportError as e:
                raise SystemExit(f"relevance extraction unavailable: {e}") from e

            stats: dict = {}
            weights = run_relevance_extraction(
                cfg, params, corpus, max_length=max_length, stride=stride,
                max_chunks=args.max_chunks,
                window_batch=max(args.window_batch, 1),
                checkpoint_path=out("relevance_checkpoint.json"),
                checkpoint_every=args.checkpoint_every,
                metrics_path=out("relevance_metrics.jsonl"),
                stats=stats)
            with open(out("attention_head_weights.json"), "w") as f:
                json.dump(np.asarray(weights).tolist(), f)
            print(json.dumps({"artifact": out("attention_head_weights.json"),
                              "shape": list(np.asarray(weights).shape),
                              **stats}))
            return 0

        if experiment == "distances":
            from .analysis import (layer_importance_distributions,
                                   pairwise_layer_distances, save_heatmap)

            # per-sample forwards like the notebook's per-line loop: a multi-array
            # .npz is one sample per array; a flat corpus splits into
            # non-overlapping max_length windows
            if args.corpus and args.corpus.endswith(".npz"):
                data = np.load(args.corpus)
                samples = [np.asarray(data[f]).reshape(-1) for f in data.files]
                for i, s in enumerate(samples):  # _load_corpus only checked files[0]
                    if s.size and (s.max() >= cfg.vocab_size or s.min() < 0):
                        raise SystemExit(f"npz sample {i} has token ids outside "
                                         f"[0, {cfg.vocab_size}) — wrong tokenizer?")
            else:
                samples = [corpus[i:i + max_length]
                           for i in range(0, len(corpus), max_length)]
            samples = [s for s in samples if len(s) >= 2]
            if args.max_chunks:
                samples = samples[: args.max_chunks]
            # clipping to bucketed lengths is opt-in (params key "max_compiles"):
            # the notebook analyzes every sample at native length, and silent
            # clipping would change the JS values it claims to reproduce
            max_compiles = params_json.get("max_compiles")
            dists = layer_importance_distributions(
                cfg, params, samples, max_compiles=max_compiles)
            matrix = pairwise_layer_distances(dists)
            artifact = {"matrix": [[None if not np.isfinite(v) else float(v) for v in row]
                                   for row in matrix],
                        "n_samples": len(samples), "model": args.model,
                        "max_compiles": max_compiles,
                        "clipped": max_compiles is not None and
                        len({int(s.shape[0]) for s in samples}) > max_compiles}
            with open(out("layer_distances.json"), "w") as f:
                json.dump(artifact, f, indent=1)
            heatmap_path = out("layer_distances.png")
            save_heatmap(matrix, heatmap_path)
            print(json.dumps({"artifact": out("layer_distances.json"),
                              "heatmap": heatmap_path, "n_samples": len(samples),
                              "layers": matrix.shape[0]}))
            return 0

        if experiment == "serve":
            import jax
            import jax.numpy as jnp

            from .serve.decode import generate, generate_split
            from .serve.frontend import ServeFront
            from .serve.soak import SoakConfig, run_soak
            from .utils.clock import FakeClock

            sv = params_json["serving"]
            front_cfg = _serve_front_config(sv)
            soak = SoakConfig(**sv.get("soak", {}))
            clock = FakeClock()
            rt = None
            link_health = None
            if "cuts" in params_json:
                from .codecs.faults import FaultConfig, LinkPolicy
                from .codecs.fec import (FECConfig, HedgeConfig, LinkHealth,
                                         LinkHealthConfig)
                from .parallel import make_stage_mesh
                from .parallel.split import (PipelineConfig, SplitConfig,
                                             SplitRuntime)

                n_stages = len(params_json["cuts"]) + 1
                n_dev = len(jax.devices())
                if n_dev < n_stages:
                    raise SystemExit(
                        f"experiment 'serve' with {n_stages} pipeline stages "
                        f"needs >= {n_stages} devices, found {n_dev}")
                lp = params_json.get("link_policy")
                rt = SplitRuntime(
                    cfg,
                    SplitConfig(cuts=tuple(params_json["cuts"]),
                                hop_codecs=tuple(params_json["hop_codecs"])),
                    make_stage_mesh(n_stages),
                    faults=(FaultConfig(**params_json["faults"])
                            if "faults" in params_json else None),
                    policy=(LinkPolicy(**{**lp,
                                          "tiers": tuple(lp.get("tiers", ()))})
                            if lp else None),
                    fec=(FECConfig(**params_json["fec"])
                         if "fec" in params_json else None),
                    hedge=(HedgeConfig(**params_json["hedge"])
                           if "hedge" in params_json else None),
                    pipeline=(PipelineConfig(**params_json["pipeline"])
                              if "pipeline" in params_json else None))
                if "link_health" in params_json:
                    link_health = LinkHealth(
                        config=LinkHealthConfig(**params_json["link_health"]),
                        clock=clock)
            if "batching" in params_json:
                # continuous-batching path: the front routes every admitted
                # request through ONE paged batcher event loop instead of
                # serial per-request generate calls (REPRODUCING §13); with
                # "cuts" the ragged step runs through the split pipeline's
                # quantized boundary hops (SplitRuntime.decode_step_paged)
                from .serve.batching import BatchingConfig, ContinuousBatcher
                from .serve.frontend import Request

                prefix_kw = {}
                if "prefix_cache" in params_json:
                    from .models.paged_kv import PrefixCacheConfig

                    prefix_kw = dict(prefix_cache=PrefixCacheConfig(
                        **params_json["prefix_cache"]))
                batching_json = dict(params_json["batching"])
                if "kv_at_rest" in params_json:
                    # the at-rest tier rides the batcher pool; with
                    # "pool_bytes" the page count is re-derived from the
                    # byte budget — quantized rows are smaller, so the same
                    # HBM holds more pages (the capacity multiplier)
                    from .models.paged_kv import num_pages_for_bytes

                    kq = params_json["kv_at_rest"]
                    prefix_kw["kv_codec"] = kq["codec"]
                    if "pool_bytes" in kq:
                        batching_json["num_pages"] = num_pages_for_bytes(
                            cfg, kq["pool_bytes"],
                            batching_json.get("page_size", 16),
                            kv_codec=kq["codec"])
                bcfg = BatchingConfig(**batching_json, **prefix_kw)
                split_kw = {}
                if rt is not None:
                    split_kw = dict(split_runtime=rt,
                                    placed_params=rt.place_params(params))
                dcfg = (_disagg_config(params_json["disagg"])
                        if "disagg" in params_json else None)

                def make_batcher():
                    # the disaggregated front mirrors the batcher surface
                    # (submit/run/report/discard), so everything downstream —
                    # ServeFront.drain_batched, the cluster replica factory —
                    # is agnostic to which one it drives
                    if dcfg is not None:
                        from .serve.disagg import DisaggServer

                        return DisaggServer(cfg, params, bcfg, dcfg,
                                            clock=clock, **split_kw)
                    return ContinuousBatcher(cfg, params, bcfg, **split_kw)

                if "cluster" in params_json:
                    # replica-router path (REPRODUCING §20): N continuous-
                    # batching fronts behind prefix-affinity placement; every
                    # replica shares the (already-compiled) step plan, so one
                    # warm run heats the whole fleet's jit cache
                    from .obs.metrics import record_cluster_stats
                    from .serve.cluster import ClusterFront
                    from .serve.frontend import Request

                    ccfg = _cluster_config(params_json["cluster"])
                    if "gray" in params_json:
                        ccfg = dataclasses.replace(
                            ccfg,
                            gray=_gray_config(params_json["gray"]))

                    def replica_factory(replica_id, generation):
                        return ServeFront(cfg, params, config=front_cfg,
                                          clock=clock, batcher=make_batcher())

                    cluster = ClusterFront(replica_factory, ccfg,
                                           clock=clock)
                    _attach_front_obs(cluster)
                    warm = ContinuousBatcher(cfg, params, bcfg, **split_kw)
                    warm.submit(np.ones((soak.prompt_len,), np.int32), 2)
                    warm.run()
                    rng = np.random.default_rng(soak.seed)
                    gaps = rng.exponential(1.0 / soak.arrival_rate,
                                           size=soak.n_requests)
                    shared_pfx = (rng.integers(
                        1, cfg.vocab_size,
                        size=soak.shared_prefix_len).astype(np.int32)
                        if soak.shared_prefix_len else None)
                    records = []
                    for i in range(soak.n_requests):
                        clock.advance(float(gaps[i]))
                        pi = rng.integers(1, cfg.vocab_size,
                                          size=soak.prompt_len
                                          ).astype(np.int32)
                        if shared_pfx is not None:
                            pi[:soak.shared_prefix_len] = shared_pfx
                        cluster.submit(Request(
                            prompt_ids=pi,
                            max_new_tokens=soak.max_new_tokens,
                            temperature=soak.temperature,
                            deadline_s=soak.deadline_s, rng_seed=i))
                    while True:
                        recs = cluster.drain()
                        if not recs:
                            break
                        records.extend(recs)
                    rep = cluster.report()
                    record_cluster_stats(rep)
                    outcomes = {}
                    for rec in records:
                        outcomes[rec.outcome] = (
                            outcomes.get(rec.outcome, 0) + 1)
                    artifact = {
                        "requests": len(records), "outcomes": outcomes,
                        "mode": (("disagg_" if dcfg is not None else "")
                                 + ("cluster_batched_split" if rt is not None
                                    else "cluster_batched")),
                        "cluster": rep,
                        "records": [r.as_dict() for r in records]}
                    with open(out("cluster_report.json"), "w") as f:
                        json.dump(artifact, f, indent=1, default=float)
                    print(json.dumps({
                        "requests": len(records), "outcomes": outcomes,
                        "mode": artifact["mode"],
                        "replicas": len(rep["replicas"]),
                        "placements": rep["totals"],
                        "artifact": out("cluster_report.json")},
                        default=float))
                    if cluster.pending:
                        raise SystemExit(
                            f"cluster drain left {cluster.pending} accepted "
                            f"request(s) unterminated — the router lost "
                            f"work: {rep}")
                    return 0
                batcher = make_batcher()
                front = ServeFront(cfg, params, config=front_cfg,
                                   clock=clock, batcher=batcher)
                _attach_front_obs(front)
                # warm the ragged step + the soak's prefill shape so compile
                # time never lands on a request's service clock
                warm = ContinuousBatcher(cfg, params, bcfg, **split_kw)
                warm.submit(np.ones((soak.prompt_len,), np.int32), 2)
                warm.run()
                rng = np.random.default_rng(soak.seed)
                gaps = rng.exponential(1.0 / soak.arrival_rate,
                                       size=soak.n_requests)
                # with shared_prefix_len every request opens with the SAME
                # seeded token block (a system prompt) — the workload the
                # prefix index turns into mapped pages instead of prefill
                shared_pfx = (rng.integers(
                    1, cfg.vocab_size,
                    size=soak.shared_prefix_len).astype(np.int32)
                    if soak.shared_prefix_len else None)
                for i in range(soak.n_requests):
                    clock.advance(float(gaps[i]))
                    pi = rng.integers(1, cfg.vocab_size,
                                      size=soak.prompt_len).astype(np.int32)
                    if shared_pfx is not None:
                        pi[:soak.shared_prefix_len] = shared_pfx
                    front.submit(Request(
                        prompt_ids=pi,
                        max_new_tokens=soak.max_new_tokens,
                        temperature=soak.temperature,
                        deadline_s=soak.deadline_s, rng_seed=i))
                records = front.drain_batched()
                rep = batcher.report()
                outcomes: dict = {}
                for rec in records:
                    outcomes[rec.outcome] = outcomes.get(rec.outcome, 0) + 1
                artifact = {"requests": len(records), "outcomes": outcomes,
                            "mode": (("disagg_" if dcfg is not None else "")
                                     + ("batched_split" if rt is not None
                                        else "batched")),
                            "batcher": rep,
                            "records": [r.as_dict() for r in records]}
                with open(out("serve_report.json"), "w") as f:
                    json.dump(artifact, f, indent=1, default=float)
                pf = rep.get("prefix")
                print(json.dumps({
                    "requests": len(records), "outcomes": outcomes,
                    "mode": artifact["mode"],
                    "batched_steps": rep["steps"],
                    "jit_misses": rep["jit_misses"],
                    "occupancy_mean": round(rep["alloc_util_mean"], 4),
                    "decode_tokens_per_s": round(
                        rep["decode_tokens_per_s"], 3),
                    **({"prefix_hit_rate": round(pf["hit_rate"], 4),
                        "prefill_tokens_saved": pf["saved_tokens"]}
                       if pf else {}),
                    **({"disagg_migrations": rep["disagg"]["migrations"],
                        "disagg_degraded": rep["disagg"]["degraded"]}
                       if rep.get("disagg") else {}),
                    "artifact": out("serve_report.json")}))
                if args.serve_report:
                    _print_serve_report(front.report())
                if pf and soak.shared_prefix_len and not pf["hits"]:
                    # the config promised a shared system prompt: an index
                    # that never hit means the sharing plane is broken, not
                    # that the workload had nothing to share
                    raise SystemExit(
                        f"prefix cache enabled with shared_prefix_len="
                        f"{soak.shared_prefix_len} but the radix index "
                        f"never hit: {pf}")
                return 0
            spec = None
            if "speculative" in params_json:
                from .serve.speculative import SpecConfig

                spec = SpecConfig(**params_json["speculative"])
            front = ServeFront(cfg, params, split_runtime=rt,
                               config=front_cfg, link_health=link_health,
                               clock=clock, speculative=spec)
            _attach_front_obs(front)
            # pre-warm the jit caches for the soak's one (batch, capacity)
            # plan: the virtual clock advances by measured service time, and
            # folding tens of compile-seconds into the first request would
            # distort every arrival after it
            cr = front_cfg.capacity_round
            capacity = -(-(soak.prompt_len + soak.max_new_tokens) // cr) * cr
            warm_ids = jnp.zeros((1, soak.prompt_len), jnp.int32)
            warm_kw = dict(capacity=capacity, temperature=soak.temperature,
                           rng_key=jax.random.key(0))
            generate(cfg, params, warm_ids, soak.max_new_tokens, **warm_kw)
            if rt is not None:
                if spec is not None and spec.enabled:
                    # the front bumps capacity the same way for spec bursts
                    warm_kw["capacity"] = max(
                        capacity, soak.prompt_len + soak.max_new_tokens
                        + spec.k - 2)
                generate_split(rt, rt.place_params(params), warm_ids,
                               soak.max_new_tokens, speculative=spec,
                               raw_params=params, **warm_kw)
            artifact = run_soak(front, soak, clock=clock)
            with open(out("serve_report.json"), "w") as f:
                json.dump(artifact, f, indent=1, default=float)
            print(json.dumps({
                "requests": artifact["requests"],
                "outcomes": artifact["outcomes"],
                "goodput_tokens_per_s": round(
                    artifact["goodput_tokens_per_s"], 3),
                "slo_attainment": artifact["slo_attainment"],
                "p99_ttft_s": artifact["p99_ttft_s"],
                "token_identity_ok": (artifact["token_identity"] or
                                      {}).get("ok"),
                "artifact": out("serve_report.json")}, default=float))
            if args.serve_report:
                _print_serve_report(artifact["report"])
            return 0

        from .eval import run_token_sweep, run_initial_sweep, run_channel_sweep

        if experiment == "split":
            from .eval import run_split_eval
            from .parallel import make_stage_mesh

            # optional extra mesh axes: "n_data" shards the window batch
            # (window_batch must be a multiple), "n_model" tensor-parallelizes
            # each stage; default is one device per pipeline stage
            mesh = None
            n_stages = len(params_json["cuts"]) + 1
            if params_json.get("n_seq", 1) > 1 and (
                    params_json.get("n_data", 1) > 1
                    or params_json.get("n_model", 1) > 1):
                raise SystemExit(
                    "n_seq composes the pipeline with sequence sharding only; "
                    "combining it with n_data/n_model is not supported")
            if args.distributed:
                # slice-aware layout: stage/seq/model within a slice, data across
                from .parallel import (make_multihost_sp_stage_mesh,
                                       make_multihost_stage_mesh)

                if params_json.get("n_seq", 1) > 1:
                    mesh = make_multihost_sp_stage_mesh(
                        n_stages, params_json["n_seq"])
                else:
                    mesh = make_multihost_stage_mesh(
                        n_stages, n_data=params_json.get("n_data"),
                        n_model=params_json.get("n_model", 1))
            elif (params_json.get("n_data", 1) > 1
                  or params_json.get("n_model", 1) > 1):
                mesh = make_stage_mesh(n_stages,
                                       n_data=params_json.get("n_data", 1),
                                       n_model=params_json.get("n_model", 1))
            result = run_split_eval(
                cfg, params, corpus,
                cuts=params_json["cuts"],
                hop_codecs=params_json["hop_codecs"],
                max_length=max_length, stride=stride,
                importance_method=params_json.get("importance_method"),
                head_weights=load_head_weights(),
                max_chunks=args.max_chunks,
                mesh=mesh,
                window_batch=max(args.window_batch, 1),
                n_seq=params_json.get("n_seq", 1),
                checkpoint_path=out("split_checkpoint.json"),
                checkpoint_every=args.checkpoint_every,
                metrics_path=out("split_metrics.jsonl"),
                faults=params_json.get("faults"),
                link_policy=params_json.get("link_policy"),
                fec=params_json.get("fec"),
                hedge=params_json.get("hedge"),
                link_health=params_json.get("link_health"),
                deadline_s=(args.deadline_s if args.deadline_s is not None
                            else params_json.get("deadline")),
                stage_failure=params_json.get("stage_failure"),
                recovery=params_json.get("recovery"),
                pipeline=_pipeline_config(params_json))
            with open(out("split_eval_results.json"), "w") as f:
                json.dump(result, f, indent=1)
            print(json.dumps(result))
            if args.fault_report:
                _print_fault_report(result)
            return 0

        if experiment == "initial":
            result = run_initial_sweep(
                cfg, params, corpus, layers_of_interest=params_json["layers_of_interest"],
                ratios=params_json["ratios"], **common)
        elif methods and "channel" in methods[0]:
            result = run_channel_sweep(
                cfg, params, corpus, methods=methods,
                layers_of_interest=params_json["layers_of_interest"], **common)
        else:
            head_weights = load_head_weights()
            if head_weights is None and "weighted_importance" in methods:
                raise SystemExit("weighted_importance requires --head-weights "
                                 "(produce it with experiment: \"relevance\")")
            import jax

            if jax.default_backend() == "tpu" and common["window_batch"] > 1:
                # a real TPU OOM poisons the process allocator; pre-shrink the
                # window batch by AOT memory analysis (no allocation) so big
                # real-corpus runs degrade instead of dying (bench.py does the
                # same)
                from .tools.wb_preflight import preflight_token_sweep_batch

                wb = preflight_token_sweep_batch(
                    cfg, common["window_batch"], max_length=max_length,
                    stride=stride,
                    layers_of_interest=params_json["layers_of_interest"],
                    ratios=params_json["ratios"],
                    dtype=next(iter(jax.tree_util.tree_leaves(params))).dtype)
                if wb != common["window_batch"]:
                    print(f"window_batch {common['window_batch']} exceeds the "
                          f"memory budget; running at {wb}", flush=True)
                    common["window_batch"] = wb
            result = run_token_sweep(
                cfg, params, corpus, methods=methods or ["regular_importance"],
                layers_of_interest=params_json["layers_of_interest"],
                ratios=params_json["ratios"], head_weights=head_weights, **common)

        with open(out("avg_ppl_results.json"), "w") as f:
            json.dump(result.to_json(), f, indent=1)
        print(result.table())
        print(json.dumps({"chunks": result.chunks, "n_tokens": result.n_tokens,
                          "wall_s": round(result.wall_s, 3),
                          "ppl": np.round(result.ppl(), 4).tolist()}))
        return 0

    # fused_hops maps onto the EDGELLM_FUSED_HOP gate BEFORE any runtime is
    # built (SplitRuntime resolves its fused plans at construction):
    # "auto" leaves the measured-win default, "off" pins the pre-fusion
    # graph, "wire"/"remote" force a mode (remote still refuses off-TPU)
    fused_hops = params_json.get("fused_hops")
    if fused_hops == "auto":
        os.environ.pop("EDGELLM_FUSED_HOP", None)
    elif fused_hops is not None:
        os.environ["EDGELLM_FUSED_HOP"] = \
            {"off": "0", "wire": "wire", "remote": "remote"}[fused_hops]

    with profile_cm:
        try:
            return _dispatch()
        finally:
            # export even when the experiment dies: a partial trace/snapshot
            # is exactly what a post-mortem needs
            _export_observability()
            if args.trace_report:
                _print_trace_report(obs.get_tracer())


if __name__ == "__main__":
    sys.exit(main())
