"""Ring-attention sequence parallelism: long contexts sharded across devices.

The reference handles its 299k-token corpus by clipping to 512/2048-token windows
(``Qwen2-0.5B/main.py:151-156``) — the window *is* the context limit. Here the
sequence axis itself shards across a ``"seq"`` mesh axis: every device holds the
full weights and 1/n of the tokens; attention is computed blockwise with K/V
blocks rotating around the ring via ``lax.ppermute`` (one hop per step, overlapped
by XLA with the local matmuls), with flash-style online-softmax accumulation so
no device ever materializes the full S x S score matrix. This is the standard
ring-attention construction (Liu et al.; see PAPERS.md) on XLA collectives
instead of NCCL P2P.

Composability: the "seq" axis is orthogonal to the split runtime's "stage" axis —
:class:`SplitRingRuntime` below pipeline-splits the layer stack AND ring-shards
the sequence on a ("stage", "seq") mesh, with per-token-compressed boundary hops
(tested equal to the dense forward in ``tests/test_ring.py``).

Everything is jit-safe: the ring loop is a ``lax.fori_loop`` with static block
shapes; the causal mask is computed from global block offsets.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.jax_compat import axis_size, shard_map

from ..models.configs import ModelConfig
from ..models.transformer import (
    apply_rotary, embed, precompute_rope, mlp, unembed, _layernorm, _rmsnorm,
)

NEG_INF = -1e30  # finite mask value: keeps exp() well-defined for empty blocks


def make_seq_mesh(n_seq: int, devices=None) -> Mesh:
    devices = np.asarray(devices if devices is not None else jax.devices())
    if devices.size < n_seq:
        raise ValueError(f"need {n_seq} devices, have {devices.size}")
    return Mesh(devices.reshape(-1)[:n_seq], ("seq",))


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str = "seq", capture_stats: bool = False,
                   kv_codec=None):
    """Causal ring attention over locally-sharded (B, S_loc, H, hd) query blocks.

    Must run inside ``shard_map`` with the sequence sharded on ``axis_name``.
    K/V blocks circulate the ring (device i sends to i+1); after n steps every
    query block has seen every key block once. Online softmax keeps running
    (max, denominator, accumulator) per query — the flash-attention recurrence.

    K/V may carry fewer (grouped-query) heads than Q: the unexpanded
    (B, S_loc, KV, hd) blocks are what circulates — h/kv times less ring
    traffic — and the head broadcast happens locally per step. The ring is
    statically unrolled (n is a trace-time constant), so XLA can overlap each
    hop's ppermute with the previous block's matmuls, and the last iteration
    sends nothing.

    ``capture_stats``: also return the reduced attention statistics the
    importance metrics consume (``AttnStats`` semantics, but sequence-sharded:
    each device ends holding the (B, H, S_loc) slice for ITS key block) —
    ``(col_sum / S, last_row)``. The column sums are accumulated during a
    second K rotation: exact per-key probabilities need the FINAL softmax max
    and denominator of every query row, which only exist after the first full
    rotation (a running column sum cannot be corrected retroactively — the
    per-query corrections collapse when summed over queries). The stats
    accumulators travel WITH the circulating K block and arrive back at its
    home device after n hops; the extra pass reuses the pass-1 scores math but
    skips the value matmul (~half an attention pass, only when stats are
    requested). Returns ``(out, (col_sum/S, last_row))`` with stats on,
    plain ``out`` otherwise (a bare array composes with shard_map out_specs).

    ``kv_codec`` (a batch-invariant :class:`~edgellm_tpu.codecs.packing.
    WireCodec`, opt-in) is the fused-quantized-collective trick applied to
    the ring's all-gather: each device encodes its home K/V blocks ONCE,
    the two packed payloads circulate as a single flat uint8 buffer (one
    ppermute per rotation step instead of one per K/V leaf), and every
    step dequantizes the arrived payload locally. Quantization happens
    exactly once per block — no per-hop re-encode, so error does not
    compound around the ring (EQuARX-style). Lossy by construction; None
    (the default) leaves the graph byte-identical to the uncompressed
    ring.
    """
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, hd = q.shape
    rep = h // k.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    q_pos = idx * s_loc + jnp.arange(s_loc)  # global positions of local queries

    m = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_loc), jnp.float32)
    acc = jnp.zeros((b, h, s_loc, hd), jnp.float32)
    k_blk, v_blk = k, v
    ring = [(i, (i + 1) % n) for i in range(n)]

    if kv_codec is not None:
        from ..codecs.wire_format import flatten_bytes, unflatten_bytes

        kv = k.shape[2]
        k_payload = kv_codec.encode(k.reshape(b, s_loc, kv * hd))
        v_payload = kv_codec.encode(v.reshape(b, s_loc, kv * hd))
        kv_spec = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            {"k": k_payload, "v": v_payload})
        kv_wire = flatten_bytes({"k": k_payload, "v": v_payload})

        def kv_decode(buf):
            p = unflatten_bytes(buf, kv_spec)
            dk = kv_codec.decode(p["k"]).reshape(b, s_loc, kv, hd)
            dv = kv_codec.decode(p["v"]).reshape(b, s_loc, kv, hd)
            return dk.astype(k.dtype), dv.astype(v.dtype)

    def scores_for(k_blk, src):
        k_pos = src * s_loc + jnp.arange(s_loc)
        k_t = jnp.repeat(k_blk, rep, axis=2) if rep > 1 else k_blk
        scores = jnp.einsum("bshd,bthd->bhst", q, k_t,
                            preferred_element_type=jnp.float32) * scale
        mask = q_pos[:, None] >= k_pos[None, :]  # global causal
        return jnp.where(mask[None, None], scores, NEG_INF), mask

    for t in range(n):
        src = (idx - t) % n  # which global block this K/V is
        if kv_codec is not None:
            # every device decodes the payload that just arrived; blocks were
            # quantized exactly once, at home, before the first rotation
            k_blk, v_blk = kv_decode(kv_wire)
        scores, mask = scores_for(k_blk, src)
        v_t = jnp.repeat(v_blk, rep, axis=2) if rep > 1 else v_blk
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        p = jnp.exp(scores - m_new[..., None]) * mask[None, None]
        correction = jnp.exp(m - m_new)
        l = l * correction + jnp.sum(p, axis=-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhst,bthd->bhsd", p, v_t.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        m = m_new
        if t < n - 1:
            if kv_codec is not None:
                # ONE ppermute per step over the packed buffer instead of one
                # per K/V leaf — the quantized-collective trick on the ring
                kv_wire = jax.lax.ppermute(kv_wire, axis_name, ring)
            else:
                k_blk = jax.lax.ppermute(k_blk, axis_name, ring)
                v_blk = jax.lax.ppermute(v_blk, axis_name, ring)

    out = acc / jnp.maximum(l, 1e-30)[..., None]  # (B, H, S_loc, hd)
    out = jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)
    if not capture_stats:
        return out

    # second rotation: exact probabilities from the now-final (m, l); the
    # (B, H, S_loc) column-sum / last-row accumulators ride the ring with
    # their K block and land home after n hops
    l_safe = jnp.maximum(l, 1e-30)
    k_blk = k
    if kv_codec is not None:
        from ..codecs.wire_format import flatten_bytes, unflatten_bytes
        kv = k.shape[2]
        k_spec = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), k_payload)
        # rebuild a K-only wire buffer from the home payload saved in pass 1;
        # the f32 accumulators stay raw — they carry exact statistics
        k_wire = flatten_bytes(k_payload)
    col_acc = jnp.zeros((b, h, s_loc), jnp.float32)
    last_acc = jnp.zeros((b, h, s_loc), jnp.float32)
    is_last = (idx == n - 1)  # device holding the globally-last query row
    for t in range(n):
        src = (idx - t) % n
        if kv_codec is not None:
            k_blk = kv_codec.decode(unflatten_bytes(k_wire, k_spec)) \
                .reshape(b, s_loc, kv, hd).astype(k.dtype)
        scores, mask = scores_for(k_blk, src)
        probs = jnp.exp(scores - m[..., None]) * mask[None, None] \
            / l_safe[..., None]  # (B, H, S_loc_q, S_loc_k), exact
        col_acc = col_acc + jnp.sum(probs, axis=2)
        last_acc = last_acc + jnp.where(is_last, probs[:, :, -1, :], 0.0)
        # permute on EVERY step (unlike pass 1) so block and accumulators
        # complete the full circle back to the block's home device
        if kv_codec is not None:
            k_wire = jax.lax.ppermute(k_wire, axis_name, ring)
        else:
            k_blk = jax.lax.ppermute(k_blk, axis_name, ring)
        col_acc = jax.lax.ppermute(col_acc, axis_name, ring)
        last_acc = jax.lax.ppermute(last_acc, axis_name, ring)
    s_total = n * s_loc
    return out, (col_acc / s_total, last_acc)


def _sp_attention(cfg: ModelConfig, lp: dict, x, cos_loc, sin_loc, axis_name,
                  capture_stats: bool = False, kv_codec=None):
    """Per-layer attention with ring communication; x is (B, S_loc, D)."""
    b, s_loc, d = x.shape
    h, kv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    q = (x @ lp["wq"]).reshape(b, s_loc, h, hd)
    k = (x @ lp["wk"]).reshape(b, s_loc, kv, hd)
    v = (x @ lp["wv"]).reshape(b, s_loc, kv, hd)
    if "bq" in lp:
        q = q + lp["bq"].reshape(h, hd)
        k = k + lp["bk"].reshape(kv, hd)
        v = v + lp["bv"].reshape(kv, hd)
    q = apply_rotary(q, cos_loc, sin_loc, cfg.rotary_dim)
    k = apply_rotary(k, cos_loc, sin_loc, cfg.rotary_dim)
    # GQA: the unexpanded KV-head blocks circulate the ring; ring_attention
    # broadcasts heads locally per step
    if capture_stats:
        out, stats = ring_attention(q, k, v, axis_name, capture_stats=True,
                                    kv_codec=kv_codec)
    else:
        out, stats = ring_attention(q, k, v, axis_name,
                                    kv_codec=kv_codec), None
    out = out.reshape(b, s_loc, h * hd) @ lp["wo"]
    if "bo" in lp:
        out = out + lp["bo"]
    return out, stats


def _sp_block(cfg: ModelConfig, lp: dict, hidden, cos_loc, sin_loc, axis_name,
              capture_stats: bool = False, kv_codec=None):
    """Decoder block with ring attention; norms/MLP are per-token (trivially SP).
    Returns ``(hidden, stats)`` — stats None unless ``capture_stats``."""
    if cfg.family == "gpt_neox":
        attn_in = _layernorm(hidden, lp["ln1_scale"], lp["ln1_bias"], cfg.norm_eps)
        attn_out, stats = _sp_attention(cfg, lp, attn_in, cos_loc, sin_loc,
                                        axis_name, capture_stats, kv_codec)
        mlp_in = _layernorm(hidden, lp["ln2_scale"], lp["ln2_bias"], cfg.norm_eps)
        return hidden + attn_out + mlp(cfg, lp, mlp_in), stats
    attn_in = _rmsnorm(hidden, lp["ln1_scale"], cfg.norm_eps)
    attn_out, stats = _sp_attention(cfg, lp, attn_in, cos_loc, sin_loc,
                                    axis_name, capture_stats, kv_codec)
    hidden = hidden + attn_out
    mlp_in = _rmsnorm(hidden, lp["ln2_scale"], cfg.norm_eps)
    return hidden + mlp(cfg, lp, mlp_in), stats


@functools.lru_cache(maxsize=None)
def _sp_forward(cfg: ModelConfig, mesh: Mesh, axis_name: str, kv_codec=None):
    @jax.jit
    def fn(params, input_ids):
        seq = input_ids.shape[1]
        if seq % mesh.shape[axis_name]:
            raise ValueError(f"sequence length {seq} not divisible by "
                             f"{axis_name} axis size {mesh.shape[axis_name]}")
        cos, sin = precompute_rope(cfg, seq)

        def body(params, ids_loc, cos_loc, sin_loc):
            hidden = embed(params, ids_loc)  # already ring-varying via ids_loc

            def scan_body(h, lp):
                out, _ = _sp_block(cfg, lp, h, cos_loc, sin_loc, axis_name,
                                   kv_codec=kv_codec)
                return out, None

            hidden, _ = jax.lax.scan(scan_body, hidden, params["layers"])
            return unembed(cfg, params, hidden)

        return shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(None, axis_name), P(axis_name), P(axis_name)),
            out_specs=P(None, axis_name),
        )(params, input_ids, cos, sin)

    return fn


def forward_sp(cfg: ModelConfig, params, input_ids, mesh: Mesh,
               axis_name: str = "seq", kv_codec=None) -> jnp.ndarray:
    """Sequence-parallel forward: ids (B, S) with S sharded over ``axis_name`` ->
    full fp32 logits. Weights replicated, activations 1/n per device, attention
    via the K/V ring. ``kv_codec`` (opt-in, lossy) compresses the circulating
    K/V blocks into a single packed wire buffer per rotation step — see
    :func:`ring_attention`."""
    return _sp_forward(cfg, mesh, axis_name, kv_codec)(
        params, jnp.asarray(input_ids))


@functools.lru_cache(maxsize=None)
def _sp_importance(cfg: ModelConfig, mesh: Mesh, method: str, axis_name: str):
    from ..models.transformer import AttnStats
    from ..importance import importance_per_layer

    @jax.jit
    def fn(params, input_ids, head_weights):
        seq = input_ids.shape[1]
        if seq % mesh.shape[axis_name]:
            raise ValueError(f"sequence length {seq} not divisible by "
                             f"{axis_name} axis size {mesh.shape[axis_name]}")
        cos, sin = precompute_rope(cfg, seq)

        def body(params, hw, ids_loc, cos_loc, sin_loc):
            hidden = embed(params, ids_loc)

            def scan_body(h, lp):
                out, stats = _sp_block(cfg, lp, h, cos_loc, sin_loc, axis_name,
                                       capture_stats=True)
                return out, stats

            _, (col, last) = jax.lax.scan(scan_body, hidden, params["layers"])
            stats = AttnStats(col_mean=col, last_row=last)  # (L, B, H, S_loc)
            # every metric is per-token over reduced stats, so the local
            # shard's importance slice is computable entirely locally
            return importance_per_layer(stats, method, hw)  # (L, B, S_loc)

        return shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(), P(None, axis_name), P(axis_name), P(axis_name)),
            out_specs=P(None, None, axis_name),
        )(params, head_weights, input_ids, cos, sin)

    return fn


def importance_sp(cfg: ModelConfig, params, input_ids, mesh: Mesh,
                  method: str, head_weights=None,
                  axis_name: str = "seq") -> jnp.ndarray:
    """Sequence-parallel importance: the (L, B, S) scores of
    ``importance_per_layer``, computed WITHOUT any device ever holding the
    full sequence — the attention statistics (column sums, last query row) are
    accumulated inside ``ring_attention``'s K rotation and stay sequence-
    sharded; so does the returned importance (a global array sharded on S).

    This is the long-context replacement for the dense stats forward the
    simulate harness uses (``eval/harness.py:_stats_forward``): same methods,
    same values (up to flash-vs-dense softmax roundoff), no O(S^2) buffer and
    no full-S activation anywhere.
    """
    if method == "weighted_importance" and head_weights is None:
        raise ValueError("weighted_importance requires head_weights (L, H)")
    hw = jnp.zeros((cfg.num_layers, cfg.num_heads), jnp.float32) \
        if head_weights is None else jnp.asarray(head_weights)
    return _sp_importance(cfg, mesh, method, axis_name)(
        params, jnp.asarray(input_ids), hw)


# ---------- stage x seq composition ----------


def make_sp_stage_mesh(n_stages: int, n_seq: int, devices=None) -> Mesh:
    """("stage", "seq") mesh: pipeline stages x ring-attention sequence shards."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    need = n_stages * n_seq
    if devices.size < need:
        raise ValueError(f"need {need} devices, have {devices.size}")
    return Mesh(devices.reshape(-1)[:need].reshape(n_stages, n_seq),
                ("stage", "seq"))


class SplitRingRuntime:
    """Pipeline-split forward with each stage's sequence ring-sharded.

    The composition claimed at the top of this module, made concrete: the layer
    stack is cut into stages along "stage" (stage-sharded parameter groups,
    boundary activations crossing by ``ppermute`` exactly like
    ``split.SplitRuntime``) while WITHIN every stage the sequence axis is
    sharded over "seq" and attention runs as the K/V ring. Boundary hops move
    each device's local 1/n_seq sequence shard — with a per-token wire codec,
    the compressed payload — so long contexts never gather onto one device at
    the cut either.

    Hop codecs must be per-token (``batch_invariant``) — their scales reduce
    only over the feature axis, so encoding a sequence shard locally is
    identical to encoding the full sequence — OR explicitly ring-aware
    (:class:`~edgellm_tpu.codecs.ring_codecs.RingWireCodec`): the selective
    mixed-precision codec runs under "seq" by agreeing on ordering and global
    scale across shards with small collectives (an all_gather of the per-token
    importance scalars + a pmax of the scale). Other batch/sequence-reducing
    codecs are rejected.
    """

    def __init__(self, cfg: ModelConfig, cuts, hop_codecs, mesh: Mesh,
                 faults=None, policy=None, fec=None, hedge=None):
        from .split import SplitConfig, apply_default_codec_backend
        from ..codecs.ring_codecs import RingWireCodec
        from ..codecs.faults import FaultConfig, FaultyLink, LinkPolicy

        self.cfg = cfg
        self.mesh = mesh
        self.faults = faults
        self.policy = policy if policy is not None else LinkPolicy()
        self.fec = fec
        self.hedge = hedge
        # same activation rule as SplitRuntime: zero rates build the exact
        # fault-free graph (a disabled FEC/hedge config traces the PR 2 hop)
        self._link = (FaultyLink(faults, self.policy, fec=fec, hedge=hedge)
                      if faults is not None and faults.enabled else None)
        self._counter_accum: list = []
        self._lost_stage = None
        self.split = SplitConfig(cuts=tuple(cuts), hop_codecs=tuple(hop_codecs))
        self.codecs = apply_default_codec_backend(list(self.split.hop_codecs))
        bad = [c.name for c in self.codecs
               if not c.batch_invariant and not isinstance(c, RingWireCodec)]
        if bad:
            raise ValueError(
                f"stage x seq hops need per-token or ring-aware codecs; {bad} "
                f"reduce over batch/sequence and would disagree across "
                f"sequence shards")
        missing = [a for a in ("stage", "seq") if a not in mesh.shape]
        if missing:
            raise ValueError(f"SplitRingRuntime needs a mesh with 'stage' and "
                             f"'seq' axes (got {tuple(mesh.shape)}, missing "
                             f"{missing}); build a ('stage', 'seq') mesh")
        if mesh.shape["stage"] != self.split.n_stages:
            raise ValueError(f"mesh has {mesh.shape['stage']} stages, split "
                             f"needs {self.split.n_stages}")
        for c in self.codecs:
            if isinstance(c, RingWireCodec) and (c.ring_axis != "seq"
                                                 or c.n_seq != mesh.shape["seq"]):
                raise ValueError(
                    f"ring codec {c.name} was built for axis "
                    f"{c.ring_axis!r} x{c.n_seq}, mesh has 'seq' "
                    f"x{mesh.shape['seq']}")
        self.bounds = self.split.stage_bounds(cfg.num_layers)
        self.stage_size = max(stop - start for start, stop in self.bounds)
        self._forward = self._build_forward()

    def mark_stage_lost(self, stage: int) -> None:
        """Same contract as ``SplitRuntime.mark_stage_lost``: subsequent
        forwards raise the typed ``StageLostError``. (Failover re-planning
        for the stage x seq composition is not implemented — the eval driver
        rejects ``stage_failure`` with ``n_seq > 1`` up front.)"""
        if not 0 <= stage < self.split.n_stages:
            raise ValueError(f"stage {stage} out of range for "
                             f"{self.split.n_stages} stages")
        self._lost_stage = stage

    def place_params(self, params: dict) -> dict:
        """Stage-shard the stacked layer groups, replicate the rest (same
        regrouping as the split runtime; no "model"/"data" axes here)."""
        from jax.sharding import NamedSharding

        from .split import regroup_layers

        groups, valid = regroup_layers(params["layers"], self.bounds, self.stage_size)
        stage_spec = NamedSharding(self.mesh, P("stage"))
        repl = NamedSharding(self.mesh, P())
        placed = {
            "layers": {k: jax.device_put(v, stage_spec) for k, v in groups.items()},
            "layers_valid": jax.device_put(valid, stage_spec),
        }
        for k, v in params.items():
            if k != "layers":
                placed[k] = jax.device_put(v, repl)
        return placed

    def _build_forward(self):
        from .split import run_pipeline_stages

        cfg, n_stages = self.cfg, self.split.n_stages
        codecs, mesh = self.codecs, self.mesh
        link = self._link

        def body(local_layers, local_valid, other, ids_loc, cos_loc, sin_loc,
                 hop_imps, fault_step=None):
            lv = {k: v[0] for k, v in local_layers.items()}
            valid = local_valid[0]
            hidden = embed(other, ids_loc)  # (B, S_loc, D), seq-sharded

            def scan_body(h, xs):
                lp, ok = xs
                out, _ = _sp_block(cfg, lp, h, cos_loc, sin_loc, "seq")
                return jnp.where(ok, out, h), None

            def run_stage(h):
                computed, _ = jax.lax.scan(scan_body, h, (lv, valid))
                return computed

            # the shared hop protocol moves each device's local seq shard
            # (per-token codecs encode shard-locally == full-sequence encode;
            # ring-aware selective codecs agree on ordering/scale via their
            # own small collectives over "seq")
            if link is None:
                hidden = run_pipeline_stages(n_stages, codecs, run_stage,
                                             hidden, hop_imps)
                return unembed(cfg, other, hidden)
            # each seq shard ships its OWN payload across the cut, so each
            # gets its own fault stream (fold the shard index into the key);
            # counters then sum over both axes — stage hops x seq shards
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.key(link.faults.seed),
                                   fault_step),
                jax.lax.axis_index("seq"))
            hidden, counters = run_pipeline_stages(
                n_stages, codecs, run_stage, hidden, hop_imps,
                link=link, fault_key=key)
            counters = {k: jax.lax.psum(v, "seq") for k, v in counters.items()}
            return unembed(cfg, other, hidden), counters

        @jax.jit
        def fn(placed, input_ids, hop_imps, fault_step=None):
            seq = input_ids.shape[1]
            if seq % mesh.shape["seq"]:
                raise ValueError(f"sequence length {seq} not divisible by seq "
                                 f"axis size {mesh.shape['seq']}")
            cos, sin = precompute_rope(cfg, seq)
            other = {k: v for k, v in placed.items()
                     if k not in ("layers", "layers_valid")}
            lspecs = jax.tree_util.tree_map(lambda _: P("stage"), placed["layers"])
            # importance shards ride the seq axis on the token dimension, like
            # the hidden: (n_hops, B, S) or (n_hops, S)
            imp_spec = P(None, None, "seq") if hop_imps.ndim == 3 else P(None, "seq")
            if link is None:
                return shard_map(
                    body, mesh=mesh,
                    in_specs=(lspecs, P("stage"), P(), P(None, "seq"), P("seq"),
                              P("seq"), imp_spec),
                    out_specs=P(None, "seq"),
                    check_vma=False,
                )(placed["layers"], placed["layers_valid"], other, input_ids,
                  cos, sin, hop_imps)
            return shard_map(
                body, mesh=mesh,
                in_specs=(lspecs, P("stage"), P(), P(None, "seq"), P("seq"),
                          P("seq"), imp_spec, P()),
                out_specs=(P(None, "seq"), P()),
                check_vma=False,
            )(placed["layers"], placed["layers_valid"], other, input_ids,
              cos, sin, hop_imps, fault_step)

        return fn

    def hop_bytes(self, batch: int, seq: int) -> list:
        """Measured payload bytes per hop for one (batch, seq, D) activation
        (sum over the ``n_seq`` local-shard payloads; see
        ``split.hop_payload_bytes``)."""
        from .split import hop_payload_bytes

        return hop_payload_bytes(self.codecs, self.cfg, batch, seq)

    def bytes_per_token(self, seq: int) -> list:
        """Per-hop boundary bytes per token (the BASELINE.json metric)."""
        return [b / seq for b in self.hop_bytes(1, seq)]

    def decode_hop_bytes(self, batch: int) -> list:
        """No per-token decode surface on the ring runtime (it is a
        whole-window forward) — nothing crosses a wire per decode step.
        Present so the runtime satisfies the
        :class:`~edgellm_tpu.obs.metrics.CounterSource` protocol."""
        return []

    def time_hops(self, batch: int, seq: int, iters: int = 20) -> list:
        """Per-hop transfer time (ms) with the probe activation seq-sharded the
        way the runtime's hops actually move it (each device sends its local
        shard in parallel)."""
        from .split import measure_hop_times

        if seq % self.mesh.shape["seq"]:
            raise ValueError(f"seq {seq} not divisible by the seq axis "
                             f"({self.mesh.shape['seq']})")
        return measure_hop_times(self.mesh, self.codecs, self.cfg, batch, seq,
                                 iters=iters, hidden_spec=P(None, "seq"))

    def forward(self, placed_params: dict, input_ids,
                hop_importance: Optional[list] = None,
                fault_step: int = 0) -> jnp.ndarray:
        """ids (B, S) -> full fp32 logits; layers stage-split, sequence
        ring-sharded, boundary hops carry packed per-token payload shards.

        ``hop_importance``: one (S,) / (B, S) entry per hop for ring-aware
        selective codecs (``needs_importance``); arrays may be global
        seq-sharded outputs of :func:`importance_sp` — the runtime shards them
        over "seq" alongside the hidden, and the codec's own collectives
        reconstruct the global ordering.

        ``fault_step``: per-call fault-PRNG fold (see
        ``SplitRuntime.forward``); each sequence shard additionally folds its
        shard index, so shards draw independent faults. Counters accumulate on
        the runtime — read with :meth:`link_counters`."""
        if self._lost_stage is not None:
            from ..serve.recovery import StageLostError

            raise StageLostError(self._lost_stage)
        input_ids = jnp.asarray(input_ids)
        batch, seq = input_ids.shape
        n_hops = len(self.codecs)
        imps = list(hop_importance) if hop_importance is not None \
            else [None] * n_hops
        if len(imps) != n_hops:
            raise ValueError(f"expected {n_hops} hop_importance entries, "
                             f"got {len(imps)}")
        for c, imp in zip(self.codecs, imps):
            if c.needs_importance and imp is None:
                raise ValueError(f"hop codec {c.name} requires an importance "
                                 f"vector")
            if c.needs_importance and batch > 1 and (
                    jnp.ndim(imp) != 2 or jnp.shape(imp)[0] != batch):
                raise ValueError(
                    f"hop codec {c.name} with batch {batch} needs per-row "
                    f"({batch}, S) importance (got shape {jnp.shape(imp)})")
        per_row = any(i is not None and jnp.ndim(i) == 2 for i in imps) or (
            batch > 1 and any(c.needs_importance for c in self.codecs))
        blank = jnp.zeros((batch, seq) if per_row else (seq,), jnp.float32)
        stacked = (jnp.zeros((0,) + blank.shape, jnp.float32) if not imps else
                   jnp.stack([blank if i is None
                              else jnp.broadcast_to(jnp.asarray(i, jnp.float32),
                                                    blank.shape)
                              for i in imps]))
        if self._link is None:
            return self._forward(placed_params, input_ids, stacked)
        logits, counters = self._forward(placed_params, input_ids, stacked,
                                         jnp.asarray(fault_step, jnp.int32))
        self._counter_accum.append(counters)
        return logits

    def link_counters(self, reset: bool = False) -> Optional[dict]:
        """Per-hop fault counters summed over all forward calls and all
        sequence shards: {name: (n_hops,) int64}. None when faults are off."""
        from ..codecs.faults import sum_counters

        if self._link is None:
            return None
        tot = sum_counters(self._counter_accum)
        if tot is None:
            n_hops = len(self.codecs)
            tot = {k: np.zeros((n_hops,), np.int64)
                   for k in self._link.init_counters(n_hops)}
        if reset:
            self._counter_accum = []
        return tot
