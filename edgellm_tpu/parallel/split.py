"""Pipeline-split forward over a device mesh with packed boundary transfers.

Maps the reference's conceptual architecture (a causal LM cut at "boundary
layers", activations compressed across each cut — ``README.md:16-23``) onto a TPU
mesh:

- mesh axes: ``("stage", "data", "model")`` — pipeline stages (explicit
  ``ppermute`` hops), data parallelism over evaluation windows, and tensor
  parallelism of the per-stage weights (Megatron-style column/row splits with
  an explicit in-block ``psum`` — see ``place_params._layer_pspec``).
- each stage owns a contiguous slice of the stacked layer parameters; stages are
  padded to equal layer counts with zero layers that are masked to identity, so
  the whole pipeline is one ``shard_map`` body with a static stage unroll.
- at each cut the boundary activation is ENCODED to a packed payload (int4
  nibbles, ternary crumbs, int8 + scales — ``edgellm_tpu.codecs.packing``), the
  payload pytree crosses to the next device via ``lax.ppermute`` over ICI, and is
  DECODED on arrival. Bytes-per-token is measured from the payload buffers.

This executes the *same math* as the reference's in-place simulation (verified in
tests: a wire-codec split run reproduces the simulate-codec PPL exactly) while
actually moving compressed bytes between devices. The multi-hop chain
(BASELINE.json configs[4]: 3-device Qwen2-1.5B with per-hop codecs) is the same
code with two cuts.
"""
from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.configs import ModelConfig
from ..models.transformer import (block, block_decode, block_verify, embed,
                                  unembed, precompute_rope, KVCache)
from ..models.paged_kv import KVTierMismatchError, block_decode_paged, \
    block_decode_paged_quant, resolve_kv_codec
from ..codecs.packing import get_wire_codec, WireCodec
from ..codecs.faults import FaultConfig, FaultyLink, LinkPolicy, sum_counters
from ..codecs.pallas_kernels import fused_hop, fused_hop_plan
from ..lint import graph_contract
from ..serve.recovery import StageLostError
from ..utils.jax_compat import shard_map, pcast_varying


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _adopt_paged_impl(pool_k, pool_v, k_seq, v_seq, dest):
    """Scatter one stream's (n_stages, sz, n, KV, hd) prefill K/V into the
    per-stage pools at flat token indices ``dest``. Donated in-place update;
    elementwise along "stage", so the pool sharding propagates hop-free."""
    ns, sz, pn, ps = pool_k.shape[:4]
    tail = pool_k.shape[4:]
    flat_k = pool_k.reshape(ns, sz, pn * ps, *tail)
    flat_v = pool_v.reshape(ns, sz, pn * ps, *tail)
    flat_k = flat_k.at[:, :, dest].set(k_seq.astype(flat_k.dtype))
    flat_v = flat_v.at[:, :, dest].set(v_seq.astype(flat_v.dtype))
    return (flat_k.reshape(pool_k.shape), flat_v.reshape(pool_v.shape))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _copy_paged_impl(pool_k, pool_v, src, dst):
    """Duplicate whole pages inside the per-stage pools: pages ``src``
    (1-D int32) are copied to pages ``dst`` — the device half of a prefix
    COW fork (the host allocator already repointed the forking slot's table
    rows). Donated, elementwise along "stage"."""
    return (pool_k.at[:, :, dst].set(pool_k[:, :, src]),
            pool_v.at[:, :, dst].set(pool_v[:, :, src]))


@jax.jit
def _gather_paged_impl(pool_k, pool_v, idx):
    """Inverse of :func:`_adopt_paged_impl` for one stream: gather the
    (n_stages, sz, n, KV, hd) K/V rows at flat token indices ``idx`` out of
    the per-stage pools. NOT donated — the pool stays live (eviction frees
    pages host-side; checkpointing must not consume the pool)."""
    ns, sz, pn, ps = pool_k.shape[:4]
    tail = pool_k.shape[4:]
    flat_k = pool_k.reshape(ns, sz, pn * ps, *tail)
    flat_v = pool_v.reshape(ns, sz, pn * ps, *tail)
    return flat_k[:, :, idx], flat_v[:, :, idx]


# Quantized-pool twins (KV-at-rest tiers, models.paged_kv): the per-stage
# pool becomes FOUR arrays — packed K/V codes plus per-row fp32 scales —
# and page surgery moves them together as bytes. Only adopt (fp rows in,
# quantize on append) and gather (dequantize out) touch the codec; the
# *_packed pair is the lossless checkpoint/eviction form.


def _paged_rows_set(arr, dest, rows):
    ns, sz, pn, ps = arr.shape[:4]
    tail = arr.shape[4:]
    return (arr.reshape(ns, sz, pn * ps, *tail).at[:, :, dest]
            .set(rows.astype(arr.dtype)).reshape(arr.shape))


def _paged_rows_get(arr, idx):
    ns, sz, pn, ps = arr.shape[:4]
    tail = arr.shape[4:]
    return arr.reshape(ns, sz, pn * ps, *tail)[:, :, idx]


@functools.partial(jax.jit, static_argnames=("kv_codec",),
                   donate_argnums=(0,))
def _adopt_paged_quant_impl(arrays, k_seq, v_seq, dest, kv_codec: str):
    from ..models.flash_attention import quantize_kv_rows

    pk, pv, ks, vs = arrays
    qk, sk = quantize_kv_rows(k_seq, kv_codec)
    qv, sv = quantize_kv_rows(v_seq, kv_codec)
    return (_paged_rows_set(pk, dest, qk), _paged_rows_set(pv, dest, qv),
            _paged_rows_set(ks, dest, sk), _paged_rows_set(vs, dest, sv))


@functools.partial(jax.jit, donate_argnums=(0,))
def _adopt_paged_packed_impl(arrays, k_codes, v_codes, k_scale, v_scale,
                             dest):
    pk, pv, ks, vs = arrays
    return (_paged_rows_set(pk, dest, k_codes),
            _paged_rows_set(pv, dest, v_codes),
            _paged_rows_set(ks, dest, k_scale),
            _paged_rows_set(vs, dest, v_scale))


@jax.jit
def _gather_paged_packed_impl(arrays, idx):
    return tuple(_paged_rows_get(a, idx) for a in arrays)


@functools.partial(jax.jit, static_argnames=("kv_codec",))
def _gather_paged_quant_impl(arrays, idx, kv_codec: str):
    from ..models.flash_attention import dequantize_kv_rows

    kc, vc, ks, vs = _gather_paged_packed_impl(arrays, idx)
    return (dequantize_kv_rows(kc, ks, kv_codec),
            dequantize_kv_rows(vc, vs, kv_codec))


@functools.partial(jax.jit, donate_argnums=(0,))
def _copy_paged_pool_impl(arrays, src, dst):
    return tuple(a.at[:, :, dst].set(a[:, :, src]) for a in arrays)


def make_stage_mesh(n_stages: int, n_data: int = 1, n_model: int = 1,
                    devices=None) -> Mesh:
    """Build a ("stage", "data", "model") mesh from the first
    n_stages*n_data*n_model available devices."""
    need = n_stages * n_data * n_model
    devices = np.asarray(devices if devices is not None else jax.devices())
    if devices.size < need:
        raise ValueError(f"need {need} devices, have {devices.size}")
    grid = devices.reshape(-1)[:need].reshape(n_stages, n_data, n_model)
    return Mesh(grid, ("stage", "data", "model"))


def apply_default_codec_backend(codecs: list) -> list:
    """Resolve hop-codec specs (names or ``WireCodec`` instances) to the
    backend's default implementation. On TPU the fused Pallas kernels are the
    default — but only where the kernel is a MEASURED on-silicon win for
    this chip (``pallas_kernels.default_substituted``: the probe cache keyed
    by chip fingerprint, with ``PALLAS_DEFAULT_WINS`` as the no-data
    fallback; the probe showed int8_per_channel marginally slower than its
    already-fused jnp twin, and the selective codec's twin was deleted
    outright on measurement — ``SELECTIVE_EXCLUSION``). EDGELLM_PALLAS
    forces substitution of every kernel twin (=1) or none (=0) on any
    backend; explicit ``*_pallas`` names are always honored. Shared by every
    runtime that owns hop codecs."""
    codecs = [c if isinstance(c, WireCodec) else get_wire_codec(c) for c in codecs]
    flag = os.environ.get("EDGELLM_PALLAS")
    if flag == "1":
        from ..codecs.pallas_kernels import pallas_variant

        return [pallas_variant(c) or c for c in codecs]
    if flag is None and jax.default_backend() == "tpu":
        from ..codecs.pallas_kernels import pallas_variant

        return [pallas_variant(c, measured_wins_only=True) or c for c in codecs]
    return codecs


def regroup_layers(layers: dict, bounds: list, stage_size: int) -> tuple:
    """(L, ...) stacked layers -> (n_stages, stage_size, ...) padded groups +
    validity mask. Padding layers are zeros and masked to identity in the
    stage body."""
    n_stages = len(bounds)
    groups, valid = {}, np.zeros((n_stages, stage_size), np.bool_)
    for s, (start, stop) in enumerate(bounds):
        valid[s, : stop - start] = True
    for k, v in layers.items():
        arr = np.zeros((n_stages, stage_size) + v.shape[1:], np.asarray(v).dtype)
        for s, (start, stop) in enumerate(bounds):
            arr[s, : stop - start] = np.asarray(v[start:stop])
        groups[k] = arr
    return groups, valid


def run_pipeline_stages(n_stages: int, codecs: list, run_stage, hidden,
                        hop_imps=None, axis_name: str = "stage",
                        link=None, fault_key=None, fused_plans=None):
    """The pipeline-unroll + boundary-hop protocol, shared by SplitRuntime and
    the stage x seq SplitRingRuntime (must run inside shard_map on
    ``axis_name``).

    Every device executes ``run_stage`` (its local layer scan) once per unroll
    step, keeping the result only when the step index matches its stage; at
    each cut the boundary activation is ENCODED to a packed payload, crossed to
    the next device via ``ppermute``, and DECODED on arrival. The final psum
    replicates the last stage's output structurally (no vma typing needed for
    Pallas-backed codecs).

    ``link`` (a :class:`~edgellm_tpu.codecs.faults.FaultyLink`) reroutes every
    hop through the faulty-wire protocol — seal, inject, verify, retry — keyed
    by ``fault_key``; the return value then becomes ``(out, counters)`` with
    the per-hop counters psum-replicated over ``axis_name``. With ``link``
    None this is byte-for-byte the original lossless path.

    ``fused_plans`` (one :class:`~edgellm_tpu.codecs.pallas_kernels.
    FusedHopPlan`-or-None per cut, resolved by ``fused_hop_plan``) routes a
    hop through the fused quantize->transport path instead; an all-None
    plan list leaves this function byte-for-byte the pre-fusion graph, and
    plans are only ever resolved when ``link`` is None (the gate refuses
    under an active link)."""
    idx = jax.lax.axis_index(axis_name)
    counters = link.init_counters(n_stages - 1) if link is not None else None
    for s in range(n_stages):
        computed = run_stage(hidden)
        hidden = jnp.where(idx == s, computed, hidden)
        if s < n_stages - 1:
            if link is not None:
                imp = hop_imps[s] if codecs[s].needs_importance else None
                hidden, counters = link.hop(codecs[s], hidden, s, axis_name,
                                            idx, fault_key, counters,
                                            hop_imp=imp)
                continue
            if fused_plans is not None and fused_plans[s] is not None:
                hidden = fused_hop(fused_plans[s], codecs[s], hidden, s,
                                   axis_name, idx, n_dev=n_stages)
                continue
            if codecs[s].needs_importance:
                payload = codecs[s].encode(hidden, hop_imps[s])
            else:
                payload = codecs[s].encode(hidden)
            moved = jax.tree_util.tree_map(
                lambda a: jax.lax.ppermute(a, axis_name, [(s, s + 1)]), payload)
            hidden = jnp.where(idx == s + 1, codecs[s].decode(moved), hidden)
    out = jax.lax.psum(
        jnp.where(idx == n_stages - 1, hidden, jnp.zeros_like(hidden)), axis_name)
    if link is None:
        return out
    counters = {k: jax.lax.psum(v, axis_name) for k, v in counters.items()}
    return out, counters


def run_pipeline_stages_carry(n_stages: int, codecs: list, run_stage, hidden,
                              carry, axis_name: str = "stage",
                              link=None, fault_key=None, fused_plans=None):
    """:func:`run_pipeline_stages` for stage bodies that thread stage-local
    state (the decode KV cache): ``run_stage(hidden, carry) -> (hidden,
    carry)``. Each device keeps the carry produced at ITS unroll step — the
    step where the hidden it transformed was the real pipeline activation —
    so per-stage caches update exactly once per token, and nothing but the
    (B, 1, D) boundary activation ever crosses a cut. Returns
    (final hidden, carry), plus the psum-replicated fault counters when
    ``link`` is given (see :func:`run_pipeline_stages`)."""
    idx = jax.lax.axis_index(axis_name)
    counters = link.init_counters(n_stages - 1) if link is not None else None
    for s in range(n_stages):
        computed, new_carry = run_stage(hidden, carry)
        keep = idx == s
        hidden = jnp.where(keep, computed, hidden)
        carry = jax.tree_util.tree_map(
            lambda new, old: jnp.where(keep, new, old), new_carry, carry)
        if s < n_stages - 1:
            if link is not None:
                hidden, counters = link.hop(codecs[s], hidden, s, axis_name,
                                            idx, fault_key, counters)
                continue
            if fused_plans is not None and fused_plans[s] is not None:
                hidden = fused_hop(fused_plans[s], codecs[s], hidden, s,
                                   axis_name, idx, n_dev=n_stages)
                continue
            payload = codecs[s].encode(hidden)
            moved = jax.tree_util.tree_map(
                lambda a: jax.lax.ppermute(a, axis_name, [(s, s + 1)]), payload)
            hidden = jnp.where(idx == s + 1, codecs[s].decode(moved), hidden)
    out = jax.lax.psum(
        jnp.where(idx == n_stages - 1, hidden, jnp.zeros_like(hidden)), axis_name)
    if link is None:
        return out, carry
    counters = {k: jax.lax.psum(v, axis_name) for k, v in counters.items()}
    return out, carry, counters


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """Micro-batch pipelining of the stage unroll (ROADMAP item 4).

    ``num_microbatches`` (M): the batch is split into M contiguous row
    groups and the stage loop runs a GPipe-style fill/steady/drain schedule
    of M + n_stages - 1 unroll steps, so in steady state every stage
    computes a different µ-batch in the same step while the quantized
    boundary activations of the others are on the wire — instead of one
    stage computing and n_stages - 1 idling. M == 1 is the disabled
    configuration: the runtime dispatches to the ORIGINAL sequential
    unroll, byte-identical to a build that never saw this class (the
    "split.*.pipeline-disabled-identity" lint pins hold it to that).

    The schedule preserves token identity with the sequential path at any
    M: each µ-batch flows through exactly the same per-stage math and the
    same per-cut codec, just interleaved in time. That holds only when
    codecs treat batch rows independently (``WireCodec.batch_invariant``;
    scales reduced over the whole batch would change with the µ-batch
    split), which :class:`SplitRuntime` validates at construction.
    """

    num_microbatches: int = 1

    def __post_init__(self):
        if self.num_microbatches < 1:
            raise ValueError(
                f"num_microbatches must be >= 1, got {self.num_microbatches}")

    @property
    def enabled(self) -> bool:
        return self.num_microbatches > 1

    def validate_batch(self, batch: int, what: str = "batch") -> int:
        """Check ``batch`` splits evenly into µ-batches; return rows per
        µ-batch. Every pipelined entry point calls this, so a bad batch
        fails loudly host-side instead of tracing a ragged schedule."""
        m = self.num_microbatches
        if batch < m or batch % m:
            raise ValueError(
                f"{what} {batch} must be a positive multiple of "
                f"num_microbatches={m} (each µ-batch needs >= 1 row)")
        return batch // m

    def summary(self, n_stages: int) -> dict:
        """Host-side schedule accounting: unroll length, per-stage
        occupancy (every stage is busy M of the M + n - 1 steps) and the
        analytic bubble fraction (n - 1) / (M + n - 1) — the number
        BENCH_PIPE gates against the sequential (n - 1) / n bound."""
        m, n = self.num_microbatches, n_stages
        t = m + n - 1
        return {
            "enabled": self.enabled,
            "num_microbatches": m,
            "n_stages": n,
            "unroll_steps": t,
            "stage_occupancy": [m / t] * n,
            "bubble_fraction_schedule": (n - 1) / t,
            "bubble_fraction_sequential": (n - 1) / n,
        }


def _microbatch_imp(codec, hop_imps, s: int, mb: int, mb_rows: int):
    """The importance entry one (cut, µ-batch) hop ships: per-row (B, S)
    importance is sliced to the µ-batch's own rows (static slice — mb is a
    Python int in the schedule), shared (S,) importance is passed whole."""
    if not codec.needs_importance:
        return None
    imp = hop_imps[s]
    if imp.ndim == 2:
        return jax.lax.slice_in_dim(imp, mb * mb_rows, (mb + 1) * mb_rows,
                                    axis=0)
    return imp


def run_pipeline_stages_microbatched(n_stages: int, codecs: list,
                                     num_microbatches: int, run_stage, hidden,
                                     hop_imps=None, axis_name: str = "stage",
                                     link=None, fault_key=None,
                                     fused_plans=None):
    """Micro-batch pipelined twin of :func:`run_pipeline_stages` (must run
    inside shard_map on ``axis_name``).

    Fill/steady/drain over T = M + n_stages - 1 unroll steps. Each device
    keeps one µ-batch-sized activation register; at step t the device at
    stage s is working on µ-batch b = t - s (valid iff 0 <= b < M — the
    fill and drain triangles are masked, their compute discarded). Stage 0
    ingests µ-batch t while t < M; the last stage emits µ-batch
    t - (n_stages - 1) as it completes. Hops run in REVERSED cut order so a
    cut's send reads the activation its stage just computed before the
    upstream cut's receive overwrites the register with the next µ-batch.
    Because both t and s are Python ints, the µ-batch index mb = t - s of
    every hop is static: hops outside [0, M) are simply not traced (the
    wire carries exactly M payloads per cut, which the
    "split.*.pipelined" lint contracts count), and under ``link`` each
    µ-batch draws its own fault key (``fold_in(fault_key, mb)``) and bumps
    its own counter row — the return value's counters are {key: (M,
    n_hops)}, one row per µ-batch, psum-replicated like the sequential
    path's.

    Output: the M emitted (B/M, ...) blocks are stacked, psum-replicated
    in ONE collective, and re-flattened to the caller's (B, ...) batch —
    same contract as the sequential function, one psum in the graph."""
    idx = jax.lax.axis_index(axis_name)
    m = int(num_microbatches)
    n_hops = n_stages - 1
    batch = hidden.shape[0]
    mb_rows = batch // m
    micro = [jax.lax.slice_in_dim(hidden, b * mb_rows, (b + 1) * mb_rows,
                                  axis=0) for b in range(m)]
    counters = ([link.init_counters(n_hops) for _ in range(m)]
                if link is not None else None)
    act = jnp.zeros_like(micro[0])
    outs = []
    for t in range(m + n_stages - 1):
        if t < m:
            act = jnp.where(idx == 0, micro[t], act)
        here = t - idx  # which µ-batch THIS device holds (traced)
        valid = (here >= 0) & (here < m)
        computed = run_stage(act)
        act = jnp.where(valid, computed, act)
        if 0 <= t - (n_stages - 1) < m:
            outs.append(jnp.where(idx == n_stages - 1, act,
                                  jnp.zeros_like(act)))
        for s in reversed(range(n_hops)):
            mb = t - s  # static: only in-flight (cut, µ-batch) hops trace
            if not 0 <= mb < m:
                continue
            if link is not None:
                imp = _microbatch_imp(codecs[s], hop_imps, s, mb, mb_rows)
                act, counters[mb] = link.hop(
                    codecs[s], act, s, axis_name, idx,
                    jax.random.fold_in(fault_key, mb), counters[mb],
                    hop_imp=imp)
                continue
            if fused_plans is not None and fused_plans[s] is not None:
                act = fused_hop(fused_plans[s], codecs[s], act, s,
                                axis_name, idx, n_dev=n_stages)
                continue
            imp = _microbatch_imp(codecs[s], hop_imps, s, mb, mb_rows)
            payload = (codecs[s].encode(act, imp) if imp is not None
                       else codecs[s].encode(act))
            moved = jax.tree_util.tree_map(
                lambda a: jax.lax.ppermute(a, axis_name, [(s, s + 1)]),
                payload)
            act = jnp.where(idx == s + 1, codecs[s].decode(moved), act)
    out = jax.lax.psum(jnp.stack(outs), axis_name)  # (M, B/M, ...)
    out = out.reshape((batch,) + out.shape[2:])
    if link is None:
        return out
    counters = {k: jax.lax.psum(jnp.stack([c[k] for c in counters]),
                                axis_name)
                for k in counters[0]}
    return out, counters


def run_pipeline_stages_carry_microbatched(n_stages: int, codecs: list,
                                           num_microbatches: int, run_stage,
                                           hidden, carry,
                                           axis_name: str = "stage",
                                           link=None, fault_key=None,
                                           fused_plans=None):
    """:func:`run_pipeline_stages_microbatched` for stage bodies that
    thread stage-local state (the decode KV caches): ``run_stage(h_mu,
    carry, b, valid) -> (h_mu, carry)`` where ``b`` is the device's current
    µ-batch index clipped into [0, M) (traced — each device is at a
    different µ-batch in the same unroll step) and ``valid`` gates the fill
    and drain triangles. The stage body owns the µ-batch view of its carry
    — slicing the µ-batch's cache rows at ``b`` and masking the write-back
    when ``valid`` is False (contiguous caches) or redirecting it to the
    trash page (paged pools) — so each µ-batch's cache rows update exactly
    once per token, same as the sequential schedule. Returns (hidden,
    carry) plus the {key: (M, n_hops)} psum-replicated counters when
    ``link`` is given."""
    idx = jax.lax.axis_index(axis_name)
    m = int(num_microbatches)
    n_hops = n_stages - 1
    batch = hidden.shape[0]
    mb_rows = batch // m
    micro = [jax.lax.slice_in_dim(hidden, b * mb_rows, (b + 1) * mb_rows,
                                  axis=0) for b in range(m)]
    counters = ([link.init_counters(n_hops) for _ in range(m)]
                if link is not None else None)
    act = jnp.zeros_like(micro[0])
    outs = []
    for t in range(m + n_stages - 1):
        if t < m:
            act = jnp.where(idx == 0, micro[t], act)
        here = t - idx
        valid = (here >= 0) & (here < m)
        b = jnp.clip(here, 0, m - 1)
        computed, carry = run_stage(act, carry, b, valid)
        act = jnp.where(valid, computed, act)
        if 0 <= t - (n_stages - 1) < m:
            outs.append(jnp.where(idx == n_stages - 1, act,
                                  jnp.zeros_like(act)))
        for s in reversed(range(n_hops)):
            mb = t - s
            if not 0 <= mb < m:
                continue
            if link is not None:
                act, counters[mb] = link.hop(
                    codecs[s], act, s, axis_name, idx,
                    jax.random.fold_in(fault_key, mb), counters[mb])
                continue
            if fused_plans is not None and fused_plans[s] is not None:
                act = fused_hop(fused_plans[s], codecs[s], act, s,
                                axis_name, idx, n_dev=n_stages)
                continue
            payload = codecs[s].encode(act)
            moved = jax.tree_util.tree_map(
                lambda a: jax.lax.ppermute(a, axis_name, [(s, s + 1)]),
                payload)
            act = jnp.where(idx == s + 1, codecs[s].decode(moved), act)
    out = jax.lax.psum(jnp.stack(outs), axis_name)
    out = out.reshape((batch,) + out.shape[2:])
    if link is None:
        return out, carry
    counters = {k: jax.lax.psum(jnp.stack([c[k] for c in counters]),
                                axis_name)
                for k in counters[0]}
    return out, carry, counters


def hop_payload_bytes(codecs, cfg, batch: int, seq: int) -> list:
    """Measured payload bytes per hop for one (batch, seq, D) boundary
    activation — the BASELINE.json metric's numerator, shared by every runtime.
    (For the stage x seq runtime each device moves its local sequence shard;
    per-token codecs' payloads are sequence-additive, so the total equals one
    full-sequence encode.)"""
    shape = (batch, seq, cfg.hidden_size)
    return [c.payload_bytes(shape) for c in codecs]


def measure_hop_times(mesh, codecs, cfg, batch: int, seq: int, *,
                      iters: int = 20, warmup: int = 1,
                      hidden_spec: P = P()) -> list:
    """Per-hop boundary-transfer time (ms): encode -> ppermute over "stage" ->
    decode, isolated from stage compute. ``hidden_spec`` places the probe
    activation on the mesh (replicated for the plain split runtime,
    seq-sharded ``P(None, "seq")`` for the stage x seq runtime, which times the
    local-shard payloads its hops actually move).

    ``warmup`` is clamped to >= 1: the first call compiles the hop
    executable, and a compile second leaking into a per-hop millisecond
    poisons every downstream SLO/bench number (the BENCH_SOAK rule)."""
    from ..utils.profiling import timed

    warmup = max(1, int(warmup))

    results = []
    hidden = jax.random.normal(
        jax.random.key(0), (batch, seq, cfg.hidden_size), jnp.float32)
    # match forward's wire format: batched windows ship per-row importance
    # (B x S order side channel), so time that payload, not the shared one
    imp = (jnp.arange(seq, dtype=jnp.float32) if batch == 1 else
           jnp.broadcast_to(jnp.arange(seq, dtype=jnp.float32), (batch, seq)))
    # the probe importance shards over the token axis exactly like the hidden:
    # hidden_spec's axes are (batch, tokens[, features]), so the token entry
    # is hidden_spec[1] (None for the replicated plain-split probe, "seq" for
    # the stage x seq probe)
    token_axis = hidden_spec[1] if len(hidden_spec) > 1 else None
    imp_spec = (P(token_axis) if imp.ndim == 1
                else P(hidden_spec[0] if hidden_spec else None, token_axis))
    for s, codec in enumerate(codecs):

        def hop_body(h, imp_loc):
            idx = jax.lax.axis_index("stage")
            if codec.needs_importance:
                payload = codec.encode(h, imp_loc)
            else:
                payload = codec.encode(h)
            moved = jax.tree_util.tree_map(
                lambda a: jax.lax.ppermute(a, "stage", [(s, s + 1)]), payload)
            decoded = codec.decode(moved)
            return jax.lax.psum(
                jnp.where(idx == s + 1, decoded, jnp.zeros_like(decoded)), "stage")

        fn = jax.jit(shard_map(hop_body, mesh=mesh,
                               in_specs=(hidden_spec, imp_spec),
                               out_specs=hidden_spec, check_vma=False))
        sec, _ = timed(fn, hidden, imp, warmup=warmup, iters=iters)
        results.append(sec * 1000.0)
    return results


@dataclasses.dataclass(frozen=True)
class SplitConfig:
    """Where the model is cut and what crosses each cut.

    cuts: boundary layers — the activation is transferred *after* layer ``cuts[i]``
        (the reference's ``layer_of_interest`` / ``quant_layer``).
    hop_codecs: one entry per cut — either a registry name
        (``edgellm_tpu.codecs.packing.WIRE_CODECS``) or a ``WireCodec`` instance
        for parameterized codecs like ``selective_int4(ratio, high)``.
    """

    cuts: tuple
    hop_codecs: tuple

    def __post_init__(self):
        if len(self.hop_codecs) != len(self.cuts):
            raise ValueError("need exactly one hop codec per cut")
        if list(self.cuts) != sorted(set(self.cuts)):
            raise ValueError("cuts must be strictly increasing")

    @property
    def n_stages(self) -> int:
        return len(self.cuts) + 1

    def stage_bounds(self, num_layers: int) -> list:
        """[(start, stop)] per stage; stage i owns layers [start, stop)."""
        edges = [0] + [c + 1 for c in self.cuts] + [num_layers]
        if not all(0 <= c < num_layers - 1 for c in self.cuts):
            raise ValueError(f"cuts {self.cuts} out of range for {num_layers} layers")
        return list(zip(edges[:-1], edges[1:]))

    def replan(self, num_layers: int, n_stages: int,
               codec=None) -> "SplitConfig":
        """Recompute the split for a different stage count — the runtime
        re-planning failover needs when a stage dies (MCAP-style: the split
        point is a runtime decision, not a construction-time constant).

        Cuts are evenly spaced over ``num_layers``; every new cut carries
        ``codec`` (default: this plan's first hop codec — there is no
        per-cut tuning signal left once the original cut set is gone).
        ``n_stages == 1`` degenerates to the cut-free single-stage plan."""
        if not 1 <= n_stages <= num_layers:
            raise ValueError(
                f"cannot re-plan {num_layers} layers onto {n_stages} stage(s)")
        if n_stages == 1:
            return SplitConfig(cuts=(), hop_codecs=())
        if codec is None:
            if not self.hop_codecs:
                raise ValueError("re-planning a cut-free split needs an "
                                 "explicit codec")
            codec = self.hop_codecs[0]
        cuts = tuple(round(i * num_layers / n_stages) - 1
                     for i in range(1, n_stages))
        return SplitConfig(cuts=cuts, hop_codecs=(codec,) * len(cuts))


class SplitRuntime:
    """Executes a pipeline-split forward for one (cfg, split, mesh) combination.

    Usage::

        mesh = make_stage_mesh(2)
        rt = SplitRuntime(cfg, SplitConfig(cuts=(3,), hop_codecs=("int4_global",)), mesh)
        placed = rt.place_params(params)
        logits = rt.forward(placed, ids)          # boundary crossed via ppermute
        rt.hop_bytes(batch, seq)                  # measured payload bytes per hop
    """

    def __init__(self, cfg: ModelConfig, split: SplitConfig, mesh: Mesh,
                 faults: Optional[FaultConfig] = None,
                 policy: Optional[LinkPolicy] = None,
                 fec: Optional[Any] = None,
                 hedge: Optional[Any] = None,
                 pipeline: Optional[PipelineConfig] = None):
        self.cfg = cfg
        self.split = split
        self.mesh = mesh
        self.faults = faults
        self.policy = policy if policy is not None else LinkPolicy()
        self.fec = fec
        self.hedge = hedge
        self.pipeline = pipeline
        # an all-zero-rate config builds the exact fault-free graph: the link
        # machinery only exists in the jaxpr when a fault can actually fire
        # (and a disabled FEC/hedge config traces the exact PR 2 hop)
        self._link = (FaultyLink(faults, self.policy, fec=fec, hedge=hedge)
                      if faults is not None and faults.enabled else None)
        self._counter_accum: list = []
        self._mb_counter_accum: list = []  # pipelined: {key: (M, n_hops)}
        self._lost_stage: Optional[int] = None
        self.bounds = split.stage_bounds(cfg.num_layers)
        self.stage_size = max(stop - start for start, stop in self.bounds)
        self.codecs: list[WireCodec] = apply_default_codec_backend(
            list(split.hop_codecs))
        # per-cut fused-transport decision, resolved ONCE at build time so
        # the compiled graphs embed it: None = the pre-fusion ladder (an
        # all-None list leaves every traced graph byte-identical — the
        # "split.*.fused-disabled-identity" lint checks pin this). The gate
        # refuses whenever the faulty link is armed: fault injection, FEC
        # and hedging own the hop there.
        self.fused_plans: list = [
            fused_hop_plan(c, link_active=self._link is not None)
            for c in self.codecs]
        n_model = mesh.shape["model"]
        if n_model > 1:
            bad = [(name, dim) for name, dim in
                   [("num_heads", cfg.num_heads), ("num_kv_heads", cfg.num_kv_heads),
                    ("intermediate_size", cfg.intermediate_size)] if dim % n_model]
            if bad:
                raise ValueError(
                    f"tensor parallelism n_model={n_model} requires head/FFN dims "
                    f"divisible by the axis; offending: {bad}")
        n_stages = split.n_stages
        if mesh.shape["stage"] != n_stages:
            raise ValueError(
                f"mesh has {mesh.shape['stage']} stage slots, split needs {n_stages}")
        if mesh.shape["data"] > 1:
            # token-selective codecs are exempt: ``forward`` forces per-row
            # (B, S) importance for batched windows, making their ordering and
            # scale row-local — identical on any batch sharding
            bad = [c.name for c in self.codecs
                   if not c.batch_invariant and not c.needs_importance]
            if bad:
                raise ValueError(
                    f"codecs {bad} compute scales over the batch axis and would "
                    f"diverge from a single-device run under data parallelism "
                    f"(n_data={mesh.shape['data']}); use per-token codecs or n_data=1")
        if pipeline is not None and pipeline.enabled:
            if n_stages < 2:
                raise ValueError(
                    "micro-batch pipelining needs a cut to hide hops behind; "
                    f"got n_stages={n_stages} with num_microbatches="
                    f"{pipeline.num_microbatches}")
            if mesh.shape["data"] > 1 or mesh.shape["model"] > 1:
                raise ValueError(
                    "micro-batch pipelining supports stage-only meshes "
                    "(n_data=n_model=1): the µ-batch split owns the batch "
                    f"axis; got data={mesh.shape['data']}, "
                    f"model={mesh.shape['model']}")
            # same row-locality argument as the data-parallel check above:
            # a batch-wide codec scale changes when the batch is split into
            # µ-batches, which would break token parity with the sequential
            # schedule (token-selective codecs again ship per-row importance
            # under any batch > 1, making their payloads row-local)
            bad = [c.name for c in self.codecs
                   if not c.batch_invariant and not c.needs_importance]
            if bad:
                raise ValueError(
                    f"codecs {bad} compute scales over the batch axis; their "
                    f"payloads change when the batch splits into "
                    f"{pipeline.num_microbatches} µ-batches, breaking the "
                    f"token-identity guarantee — use per-token codecs or "
                    f"num_microbatches=1")
        self._forward = self._build_forward()
        self._decode_fns_cache: dict = {}  # capacity -> (prefill_fn, step_fn)
        self._paged_fns_cache: dict = {}   # pool geometry -> step_fn
        self._verify_fns_cache: dict = {}  # (capacity, k) -> verify_fn

    # ---------- stage liveness ----------

    def mark_stage_lost(self, stage: int) -> None:
        """Record a dark stage (failure injection, or a caller's own device
        health signal): every subsequent forward/prefill/step raises the
        typed :class:`StageLostError` until the caller fails over — re-plans
        the split onto the survivors (``SplitConfig.replan``) and rebuilds
        the runtime. Host-side state only: the compiled executables are
        untouched, so a runtime that never loses a stage runs the exact
        pre-recovery graph."""
        if not 0 <= stage < self.split.n_stages:
            raise ValueError(f"stage {stage} out of range for "
                             f"{self.split.n_stages} stages")
        self._lost_stage = stage

    @property
    def lost_stage(self) -> Optional[int]:
        return self._lost_stage

    def _check_alive(self) -> None:
        if self._lost_stage is not None:
            raise StageLostError(self._lost_stage)

    # ---------- parameter placement ----------

    # Megatron-style column/row pairing for the "model" axis: the first matmul
    # of each pair is column-split (head-contiguous for q/k/v, F-contiguous for
    # the MLP up/gate), the second is row-split, and the row-split partial
    # product is psum-reduced inside the block (transformer.attention/mlp).
    _TP_COL_SPLIT = frozenset(
        {"wq", "wk", "wv", "bq", "bk", "bv", "w_gate", "w_up", "w_in", "b_in"})
    _TP_ROW_SPLIT = frozenset({"wo", "w_down", "w_out"})

    def _layer_pspec(self, key: str, ndim: int) -> P:
        """PartitionSpec for one stacked layer-group array (n_stages, sz, ...)."""
        if self.mesh.shape["model"] > 1:
            if key in self._TP_COL_SPLIT:  # split the last (output-feature) axis
                return P(*(("stage",) + (None,) * (ndim - 2) + ("model",)))
            if key in self._TP_ROW_SPLIT:  # split the input-feature axis
                return P("stage", None, "model")
        return P("stage")

    def place_params(self, params: dict) -> dict:
        """Shard the parameter pytree over the mesh: layer groups along "stage",
        attention/MLP weights additionally column/row-split along "model"
        (real tensor parallelism — each model-axis device holds 1/n of the
        heads and FFN columns and computes its slice; see ``_layer_pspec``),
        everything else replicated. Hidden activations ride the "data" axis on
        the batch dimension."""
        groups, valid = regroup_layers(params["layers"], self.bounds, self.stage_size)
        stage_spec = NamedSharding(self.mesh, P("stage"))
        repl = NamedSharding(self.mesh, P())
        placed = {
            "layers": {
                k: jax.device_put(v, NamedSharding(self.mesh, self._layer_pspec(k, v.ndim)))
                for k, v in groups.items()},
            "layers_valid": jax.device_put(valid, stage_spec),
        }
        for k, v in params.items():
            if k != "layers":
                placed[k] = jax.device_put(v, repl)
        return placed

    # ---------- forward ----------

    def _build_forward(self):
        cfg, n_stages, sz = self.cfg, self.split.n_stages, self.stage_size
        codecs = self.codecs
        mesh = self.mesh
        link = self._link
        fused_plans = self.fused_plans
        # resolved once at build time, like the fused plans: the disabled /
        # M == 1 build traces the ORIGINAL schedule functions (the
        # pipeline-disabled-identity lint pins hold it byte-identical)
        n_micro = (self.pipeline.num_microbatches if self.pipelined else 1)

        tp_axis = "model" if mesh.shape["model"] > 1 else None

        def stage_body(local_layers, local_valid, hidden, cos, sin, hop_imps,
                       fault_step=None):
            """Runs inside shard_map: one device = one pipeline stage (and one
            tensor-parallel shard of it when the "model" axis is populated)."""
            lv = {k: v[0] for k, v in local_layers.items()}  # (sz, ...)
            valid = local_valid[0]  # (sz,)
            # the carry becomes stage-varying after the first scan step; promote
            # the replicated input so the vma types line up
            hidden = pcast_varying(hidden, ("stage",))

            def scan_body(h, xs):
                lp, ok = xs
                out, _ = block(cfg, lp, h, cos, sin, capture_stats=False,
                               tp_axis=tp_axis)
                return jnp.where(ok, out, h), None

            def run_stage(h):
                computed, _ = jax.lax.scan(scan_body, h, (lv, valid))
                return computed

            if link is None:
                if n_micro > 1:
                    return run_pipeline_stages_microbatched(
                        n_stages, codecs, n_micro, run_stage, hidden,
                        hop_imps, fused_plans=fused_plans)
                return run_pipeline_stages(n_stages, codecs, run_stage, hidden,
                                           hop_imps, fused_plans=fused_plans)
            # one fold per forward call keeps chunks decorrelated while two
            # same-seed runs replay the identical fault sequence
            key = jax.random.fold_in(jax.random.key(link.faults.seed),
                                     fault_step)
            if n_micro > 1:
                return run_pipeline_stages_microbatched(
                    n_stages, codecs, n_micro, run_stage, hidden, hop_imps,
                    link=link, fault_key=key)
            return run_pipeline_stages(n_stages, codecs, run_stage, hidden,
                                       hop_imps, link=link, fault_key=key)

        # batch axis rides the "data" mesh axis (data parallelism over evaluation
        # windows); each data-parallel group runs the full pipeline over "stage"
        batch_spec = P("data") if mesh.shape["data"] > 1 else P()

        layer_pspec = self._layer_pspec

        @jax.jit
        def fn(placed, input_ids, hop_imps, fault_step=None):
            hidden = embed(placed, input_ids)
            cos, sin = precompute_rope(cfg, input_ids.shape[1])
            lspecs = {k: layer_pspec(k, v.ndim) for k, v in placed["layers"].items()}
            # per-row (H, B, S) importance rides the "data" axis with the batch;
            # shared (H, S) importance is replicated (ndim is static under jit)
            imp_spec = (P(None, "data") if hop_imps.ndim == 3
                        and mesh.shape["data"] > 1 else P())
            if link is None:
                out = shard_map(
                    stage_body,
                    mesh=mesh,
                    in_specs=(lspecs, P("stage"), batch_spec, P(), P(), imp_spec),
                    out_specs=batch_spec,
                    # vma tracking cannot type pallas_call outputs inside the body
                    # (hop codecs may be Pallas kernels); replication is enforced
                    # structurally by the final psum instead
                    check_vma=False,
                )(placed["layers"], placed["layers_valid"], hidden, cos, sin,
                  hop_imps)
                return unembed(cfg, placed, out)
            out, counters = shard_map(
                stage_body,
                mesh=mesh,
                in_specs=(lspecs, P("stage"), batch_spec, P(), P(), imp_spec,
                          P()),
                out_specs=(batch_spec, P()),
                check_vma=False,
            )(placed["layers"], placed["layers_valid"], hidden, cos, sin,
              hop_imps, fault_step)
            return unembed(cfg, placed, out), counters

        return fn

    @graph_contract(
        "split.forward",
        # one ppermute per payload leaf per cut, one structural psum; the
        # driver supplies the measured counts/bytes from the codec registry
        collectives=lambda ctx: {"ppermute": ctx["hop_eqns"], "psum": 1},
        wire_dtypes=lambda ctx: ctx["wire_dtypes"],
        wire_bytes=lambda ctx: ctx["wire_bytes"])
    @graph_contract(
        "split.forward.fused",
        # fused wire mode: the whole sealed tree crosses each cut as ONE
        # flat uint8 buffer (hop_eqns == n_cuts), and the bytes are exactly
        # hop_bytes + the 8-byte canary/crc seal per cut — the driver traces
        # a forced-fused build against this declaration
        collectives=lambda ctx: {"ppermute": ctx["hop_eqns"], "psum": 1},
        wire_dtypes=lambda ctx: ctx["wire_dtypes"],
        wire_bytes=lambda ctx: ctx["wire_bytes"])
    @graph_contract(
        "split.forward.pipelined",
        # µ-batch schedule: every cut moves M payloads of (B/M, S, D) —
        # hop_eqns scales by M, wire bytes are M x the µ-batch payload, and
        # the M emitted blocks still replicate through ONE stacked psum
        collectives=lambda ctx: {"ppermute": ctx["hop_eqns"], "psum": 1},
        wire_dtypes=lambda ctx: ctx["wire_dtypes"],
        wire_bytes=lambda ctx: ctx["wire_bytes"])
    def forward(self, placed_params: dict, input_ids: jnp.ndarray,
                hop_importance: Optional[Sequence] = None,
                fault_step: int = 0) -> jnp.ndarray:
        """ids -> fp32 logits, with every cut crossed as a packed ppermute.

        ``hop_importance``: per-hop token-importance entries, required when any
        hop codec is token-selective (``needs_importance``); hops that don't
        use importance may pass None entries. Each entry is (S,), or — when
        batching evaluation windows — (B, S) so every window keeps its OWN
        ordering and codec scale (the reference selects per window at batch 1,
        ``Qwen2-0.5B/main.py:161-165``; with the "data" mesh axis populated the
        rows ride it alongside the hidden batch).

        ``fault_step``: the fault layer's per-call PRNG fold (pass the chunk
        index so each chunk draws distinct faults; a traced scalar, so it
        never retraces). Ignored when faults are off. Per-hop fault counters
        accumulate on the runtime — read them with :meth:`link_counters`."""
        self._check_alive()
        n_hops = len(self.codecs)
        batch, seq = input_ids.shape
        if self.pipelined:
            self.pipeline.validate_batch(batch, "forward batch")
        imps = list(hop_importance) if hop_importance is not None else [None] * n_hops
        if len(imps) != n_hops:
            raise ValueError(f"expected {n_hops} hop_importance entries, got {len(imps)}")
        for c, imp in zip(self.codecs, imps):
            if c.needs_importance and imp is None:
                raise ValueError(f"hop codec {c.name} requires an importance vector")
            if c.needs_importance and batch > 1 and (
                    jnp.ndim(imp) != 2 or jnp.shape(imp)[0] != batch):
                # one (S,) vector (or a single broadcast row) cannot speak for
                # several evaluation windows: each window has its own token
                # ordering in the reference
                raise ValueError(
                    f"hop codec {c.name} with batch {batch} needs per-row "
                    f"({batch}, S) importance (got shape {jnp.shape(imp)})")
        per_row = any(i is not None and jnp.ndim(i) == 2 for i in imps) or (
            batch > 1 and any(c.needs_importance for c in self.codecs))
        blank = jnp.zeros((batch, seq) if per_row else (seq,), jnp.float32)
        stacked = (jnp.zeros((0,) + blank.shape, jnp.float32) if not imps else
                   jnp.stack([blank if i is None
                              else jnp.broadcast_to(jnp.asarray(i, jnp.float32),
                                                    blank.shape)
                              for i in imps]))
        if self._link is None:
            return self._forward(placed_params, input_ids, stacked)
        logits, counters = self._forward(placed_params, input_ids, stacked,
                                         jnp.asarray(fault_step, jnp.int32))
        self._accum_counters(counters)
        return logits

    @property
    def pipelined(self) -> bool:
        """True when the µ-batch schedule is armed (num_microbatches > 1).
        False — including for ``PipelineConfig(num_microbatches=1)`` — means
        every entry point dispatches to the original sequential unroll,
        byte-identical to a pre-pipeline build (lint-pinned)."""
        return self.pipeline is not None and self.pipeline.enabled

    def pipeline_summary(self) -> dict:
        """Schedule accounting for the obs gauges and bench artifacts: µ-batch
        count, unroll length, per-stage occupancy, analytic bubble fraction.
        Meaningful (occupancy 1/n per stage) even when pipelining is off."""
        pipe = self.pipeline if self.pipeline is not None else PipelineConfig()
        return pipe.summary(self.split.n_stages)

    def _accum_counters(self, counters) -> None:
        """Park one call's replicated counter pytree. Pipelined steps return
        {key: (M, n_hops)} — the per-µ-batch rows accumulate separately
        (:meth:`microbatch_counters`) and the hop totals fold into the same
        (n_hops,) stream :meth:`link_counters` has always reported."""
        first = next(iter(counters.values()))
        if getattr(first, "ndim", 1) == 2:
            self._mb_counter_accum.append(counters)
            counters = {k: v.sum(axis=0) for k, v in counters.items()}
        self._counter_accum.append(counters)

    def link_counters(self, reset: bool = False) -> Optional[dict]:
        """Per-hop fault counters accumulated over every forward/prefill/step
        call so far: {name: (n_hops,) int64}. None when faults are off.
        Reading forces a sync of the pending counter arrays — call it at
        reporting boundaries, not per chunk."""
        if self._link is None:
            return None
        tot = sum_counters(self._counter_accum)
        if tot is None:
            n_hops = len(self.codecs)
            tot = {k: np.zeros((n_hops,), np.int64)
                   for k in self._link.init_counters(n_hops)}
        if reset:
            self._counter_accum = []
        return tot

    def microbatch_counters(self, reset: bool = False) -> Optional[dict]:
        """Per-µ-batch fault counters from pipelined steps: {name: (M,
        n_hops) int64} — row m is the faults µ-batch m's payloads drew on
        each cut (each µ-batch folds its own fault key, so the rows are
        decorrelated). None when faults are off or pipelining is disabled.
        Sequential calls on the same runtime (prefill, verify) are not
        µ-batched and only appear in :meth:`link_counters`."""
        if self._link is None or not self.pipelined:
            return None
        tot = sum_counters(self._mb_counter_accum)
        if tot is None:
            m, n_hops = self.pipeline.num_microbatches, len(self.codecs)
            tot = {k: np.zeros((m, n_hops), np.int64)
                   for k in self._link.init_counters(n_hops)}
        if reset:
            self._mb_counter_accum = []
        return tot

    def wire_summary(self, batch: int, seq: int) -> list:
        """Per-hop wire accounting in one shot — the shape the obs registry
        and bench artifacts consume: codec name, whole-window forward bytes,
        single-step decode bytes, and steady-state bytes/token."""
        fwd = self.hop_bytes(batch, seq)
        dec = self.decode_hop_bytes(batch)
        per_tok = self.bytes_per_token(seq)
        return [{"hop": i, "codec": self.codecs[i].name,
                 "forward_bytes": int(fwd[i]),
                 "decode_step_bytes": int(dec[i]) if i < len(dec) else 0,
                 "bytes_per_token": float(per_tok[i]),
                 "fused": (None if self.fused_plans[i] is None else
                           {"mode": self.fused_plans[i].mode,
                            "reason": self.fused_plans[i].reason})}
                for i in range(len(self.codecs))]

    def hop_attribution(self, delta: Optional[dict],
                        per_hop_bytes: Optional[list] = None, *,
                        link_tier: Optional[int] = None) -> list:
        """Host-side per-cut attribution rows for the tracing plane: one row
        per boundary hop carrying {hop, cut layer, codec tier, wire bytes,
        ladder outcome} — what a request-scoped hop span records.

        ``delta`` is one call's :meth:`link_counters` delta (None when the
        link machinery is off); ``per_hop_bytes`` the call's per-hop wire
        bytes (already multiplied by its step/burst count); ``link_tier``
        the LinkHealth degradation tier if the caller tracks one. The
        outcome collapses the resilience ladder to the *worst* thing that
        happened on the hop, in severity order: substituted > hedged >
        retried > repaired > degraded (tier > 0) > clean. Pure host
        arithmetic on already-synced numpy counters — nothing here touches
        a traced value.
        """
        def counted(key: str, i: int) -> int:
            if not delta or key not in delta:
                return 0
            v = delta[key]
            try:
                return int(v[i])
            except (TypeError, IndexError):
                return int(v)

        rows = []
        for i, codec in enumerate(self.codecs):
            if counted("substituted", i):
                outcome = "substituted"
            elif counted("hedge_wins", i):
                outcome = "hedged"
            elif counted("retried", i):
                outcome = "retried"
            elif counted("repaired", i):
                outcome = "repaired"
            elif link_tier:
                outcome = "degraded"
            else:
                outcome = "clean"
            wire = 0.0
            if per_hop_bytes is not None and i < len(per_hop_bytes):
                wire = float(per_hop_bytes[i])
            rows.append({"hop": i, "cut": int(self.split.cuts[i]),
                         "codec": codec.name, "wire_bytes": wire,
                         "outcome": outcome})
        return rows

    # ---------- incremental decode ----------
    #
    # The regime where the paper's boundary-quantization question bites
    # hardest: at decode time each cut moves ONE token's hidden state per
    # step, so codec overhead dominates the hop. The per-stage KV caches
    # never cross a cut — each stage keeps its own layers' cache sharded on
    # "stage"; only the (B, 1, D) activation is encoded/ppermuted/decoded.

    def _check_decode_supported(self):
        if self.mesh.shape["data"] > 1 or self.mesh.shape["model"] > 1:
            raise ValueError(
                "split decode supports stage-only meshes (n_data=n_model=1); "
                f"got data={self.mesh.shape['data']}, model={self.mesh.shape['model']}")
        bad = [c.name for c in self.codecs if c.needs_importance]
        if bad:
            raise ValueError(
                f"token-selective hop codecs {bad} have no importance source "
                f"for a single decode position; use per-token/channel codecs")

    def _decode_fns(self, capacity: int):
        """Build (or fetch) the jitted prefill/step executables for one cache
        capacity. Capacity is static (it fixes the cache buffers); the fill
        level rides as a traced scalar, so each capacity compiles exactly one
        step executable no matter how many tokens are emitted."""
        if capacity in self._decode_fns_cache:
            return self._decode_fns_cache[capacity]
        cfg, n_stages, sz = self.cfg, self.split.n_stages, self.stage_size
        codecs, mesh = self.codecs, self.mesh
        layer_pspec = self._layer_pspec
        link = self._link
        fused_plans = self.fused_plans
        n_micro = (self.pipeline.num_microbatches if self.pipelined else 1)

        def _hop_protocol(run_stage, hidden, carry, fault_key):
            """Dispatch the carry protocol with or without the faulty link —
            the link-free branch is byte-for-byte the original call."""
            if link is None:
                out, c = run_pipeline_stages_carry(
                    n_stages, codecs, run_stage, hidden, carry,
                    fused_plans=fused_plans)
                return out, c, None
            return run_pipeline_stages_carry(
                n_stages, codecs, run_stage, hidden, carry,
                link=link, fault_key=fault_key)

        def _hop_protocol_pipelined(run_stage, hidden, carry, fault_key):
            """The µ-batch schedule's twin of ``_hop_protocol`` —
            ``run_stage`` takes the pipelined (h_mu, carry, b, valid)
            contract. Only decode steps route here; prefill fills the whole
            cache in one sequential pass either way."""
            if link is None:
                out, c = run_pipeline_stages_carry_microbatched(
                    n_stages, codecs, n_micro, run_stage, hidden, carry,
                    fused_plans=fused_plans)
                return out, c, None
            return run_pipeline_stages_carry_microbatched(
                n_stages, codecs, n_micro, run_stage, hidden, carry,
                link=link, fault_key=fault_key)

        def stage_prefill(local_layers, local_valid, hidden, cos, sin,
                          fault_step=None):
            lv = {k: v[0] for k, v in local_layers.items()}  # (sz, ...)
            valid = local_valid[0]
            s = hidden.shape[1]
            hidden = pcast_varying(hidden, ("stage",))
            zeros = jnp.zeros((sz,) + hidden.shape[:1] + (capacity,)
                              + (cfg.num_kv_heads, cfg.head_dim), hidden.dtype)

            def scan_body(h, xs):
                lp, ok = xs
                out, _, (kl, vl) = block(cfg, lp, h, cos, sin,
                                         capture_stats=False, return_kv=True)
                return jnp.where(ok, out, h), (kl, vl)

            def run_stage(h, cache):
                computed, (ks, vs) = jax.lax.scan(scan_body, h, (lv, valid))
                kc, vc = cache  # (sz, B, capacity, KV, hd)
                return computed, (kc.at[:, :, :s].set(ks),
                                  vc.at[:, :, :s].set(vs))

            fkey = None if link is None else jax.random.fold_in(
                jax.random.fold_in(jax.random.key(link.faults.seed), 0x9EF1),
                fault_step)
            out, (kc, vc), counters = _hop_protocol(
                run_stage, hidden, (zeros, zeros), fkey)
            if link is None:
                return out, kc[None], vc[None]
            return out, kc[None], vc[None], counters

        def stage_step(local_layers, local_valid, hidden, k_loc, v_loc,
                       cos_t, sin_t, pos):
            lv = {k: v[0] for k, v in local_layers.items()}
            valid = local_valid[0]
            hidden = pcast_varying(hidden, ("stage",))

            def scan_body(h, xs):
                lp, ok, kl, vl = xs
                out, kl2, vl2 = block_decode(cfg, lp, h, cos_t, sin_t,
                                             kl, vl, pos)
                # padding layers are identity AND must not touch their cache
                return jnp.where(ok, out, h), (jnp.where(ok, kl2, kl),
                                               jnp.where(ok, vl2, vl))

            def run_stage(h, cache):
                kc, vc = cache
                h2, (kc2, vc2) = jax.lax.scan(scan_body, h,
                                              (lv, valid, kc, vc))
                return h2, (kc2, vc2)

            # the cache fill level is the fault step: distinct per emitted
            # token, identical across same-seed runs, no extra traced arg
            fkey = None if link is None else jax.random.fold_in(
                jax.random.fold_in(jax.random.key(link.faults.seed), 0x57E9),
                pos)
            if n_micro == 1:
                out, (kc, vc), counters = _hop_protocol(
                    run_stage, hidden, (k_loc[0], v_loc[0]), fkey)
            else:
                mb_rows = hidden.shape[0] // n_micro

                def run_stage_mu(h_mu, cache, b, ok):
                    # each device sits at µ-batch b of the schedule: advance
                    # ONLY that µ-batch's cache rows, and write nothing on
                    # the fill/drain steps where ok is False
                    kc, vc = cache  # (sz, B, capacity, KV, hd)
                    start = b * mb_rows
                    kc_mu = jax.lax.dynamic_slice_in_dim(kc, start, mb_rows,
                                                         axis=1)
                    vc_mu = jax.lax.dynamic_slice_in_dim(vc, start, mb_rows,
                                                         axis=1)
                    h2, (kc2, vc2) = jax.lax.scan(scan_body, h_mu,
                                                  (lv, valid, kc_mu, vc_mu))
                    kc = jnp.where(ok, jax.lax.dynamic_update_slice_in_dim(
                        kc, kc2, start, axis=1), kc)
                    vc = jnp.where(ok, jax.lax.dynamic_update_slice_in_dim(
                        vc, vc2, start, axis=1), vc)
                    return h2, (kc, vc)

                out, (kc, vc), counters = _hop_protocol_pipelined(
                    run_stage_mu, hidden, (k_loc[0], v_loc[0]), fkey)
            if link is None:
                return out, kc[None], vc[None]
            return out, kc[None], vc[None], counters

        @jax.jit
        def prefill_fn(placed, input_ids, fault_step=None):
            hidden = embed(placed, input_ids)
            cos, sin = precompute_rope(cfg, input_ids.shape[1])
            lspecs = {k: layer_pspec(k, v.ndim)
                      for k, v in placed["layers"].items()}
            if link is None:
                out, kc, vc = shard_map(
                    stage_prefill, mesh=mesh,
                    in_specs=(lspecs, P("stage"), P(), P(), P()),
                    out_specs=(P(), P("stage"), P("stage")),
                    check_vma=False,
                )(placed["layers"], placed["layers_valid"], hidden, cos, sin)
                return unembed(cfg, placed, out), kc, vc
            out, kc, vc, counters = shard_map(
                stage_prefill, mesh=mesh,
                in_specs=(lspecs, P("stage"), P(), P(), P(), P()),
                out_specs=(P(), P("stage"), P("stage"), P()),
                check_vma=False,
            )(placed["layers"], placed["layers_valid"], hidden, cos, sin,
              fault_step)
            return unembed(cfg, placed, out), kc, vc, counters

        # per-stage KV buffers are donated: each emitted token updates the
        # (n_stages, sz, B, capacity) caches in place instead of copying them
        # (the "split.decode_step" contract asserts the aliasing survives)
        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def step_fn(placed, k_cache, v_cache, length, token_ids):
            hidden = embed(placed, token_ids[:, None])  # (B, 1, D)
            cos, sin = precompute_rope(cfg, capacity)
            cos_t = jax.lax.dynamic_slice_in_dim(cos, length, 1)
            sin_t = jax.lax.dynamic_slice_in_dim(sin, length, 1)
            lspecs = {k: layer_pspec(k, v.ndim)
                      for k, v in placed["layers"].items()}
            if link is None:
                out, kc, vc = shard_map(
                    stage_step, mesh=mesh,
                    in_specs=(lspecs, P("stage"), P(), P("stage"), P("stage"),
                              P(), P(), P()),
                    out_specs=(P(), P("stage"), P("stage")),
                    check_vma=False,
                )(placed["layers"], placed["layers_valid"], hidden,
                  k_cache, v_cache, cos_t, sin_t, length)
                return unembed(cfg, placed, out)[:, -1], kc, vc
            out, kc, vc, counters = shard_map(
                stage_step, mesh=mesh,
                in_specs=(lspecs, P("stage"), P(), P("stage"), P("stage"),
                          P(), P(), P()),
                out_specs=(P(), P("stage"), P("stage"), P()),
                check_vma=False,
            )(placed["layers"], placed["layers_valid"], hidden,
              k_cache, v_cache, cos_t, sin_t, length)
            return unembed(cfg, placed, out)[:, -1], kc, vc, counters

        self._decode_fns_cache[capacity] = (prefill_fn, step_fn)
        return self._decode_fns_cache[capacity]

    def prefill_decode(self, placed_params: dict, input_ids: jnp.ndarray,
                       capacity: int, fault_step: int = 0) -> tuple:
        """Pipeline-split prefill that also fills the per-stage KV caches.
        Returns (logits (B, S, V) fp32, cache dict) — feed the cache to
        :meth:`decode_step`. Cache k/v: (n_stages, sz, B, capacity, KV, hd),
        sharded P("stage") like the layer groups they mirror."""
        self._check_alive()
        self._check_decode_supported()
        s = input_ids.shape[1]
        if not 0 < s <= capacity:
            raise ValueError(
                f"prompt length {s} must be in [1, capacity={capacity}]")
        prefill_fn, _ = self._decode_fns(int(capacity))
        if self._link is None:
            logits, kc, vc = prefill_fn(placed_params, input_ids)
        else:
            logits, kc, vc, counters = prefill_fn(
                placed_params, input_ids, jnp.asarray(fault_step, jnp.int32))
            self._accum_counters(counters)
        return logits, {"k": kc, "v": vc, "length": jnp.asarray(s, jnp.int32)}

    @graph_contract(
        "split.decode_step",
        collectives=lambda ctx: {"ppermute": ctx["hop_eqns"], "psum": 1},
        wire_dtypes=lambda ctx: ctx["wire_dtypes"],
        wire_bytes=lambda ctx: ctx["wire_bytes"],
        donate=lambda ctx: ctx.get("donate_min", 2))
    @graph_contract(
        "split.decode_step.fused",
        # decode-shape twin of split.forward.fused: one flat sealed buffer
        # per cut at (B, 1, D), byte-checked against decode_hop_bytes + 8,
        # with the KV donation discipline intact under fusion
        collectives=lambda ctx: {"ppermute": ctx["hop_eqns"], "psum": 1},
        wire_dtypes=lambda ctx: ctx["wire_dtypes"],
        wire_bytes=lambda ctx: ctx["wire_bytes"],
        donate=lambda ctx: ctx.get("donate_min", 2))
    @graph_contract(
        "split.decode_step.pipelined",
        # µ-batch twin of split.decode_step: M payloads of (B/M, 1, D) per
        # cut per step (pipelined_decode_hop_bytes), ONE stacked psum, and
        # the KV donation discipline intact under the schedule
        collectives=lambda ctx: {"ppermute": ctx["hop_eqns"], "psum": 1},
        wire_dtypes=lambda ctx: ctx["wire_dtypes"],
        wire_bytes=lambda ctx: ctx["wire_bytes"],
        donate=lambda ctx: ctx.get("donate_min", 2))
    def decode_step(self, placed_params: dict, cache: dict,
                    token_ids: jnp.ndarray) -> tuple:
        """One decode position across the pipeline: each cut quantizes the
        single-token hidden state through its wire codec (under faults, via
        the sealed/verified link, keyed by the cache fill level). Returns
        (logits (B, V) fp32, updated cache)."""
        self._check_alive()
        if self.pipelined:
            self.pipeline.validate_batch(int(cache["k"].shape[2]),
                                         "decode batch")
        capacity = cache["k"].shape[3]
        _, step_fn = self._decode_fns(int(capacity))
        if self._link is None:
            logits, kc, vc = step_fn(placed_params, cache["k"], cache["v"],
                                     cache["length"], token_ids)
        else:
            logits, kc, vc, counters = step_fn(
                placed_params, cache["k"], cache["v"], cache["length"],
                token_ids)
            self._accum_counters(counters)
        return logits, {"k": kc, "v": vc, "length": cache["length"] + 1}

    def decode_hop_bytes(self, batch: int) -> list:
        """Measured payload bytes per hop for ONE decode step's (batch, 1, D)
        boundary activation — bytes/token is this divided by ``batch``."""
        return hop_payload_bytes(self.codecs, self.cfg, batch, 1)

    def pipelined_decode_hop_bytes(self, batch: int) -> list:
        """:meth:`decode_hop_bytes` under the µ-batch schedule: each cut
        moves M payloads of (batch/M, 1, D) per step instead of one
        (batch, 1, D) payload (identical totals for row-local codecs, but
        per-µ-batch sidecars — scales, seals — replicate M-fold). Falls back
        to the sequential accounting when pipelining is off or ``batch``
        doesn't µ-batch."""
        if (not self.pipelined
                or batch % self.pipeline.num_microbatches or batch < 1):
            return self.decode_hop_bytes(batch)
        m = self.pipeline.num_microbatches
        return [m * b for b in
                hop_payload_bytes(self.codecs, self.cfg, batch // m, 1)]

    # ---------- speculative verify ----------
    #
    # The k-token twin of the decode step: serve/speculative drafts k tokens
    # on stage 0 and this verifies them all in ONE split pass — each cut
    # moves one quantized (B, k, D) activation block instead of k single-
    # token hops, amortizing the boundary round-trip (and the whole
    # faulty/FEC/hedge/fused hop ladder, which is shape-generic and flows
    # unchanged) k-fold per accepted run.

    def _verify_fns(self, capacity: int, k: int):
        """Build (or fetch) the jitted q_len=k verify executable for one
        (capacity, k) pair. Both are static (cache buffer shape / verify
        window); the fill level rides as a traced scalar, so every verify
        burst of a run reuses one executable — the spec loop is jit-miss-free
        after the first burst. Always the sequential schedule: speculation
        is per-stream (B == 1), so there is no batch to µ-batch — a
        pipelined runtime's verify bursts trace the unchanged pre-pipeline
        graph."""
        key = (capacity, k)
        if key in self._verify_fns_cache:
            return self._verify_fns_cache[key]
        cfg, n_stages, sz = self.cfg, self.split.n_stages, self.stage_size
        codecs, mesh = self.codecs, self.mesh
        layer_pspec = self._layer_pspec
        link = self._link
        fused_plans = self.fused_plans

        def _hop_protocol(run_stage, hidden, carry, fault_key):
            if link is None:
                out, c = run_pipeline_stages_carry(
                    n_stages, codecs, run_stage, hidden, carry,
                    fused_plans=fused_plans)
                return out, c, None
            return run_pipeline_stages_carry(
                n_stages, codecs, run_stage, hidden, carry,
                link=link, fault_key=fault_key)

        def stage_verify(local_layers, local_valid, hidden, k_loc, v_loc,
                         cos_t, sin_t, pos):
            lv = {k2: v[0] for k2, v in local_layers.items()}
            valid = local_valid[0]
            hidden = pcast_varying(hidden, ("stage",))

            def scan_body(h, xs):
                lp, ok, kl, vl = xs
                out, kl2, vl2 = block_verify(cfg, lp, h, cos_t, sin_t,
                                             kl, vl, pos)
                # padding layers are identity AND must not touch their cache
                return jnp.where(ok, out, h), (jnp.where(ok, kl2, kl),
                                               jnp.where(ok, vl2, vl))

            def run_stage(h, cache):
                kc, vc = cache
                h2, (kc2, vc2) = jax.lax.scan(scan_body, h,
                                              (lv, valid, kc, vc))
                return h2, (kc2, vc2)

            # the cache fill level keys the fault step, exactly like the
            # single-token step: distinct per burst, identical across
            # same-seed runs (a resumed run replays the same fill levels)
            fkey = None if link is None else jax.random.fold_in(
                jax.random.fold_in(jax.random.key(link.faults.seed), 0x57E9),
                pos)
            out, (kc, vc), counters = _hop_protocol(
                run_stage, hidden, (k_loc[0], v_loc[0]), fkey)
            if link is None:
                return out, kc[None], vc[None]
            return out, kc[None], vc[None], counters

        # same KV donation discipline as step_fn: each burst updates the
        # (n_stages, sz, B, capacity) caches in place (the
        # "split.verify_step" contract asserts the aliasing survives)
        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def verify_fn(placed, k_cache, v_cache, length, token_ids):
            hidden = embed(placed, token_ids)  # (B, k, D)
            cos, sin = precompute_rope(cfg, capacity)
            cos_t = jax.lax.dynamic_slice_in_dim(cos, length, k)
            sin_t = jax.lax.dynamic_slice_in_dim(sin, length, k)
            lspecs = {k2: layer_pspec(k2, v.ndim)
                      for k2, v in placed["layers"].items()}
            if link is None:
                out, kc, vc = shard_map(
                    stage_verify, mesh=mesh,
                    in_specs=(lspecs, P("stage"), P(), P("stage"), P("stage"),
                              P(), P(), P()),
                    out_specs=(P(), P("stage"), P("stage")),
                    check_vma=False,
                )(placed["layers"], placed["layers_valid"], hidden,
                  k_cache, v_cache, cos_t, sin_t, length)
                return unembed(cfg, placed, out), kc, vc
            out, kc, vc, counters = shard_map(
                stage_verify, mesh=mesh,
                in_specs=(lspecs, P("stage"), P(), P("stage"), P("stage"),
                          P(), P(), P()),
                out_specs=(P(), P("stage"), P("stage"), P()),
                check_vma=False,
            )(placed["layers"], placed["layers_valid"], hidden,
              k_cache, v_cache, cos_t, sin_t, length)
            return unembed(cfg, placed, out), kc, vc, counters

        self._verify_fns_cache[key] = verify_fn
        return verify_fn

    @graph_contract(
        "split.verify_step",
        collectives=lambda ctx: {"ppermute": ctx["hop_eqns"], "psum": 1},
        wire_dtypes=lambda ctx: ctx["wire_dtypes"],
        wire_bytes=lambda ctx: ctx["wire_bytes"],
        donate=lambda ctx: ctx.get("donate_min", 2))
    @graph_contract(
        "split.verify_step.fused",
        # verify-shape twin of split.decode_step.fused: one flat sealed
        # buffer per cut at (B, k, D) — the ISSUE's k x hop_bytes + 8 wire
        # contract: ONE hop per verify burst, not k single-token hops
        collectives=lambda ctx: {"ppermute": ctx["hop_eqns"], "psum": 1},
        wire_dtypes=lambda ctx: ctx["wire_dtypes"],
        wire_bytes=lambda ctx: ctx["wire_bytes"],
        donate=lambda ctx: ctx.get("donate_min", 2))
    def verify_step(self, placed_params: dict, cache: dict,
                    token_ids: jnp.ndarray) -> tuple:
        """Verify k drafted positions in one split pass: ``token_ids`` is
        (B, k) — the last committed token followed by the k-1 draft tokens —
        and each cut quantizes ONE (B, k, D) activation block through its
        wire codec. All k K/V rows are written at ``cache["length"]``; the
        returned cache claims all of them (``length + k``) and the caller
        commits the accepted prefix by shrinking ``length`` (garbage past
        the fill level is masked, so rollback is a length rewrite — no data
        movement). Returns (logits (B, k, V) fp32, updated cache)."""
        self._check_alive()
        self._check_decode_supported()
        capacity = cache["k"].shape[3]
        kq = token_ids.shape[1]
        verify_fn = self._verify_fns(int(capacity), int(kq))
        if self._link is None:
            logits, kc, vc = verify_fn(placed_params, cache["k"], cache["v"],
                                       cache["length"], token_ids)
        else:
            logits, kc, vc, counters = verify_fn(
                placed_params, cache["k"], cache["v"], cache["length"],
                token_ids)
            self._accum_counters(counters)
        return logits, {"k": kc, "v": vc, "length": cache["length"] + kq}

    def verify_hop_bytes(self, batch: int, k: int) -> list:
        """Measured payload bytes per hop for ONE verify burst's (batch, k, D)
        boundary activation — the whole burst's wire cost; divide by the
        accepted run length for bytes/token."""
        return hop_payload_bytes(self.codecs, self.cfg, batch, k)

    # ---------- paged incremental decode ----------
    #
    # The continuous-batching twin of the block above: per-stage KV caches
    # page exactly like serve/batching's local pools (fixed-size pages, a
    # traced page table, trash page 0), so streams with different prompt
    # lengths and fill levels share ONE compiled ragged step per pool
    # geometry while every cut still moves its quantized (B, 1, D) boundary
    # activation.  Pool layout: (n_stages, sz, num_pages, page_size, KV, hd)
    # sharded P("stage") — each stage owns its own layers' pages, pages never
    # cross a cut.

    def init_paged_pool(self, num_pages: int, page_size: int,
                        dtype=jnp.float32, kv_codec: str = "fp") -> dict:
        """Zeroed per-stage paged KV pools, placed sharded on "stage".
        Page 0 is the trash page (see models.paged_kv) — host-side page
        tables must never hand it out. Quantized ``kv_codec`` tiers return
        FOUR arrays — packed codes {"k", "v"} plus per-row fp32 scales
        {"k_scale", "v_scale"} — and a "kv_codec" tag the paged methods
        dispatch on; the fp pool dict is unchanged."""
        self._check_decode_supported()
        if num_pages < 2:
            raise ValueError("need num_pages >= 2 (page 0 is the trash page)")
        cfg = self.cfg
        codec = resolve_kv_codec(kv_codec)
        sh = NamedSharding(self.mesh, P("stage"))
        if not codec.quantized:
            shape = (self.split.n_stages, self.stage_size, num_pages,
                     page_size, cfg.num_kv_heads, cfg.head_dim)
            zeros = functools.partial(jax.jit, static_argnums=0,
                                      out_shardings=sh)(
                lambda s: jnp.zeros(s, dtype))
            return {"k": zeros(shape), "v": zeros(shape)}
        hdc = codec.code_lanes(cfg.head_dim)
        cshape = (self.split.n_stages, self.stage_size, num_pages, page_size,
                  cfg.num_kv_heads, hdc)
        sshape = cshape[:-1]
        czeros = functools.partial(jax.jit, static_argnums=0,
                                   out_shardings=sh)(
            lambda s: jnp.zeros(s, codec.code_dtype))
        szeros = functools.partial(jax.jit, static_argnums=0,
                                   out_shardings=sh)(
            lambda s: jnp.zeros(s, jnp.float32))
        return {"k": czeros(cshape), "v": czeros(cshape),
                "k_scale": szeros(sshape), "v_scale": szeros(sshape),
                "kv_codec": codec.name}

    @staticmethod
    def _pool_codec(pool: dict) -> str:
        return pool.get("kv_codec", "fp") if "k_scale" in pool else "fp"

    @staticmethod
    def _pool_arrays(pool: dict) -> tuple:
        return (pool["k"], pool["v"], pool["k_scale"], pool["v_scale"])

    @staticmethod
    def _pool_dict(arrays: tuple, kv_codec: str) -> dict:
        pk, pv, ks, vs = arrays
        return {"k": pk, "v": pv, "k_scale": ks, "v_scale": vs,
                "kv_codec": kv_codec}

    def adopt_paged(self, pool: dict, cache: dict, row: int,
                    dest: np.ndarray, length: int) -> dict:
        """Move one stream's prefilled contiguous cache (``prefill_decode``
        row ``row``) into pool pages at flat token indices ``dest``
        ((length,) int32, from PagedKVCache._flat_indices). Donates the pool
        buffers — the scatter is stage-elementwise, no collectives. On a
        quantized pool the fp rows quantize on append."""
        dest = jnp.asarray(dest, jnp.int32)
        k_seq = cache["k"][:, :, row, :length]   # (n_stages, sz, n, KV, hd)
        v_seq = cache["v"][:, :, row, :length]
        codec = self._pool_codec(pool)
        if codec != "fp":
            return self._pool_dict(_adopt_paged_quant_impl(
                self._pool_arrays(pool), k_seq, v_seq, dest,
                kv_codec=codec), codec)
        pk, pv = _adopt_paged_impl(pool["k"], pool["v"], k_seq, v_seq, dest)
        return {"k": pk, "v": pv}

    def adopt_paged_rows(self, pool: dict, k_seq, v_seq,
                         dest: np.ndarray) -> dict:
        """Scatter an already-contiguous (n_stages, sz, n, KV, hd) K/V prefix
        — a :meth:`gather_paged` payload, possibly round-tripped through a
        checkpoint — into pool pages at flat token indices ``dest``. The
        re-admission half of eviction for the split batcher. Quantized pools
        requantize fp rows here; bit-exact resume uses the packed twin."""
        dest = jnp.asarray(dest, jnp.int32)
        codec = self._pool_codec(pool)
        if codec != "fp":
            return self._pool_dict(_adopt_paged_quant_impl(
                self._pool_arrays(pool), jnp.asarray(k_seq),
                jnp.asarray(v_seq), dest, kv_codec=codec), codec)
        pk, pv = _adopt_paged_impl(pool["k"], pool["v"], jnp.asarray(k_seq),
                                   jnp.asarray(v_seq), dest)
        return {"k": pk, "v": pv}

    def adopt_paged_rows_packed(self, pool: dict, k_codes, v_codes,
                                k_scale, v_scale, dest: np.ndarray) -> dict:
        """Scatter a :meth:`gather_paged_packed` payload back — raw codes +
        scales, no requantize, so evict -> readmit is bit-exact."""
        codec = self._pool_codec(pool)
        if codec == "fp":
            raise KVTierMismatchError(
                offered="quantized", pool=codec,
                where="adopt_paged_rows_packed",
                detail="packed payloads need a quantized pool; fp pools "
                       "adopt fp rows via adopt_paged_rows")
        return self._pool_dict(_adopt_paged_packed_impl(
            self._pool_arrays(pool), jnp.asarray(k_codes),
            jnp.asarray(v_codes), jnp.asarray(k_scale),
            jnp.asarray(v_scale), jnp.asarray(dest, jnp.int32)), codec)

    def copy_paged_pages(self, pool: dict, src, dst) -> dict:
        """Apply prefix-cache COW forks to the per-stage pools: duplicate
        pages ``src`` to ``dst`` (parallel 1-D index lists from
        ``PagedKVCache.ensure_writable``'s (old, new) pairs). Donates the
        pool buffers; stage-elementwise, no collectives. Quantized pools
        copy codes AND scales — a fork is a byte move, never a requantize."""
        src = jnp.asarray(src, jnp.int32)
        dst = jnp.asarray(dst, jnp.int32)
        codec = self._pool_codec(pool)
        if codec != "fp":
            return self._pool_dict(_copy_paged_pool_impl(
                self._pool_arrays(pool), src, dst), codec)
        pk, pv = _copy_paged_impl(pool["k"], pool["v"], src, dst)
        return {"k": pk, "v": pv}

    def gather_paged(self, pool: dict, idx: np.ndarray) -> tuple:
        """Gather one stream's (n_stages, sz, n, KV, hd) K/V prefix from pool
        pages at flat token indices ``idx`` — byte-identical to the
        contiguous cache rows :meth:`adopt_paged` scattered (the split twin
        of ``PagedKVCache.gather_slot``, for eviction and checkpointing).
        Returns host (k_seq, v_seq) numpy arrays; the pool is NOT consumed.
        Quantized pools come back DEQUANTIZED to fp32 (the suffix-prefill
        compute form); the packed twin preserves the raw bytes."""
        idx = jnp.asarray(idx, jnp.int32)
        codec = self._pool_codec(pool)
        if codec != "fp":
            k_seq, v_seq = _gather_paged_quant_impl(
                self._pool_arrays(pool), idx, kv_codec=codec)
            return np.asarray(k_seq), np.asarray(v_seq)
        k_seq, v_seq = _gather_paged_impl(pool["k"], pool["v"], idx)
        return np.asarray(k_seq), np.asarray(v_seq)

    def gather_paged_packed(self, pool: dict, idx: np.ndarray) -> tuple:
        """Quantized-pool eviction/checkpoint form: host (k_codes, v_codes,
        k_scale, v_scale) numpy arrays at flat token indices ``idx`` — the
        raw pool bytes, so the adopt_paged_rows_packed round-trip is
        bit-exact by construction."""
        if self._pool_codec(pool) == "fp":
            raise KVTierMismatchError(
                offered="quantized", pool="fp",
                where="gather_paged_packed",
                detail="the packed gather form needs a quantized pool; fp "
                       "pools use gather_paged")
        out = _gather_paged_packed_impl(self._pool_arrays(pool),
                                        jnp.asarray(idx, jnp.int32))
        return tuple(np.asarray(a) for a in out)

    def _paged_decode_fns(self, num_pages: int, page_size: int,
                          kv_codec: str = "fp"):
        """Build (or fetch) the jitted ragged step executable for one pool
        geometry. Page table and lengths are TRACED — one executable per
        (num_pages, page_size, max_slots, pages_per_slot) shape serves every
        admit/evict/fill state (the jit-miss-free property batching relies
        on). Quantized ``kv_codec`` tiers get their own executable carrying
        four pool arrays (codes + scales) through every hop."""
        if kv_codec != "fp":
            return self._paged_decode_fns_quant(num_pages, page_size,
                                                kv_codec)
        key = ("paged", num_pages, page_size)
        if key in self._paged_fns_cache:
            return self._paged_fns_cache[key]
        cfg, n_stages, sz = self.cfg, self.split.n_stages, self.stage_size
        codecs, mesh = self.codecs, self.mesh
        layer_pspec = self._layer_pspec
        link = self._link
        fused_plans = self.fused_plans
        n_micro = (self.pipeline.num_microbatches if self.pipelined else 1)

        def _hop_protocol(run_stage, hidden, carry, fault_key):
            if link is None:
                out, c = run_pipeline_stages_carry(
                    n_stages, codecs, run_stage, hidden, carry,
                    fused_plans=fused_plans)
                return out, c, None
            return run_pipeline_stages_carry(
                n_stages, codecs, run_stage, hidden, carry,
                link=link, fault_key=fault_key)

        def _hop_protocol_pipelined(run_stage, hidden, carry, fault_key):
            if link is None:
                out, c = run_pipeline_stages_carry_microbatched(
                    n_stages, codecs, n_micro, run_stage, hidden, carry,
                    fused_plans=fused_plans)
                return out, c, None
            return run_pipeline_stages_carry_microbatched(
                n_stages, codecs, n_micro, run_stage, hidden, carry,
                link=link, fault_key=fault_key)

        def stage_step_paged(local_layers, local_valid, hidden, kp_loc,
                             vp_loc, page_table, lengths, cos_b, sin_b):
            lv = {k: v[0] for k, v in local_layers.items()}
            valid = local_valid[0]
            hidden = pcast_varying(hidden, ("stage",))

            # the deepest slot's fill level keys the fault step: distinct as
            # decoding advances, identical across same-seed replays of the
            # same admit/evict schedule
            fkey = None if link is None else jax.random.fold_in(
                jax.random.fold_in(jax.random.key(link.faults.seed), 0x57E9),
                jnp.max(lengths))
            if n_micro == 1:
                def scan_body(h, xs):
                    lp, ok, kp, vp = xs
                    out, kp2, vp2 = block_decode_paged(
                        cfg, lp, h, cos_b, sin_b, kp, vp, page_table, lengths)
                    # padding layers are identity AND must not touch their
                    # pages
                    return jnp.where(ok, out, h), (jnp.where(ok, kp2, kp),
                                                   jnp.where(ok, vp2, vp))

                def run_stage(h, cache):
                    kp, vp = cache
                    h2, (kp2, vp2) = jax.lax.scan(scan_body, h,
                                                  (lv, valid, kp, vp))
                    return h2, (kp2, vp2)

                out, (kp, vp), counters = _hop_protocol(
                    run_stage, hidden, (kp_loc[0], vp_loc[0]), fkey)
            else:
                mb_rows = hidden.shape[0] // n_micro

                def run_stage_mu(h_mu, cache, b, ok):
                    # the pool is shared across slots so it is NOT sliced per
                    # µ-batch; instead each step sees only its µ-batch's slot
                    # rows of the page table, and fill/drain steps (ok False)
                    # have their writes routed to the trash page (page 0) so
                    # no real page is touched
                    start = b * mb_rows
                    pt_mu = jax.lax.dynamic_slice_in_dim(page_table, start,
                                                         mb_rows, axis=0)
                    pt_mu = jnp.where(ok, pt_mu, 0)
                    ln_mu = jax.lax.dynamic_slice_in_dim(lengths, start,
                                                         mb_rows, axis=0)
                    cb_mu = jax.lax.dynamic_slice_in_dim(cos_b, start,
                                                         mb_rows, axis=0)
                    sb_mu = jax.lax.dynamic_slice_in_dim(sin_b, start,
                                                         mb_rows, axis=0)

                    def scan_body_mu(h, xs):
                        lp, okl, kp, vp = xs
                        out, kp2, vp2 = block_decode_paged(
                            cfg, lp, h, cb_mu, sb_mu, kp, vp, pt_mu, ln_mu)
                        return jnp.where(okl, out, h), (
                            jnp.where(okl, kp2, kp), jnp.where(okl, vp2, vp))

                    kp, vp = cache
                    h2, (kp2, vp2) = jax.lax.scan(scan_body_mu, h_mu,
                                                  (lv, valid, kp, vp))
                    return h2, (kp2, vp2)

                out, (kp, vp), counters = _hop_protocol_pipelined(
                    run_stage_mu, hidden, (kp_loc[0], vp_loc[0]), fkey)
            if link is None:
                return out, kp[None], vp[None]
            return out, kp[None], vp[None], counters

        # pools are donated: every ragged step scatters in place, same
        # aliasing discipline the "split.decode_step_paged" contract asserts
        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def step_paged_fn(placed, pool_k, pool_v, page_table, lengths,
                          token_ids):
            hidden = embed(placed, token_ids[:, None])  # (B, 1, D)
            span = page_table.shape[1] * page_size
            cos, sin = precompute_rope(cfg, span)
            cos_b = cos[lengths]  # (B, rot) — each slot's own position
            sin_b = sin[lengths]
            lspecs = {k: layer_pspec(k, v.ndim)
                      for k, v in placed["layers"].items()}
            if link is None:
                out, kp, vp = shard_map(
                    stage_step_paged, mesh=mesh,
                    in_specs=(lspecs, P("stage"), P(), P("stage"), P("stage"),
                              P(), P(), P(), P()),
                    out_specs=(P(), P("stage"), P("stage")),
                    check_vma=False,
                )(placed["layers"], placed["layers_valid"], hidden,
                  pool_k, pool_v, page_table, lengths, cos_b, sin_b)
                return unembed(cfg, placed, out)[:, -1], kp, vp
            out, kp, vp, counters = shard_map(
                stage_step_paged, mesh=mesh,
                in_specs=(lspecs, P("stage"), P(), P("stage"), P("stage"),
                          P(), P(), P(), P()),
                out_specs=(P(), P("stage"), P("stage"), P()),
                check_vma=False,
            )(placed["layers"], placed["layers_valid"], hidden,
              pool_k, pool_v, page_table, lengths, cos_b, sin_b)
            return unembed(cfg, placed, out)[:, -1], kp, vp, counters

        self._paged_fns_cache[key] = step_paged_fn
        return step_paged_fn

    def _paged_decode_fns_quant(self, num_pages: int, page_size: int,
                                kv_codec: str):
        """Quantized twin of :meth:`_paged_decode_fns`: the scan carries
        packed codes AND per-row scales, every layer dequantizes in-kernel
        (models.flash_attention.paged_decode_attention_quant), and appends
        quantize before the scatter. Unpipelined only — the µ-batch trash
        -page routing has no quant twin (ContinuousBatcher refuses the
        combination up front)."""
        key = ("paged_quant", num_pages, page_size, kv_codec)
        if key in self._paged_fns_cache:
            return self._paged_fns_cache[key]
        if self.pipelined and self.pipeline.num_microbatches > 1:
            raise ValueError(
                "quantized paged decode composes with the unpipelined split "
                "runtime only (n_micro must be 1)")
        cfg, n_stages, sz = self.cfg, self.split.n_stages, self.stage_size
        codecs, mesh = self.codecs, self.mesh
        layer_pspec = self._layer_pspec
        link = self._link
        fused_plans = self.fused_plans

        def _hop_protocol(run_stage, hidden, carry, fault_key):
            if link is None:
                out, c = run_pipeline_stages_carry(
                    n_stages, codecs, run_stage, hidden, carry,
                    fused_plans=fused_plans)
                return out, c, None
            return run_pipeline_stages_carry(
                n_stages, codecs, run_stage, hidden, carry,
                link=link, fault_key=fault_key)

        def stage_step_paged_quant(local_layers, local_valid, hidden, kp_loc,
                                   vp_loc, ks_loc, vs_loc, page_table,
                                   lengths, cos_b, sin_b):
            lv = {k: v[0] for k, v in local_layers.items()}
            valid = local_valid[0]
            hidden = pcast_varying(hidden, ("stage",))
            fkey = None if link is None else jax.random.fold_in(
                jax.random.fold_in(jax.random.key(link.faults.seed), 0x57E9),
                jnp.max(lengths))

            def scan_body(h, xs):
                lp, ok, kp, vp, ks, vs = xs
                out, kp2, vp2, ks2, vs2 = block_decode_paged_quant(
                    cfg, lp, h, cos_b, sin_b, kp, vp, ks, vs, page_table,
                    lengths, kv_codec)
                # padding layers are identity AND must not touch their pages
                return jnp.where(ok, out, h), (
                    jnp.where(ok, kp2, kp), jnp.where(ok, vp2, vp),
                    jnp.where(ok, ks2, ks), jnp.where(ok, vs2, vs))

            def run_stage(h, cache):
                kp, vp, ks, vs = cache
                h2, cache2 = jax.lax.scan(scan_body, h,
                                          (lv, valid, kp, vp, ks, vs))
                return h2, cache2

            out, (kp, vp, ks, vs), counters = _hop_protocol(
                run_stage, hidden,
                (kp_loc[0], vp_loc[0], ks_loc[0], vs_loc[0]), fkey)
            if link is None:
                return out, kp[None], vp[None], ks[None], vs[None]
            return out, kp[None], vp[None], ks[None], vs[None], counters

        @functools.partial(jax.jit, donate_argnums=(1, 2, 3, 4))
        def step_paged_quant_fn(placed, pool_k, pool_v, pool_ks, pool_vs,
                                page_table, lengths, token_ids):
            hidden = embed(placed, token_ids[:, None])  # (B, 1, D)
            span = page_table.shape[1] * page_size
            cos, sin = precompute_rope(cfg, span)
            cos_b = cos[lengths]
            sin_b = sin[lengths]
            lspecs = {k: layer_pspec(k, v.ndim)
                      for k, v in placed["layers"].items()}
            if link is None:
                out, kp, vp, ks, vs = shard_map(
                    stage_step_paged_quant, mesh=mesh,
                    in_specs=(lspecs, P("stage"), P(), P("stage"), P("stage"),
                              P("stage"), P("stage"), P(), P(), P(), P()),
                    out_specs=(P(), P("stage"), P("stage"), P("stage"),
                               P("stage")),
                    check_vma=False,
                )(placed["layers"], placed["layers_valid"], hidden,
                  pool_k, pool_v, pool_ks, pool_vs, page_table, lengths,
                  cos_b, sin_b)
                return unembed(cfg, placed, out)[:, -1], kp, vp, ks, vs
            out, kp, vp, ks, vs, counters = shard_map(
                stage_step_paged_quant, mesh=mesh,
                in_specs=(lspecs, P("stage"), P(), P("stage"), P("stage"),
                          P("stage"), P("stage"), P(), P(), P(), P()),
                out_specs=(P(), P("stage"), P("stage"), P("stage"),
                           P("stage"), P()),
                check_vma=False,
            )(placed["layers"], placed["layers_valid"], hidden,
              pool_k, pool_v, pool_ks, pool_vs, page_table, lengths,
              cos_b, sin_b)
            return unembed(cfg, placed, out)[:, -1], kp, vp, ks, vs, counters

        self._paged_fns_cache[key] = step_paged_quant_fn
        return step_paged_quant_fn

    @graph_contract(
        "split.decode_step_paged",
        collectives=lambda ctx: {"ppermute": ctx["hop_eqns"], "psum": 1},
        wire_dtypes=lambda ctx: ctx["wire_dtypes"],
        wire_bytes=lambda ctx: ctx["wire_bytes"],
        donate=lambda ctx: ctx.get("donate_min", 2))
    @graph_contract(
        "split.decode_step_paged.pipelined",
        # the ragged twin under the µ-batch schedule: M payloads of
        # (max_slots/M, 1, D) per cut, pools still donated, one psum
        collectives=lambda ctx: {"ppermute": ctx["hop_eqns"], "psum": 1},
        wire_dtypes=lambda ctx: ctx["wire_dtypes"],
        wire_bytes=lambda ctx: ctx["wire_bytes"],
        donate=lambda ctx: ctx.get("donate_min", 2))
    def decode_step_paged(self, placed_params: dict, pool: dict,
                          page_table: jnp.ndarray, lengths: jnp.ndarray,
                          token_ids: jnp.ndarray) -> tuple:
        """One ragged decode position across the pipeline: every active slot
        advances at its OWN fill level; each cut quantizes the single-token
        hidden batch through its wire codec. page_table (max_slots,
        pages_per_slot) / lengths (max_slots,) come from a host-side
        PagedKVCache (cache_dim=... n/a — the host object tracks pages, this
        runs the math). Returns (logits (max_slots, V) fp32, updated pool).
        Per-slot tokens are bit-identical to :meth:`decode_step` at the same
        position (tests/test_batching.py asserts it end to end)."""
        self._check_alive()
        self._check_decode_supported()
        if self.pipelined:
            self.pipeline.validate_batch(int(np.shape(page_table)[0]),
                                         "paged decode slot count")
        num_pages, page_size = pool["k"].shape[2], pool["k"].shape[3]
        codec = self._pool_codec(pool)
        step_fn = self._paged_decode_fns(int(num_pages), int(page_size),
                                         kv_codec=codec)
        page_table = jnp.asarray(page_table, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        if codec != "fp":
            if self._link is None:
                logits, pk, pv, ks, vs = step_fn(
                    placed_params, pool["k"], pool["v"], pool["k_scale"],
                    pool["v_scale"], page_table, lengths, token_ids)
            else:
                logits, pk, pv, ks, vs, counters = step_fn(
                    placed_params, pool["k"], pool["v"], pool["k_scale"],
                    pool["v_scale"], page_table, lengths, token_ids)
                self._accum_counters(counters)
            return logits, self._pool_dict((pk, pv, ks, vs), codec)
        if self._link is None:
            logits, pk, pv = step_fn(placed_params, pool["k"], pool["v"],
                                     page_table, lengths, token_ids)
        else:
            logits, pk, pv, counters = step_fn(
                placed_params, pool["k"], pool["v"], page_table, lengths,
                token_ids)
            self._accum_counters(counters)
        return logits, {"k": pk, "v": pv}

    # ---------- accounting ----------

    def hop_bytes(self, batch: int, seq: int) -> list:
        """Measured payload bytes per hop for one (batch, seq, D) activation."""
        return hop_payload_bytes(self.codecs, self.cfg, batch, seq)

    def bytes_per_token(self, seq: int) -> list:
        """Per-hop boundary bytes per token (the BASELINE.json metric)."""
        return [b / seq for b in self.hop_bytes(1, seq)]

    def time_hops(self, batch: int, seq: int, iters: int = 20,
                  warmup: int = 1) -> list:
        """Measured per-hop boundary-transfer time (ms): encode -> ppermute ->
        decode of one (batch, seq, D) activation, isolated from the stage
        compute so the observability numbers attribute wire cost separately
        (the reference has no transfer at all to time — SURVEY.md section 5).
        Always pre-warmed (``warmup`` clamps to >= 1) so compile seconds
        never pollute the per-hop ms."""
        return measure_hop_times(self.mesh, self.codecs, self.cfg, batch, seq,
                                 iters=iters, warmup=warmup)

    def time_decode_hops(self, batch: int = 1, iters: int = 20,
                         warmup: int = 1) -> list:
        """:meth:`time_hops` at the decode shape — one (batch, 1, D) token
        per step, the regime where codec overhead dominates the hop and
        where an unwarmed jit would mis-report compile time as transfer
        time (the per-hop payload is a few KB; the first-call compile is
        seconds)."""
        return measure_hop_times(self.mesh, self.codecs, self.cfg, batch, 1,
                                 iters=iters, warmup=warmup)
