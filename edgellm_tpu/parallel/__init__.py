"""Split-LLM runtime over a TPU device mesh.

The reference's "two edge devices" are a fiction — one process edits a tensor in
place at the split layer (``qwen_layer_wise.py:54-73``). Here the split is real:
each pipeline stage's layer parameters live on their own device of a
``jax.sharding.Mesh``, and the boundary activation crosses between neighbouring
devices as a *packed, quantized* payload via ``lax.ppermute`` inside
``shard_map`` — over ICI on a real TPU slice, over host memory on the spoofed
CPU mesh the tests use.

Multi-host scaling: every runtime here is written against ``jax.devices()``
and a named ``Mesh``, so the same code runs across hosts once
``jax.distributed.initialize()`` has joined them (``run.py --distributed`` /
:func:`initialize_distributed`) — ``jax.devices()`` then spans the full
slice/pod and the slice-aware builders (:func:`make_multihost_stage_mesh`,
:func:`make_multihost_sp_stage_mesh`) lay stages/seq shards over it.
Axis layout determines the fabric each collective rides: keep the "stage" and
"seq" axes within a slice so the per-cut ``ppermute`` and the ring's K/V
rotation stay on ICI, and put the embarrassingly-parallel "data" axis
outermost so any cross-slice (DCN) edge only carries the per-window NLL
reductions, never per-token activation traffic.

Compile-time scaling of the static unrolls (the pipeline protocol unrolls its
stages, the ring unrolls its n_seq hops): measured first-call time
(trace+compile, tiny shapes, CPU) grows LINEARLY — ~0.3 s/stage and
~0.3 s/hop out to 32 of either, with no cliff. The composed stage x seq
runtime multiplies the two (O(stages * n_seq) unrolled hops), so a
4-stage x 8-seq pod layout compiles in the same ballpark as 32 plain stages;
at the BASELINE configs' 2-3 stages compile cost is negligible.
"""
from .split import PipelineConfig, SplitConfig, SplitRuntime, make_stage_mesh
from .ring import (ring_attention, forward_sp, make_seq_mesh,
                   SplitRingRuntime, make_sp_stage_mesh)
from .distributed import (initialize_distributed, build_stage_grid,
                          make_multihost_stage_mesh, make_multihost_sp_stage_mesh)

__all__ = ["SplitConfig", "SplitRuntime", "make_stage_mesh",
           "ring_attention", "forward_sp", "make_seq_mesh",
           "SplitRingRuntime", "make_sp_stage_mesh",
           "initialize_distributed", "build_stage_grid",
           "make_multihost_stage_mesh", "make_multihost_sp_stage_mesh"]
