"""Multi-host (multi-process / multi-slice) entry path.

The reference scales past one machine with an MPI/NCCL-style backend (its
device-migration helpers assume one process per GPU). The JAX equivalent is
``jax.distributed.initialize()`` — after it, ``jax.devices()`` spans every
host's chips and the SAME runtimes (``SplitRuntime``, ``SplitRingRuntime``)
run unchanged over a global mesh; XLA routes each collective over ICI within
a slice and DCN between slices.

What this module adds over the plain mesh builders is the AXIS LAYOUT the
package docstring (``parallel/__init__.py``) promises:

- "stage" / "seq" / "model" axes are packed WITHIN a slice, so the per-cut
  ``ppermute`` hops and the ring's K/V rotation ride ICI;
- the embarrassingly-parallel "data" axis is the only axis that crosses
  slices, so any DCN edge carries per-window NLL reductions, never per-token
  activation traffic.

``build_stage_grid`` is pure device-list bookkeeping (testable against mocked
device objects — multi-process can't run in a single-host test environment);
the ``make_multihost_*`` builders wrap the grid in a named ``Mesh``.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

_initialized = False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> int:
    """Join this process to the distributed runtime -> number of processes.

    On TPU pods ``jax.distributed.initialize()`` auto-discovers everything
    from the environment metadata; explicit args cover manual (e.g. GPU/CPU)
    bring-up. Idempotent: repeated calls are no-ops.
    """
    global _initialized
    if _initialized:
        return jax.process_count()
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except (ValueError, RuntimeError) as e:
        # on TPU pods initialize() auto-discovers everything; elsewhere it
        # demands a coordinator. With none configured (no args, no cluster
        # environment) this is a single-process run — degrade instead of
        # dying so `--distributed` scripts work unchanged on one host.
        # Explicit args or any sign of an actual multi-host launch (cluster
        # env vars whose auto-detect failed) still raise loudly: N workers
        # silently proceeding as N independent "process 0 of 1" runs would
        # write conflicting outputs. The match is loose about exception type
        # and phrasing (both have drifted across JAX versions) but must
        # indicate a MISSING coordinator configuration — a coordinator
        # *connect* failure ("failed to connect to coordinator ...") is a real
        # broken launch and propagates.
        if kwargs or not _is_missing_coordinator(e) or _in_cluster_env():
            raise
        import warnings

        warnings.warn("initialize_distributed: no coordinator configured; "
                      "continuing as a single process")
        _initialized = True
        return 1
    _initialized = True
    return jax.process_count()


def _is_missing_coordinator(e: BaseException) -> bool:
    """True when ``jax.distributed.initialize()`` failed because no coordinator
    was CONFIGURED (the benign single-host case), as opposed to a configured
    coordinator that could not be reached."""
    msg = str(e).lower()
    if "coordinator" not in msg:
        return False
    return any(w in msg for w in ("defined", "specified", "configured",
                                  "required", "missing", "not set"))


def _in_cluster_env() -> bool:
    """Signs this process is part of a multi-host launch even though
    coordinator auto-detection failed."""
    import os

    # world-size style launchers: slurm, mpirun/OpenMPI, PMI, torchrun-style
    for var in ("SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE",
                "WORLD_SIZE"):
        try:
            if int(os.environ.get(var, "1") or 1) > 1:
                return True
        except ValueError:
            pass
    # a single-entry TPU_WORKER_HOSTNAMES (e.g. "localhost") is a one-host
    # setup; only a multi-entry list implies a pod launch
    if "," in os.environ.get("TPU_WORKER_HOSTNAMES", ""):
        return True
    return any(os.environ.get(k) for k in (
        "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
        "MEGASCALE_COORDINATOR_ADDRESS"))


def _slice_of(d) -> int:
    """Slice index of a device: multi-slice TPUs expose ``slice_index``;
    single-slice and CPU devices all land in slice 0 (treating each process
    as its own 'slice' would forbid intra-slice multi-host stages, which ARE
    ICI-connected on a real pod slice)."""
    return getattr(d, "slice_index", 0) or 0


def build_stage_grid(devices: Sequence, n_stages: int, n_data: Optional[int],
                     n_model: int = 1, inner: str = "stage") -> np.ndarray:
    """Arrange ``devices`` into an (n_stages, n_data, n_model) object grid such
    that every (stage x model) group lives within ONE slice and the data axis
    enumerates groups across slices.

    ``n_data=None`` infers the data extent from the device count (every slice
    must hold a whole number of groups). ``inner`` names the second axis only
    for error messages ("stage" or "seq" — the ring layout is the same math).
    """
    group = n_stages * n_model
    by_slice: dict = {}
    for d in devices:
        by_slice.setdefault(_slice_of(d), []).append(d)
    for s in by_slice:
        by_slice[s].sort(key=lambda d: (d.process_index, d.id))
        if len(by_slice[s]) % group:
            raise ValueError(
                f"slice {s} holds {len(by_slice[s])} devices, not a multiple "
                f"of the {inner} x model group size {group} — a group may not "
                f"span slices (its hops must stay on ICI)")
    total_groups = sum(len(v) // group for v in by_slice.values())
    if n_data is None:
        n_data = total_groups
    if total_groups != n_data:
        raise ValueError(f"device list yields {total_groups} ({inner} x model) "
                         f"groups, but n_data={n_data} requested")
    groups = []
    for s in sorted(by_slice):
        devs = by_slice[s]
        for i in range(len(devs) // group):
            flat = devs[i * group:(i + 1) * group]
            groups.append(np.asarray(flat, object).reshape(n_stages, n_model))
    # (n_data, n_stages, n_model) -> (n_stages, n_data, n_model)
    return np.stack(groups, axis=0).transpose(1, 0, 2)


def make_multihost_stage_mesh(n_stages: int, n_data: Optional[int] = None,
                              n_model: int = 1, devices=None) -> Mesh:
    """Slice-aware ("stage", "data", "model") mesh over every process's
    devices. Drop-in for ``make_stage_mesh`` after
    ``initialize_distributed()``; on one host the two agree."""
    devices = list(devices) if devices is not None else jax.devices()
    grid = build_stage_grid(devices, n_stages, n_data, n_model)
    return Mesh(grid, ("stage", "data", "model"))


def make_multihost_sp_stage_mesh(n_stages: int, n_seq: int,
                                 devices=None) -> Mesh:
    """Slice-aware ("stage", "seq") mesh for the composed stage x seq ring
    runtime: each stage x seq group (whose hops and K/V rotation are the
    per-token traffic) is pinned within a slice."""
    devices = list(devices) if devices is not None else jax.devices()
    grid = build_stage_grid(devices, n_stages, None, n_seq, inner="seq")
    if grid.shape[1] != 1:
        raise ValueError(
            f"stage x seq mesh needs exactly n_stages*n_seq={n_stages * n_seq} "
            f"devices, got {grid.shape[1]} groups; shrink the device list or "
            f"run data-parallel ring groups as separate processes")
    return Mesh(grid[:, 0, :], ("stage", "seq"))
