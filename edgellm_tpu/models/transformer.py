"""Functional transformer core shared by the GPT-NeoX (Pythia) and Qwen2 families.

TPU-first re-design of the reference's layer-wise model wrappers
(``/root/reference/Experiments/Pythia-70M/pythia_model.py:153-206`` and
``Experiments/Qwen2-0.5B/qwen_layer_wise.py:42-104``):

- Parameters are a plain pytree with **all layers stacked along a leading axis**, so
  the layer loop is a single ``lax.scan`` (one traced block, fast compiles, and the
  stack shards naturally along a pipeline-stage mesh axis).
- The reference runs a *second* full model with eager attention just to obtain
  attention maps for importance scoring (``last_row_exp.py:66-70``,
  ``Qwen2-0.5B/main.py:132-134``). Here the same forward can capture reduced
  attention statistics (per-head column means and last rows) in one pass — the
  only quantities the importance metrics actually consume — so no second model and
  no O(S^2) attention-map materialization on the hot path.
- A ``boundary_fn(layer_idx, hidden) -> hidden`` hook reproduces the reference's
  in-place edit of the hidden state after ``layer_of_interest``; in the split
  runtime the same hook is where the activation is quantized, packed, and sent
  across the mesh (``edgellm_tpu.parallel``).
- ``run_layers`` exposes a statically-sliced segment of the stack so sweep drivers
  can resume from a cached boundary activation instead of recomputing the prefix
  (the reference recomputes the full forward for every method x layer x ratio
  combination — ``Qwen2-0.5B/main.py:170-178``).

Everything is jit-safe: no data-dependent Python control flow, static shapes.
"""
from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .configs import ModelConfig
from ..lint import graph_contract


class AttnStats(NamedTuple):
    """Per-layer reduced attention statistics (enough for every importance metric).

    col_mean: (L, B, H, S) — mean over the query axis of the post-softmax attention
        map, i.e. average attention *received* by each key position per head
        (the "column-wise mean" of README.md:63-67).
    last_row: (L, B, H, S) — final query row of the attention map per head.
    """

    col_mean: jnp.ndarray
    last_row: jnp.ndarray


def precompute_rope(cfg: ModelConfig, seq_len: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables, fp32, HF convention: emb = concat(freqs, freqs)."""
    rot = cfg.rotary_dim
    inv_freq = 1.0 / (cfg.rope_theta ** (jnp.arange(0, rot, 2, dtype=jnp.float32) / rot))
    if cfg.rope_scaling is not None:
        inv_freq = _llama3_scale_freqs(inv_freq, cfg.rope_scaling)
    pos = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(pos, inv_freq)  # (S, rot/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # (S, rot)
    return jnp.cos(emb), jnp.sin(emb)


def _llama3_scale_freqs(inv_freq: jnp.ndarray, scaling: tuple) -> jnp.ndarray:
    """Llama-3.x RoPE frequency rescaling (transformers'
    ``_compute_llama3_parameters``): long-wavelength components are slowed by
    ``factor``, short ones kept, with a smooth ramp between the two cutoff
    wavelengths. ``scaling`` = ("llama3", factor, low_freq_factor,
    high_freq_factor, original_max_position_embeddings)."""
    kind, factor, low_ff, high_ff, orig = scaling
    if kind != "llama3":
        raise ValueError(f"unsupported rope_scaling type {kind!r}")
    low_wavelen = orig / low_ff
    high_wavelen = orig / high_ff
    wavelen = 2.0 * jnp.pi / inv_freq
    smooth = (orig / wavelen - low_ff) / (high_ff - low_ff)
    smoothed = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    scaled = jnp.where(wavelen > low_wavelen, inv_freq / factor,
                       jnp.where(wavelen < high_wavelen, inv_freq, smoothed))
    return scaled


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rotary(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray, rot: int) -> jnp.ndarray:
    """Apply rotary embedding to the first ``rot`` dims of the head dimension.

    x: (B, S, H, hd); cos/sin: (S, rot). Partial rotary (rot < hd) is the GPT-NeoX
    ``rotary_pct`` path; Qwen2 uses rot == hd.
    """
    c = cos[None, :, None, :].astype(x.dtype)
    s = sin[None, :, None, :].astype(x.dtype)
    if rot == x.shape[-1]:
        return x * c + _rotate_half(x) * s
    x_rot, x_pass = x[..., :rot], x[..., rot:]
    x_rot = x_rot * c + _rotate_half(x_rot) * s
    return jnp.concatenate([x_rot, x_pass], axis=-1)


def _layernorm(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (out * scale + bias).astype(x.dtype)


def _rmsnorm(x, scale, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return ((xf * jax.lax.rsqrt(var + eps)).astype(x.dtype)) * scale


def _norm(cfg: ModelConfig, x, scale, bias):
    if cfg.family == "gpt_neox":
        return _layernorm(x, scale, bias, cfg.norm_eps)
    return _rmsnorm(x, scale, cfg.norm_eps)


def _stats_block_size(s: int, requested: Optional[int]) -> int:
    """Query-block length for the streaming stats path. ``None`` auto-picks the
    largest sublane-friendly divisor of S; explicit sizes must divide S; 0 (or
    a full-length block) selects the single-block path, which is exactly the
    old full-probs formulation."""
    if requested is not None:
        if requested == 0:
            return s
        if s % requested:
            raise ValueError(f"stats_block {requested} must divide seq len {s}")
        return requested
    for q in (128, 64, 32, 16, 8):
        if s % q == 0 and q < s:
            return q
    return s


def attention(cfg: ModelConfig, lp: dict, x: jnp.ndarray, cos, sin,
              capture_stats: bool,
              tp_axis: Optional[str] = None,
              stats_block: Optional[int] = None,
              return_kv: bool = False):
    """Eager-math attention (explicit softmax) with optional reduced-stat capture.

    The explicit-softmax formulation is what lets importance statistics fall out of
    the same pass (the constraint the reference hit with SDPA at
    ``last_row_exp.py:93-95``). XLA fuses the mask+softmax chain; the matmuls hit
    the MXU with fp32 accumulation.

    The stats path STREAMS query blocks (``stats_block`` rows at a time): each
    block's probabilities are materialized at (B, H, q_blk, S), its column sum
    accumulated, and the block discarded — peak memory drops S/q_blk-fold vs
    the (B, H, S, S) tensor while every importance statistic (per-head column
    means + last rows) stays exact. The softmax math per query row is identical
    to the full-probs formulation (rows are complete — no online rescaling), so
    ``stats_block=0`` (single block) IS the old path and serves as the oracle
    in tests. This is SURVEY section 7 hard-part #1 solved at the memory level.

    Head counts derive from the *weight shapes*, not the config, so the same code
    runs a tensor-parallel shard: with q/k/v columns split head-contiguously
    along ``tp_axis``, each device attends over its local heads and the row-split
    output projection's partial product is ``psum``-reduced across the axis
    (Megatron-style column/row pairing, expressed as a shard_map collective).
    """
    b, s, d = x.shape
    hd = cfg.head_dim
    h, kv = lp["wq"].shape[-1] // hd, lp["wk"].shape[-1] // hd  # local heads

    q = (x @ lp["wq"]).reshape(b, s, h, hd)
    k = (x @ lp["wk"]).reshape(b, s, kv, hd)
    v = (x @ lp["wv"]).reshape(b, s, kv, hd)
    if "bq" in lp:
        q = q + lp["bq"].reshape(h, hd)
        k = k + lp["bk"].reshape(kv, hd)
        v = v + lp["bv"].reshape(kv, hd)

    q = apply_rotary(q, cos, sin, cfg.rotary_dim)
    k = apply_rotary(k, cos, sin, cfg.rotary_dim)
    # the cacheable K/V: post-rotary, PRE-GQA-repeat (the cache stores
    # num_kv_heads — decode_attention re-broadcasts per query group)
    cache_kv = (k, v) if return_kv else None

    def project_out(out, stats):
        """The shared output epilogue: row-split projection, tp reduction,
        bias — one copy for the kernel, XLA, and blocked-scan paths."""
        out = out.reshape(b, s, h * hd) @ lp["wo"]
        if tp_axis is not None:
            out = jax.lax.psum(out, tp_axis)
        if "bo" in lp:
            out = out + lp["bo"]
        return (out, stats, cache_kv) if return_kv else (out, stats)

    from .flash_attention import (causal_attention, causal_attention_stats,
                                  kernel_plan)

    attn_plan = kernel_plan(s, h, kv, hd,
                            itemsize=jnp.dtype(x.dtype).itemsize)
    use_kernel = attn_plan is not None
    if not capture_stats:
        # Hot path. On TPU at S <= 1024 the whole-S Pallas kernel (one
        # (batch, head) score matrix per grid step, entirely in VMEM) measures
        # ~2.4x XLA's fused attention at the flagship's hd=64 shapes and
        # ~3.4x at qwen2-1.5b's hd=128; longer sequences (S=2048, the
        # reference's own Pythia window) and wider rows (llama-1b) take the
        # query-blocked / head-group-split kernel (models/flash_attention.py);
        # shapes outside both envelopes use XLA's fused path (flash-style
        # schedule, no O(S^2) HBM probs, native GQA). This is the analogue of
        # the reference's
        # SDPA instance for quantized forwards (pythia_model.py:25) while the
        # stats branch below replaces its second, eager-attention model
        # (last_row_exp.py:68).
        if use_kernel:
            return project_out(causal_attention(q, k, v, plan=attn_plan), None)
        return project_out(
            jax.nn.dot_product_attention(q, k, v, is_causal=True), None)

    if stats_block is None and use_kernel:
        # fused stats capture: col_sum and last_row read directly off the
        # in-VMEM probability matrix (the blocked-scan path below stays as
        # the portable implementation and, at stats_block=0, the oracle)
        out, stats = causal_attention_stats(q, k, v, plan=attn_plan)
        return project_out(out, stats)

    rep = h // kv
    if rep > 1:  # grouped-query attention: repeat KV heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    q_blk = _stats_block_size(s, stats_block)
    inv_scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    neg_inf = jnp.finfo(jnp.float32).min
    key_pos = jnp.arange(s)

    def scores_of(q_rows, row_pos):
        sc = jnp.einsum("bqhd,bthd->bhqt", q_rows, k,
                        preferred_element_type=jnp.float32) * inv_scale
        mask = row_pos[:, None] >= key_pos[None, :]
        return jnp.where(mask[None, None], sc, neg_inf)

    if q_blk == s:  # single block == the full-probs formulation (oracle path)
        probs = jax.nn.softmax(scores_of(q, key_pos), axis=-1)  # (B, H, S, S)
        out = jnp.einsum("bhqt,bthd->bqhd", probs.astype(x.dtype), v,
                         preferred_element_type=jnp.float32).astype(x.dtype)
        col_sum = jnp.sum(probs, axis=2)
        last_row = probs[:, :, -1, :]
    else:
        q_blocks = q.reshape(b, s // q_blk, q_blk, h, hd).transpose(1, 0, 2, 3, 4)

        def body(col_acc, xs):
            q_rows, blk = xs
            rows = blk * q_blk + jnp.arange(q_blk)
            probs_blk = jax.nn.softmax(scores_of(q_rows, rows), axis=-1)
            out_blk = jnp.einsum("bhqt,bthd->bqhd", probs_blk.astype(x.dtype), v,
                                 preferred_element_type=jnp.float32
                                 ).astype(x.dtype)
            return col_acc + jnp.sum(probs_blk, axis=2), out_blk

        col_sum, outs = jax.lax.scan(
            body, jnp.zeros((b, h, s), jnp.float32),
            (q_blocks, jnp.arange(s // q_blk)))
        out = outs.transpose(1, 0, 2, 3, 4).reshape(b, s, h, hd)
        # the final causal row sees every key — one O(S) softmax, no mask
        last_row = jax.nn.softmax(
            jnp.einsum("bhd,bthd->bht", q[:, -1], k,
                       preferred_element_type=jnp.float32) * inv_scale, axis=-1)

    return project_out(out, (col_sum / s, last_row))  # stats (B, H, S) each


def mlp(cfg: ModelConfig, lp: dict, x: jnp.ndarray,
        tp_axis: Optional[str] = None) -> jnp.ndarray:
    """MLP; with ``tp_axis`` set, the hidden (F) axis is column-split per device
    and the row-split down-projection is ``psum``-reduced (biases that live on
    the model axis — ``b_in`` — are local; output biases are added post-psum)."""
    if cfg.family == "gpt_neox":
        hidden = x @ lp["w_in"] + lp["b_in"]
        hidden = jax.nn.gelu(hidden, approximate=False)
        out = hidden @ lp["w_out"]
        if tp_axis is not None:
            out = jax.lax.psum(out, tp_axis)
        return out + lp["b_out"]
    out = (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    return out


def block(cfg: ModelConfig, lp: dict, hidden: jnp.ndarray, cos, sin,
          capture_stats: bool,
          tp_axis: Optional[str] = None,
          stats_block: Optional[int] = None,
          return_kv: bool = False):
    """One decoder block. GPT-NeoX: parallel residual; Qwen2: sequential.
    With ``return_kv`` the post-rotary per-layer K/V ride along (the prefill
    path fills the decode cache from them); returns (hidden, stats[, (k, v)]).
    """
    if cfg.family == "gpt_neox":
        attn_in = _layernorm(hidden, lp["ln1_scale"], lp["ln1_bias"], cfg.norm_eps)
        attn_out, stats, *kv = attention(cfg, lp, attn_in, cos, sin, capture_stats,
                                         tp_axis, stats_block, return_kv)
        mlp_in = _layernorm(hidden, lp["ln2_scale"], lp["ln2_bias"], cfg.norm_eps)
        out = hidden + attn_out + mlp(cfg, lp, mlp_in, tp_axis)
        return (out, stats, kv[0]) if return_kv else (out, stats)
    attn_in = _rmsnorm(hidden, lp["ln1_scale"], cfg.norm_eps)
    attn_out, stats, *kv = attention(cfg, lp, attn_in, cos, sin, capture_stats,
                                     tp_axis, stats_block, return_kv)
    hidden = hidden + attn_out
    mlp_in = _rmsnorm(hidden, lp["ln2_scale"], cfg.norm_eps)
    out = hidden + mlp(cfg, lp, mlp_in, tp_axis)
    return (out, stats, kv[0]) if return_kv else (out, stats)


def embed(params: dict, input_ids: jnp.ndarray) -> jnp.ndarray:
    return jnp.take(params["embed"], input_ids, axis=0)


def unembed(cfg: ModelConfig, params: dict, hidden: jnp.ndarray) -> jnp.ndarray:
    """Final norm + LM head -> fp32 logits."""
    post = _norm(cfg, hidden, params["final_norm_scale"], params.get("final_norm_bias", 0.0))
    head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    return jnp.einsum("bsd,dv->bsv", post, head, preferred_element_type=jnp.float32)


def _slice_layers(layers: dict, start: int, stop: int) -> dict:
    return {k: v[start:stop] for k, v in layers.items()}


def run_layers(cfg: ModelConfig, params: dict, hidden: jnp.ndarray, *,
               start: int = 0, stop: Optional[int] = None,
               boundary_fn: Optional[Callable] = None,
               capture_stats: bool = False,
               collect_hidden: bool = False,
               collect_kv: bool = False,
               stats_block: Optional[int] = None):
    """Run decoder layers [start, stop) over ``hidden`` via one lax.scan.

    start/stop are static (jit caches one executable per segment); ``boundary_fn``
    receives the *global* layer index and the post-block hidden state — the same
    interception point as the reference's ``if i == layer_of_interest`` edit
    (``qwen_layer_wise.py:54``), but jit-safe.

    Returns (hidden, aux) where aux holds optional per-layer stats/hiddens and,
    with ``collect_kv``, the stacked post-rotary K/V the decode cache is
    prefilled from (aux["kv"] = (k, v), each (L, B, S, KV, hd)).
    """
    stop = cfg.num_layers if stop is None else stop
    if not (0 <= start <= stop <= cfg.num_layers):
        raise ValueError(
            f"layer segment [{start}, {stop}) out of range for {cfg.num_layers} layers")
    seq_len = hidden.shape[1]
    cos, sin = precompute_rope(cfg, seq_len)
    layer_stack = _slice_layers(params["layers"], start, stop)
    idxs = jnp.arange(start, stop)

    def body(h, xs):
        lp, idx = xs
        h, stats, *kv = block(cfg, lp, h, cos, sin, capture_stats,
                              stats_block=stats_block, return_kv=collect_kv)
        if boundary_fn is not None:
            h = boundary_fn(idx, h)
        out = (stats if capture_stats else None, h if collect_hidden else None,
               kv[0] if collect_kv else None)
        return h, out

    hidden, (stats, hiddens, kvs) = jax.lax.scan(body, hidden, (layer_stack, idxs))
    aux = {}
    if capture_stats:
        aux["stats"] = AttnStats(col_mean=stats[0], last_row=stats[1])
    if collect_hidden:
        aux["hiddens"] = hiddens  # (L, B, S, D), post-boundary_fn
    if collect_kv:
        aux["kv"] = kvs  # ((L, B, S, KV, hd), (L, B, S, KV, hd))
    return hidden, aux


def _cast_params(params: dict, compute_dtype) -> dict:
    """Cast floating params to ``compute_dtype``; None = keep as stored, so a
    bfloat16 pytree runs the MXU's native bf16 path end-to-end (NLL math stays
    fp32 regardless: ``unembed`` requests fp32 logits and ``_masked_ce``
    upcasts)."""
    if compute_dtype is None:
        return params
    return jax.tree_util.tree_map(
        lambda a: a.astype(compute_dtype)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, params)


def forward(cfg: ModelConfig, params: dict, input_ids: jnp.ndarray, *,
            boundary_fn: Optional[Callable] = None,
            capture_stats: bool = False,
            collect_hidden: bool = False,
            compute_dtype: Optional[jnp.dtype] = None,
            stats_block: Optional[int] = None):
    """Full forward: ids -> logits (fp32), optionally with attention stats/hiddens.

    Mirrors the reference's manual loop (embed -> rotary -> layers -> final norm ->
    head -> logits; ``qwen_layer_wise.py:78-104``) as one jit-compiled function.
    """
    params = _cast_params(params, compute_dtype)
    hidden = embed(params, input_ids)
    hidden, aux = run_layers(cfg, params, hidden, boundary_fn=boundary_fn,
                             capture_stats=capture_stats,
                             collect_hidden=collect_hidden,
                             stats_block=stats_block)
    logits = unembed(cfg, params, hidden)
    return logits, aux


def run_layers_from_ids(cfg: ModelConfig, params: dict, input_ids: jnp.ndarray, *,
                        capture_stats: bool = False,
                        compute_dtype: Optional[jnp.dtype] = None,
                        stats_block: Optional[int] = None):
    """Prefix pass for sweep drivers: embed -> all layers, collecting every
    post-block hidden state, WITHOUT the final norm/unembed (suffix runs redo the
    tail from a cached boundary activation, so logits here would be dead compute).

    Compute dtype follows the params pytree (pass fp32 params for reference-exact
    math; bf16 params keep the sweep on the MXU's native bf16 path) unless
    ``compute_dtype`` overrides it.
    """
    params = _cast_params(params, compute_dtype)
    hidden = embed(params, input_ids)
    return run_layers(cfg, params, hidden, capture_stats=capture_stats,
                      collect_hidden=True, stats_block=stats_block)


# ---------------------------------------------------------------------------
# KV-cached incremental decode: prefill fills the cache for the prompt, then
# decode_step appends ONE position per call — O(1) work per emitted token
# instead of the O(S) full re-forward the evaluation entry points do.
# ---------------------------------------------------------------------------


class KVCache(NamedTuple):
    """Per-layer key/value cache for incremental decode.

    k, v: (L, B, capacity, KV, hd) — post-rotary keys/values, stored at
        ``num_kv_heads`` (GQA caches the grouped heads; the decode attention
        re-broadcasts them per query group). The leading layer axis matches
        the stacked-parameter convention, so the cache rides the same
        ``lax.scan`` as the layer stack.
    length: () int32 — number of valid positions, i.e. the next write slot.
        Dynamic under jit: one executable serves every fill level of a given
        (batch, capacity) shape.
    """

    k: jnp.ndarray
    v: jnp.ndarray
    length: jnp.ndarray

    @property
    def capacity(self) -> int:
        return self.k.shape[2]


def init_cache(cfg: ModelConfig, batch: int, capacity: int,
               dtype=jnp.float32) -> KVCache:
    """An empty cache for ``batch`` sequences of up to ``capacity`` tokens."""
    shape = (cfg.num_layers, batch, capacity, cfg.num_kv_heads, cfg.head_dim)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                   jnp.zeros((), jnp.int32))


def cache_state_dict(cache) -> dict:
    """Snapshot a decode cache as host numpy arrays keyed ``k``/``v``/
    ``length`` — the serializable form the recovery checkpoint stores. Takes
    either a :class:`KVCache` or the split runtime's ``{"k","v","length"}``
    dict (both carry the position offset in ``length``)."""
    if isinstance(cache, dict):
        k, v, length = cache["k"], cache["v"], cache["length"]
    else:
        k, v, length = cache.k, cache.v, cache.length
    return {"k": np.asarray(k), "v": np.asarray(v),
            "length": np.asarray(length, np.int32)}


def cache_from_state_dict(state: dict) -> dict:
    """Rehydrate :func:`cache_state_dict` output to the on-device
    ``{"k","v","length"}`` cache dict every decode runtime consumes (wrap in
    :class:`KVCache` for the raw ``decode_step`` entry point)."""
    return {"k": jnp.asarray(state["k"]), "v": jnp.asarray(state["v"]),
            "length": jnp.asarray(state["length"], jnp.int32)}


@graph_contract("transformer.prefill", collectives={})
def prefill(cfg: ModelConfig, params: dict, input_ids: jnp.ndarray,
            capacity: int, *,
            boundary_fn: Optional[Callable] = None,
            compute_dtype: Optional[jnp.dtype] = None):
    """Full forward over the prompt that also fills the decode cache.

    Returns (logits (B, S, V) fp32, KVCache with length = S). ``capacity`` is
    static — it fixes the cache buffers' shape, so every later ``decode_step``
    reuses one executable regardless of how full the cache is.
    """
    s = input_ids.shape[1]
    if not 0 < s <= capacity:
        raise ValueError(f"prompt length {s} must be in [1, capacity={capacity}]")
    params = _cast_params(params, compute_dtype)
    hidden = embed(params, input_ids)
    hidden, aux = run_layers(cfg, params, hidden, boundary_fn=boundary_fn,
                             collect_kv=True)
    logits = unembed(cfg, params, hidden)
    k, v = aux["kv"]  # (L, B, S, KV, hd) each
    pad = ((0, 0), (0, 0), (0, capacity - s), (0, 0), (0, 0))
    return logits, KVCache(jnp.pad(k, pad), jnp.pad(v, pad),
                           jnp.asarray(s, jnp.int32))


def _attention_decode(cfg: ModelConfig, lp: dict, x: jnp.ndarray,
                      cos_t, sin_t, k_cache, v_cache, pos,
                      tp_axis: Optional[str] = None):
    """One layer's attention for a single decode position: project the (B, 1, D)
    hidden, rotate at ``pos``, write the new K/V into the cache, then attend
    q_len=1 against the length-masked cache. Returns (out, k_cache, v_cache)."""
    b, s1, d = x.shape
    hd = cfg.head_dim
    h, kv = lp["wq"].shape[-1] // hd, lp["wk"].shape[-1] // hd
    q = (x @ lp["wq"]).reshape(b, s1, h, hd)
    k = (x @ lp["wk"]).reshape(b, s1, kv, hd)
    v = (x @ lp["wv"]).reshape(b, s1, kv, hd)
    if "bq" in lp:
        q = q + lp["bq"].reshape(h, hd)
        k = k + lp["bk"].reshape(kv, hd)
        v = v + lp["bv"].reshape(kv, hd)
    q = apply_rotary(q, cos_t, sin_t, cfg.rotary_dim)
    k = apply_rotary(k, cos_t, sin_t, cfg.rotary_dim)
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k.astype(k_cache.dtype), (0, pos, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v.astype(v_cache.dtype), (0, pos, 0, 0))

    from .flash_attention import decode_attention

    out = decode_attention(q, k_cache, v_cache, pos + 1)
    out = out.reshape(b, s1, h * hd) @ lp["wo"]
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    if "bo" in lp:
        out = out + lp["bo"]
    return out, k_cache, v_cache


def block_decode(cfg: ModelConfig, lp: dict, hidden: jnp.ndarray,
                 cos_t, sin_t, k_cache, v_cache, pos,
                 tp_axis: Optional[str] = None):
    """The cache-carrying twin of :func:`block` for one decode position.
    ``k_cache``/``v_cache`` are this layer's (B, capacity, KV, hd) buffers;
    ``pos`` is the (traced) position being written. Returns
    (hidden, k_cache, v_cache)."""
    if cfg.family == "gpt_neox":
        attn_in = _layernorm(hidden, lp["ln1_scale"], lp["ln1_bias"], cfg.norm_eps)
        attn_out, k_cache, v_cache = _attention_decode(
            cfg, lp, attn_in, cos_t, sin_t, k_cache, v_cache, pos, tp_axis)
        mlp_in = _layernorm(hidden, lp["ln2_scale"], lp["ln2_bias"], cfg.norm_eps)
        return (hidden + attn_out + mlp(cfg, lp, mlp_in, tp_axis),
                k_cache, v_cache)
    attn_in = _rmsnorm(hidden, lp["ln1_scale"], cfg.norm_eps)
    attn_out, k_cache, v_cache = _attention_decode(
        cfg, lp, attn_in, cos_t, sin_t, k_cache, v_cache, pos, tp_axis)
    hidden = hidden + attn_out
    mlp_in = _rmsnorm(hidden, lp["ln2_scale"], cfg.norm_eps)
    return hidden + mlp(cfg, lp, mlp_in, tp_axis), k_cache, v_cache


def _attention_verify(cfg: ModelConfig, lp: dict, x: jnp.ndarray,
                      cos_t, sin_t, k_cache, v_cache, pos,
                      tp_axis: Optional[str] = None):
    """The K-position twin of :func:`_attention_decode` for speculative
    verify: project the (B, K, D) hidden block, rotate at positions
    ``pos .. pos+K-1`` (``cos_t``/``sin_t`` are the (K, rot) row slices),
    write all K new K/V rows into the cache at ``pos``, then attend q_len=K
    causally against the cache. Returns (out, k_cache, v_cache)."""
    b, kq, d = x.shape
    hd = cfg.head_dim
    h, kv = lp["wq"].shape[-1] // hd, lp["wk"].shape[-1] // hd
    q = (x @ lp["wq"]).reshape(b, kq, h, hd)
    k = (x @ lp["wk"]).reshape(b, kq, kv, hd)
    v = (x @ lp["wv"]).reshape(b, kq, kv, hd)
    if "bq" in lp:
        q = q + lp["bq"].reshape(h, hd)
        k = k + lp["bk"].reshape(kv, hd)
        v = v + lp["bv"].reshape(kv, hd)
    q = apply_rotary(q, cos_t, sin_t, cfg.rotary_dim)
    k = apply_rotary(k, cos_t, sin_t, cfg.rotary_dim)
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k.astype(k_cache.dtype), (0, pos, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v.astype(v_cache.dtype), (0, pos, 0, 0))

    from .flash_attention import verify_attention

    out = verify_attention(q, k_cache, v_cache, pos)
    out = out.reshape(b, kq, h * hd) @ lp["wo"]
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    if "bo" in lp:
        out = out + lp["bo"]
    return out, k_cache, v_cache


def block_verify(cfg: ModelConfig, lp: dict, hidden: jnp.ndarray,
                 cos_t, sin_t, k_cache, v_cache, pos,
                 tp_axis: Optional[str] = None):
    """The cache-carrying twin of :func:`block_decode` for a K-position
    speculative-verify block. ``hidden`` is (B, K, D); ``pos`` is the (traced)
    first position being written. Returns (hidden, k_cache, v_cache)."""
    if cfg.family == "gpt_neox":
        attn_in = _layernorm(hidden, lp["ln1_scale"], lp["ln1_bias"], cfg.norm_eps)
        attn_out, k_cache, v_cache = _attention_verify(
            cfg, lp, attn_in, cos_t, sin_t, k_cache, v_cache, pos, tp_axis)
        mlp_in = _layernorm(hidden, lp["ln2_scale"], lp["ln2_bias"], cfg.norm_eps)
        return (hidden + attn_out + mlp(cfg, lp, mlp_in, tp_axis),
                k_cache, v_cache)
    attn_in = _rmsnorm(hidden, lp["ln1_scale"], cfg.norm_eps)
    attn_out, k_cache, v_cache = _attention_verify(
        cfg, lp, attn_in, cos_t, sin_t, k_cache, v_cache, pos, tp_axis)
    hidden = hidden + attn_out
    mlp_in = _rmsnorm(hidden, lp["ln2_scale"], cfg.norm_eps)
    return hidden + mlp(cfg, lp, mlp_in, tp_axis), k_cache, v_cache


@graph_contract("transformer.decode_step", collectives={})
def decode_step(cfg: ModelConfig, params: dict, cache: KVCache,
                token_ids: jnp.ndarray, *,
                boundary_fn: Optional[Callable] = None,
                compute_dtype: Optional[jnp.dtype] = None):
    """Append one position: (B,) or (B, 1) token ids -> (logits (B, V) fp32,
    updated cache). The RoPE tables are built for the full capacity and the
    current row is dynamically sliced at ``cache.length``, so the same
    machinery (partial rotary, llama3 scaling) applies at a position offset
    without retracing; jit this per (batch, capacity) shape and every emitted
    token reuses the one executable.
    """
    params = _cast_params(params, compute_dtype)
    if token_ids.ndim == 1:
        token_ids = token_ids[:, None]
    hidden = embed(params, token_ids)  # (B, 1, D)
    pos = cache.length
    cos, sin = precompute_rope(cfg, cache.capacity)
    cos_t = jax.lax.dynamic_slice_in_dim(cos, pos, 1)
    sin_t = jax.lax.dynamic_slice_in_dim(sin, pos, 1)
    idxs = jnp.arange(cfg.num_layers)

    def body(h, xs):
        lp, kc, vc, idx = xs
        h, kc, vc = block_decode(cfg, lp, h, cos_t, sin_t, kc, vc, pos)
        if boundary_fn is not None:
            h = boundary_fn(idx, h)
        return h, (kc, vc)

    hidden, (k_new, v_new) = jax.lax.scan(
        body, hidden, (params["layers"], cache.k, cache.v, idxs))
    logits = unembed(cfg, params, hidden)[:, -1]  # (B, V) fp32
    return logits, KVCache(k_new, v_new, pos + 1)


def nll_from_logits(logits: jnp.ndarray, target_ids: jnp.ndarray,
                    per_example: bool = False) -> jnp.ndarray:
    """Shifted cross-entropy with -100 masking — the reference's NLL definition
    (``qwen_layer_wise.py:28-40``): logits[:, :-1] vs targets[:, 1:], mean over
    valid positions (over the whole batch, or per row for the batched-over-ratios
    scheme of ``pythia_model.py:36-54``).
    """
    return _masked_ce(logits[:, :-1, :], target_ids[:, 1:], per_example)


def _masked_ce(logits: jnp.ndarray, targets: jnp.ndarray,
               per_example: bool) -> jnp.ndarray:
    """Mean cross-entropy over positions where ``targets != -100``; logits and
    targets are already shift-aligned (logits[b, i] predicts targets[b, i])."""
    valid = targets != -100
    safe_targets = jnp.where(valid, targets, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tok_nll = -jnp.take_along_axis(logp, safe_targets[..., None], axis=-1)[..., 0]
    tok_nll = jnp.where(valid, tok_nll, 0.0)
    axes = (1,) if per_example else None
    return jnp.sum(tok_nll, axis=axes) / jnp.maximum(jnp.sum(valid, axis=axes), 1)


def _vocab_block_size(v: int, target: int = 8192) -> int:
    """Largest divisor of ``v`` at most ``target`` via the smallest block
    count; ``v`` itself when the vocab is small or has no useful divisor."""
    if v <= 2 * target:
        return v
    for nb in range(2, 129):
        if v % nb == 0 and v // nb <= target:
            return v // nb
    return v


def nll_tail(cfg: ModelConfig, params: dict, hidden: jnp.ndarray,
             target_ids: jnp.ndarray, tail: int,
             per_example: bool = False,
             vocab_block: Optional[int] = None) -> jnp.ndarray:
    """``nll_from_logits(unembed(cfg, params, hidden), target_ids)`` with the
    unembed restricted to the ``tail`` scoring positions.

    The sliding-window recipe masks every target outside the last ``trg_len``
    positions to -100 (``Qwen2-0.5B/main.py:152-156``), so with stride 32 only
    ~6% of a 512-token window is ever scored — yet the full-vocab unembed
    (151k columns for Qwen2) dominates suffix FLOPs. Valid targets occupy the
    last ``trg_len`` positions; their (shifted) logits come from hidden positions
    ``[S - trg_len - 1, S - 2]``, so unembedding the last ``min(tail, S-1)``
    pre-final positions is exact whenever ``tail >= trg_len``. ``tail`` must be
    static (one executable per distinct tail length).

    Large vocabularies stream: the head is processed in ``vocab_block``-column
    blocks with an online logsumexp and in-block target-logit gather, so the
    (rows, V) fp32 logits tensor — 9.6 GB for a ratio-vmapped 128-window
    Qwen2 group — never materializes. Same FLOPs on the MXU, a fraction of
    the HBM traffic. ``vocab_block=None`` auto-picks a divisor of V (~8k);
    ``0`` forces the single-block path, which is exactly the old
    full-logits formulation (the oracle in tests)."""
    s = hidden.shape[1]
    tail = min(int(tail), s - 1)
    h = hidden[:, s - 1 - tail: s - 1]
    tgt = target_ids[:, s - tail:]
    vb = (_vocab_block_size(cfg.vocab_size) if vocab_block is None
          else (cfg.vocab_size if vocab_block == 0 else vocab_block))
    if vb >= cfg.vocab_size:
        return _masked_ce(unembed(cfg, params, h), tgt, per_example)
    if cfg.vocab_size % vb:
        raise ValueError(f"vocab_block {vb} must divide vocab {cfg.vocab_size}")
    return _blocked_ce(cfg, params, h, tgt, per_example, vb)


def _blocked_ce(cfg: ModelConfig, params: dict, hidden: jnp.ndarray,
                targets: jnp.ndarray, per_example: bool, vb: int) -> jnp.ndarray:
    """Streaming cross-entropy: final norm -> per-block partial logits ->
    online (max, sumexp, target-logit) accumulation. The head tensor is
    re-viewed blockwise in its OWN layout (no transpose copy of the 272 MB
    embedding for tied heads)."""
    b, t, d = hidden.shape
    post = _norm(cfg, hidden, params["final_norm_scale"],
                 params.get("final_norm_bias", 0.0)).reshape(b * t, d)
    n = b * t
    tgt = targets.reshape(n)
    valid = tgt != -100
    safe_tgt = jnp.where(valid, tgt, 0)
    nb = cfg.vocab_size // vb
    if cfg.tie_word_embeddings:
        emb = params["embed"]  # (V, D): block rows, no transpose copy

        def piece_of(i):
            blk = jax.lax.dynamic_slice_in_dim(emb, i * vb, vb, axis=0)
            return jnp.einsum("nd,vd->nv", post, blk,
                              preferred_element_type=jnp.float32)
    else:
        head = params["lm_head"]  # (D, V): block columns in place

        def piece_of(i):
            blk = jax.lax.dynamic_slice_in_dim(head, i * vb, vb, axis=1)
            return jnp.einsum("nd,dv->nv", post, blk,
                              preferred_element_type=jnp.float32)

    init = (jnp.full((n,), -jnp.inf, jnp.float32),  # running max
            jnp.zeros((n,), jnp.float32),           # running sum of exp
            jnp.zeros((n,), jnp.float32))           # target logit

    def body(carry, i):
        m, s_acc, t_logit = carry
        piece = piece_of(i)  # (N, vb) fp32, one block's logits
        local_max = jnp.max(piece, axis=-1)
        m_new = jnp.maximum(m, local_max)
        s_acc = (s_acc * jnp.exp(m - m_new)
                 + jnp.sum(jnp.exp(piece - m_new[:, None]), axis=-1))
        local = safe_tgt - i * vb
        in_blk = (local >= 0) & (local < vb)
        val = jnp.take_along_axis(
            piece, jnp.clip(local, 0, vb - 1)[:, None], axis=1)[:, 0]
        t_logit = jnp.where(in_blk, val, t_logit)
        return (m_new, s_acc, t_logit), None

    (m, s_acc, t_logit), _ = jax.lax.scan(body, init, jnp.arange(nb))
    tok_nll = jnp.where(valid, jnp.log(s_acc) + m - t_logit, 0.0)
    tok_nll = tok_nll.reshape(b, t)
    valid = valid.reshape(b, t)
    axes = (1,) if per_example else None
    return jnp.sum(tok_nll, axis=axes) / jnp.maximum(jnp.sum(valid, axis=axes), 1)


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.float32) -> dict:
    """Random init (tests/bench only — environment has no pretrained checkpoints)."""
    keys = iter(jax.random.split(key, 32))
    init = lambda *shape: (jax.random.normal(next(keys), shape, jnp.float32) * 0.02).astype(dtype)
    L, D, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    layers = {
        "ln1_scale": jnp.ones((L, D), dtype),
        "ln2_scale": jnp.ones((L, D), dtype),
        "wq": init(L, D, H * hd), "wk": init(L, D, KV * hd), "wv": init(L, D, KV * hd),
        "wo": init(L, H * hd, D),
    }
    if cfg.qkv_bias:
        layers.update({
            "bq": jnp.zeros((L, H * hd), dtype), "bk": jnp.zeros((L, KV * hd), dtype),
            "bv": jnp.zeros((L, KV * hd), dtype),
        })
    if cfg.family == "gpt_neox":
        layers.update({
            "ln1_bias": jnp.zeros((L, D), dtype), "ln2_bias": jnp.zeros((L, D), dtype),
            "bo": jnp.zeros((L, D), dtype),
            "w_in": init(L, D, F), "b_in": jnp.zeros((L, F), dtype),
            "w_out": init(L, F, D), "b_out": jnp.zeros((L, D), dtype),
        })
    else:
        layers.update({
            "w_gate": init(L, D, F), "w_up": init(L, D, F), "w_down": init(L, F, D),
        })
    params = {
        "embed": init(cfg.vocab_size, D),
        "layers": layers,
        "final_norm_scale": jnp.ones((D,), dtype),
    }
    if cfg.family == "gpt_neox":
        params["final_norm_bias"] = jnp.zeros((D,), dtype)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = init(D, cfg.vocab_size)
    return params
