"""Direct safetensors -> parameter-pytree loading, no torch import required.

The reference can only materialize weights through
``AutoModelForCausalLM.from_pretrained`` (two full torch model instances per
experiment, ``Qwen2-0.5B/main.py:126-134``). Here checkpoints load straight
from the safetensors container into the stacked-layer pytree: the format is an
8-byte little-endian header length, a JSON header mapping tensor names to
``{dtype, shape, data_offsets}``, then one flat data buffer — trivially
readable with numpy alone. bf16 tensors (no numpy dtype) are upcast to fp32 by
bit-shifting into the float32 mantissa layout.

Entry points:
- :func:`read_safetensors` — one ``.safetensors`` file -> dict of np arrays;
- :func:`load_checkpoint` — a file or an HF model directory (handles the
  multi-shard ``model.safetensors.index.json`` layout and builds the
  :class:`ModelConfig` from the directory's ``config.json``) -> (cfg, params).
"""
from __future__ import annotations

import json
import os
import struct
from types import SimpleNamespace
from typing import Optional

import numpy as np

from .configs import ModelConfig
from .hf_loader import config_from_hf, params_from_state_dict

_DTYPES = {
    "F64": np.float64,
    "F32": np.float32,
    "F16": np.float16,
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
    # BF16 handled specially (no numpy dtype)
}


def _bf16_to_f32(raw: np.ndarray) -> np.ndarray:
    """uint16 bf16 bit patterns -> float32 (shift into the high mantissa half)."""
    return (raw.astype(np.uint32) << 16).view(np.float32)


_DTYPE_BYTES = {"F64": 8, "F32": 4, "F16": 2, "BF16": 2, "I64": 8, "I32": 4,
                "I16": 2, "I8": 1, "U8": 1, "BOOL": 1}


def _parse_header(f, path: str):
    """(header dict, data-section byte length), or ValueError saying exactly
    what is malformed — a truncated download dies here, not in numpy."""
    size = os.fstat(f.fileno()).st_size
    head = f.read(8)
    if len(head) < 8:
        raise ValueError(f"{path}: not a safetensors file — only {size} bytes "
                         f"(needs an 8-byte header length); re-download it")
    (header_len,) = struct.unpack("<Q", head)
    if header_len == 0 or 8 + header_len > size:
        raise ValueError(
            f"{path}: corrupt safetensors — header claims {header_len} bytes "
            f"but the file holds {size}; the download is likely truncated, "
            f"re-fetch it")
    try:
        header = json.loads(f.read(header_len))
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise ValueError(f"{path}: corrupt safetensors — header is not valid "
                         f"JSON ({e}); re-download the file") from e
    if not isinstance(header, dict):
        raise ValueError(f"{path}: corrupt safetensors — header must be a "
                         f"JSON object, got {type(header).__name__}")
    return header, size - 8 - header_len


def verify_safetensors_integrity(path: str) -> dict:
    """Structural integrity check of one ``.safetensors`` file, BEFORE any
    tensor is materialized: the header parses, every tensor's dtype is known,
    its ``data_offsets`` lie inside the data section in order, and the byte
    span matches ``prod(shape) * itemsize`` exactly. Returns
    ``{"tensors": n, "data_bytes": n}``; raises ValueError with an actionable
    message (which tensor, what mismatch) on the first inconsistency.
    :func:`read_safetensors` runs this on every load."""
    with open(path, "rb") as f:
        header, data_bytes = _parse_header(f, path)
    n = 0
    end_prev = 0
    entries = [(name, meta) for name, meta in header.items()
               if name != "__metadata__"]
    # safetensors stores tensors contiguously in offset order; validate in
    # that order so overlaps and gaps are caught, not just bounds
    for name, meta in sorted(entries, key=lambda kv: kv[1]["data_offsets"][0]):
        itemsize = _DTYPE_BYTES.get(meta.get("dtype"))
        if itemsize is None:
            raise ValueError(f"{path}: tensor {name!r} has unsupported dtype "
                             f"{meta.get('dtype')!r}")
        start, end = meta["data_offsets"]
        want = int(np.prod(meta["shape"], dtype=np.int64)) * itemsize
        if not 0 <= start <= end <= data_bytes:
            raise ValueError(
                f"{path}: tensor {name!r} data_offsets [{start}, {end}) fall "
                f"outside the {data_bytes}-byte data section — truncated or "
                f"corrupt download, re-fetch the file")
        if end - start != want:
            raise ValueError(
                f"{path}: tensor {name!r} spans {end - start} bytes but shape "
                f"{meta['shape']} x {meta['dtype']} needs {want} — header and "
                f"data disagree, the file is corrupt")
        if start < end_prev:
            raise ValueError(f"{path}: tensor {name!r} overlaps the previous "
                             f"tensor's bytes — the file is corrupt")
        end_prev = end
        n += 1
    return {"tensors": n, "data_bytes": data_bytes}


def read_safetensors(path: str) -> dict:
    """Parse one ``.safetensors`` file into {name: np.ndarray} (bf16 -> fp32).
    The structural integrity check runs first, so a truncated or bit-rotted
    checkpoint raises an actionable error instead of loading garbage."""
    verify_safetensors_integrity(path)
    with open(path, "rb") as f:
        (header_len,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(header_len))
        data = f.read()
    out = {}
    for name, meta in header.items():
        if name == "__metadata__":
            continue
        start, end = meta["data_offsets"]
        buf = data[start:end]
        shape = tuple(meta["shape"])
        if meta["dtype"] == "BF16":
            out[name] = _bf16_to_f32(np.frombuffer(buf, np.uint16)).reshape(shape)
        else:
            dt = _DTYPES.get(meta["dtype"])
            if dt is None:
                raise ValueError(f"unsupported safetensors dtype {meta['dtype']!r} "
                                 f"for tensor {name!r}")
            out[name] = np.frombuffer(buf, dt).reshape(shape)
    return out


def _read_dir_tensors(model_dir: str) -> dict:
    """All tensors of an HF model directory (single- or multi-shard layout)."""
    index_path = os.path.join(model_dir, "model.safetensors.index.json")
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        tensors = {}
        for shard in sorted(set(index["weight_map"].values())):
            tensors.update(read_safetensors(os.path.join(model_dir, shard)))
        return tensors
    single = os.path.join(model_dir, "model.safetensors")
    if os.path.exists(single):
        return read_safetensors(single)
    candidates = [f for f in os.listdir(model_dir) if f.endswith(".safetensors")]
    if len(candidates) == 1:
        return read_safetensors(os.path.join(model_dir, candidates[0]))
    raise FileNotFoundError(
        f"no model.safetensors(.index.json) in {model_dir!r} (found: {candidates})")


def config_from_dir(model_dir: str) -> ModelConfig:
    """Build the ModelConfig from a directory's ``config.json`` (no transformers
    import — the JSON keys are read through the same mapping as
    :func:`config_from_hf`)."""
    with open(os.path.join(model_dir, "config.json")) as f:
        raw = json.load(f)
    return config_from_hf(SimpleNamespace(**raw))


def load_checkpoint(path: str, cfg: Optional[ModelConfig] = None):
    """(cfg, params) from a ``.safetensors`` file or an HF model directory.

    For a bare file, ``cfg`` must be supplied (e.g. a preset); for a directory
    it is read from ``config.json`` unless overridden. This is the torch-free
    path that makes ``run.py --weights model.safetensors`` work the moment a
    checkpoint artifact appears.
    """
    if os.path.isdir(path):
        cfg = cfg or config_from_dir(path)
        sd = _read_dir_tensors(path)
    else:
        if cfg is None:
            raise ValueError("loading a bare .safetensors file requires a ModelConfig "
                             "(pass --model <preset>)")
        sd = read_safetensors(path)
    if cfg.tie_word_embeddings and "lm_head.weight" in sd and \
            "model.embed_tokens.weight" not in sd:
        # some exports store only the tied head; the loader expects the embed key
        sd["model.embed_tokens.weight"] = sd["lm_head.weight"]
    return cfg, params_from_state_dict(cfg, sd)
