"""Direct safetensors -> parameter-pytree loading, no torch import required.

The reference can only materialize weights through
``AutoModelForCausalLM.from_pretrained`` (two full torch model instances per
experiment, ``Qwen2-0.5B/main.py:126-134``). Here checkpoints load straight
from the safetensors container into the stacked-layer pytree: the format is an
8-byte little-endian header length, a JSON header mapping tensor names to
``{dtype, shape, data_offsets}``, then one flat data buffer — trivially
readable with numpy alone. bf16 tensors (no numpy dtype) are upcast to fp32 by
bit-shifting into the float32 mantissa layout.

Entry points:
- :func:`read_safetensors` — one ``.safetensors`` file -> dict of np arrays;
- :func:`load_checkpoint` — a file or an HF model directory (handles the
  multi-shard ``model.safetensors.index.json`` layout and builds the
  :class:`ModelConfig` from the directory's ``config.json``) -> (cfg, params).
"""
from __future__ import annotations

import json
import os
import struct
from types import SimpleNamespace
from typing import Optional

import numpy as np

from .configs import ModelConfig
from .hf_loader import config_from_hf, params_from_state_dict

_DTYPES = {
    "F64": np.float64,
    "F32": np.float32,
    "F16": np.float16,
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
    # BF16 handled specially (no numpy dtype)
}


def _bf16_to_f32(raw: np.ndarray) -> np.ndarray:
    """uint16 bf16 bit patterns -> float32 (shift into the high mantissa half)."""
    return (raw.astype(np.uint32) << 16).view(np.float32)


def read_safetensors(path: str) -> dict:
    """Parse one ``.safetensors`` file into {name: np.ndarray} (bf16 -> fp32)."""
    with open(path, "rb") as f:
        (header_len,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(header_len))
        data = f.read()
    out = {}
    for name, meta in header.items():
        if name == "__metadata__":
            continue
        start, end = meta["data_offsets"]
        buf = data[start:end]
        shape = tuple(meta["shape"])
        if meta["dtype"] == "BF16":
            out[name] = _bf16_to_f32(np.frombuffer(buf, np.uint16)).reshape(shape)
        else:
            dt = _DTYPES.get(meta["dtype"])
            if dt is None:
                raise ValueError(f"unsupported safetensors dtype {meta['dtype']!r} "
                                 f"for tensor {name!r}")
            out[name] = np.frombuffer(buf, dt).reshape(shape)
    return out


def _read_dir_tensors(model_dir: str) -> dict:
    """All tensors of an HF model directory (single- or multi-shard layout)."""
    index_path = os.path.join(model_dir, "model.safetensors.index.json")
    if os.path.exists(index_path):
        with open(index_path) as f:
            index = json.load(f)
        tensors = {}
        for shard in sorted(set(index["weight_map"].values())):
            tensors.update(read_safetensors(os.path.join(model_dir, shard)))
        return tensors
    single = os.path.join(model_dir, "model.safetensors")
    if os.path.exists(single):
        return read_safetensors(single)
    candidates = [f for f in os.listdir(model_dir) if f.endswith(".safetensors")]
    if len(candidates) == 1:
        return read_safetensors(os.path.join(model_dir, candidates[0]))
    raise FileNotFoundError(
        f"no model.safetensors(.index.json) in {model_dir!r} (found: {candidates})")


def config_from_dir(model_dir: str) -> ModelConfig:
    """Build the ModelConfig from a directory's ``config.json`` (no transformers
    import — the JSON keys are read through the same mapping as
    :func:`config_from_hf`)."""
    with open(os.path.join(model_dir, "config.json")) as f:
        raw = json.load(f)
    return config_from_hf(SimpleNamespace(**raw))


def load_checkpoint(path: str, cfg: Optional[ModelConfig] = None):
    """(cfg, params) from a ``.safetensors`` file or an HF model directory.

    For a bare file, ``cfg`` must be supplied (e.g. a preset); for a directory
    it is read from ``config.json`` unless overridden. This is the torch-free
    path that makes ``run.py --weights model.safetensors`` work the moment a
    checkpoint artifact appears.
    """
    if os.path.isdir(path):
        cfg = cfg or config_from_dir(path)
        sd = _read_dir_tensors(path)
    else:
        if cfg is None:
            raise ValueError("loading a bare .safetensors file requires a ModelConfig "
                             "(pass --model <preset>)")
        sd = read_safetensors(path)
    if cfg.tie_word_embeddings and "lm_head.weight" in sd and \
            "model.embed_tokens.weight" not in sd:
        # some exports store only the tied head; the loader expects the embed key
        sd["model.embed_tokens.weight"] = sd["lm_head.weight"]
    return cfg, params_from_state_dict(cfg, sd)
