from .configs import (ModelConfig, PYTHIA_70M, QWEN2_0_5B, QWEN2_1_5B,
                      LLAMA_3_2_1B, PRESETS, tiny_config)
from .transformer import (
    AttnStats, forward, run_layers, embed, unembed, nll_from_logits, init_params,
    precompute_rope, KVCache, init_cache, prefill, decode_step,
)
from .hf_loader import params_from_state_dict, config_from_hf
from .paged_kv import (KVTierMismatchError, OutOfPages, OutOfSlots,
                       PagedKVCache, PagePool, init_pool, paged_decode_step)

__all__ = [
    "ModelConfig", "PYTHIA_70M", "QWEN2_0_5B", "QWEN2_1_5B", "LLAMA_3_2_1B",
    "PRESETS", "tiny_config",
    "AttnStats", "forward", "run_layers", "embed", "unembed", "nll_from_logits",
    "init_params", "precompute_rope", "params_from_state_dict", "config_from_hf",
    "KVCache", "init_cache", "prefill", "decode_step",
    "KVTierMismatchError", "OutOfPages", "OutOfSlots", "PagedKVCache",
    "PagePool", "init_pool", "paged_decode_step",
]
