"""Page-table KV cache for continuous batching.

The monolithic :class:`~edgellm_tpu.models.transformer.KVCache` gives every
request a private ``(B, capacity)`` buffer sized for the worst case, so a
mixed-length request stream either pads every cache to the longest stream or
recompiles per shape — ROADMAP item 1's gap between "a compiled generate()"
and "a service". This module replaces the monolith with the paged layout of
*Ragged Paged Attention* (PAPERS.md): one shared pool of fixed-size pages,

    k, v: (L, num_pages, page_size, KV, hd)

and a small host-side allocator that maps each stream (a *slot*) to an
ordered list of pages. Logical position ``p`` of slot ``i`` lives at
``page_table[i, p // page_size]`` offset ``p % page_size``. The page table
and per-slot lengths ride through the jitted step as traced int32 arrays, so
ONE executable serves every admit/evict/fill configuration of a given pool
geometry — the continuous-batching scheduler (``serve/batching.py``) admits
and evicts mid-flight without a single retrace.

Conventions that keep the paged step bit-identical to the contiguous one:

- page 0 is the TRASH page: never allocated, written by inactive slots (their
  page-table rows are all zero). Its contents are garbage but always finite
  (inactive rows run real token-0 math), so masked attention positions
  contribute exactly 0 to every softmax.
- pages store POST-ROTARY keys at ``num_kv_heads`` width, the same values the
  contiguous cache stores; gathering a slot's pages in order reproduces that
  slot's contiguous cache prefix byte-for-byte.
- the per-slot RoPE row, attention mask, and sampling fold_in sequence match
  ``decode_step``/``generate`` exactly, and attention softmax is invariant to
  the amount of masked padding — so a slot's tokens are bit-identical to
  running it alone (``tests/test_batching.py`` asserts this, and the
  ``batching.decode-step-identity`` graphlint contract re-checks it on every
  lint run).

Donation: the jitted step and adopt/defrag helpers donate the pool buffers,
so the (L, num_pages, page_size) arrays update in place — the
``paged.decode_step`` graph contract asserts the aliasing survives lowering.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Iterator, NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..lint import graph_contract
from .configs import ModelConfig
from .transformer import (_cast_params, _layernorm, _rmsnorm, _rotate_half,
                          embed, mlp, precompute_rope, unembed)

#: slot id a page belongs to when it is on the free list
FREE = -1

#: owner sentinel for a page referenced by more than one holder (several
#: slots, or a slot plus the prefix index) — no single slot may write it
SHARED = -2


@dataclass(frozen=True)
class PrefixCacheConfig:
    """Knobs for prefix sharing over the paged pool.

    min_shared_block: minimum matched prefix length (tokens) before an admit
        takes the shared path — below it the index is consulted but the
        request prefills privately (tiny matches are not worth the COW fork
        their first decode write costs).
    max_index_pages: cap on the number of index NODES (each node pins one
        page); 0 = uncapped. At the cap, registration evicts LRU leaves
        first and gives up if every leaf is still live in some slot.
    """

    enabled: bool = True
    min_shared_block: int = 1
    max_index_pages: int = 0

    def __post_init__(self):
        if self.min_shared_block < 1:
            raise ValueError(f"min_shared_block must be >= 1, got "
                             f"{self.min_shared_block}")
        if self.max_index_pages < 0:
            raise ValueError(f"max_index_pages must be >= 0 (0 = uncapped), "
                             f"got {self.max_index_pages}")


class _PrefixNode:
    """One page's worth of a registered prompt prefix.

    A node maps one token-id block to the page holding its post-rotary K/V,
    valid only under this node's PATH (positions are absolute from 0, so
    the same block under a different parent chain is a different node).
    ``full`` nodes cover exactly ``page_size`` tokens and may have children;
    ``partial`` nodes cover the tail of a registered prompt (< page_size
    tokens) and are always leaves — a partial page cannot be extended
    in-place without invalidating sharers, which is exactly what
    :meth:`PagedKVCache.fork_page` (COW) exists to avoid.
    """

    __slots__ = ("tokens", "page", "full", "parent", "children", "partials",
                 "stamp")

    def __init__(self, tokens: tuple, page: int, full: bool,
                 parent: Optional["_PrefixNode"], stamp: int):
        self.tokens = tokens
        self.page = page
        self.full = full
        self.parent = parent
        self.children: dict[tuple, _PrefixNode] = {}
        self.partials: list[_PrefixNode] = []
        self.stamp = stamp

    @property
    def is_leaf(self) -> bool:
        return not self.children and not self.partials


class PrefixIndex:
    """Radix index over page-granular token blocks.

    Keyed by the token-id block itself (a python tuple — its hash IS the
    token-block hash; collisions are impossible by construction, unlike a
    rolling digest). Depth j in the trie is page j of a prompt: walking
    full-block children from the root matches ever-longer page-aligned
    prefixes, and each matched node names a pool page that already holds
    that block's K/V. LRU stamps order eviction; reclaiming always drops
    leaves first so interior nodes never strand unreachable holds.
    """

    def __init__(self, page_size: int):
        self.page_size = page_size
        self.root = _PrefixNode((), 0, True, None, 0)
        self._clock = 0
        self._count = 0

    # -- bookkeeping -------------------------------------------------------

    @property
    def num_nodes(self) -> int:
        return self._count

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def touch(self, node: _PrefixNode) -> None:
        self._tick()
        # refresh the whole path: evicting an ancestor of a hot leaf would
        # orphan it, so LRU order must be path-monotone (parent >= child)
        while node is not None and node is not self.root:
            node.stamp = self._clock
            node = node.parent

    def iter_nodes(self) -> Iterator[_PrefixNode]:
        """Every node except the root, preorder (parents first)."""
        stack = [self.root]
        while stack:
            node = stack.pop()
            if node is not self.root:
                yield node
            stack.extend(node.partials)
            stack.extend(node.children.values())

    def leaves(self) -> list[_PrefixNode]:
        return [n for n in self.iter_nodes() if n.is_leaf]

    # -- match / insert / remove ------------------------------------------

    def match(self, tokens) -> list[tuple[_PrefixNode, int]]:
        """Longest page-aligned match of ``tokens`` against the index.

        Returns [(node, claimed_tokens), ...] along the match path: full
        interior blocks claim ``page_size`` tokens each; one final node may
        claim fewer — the longest-common-prefix row count of a partial leaf
        (or of a full block the request diverges inside). Claimed rows of
        the final page are valid for THIS request; rows past the claim are
        the donor's K/V, which per-slot length masking never reads."""
        ps = self.page_size
        out: list[tuple[_PrefixNode, int]] = []
        node = self.root
        j = 0
        while (j + 1) * ps <= len(tokens):
            key = tuple(int(t) for t in tokens[j * ps:(j + 1) * ps])
            child = node.children.get(key)
            if child is None:
                break
            out.append((child, ps))
            node = child
            j += 1
        rest = [int(t) for t in tokens[j * ps:]]
        best, best_m = None, 0
        for cand in list(node.partials) + list(node.children.values()):
            m = 0
            for a, b in zip(cand.tokens, rest):
                if a != b:
                    break
                m += 1
            if m > best_m:
                best, best_m = cand, m
        if best is not None and best_m > 0:
            out.append((best, best_m))
        return out

    def insert_full(self, parent: _PrefixNode, key: tuple,
                    page: int) -> _PrefixNode:
        node = _PrefixNode(key, page, True, parent, self._tick())
        parent.children[key] = node
        self._count += 1
        return node

    def insert_partial(self, parent: _PrefixNode, tokens: tuple,
                       page: int) -> _PrefixNode:
        node = _PrefixNode(tokens, page, False, parent, self._tick())
        parent.partials.append(node)
        self._count += 1
        return node

    def remove(self, node: _PrefixNode) -> None:
        """Detach a LEAF node (interior nodes must shed children first)."""
        assert node.is_leaf, "only leaves are removable"
        parent = node.parent
        if node.full:
            del parent.children[node.tokens]
        else:
            parent.partials.remove(node)
        node.parent = None
        self._count -= 1

    # -- serialization (checkpoint round-trip) ----------------------------

    def to_array(self) -> np.ndarray:
        """Flatten to one int64 array: per node (preorder)
        ``[depth, full, page, stamp, ntok, tok...]`` — the ndarray-friendly
        form :class:`~edgellm_tpu.serve.recovery.DecodeCheckpoint` stores."""
        rows: list[int] = []

        def walk(node: _PrefixNode, depth: int) -> None:
            for child in list(node.children.values()) + node.partials:
                rows.extend([depth, int(child.full), child.page, child.stamp,
                             len(child.tokens)])
                rows.extend(int(t) for t in child.tokens)
                walk(child, depth + 1)

        walk(self.root, 0)
        return np.asarray(rows, np.int64)

    def load_array(self, flat: np.ndarray) -> None:
        """Rebuild from :meth:`to_array` output (clears current contents)."""
        self.root = _PrefixNode((), 0, True, None, 0)
        self._count = 0
        flat = np.asarray(flat, np.int64)
        path = [self.root]  # path[d] = parent at depth d
        i = 0
        while i < flat.size:
            depth, full, page, stamp, ntok = (int(x) for x in flat[i:i + 5])
            tokens = tuple(int(t) for t in flat[i + 5:i + 5 + ntok])
            i += 5 + ntok
            parent = path[depth]
            if full:
                node = self.insert_full(parent, tokens, page)
            else:
                node = self.insert_partial(parent, tokens, page)
            node.stamp = stamp
            del path[depth + 1:]
            path.append(node)
        self._clock = max((n.stamp for n in self.iter_nodes()), default=0)


class OutOfPages(RuntimeError):
    """The pool has no free page for a slot that must grow — the scheduler's
    signal to evict (or refuse to admit) a stream."""


class OutOfSlots(RuntimeError):
    """Every slot of the compiled step shape is occupied."""


class KVTierMismatchError(ValueError):
    """A KV payload at one ``kv_codec`` tier was offered to a pool built at
    another. Every adoption surface — packed adopts, checkpoint restore,
    page migration — raises THIS type (never a transcode): silently
    requantizing or inflating would change page bytes under the bit-exact
    round-trip promise. ``offered``/``pool`` carry both tier names so
    callers can rebuild at the right tier."""

    def __init__(self, *, offered: str, pool: str, where: str,
                 detail: str = ""):
        self.offered = offered
        self.pool = pool
        self.where = where
        super().__init__(
            f"KV tier mismatch in {where}: payload is {offered!r}, pool is "
            f"{pool!r}; rebuild the pool at kv_codec={offered!r} "
            f"(at-rest transcoding is refused)"
            + (f" — {detail}" if detail else ""))


class PagePool(NamedTuple):
    """Device-side page pool: post-rotary K/V at ``num_kv_heads`` width.

    k, v: (L, num_pages, page_size, KV, hd). Page 0 is the reserved trash
    page (see module docstring)."""

    k: jnp.ndarray
    v: jnp.ndarray

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[2]


def init_pool(cfg: ModelConfig, num_pages: int, page_size: int,
              dtype=jnp.float32) -> PagePool:
    """An all-zero pool; ``num_pages`` INCLUDES the reserved trash page 0,
    so ``num_pages - 1`` pages are allocatable."""
    if num_pages < 2:
        raise ValueError(f"num_pages must be >= 2 (page 0 is reserved), "
                         f"got {num_pages}")
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    shape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads,
             cfg.head_dim)
    return PagePool(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


# ---------------------------------------------------------------------------
# KV-at-rest compression: quantized page layouts. ROADMAP item 3 — the same
# per-channel shapes the wire codecs compress, applied to the pool so a fixed
# HBM budget holds 2-4x more live tokens. The "fp" tier IS the plain PagePool
# path above, untouched, so disabled builds trace the pre-quantization graph.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class KVPageCodec:
    """One KV-at-rest storage tier. ``bits=0`` marks the uncompressed fp
    tier (codes are the pool dtype itself, no scales). Quantized tiers store
    ``code_lanes(hd)`` packed code bytes plus ONE fp32 absmax scale per
    (token row, KV head) — per-row, not per-page, because decode appends a
    single row via scatter and must not requantize its neighbours."""

    name: str
    bits: int
    code_dtype: object  # jnp dtype of the code arrays ("fp": pool dtype)

    @property
    def quantized(self) -> bool:
        return self.bits > 0

    def code_lanes(self, head_dim: int) -> int:
        """Last-axis width of a code row (int4 packs two lanes per byte)."""
        if self.bits == 4:
            if head_dim % 2:
                raise ValueError(f"int4 packing needs an even head_dim, "
                                 f"got {head_dim}")
            return head_dim // 2
        return head_dim

    def row_bytes(self, head_dim: int, dtype=jnp.float32) -> int:
        """HBM bytes per (token row, KV head) for K or V: codes + scale."""
        if not self.quantized:
            return head_dim * jnp.dtype(dtype).itemsize
        return self.code_lanes(head_dim) + 4  # packed codes + fp32 scale


KV_PAGE_CODECS = {
    "fp": KVPageCodec("fp", 0, None),
    "int8_per_channel": KVPageCodec("int8_per_channel", 8, jnp.int8),
    "int4_per_channel": KVPageCodec("int4_per_channel", 4, jnp.uint8),
}


def resolve_kv_codec(name: str) -> KVPageCodec:
    """Registry lookup that REFUSES unknown tier names (the run.py params
    validator and every constructor route through this)."""
    try:
        return KV_PAGE_CODECS[name]
    except KeyError:
        raise ValueError(f"unknown kv_codec {name!r}; available tiers: "
                         f"{sorted(KV_PAGE_CODECS)}") from None


class QuantPagePool(NamedTuple):
    """Quantized device pool: packed int codes + per-row fp32 scales.

    k, v: (L, num_pages, page_size, KV, hdc) codes — hdc = hd (int8) or
    hd/2 (packed int4, lane i paired with lane i + hd/2, the wire codecs'
    contiguous-half pairing). k_scale, v_scale: (L, num_pages, page_size,
    KV) fp32 absmax scales. Page axis 1 and token axis 2 match PagePool, so
    the page-table/flat-index math is tier-agnostic."""

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: jnp.ndarray
    v_scale: jnp.ndarray

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[2]


def init_quant_pool(cfg: ModelConfig, num_pages: int, page_size: int,
                    kv_codec: str) -> QuantPagePool:
    """All-zero quantized pool (same trash-page-0 convention as
    :func:`init_pool`; zero codes with zero scales dequantize to zeros)."""
    codec = resolve_kv_codec(kv_codec)
    if not codec.quantized:
        raise ValueError("init_quant_pool is for quantized tiers; "
                         "use init_pool for fp")
    if num_pages < 2:
        raise ValueError(f"num_pages must be >= 2 (page 0 is reserved), "
                         f"got {num_pages}")
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    hdc = codec.code_lanes(cfg.head_dim)
    cshape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads, hdc)
    sshape = cshape[:-1]
    return QuantPagePool(jnp.zeros(cshape, codec.code_dtype),
                         jnp.zeros(cshape, codec.code_dtype),
                         jnp.zeros(sshape, jnp.float32),
                         jnp.zeros(sshape, jnp.float32))


def kv_page_bytes(cfg: ModelConfig, page_size: int, kv_codec: str = "fp",
                  dtype=jnp.float32) -> int:
    """HBM bytes ONE page costs across all layers (K + V, codes + scales) —
    the honest per-tier footprint the capacity accounting below divides by."""
    codec = resolve_kv_codec(kv_codec)
    return (2 * cfg.num_layers * page_size * cfg.num_kv_heads
            * codec.row_bytes(cfg.head_dim, dtype))


def num_pages_for_bytes(cfg: ModelConfig, pool_bytes: int, page_size: int,
                        kv_codec: str = "fp", dtype=jnp.float32) -> int:
    """Pages (trash page included) a fixed HBM budget buys at a tier — the
    pages-per-token admission math is unchanged, the pool just has MORE
    pages, which is exactly how quantization multiplies concurrency."""
    pages = int(pool_bytes) // kv_page_bytes(cfg, page_size, kv_codec, dtype)
    if pages < 2:
        raise ValueError(
            f"pool budget {pool_bytes} bytes buys {pages} {kv_codec} "
            f"page(s); need >= 2 (page 0 is reserved)")
    return pages


# ---------------------------------------------------------------------------
# jitted pool surgery: adopt a contiguous prefix, gather one back, permute
# pages for defrag. All donate the pool so surgery is in-place.
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _adopt_impl(pool_k, pool_v, k_seq, v_seq, dest):
    """Scatter a contiguous (L, S, KV, hd) K/V prefix into the pool rows
    named by ``dest`` (S,) — flat indices into the (num_pages * page_size)
    token axis. S is static per call (one executable per adopted length)."""
    l, pn, ps = pool_k.shape[:3]
    tail = pool_k.shape[3:]
    fk = pool_k.reshape(l, pn * ps, *tail).at[:, dest].set(
        k_seq.astype(pool_k.dtype))
    fv = pool_v.reshape(l, pn * ps, *tail).at[:, dest].set(
        v_seq.astype(pool_v.dtype))
    return fk.reshape(pool_k.shape), fv.reshape(pool_v.shape)


@jax.jit
def _gather_impl(pool_k, pool_v, idx):
    """Read the pool rows named by ``idx`` (span,) back as contiguous
    (L, span, KV, hd) arrays — the checkpoint/eviction serialization path."""
    l, pn, ps = pool_k.shape[:3]
    tail = pool_k.shape[3:]
    return (pool_k.reshape(l, pn * ps, *tail)[:, idx],
            pool_v.reshape(l, pn * ps, *tail)[:, idx])


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _permute_impl(pool_k, pool_v, src):
    """new_pool[p] = old_pool[src[p]] — the defrag move, one gather."""
    return pool_k[:, src], pool_v[:, src]


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _copy_pages_impl(pool_k, pool_v, src, dst):
    """COW fork: duplicate whole pages ``src`` (n,) into pages ``dst`` (n,).
    The forking slot then writes its private copy; every other holder keeps
    reading the original bytes."""
    return (pool_k.at[:, dst].set(pool_k[:, src]),
            pool_v.at[:, dst].set(pool_v[:, src]))


# Quantized-pool twins. Page moves (defrag, COW) are BYTE moves — codes and
# scales ride the same permutation/copy untouched, so a forked page is
# byte-identical to its original and defrag never requantizes. Only adopt
# (fp rows in) and gather (fp rows out) touch the codec; the *_packed pair
# moves raw codes+scales for the bit-exact checkpoint/eviction path.


def _flat_rows_set(arr, dest, rows):
    """Scatter (L, S, ...) rows into flat token positions ``dest`` (S,) of a
    (L, num_pages, page_size, ...) pool array."""
    l, pn, ps = arr.shape[:3]
    tail = arr.shape[3:]
    return (arr.reshape(l, pn * ps, *tail).at[:, dest]
            .set(rows.astype(arr.dtype)).reshape(arr.shape))


def _flat_rows_get(arr, idx):
    l, pn, ps = arr.shape[:3]
    tail = arr.shape[3:]
    return arr.reshape(l, pn * ps, *tail)[:, idx]


@functools.partial(jax.jit, static_argnames=("kv_codec",),
                   donate_argnums=(0,))
def _adopt_quant_impl(pool, k_seq, v_seq, dest, kv_codec: str):
    """Quantize contiguous (L, S, KV, hd) fp K/V rows on append and scatter
    codes + scales — 'writes quantize on append', the at-rest contract."""
    from .flash_attention import quantize_kv_rows

    qk, sk = quantize_kv_rows(k_seq, kv_codec)
    qv, sv = quantize_kv_rows(v_seq, kv_codec)
    return QuantPagePool(_flat_rows_set(pool.k, dest, qk),
                         _flat_rows_set(pool.v, dest, qv),
                         _flat_rows_set(pool.k_scale, dest, sk),
                         _flat_rows_set(pool.v_scale, dest, sv))


@functools.partial(jax.jit, donate_argnums=(0,))
def _adopt_packed_impl(pool, k_codes, v_codes, k_scale, v_scale, dest):
    """Scatter already-packed rows (a checkpoint's payload) — no requantize,
    so restore is bit-exact by construction."""
    return QuantPagePool(_flat_rows_set(pool.k, dest, k_codes),
                         _flat_rows_set(pool.v, dest, v_codes),
                         _flat_rows_set(pool.k_scale, dest, k_scale),
                         _flat_rows_set(pool.v_scale, dest, v_scale))


@jax.jit
def _gather_packed_impl(pool, idx):
    """Read rows back as packed codes + scales (checkpoint/eviction form —
    geometry-independent AND codec-lossless)."""
    return (_flat_rows_get(pool.k, idx), _flat_rows_get(pool.v, idx),
            _flat_rows_get(pool.k_scale, idx),
            _flat_rows_get(pool.v_scale, idx))


@functools.partial(jax.jit, static_argnames=("kv_codec",))
def _gather_quant_impl(pool, idx, kv_codec: str):
    """Read rows back DEQUANTIZED to fp32 (the suffix-prefill compute path,
    which needs fp rows; lossy by exactly the tier's quantization error)."""
    from .flash_attention import dequantize_kv_rows

    kc, vc, ks, vs = _gather_packed_impl(pool, idx)
    return (dequantize_kv_rows(kc, ks, kv_codec),
            dequantize_kv_rows(vc, vs, kv_codec))


@functools.partial(jax.jit, donate_argnums=(0,))
def _permute_pool_impl(arrays, src):
    """Tier-agnostic defrag move over a tuple of pool arrays (page axis 1)."""
    return tuple(a[:, src] for a in arrays)


@functools.partial(jax.jit, donate_argnums=(0,))
def _copy_pool_pages_impl(arrays, src, dst):
    """Tier-agnostic COW page copy over a tuple of pool arrays."""
    return tuple(a.at[:, dst].set(a[:, src]) for a in arrays)


class PagedKVCache:
    """Host-side allocator + device pool for up to ``max_slots`` concurrent
    streams of up to ``pages_per_slot * page_size`` tokens each.

    The device state is ``self.pool`` (swapped wholesale after each donated
    step/adopt/defrag); the host state is the page table, per-slot lengths,
    the free list, and per-page ownership. ``device_tables()`` materializes
    the traced int32 inputs of the compiled step. All mutating methods keep
    :meth:`check_invariants` true: no page owned twice, no page leaked, the
    trash page never allocated.
    """

    def __init__(self, cfg: ModelConfig, *, num_pages: int, page_size: int,
                 max_slots: int, pages_per_slot: int, dtype=jnp.float32,
                 materialize: bool = True,
                 prefix_cache: Optional[PrefixCacheConfig] = None,
                 kv_codec: str = "fp"):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if pages_per_slot < 1:
            raise ValueError(
                f"pages_per_slot must be >= 1, got {pages_per_slot}")
        self.cfg = cfg
        # KV-at-rest tier. Every page bookkeeping path below (alloc, COW,
        # refcounts, radix index, defrag permutation) is codec-agnostic — a
        # page is a page; only the device-pool surgery dispatches on tier.
        self.kv_codec = resolve_kv_codec(kv_codec).name
        # materialize=False: bookkeeping-only mode — the page table, free
        # list, and ownership machinery without a local device pool. The
        # split runtime uses this: its pools live per-stage on the mesh
        # (SplitRuntime.init_paged_pool), only the allocator is shared.
        if not materialize:
            self.pool = None
        elif self.kv_codec == "fp":
            self.pool = init_pool(cfg, num_pages, page_size, dtype)
        else:
            self.pool = init_quant_pool(cfg, num_pages, page_size,
                                        self.kv_codec)
        self.page_size = page_size
        self.num_pages = num_pages
        self.max_slots = max_slots
        self.pages_per_slot = pages_per_slot
        self.page_table = np.zeros((max_slots, pages_per_slot), np.int32)
        self.lengths = np.zeros((max_slots,), np.int32)
        self.active = np.zeros((max_slots,), bool)
        # LIFO free list, low pages first out — deterministic layouts
        self._free = list(range(num_pages - 1, 0, -1))
        self._slot_pages: list[list[int]] = [[] for _ in range(max_slots)]
        # page -> exclusive slot, or SHARED (>1 holder / index-held), or FREE
        self._owner = np.full((num_pages,), FREE, np.int32)
        # per-page reference counts: one per slot-table entry + one per
        # prefix-index node; a page returns to the free list ONLY at 0
        self._refcount = np.zeros((num_pages,), np.int32)
        self._index_holds = np.zeros((num_pages,), np.int32)
        self.prefix_cfg = prefix_cache
        self.prefix: Optional[PrefixIndex] = (
            PrefixIndex(page_size)
            if prefix_cache is not None and prefix_cache.enabled else None)
        # host counters; read lock-free by report scrapes (GIL-atomic ints)
        self.prefix_counters = {"hits": 0, "misses": 0, "saved_tokens": 0,
                                "cow_forks": 0, "index_evictions": 0,
                                "reclaimed_pages": 0}
        # migration-handoff holds: slots pinned while their pages are in
        # flight to another pool. free_slot refuses a held slot and defrag
        # defers wholesale (see hold_slot), so a _flat_indices snapshot
        # taken under a hold stays valid for the whole transfer.
        self._slot_holds = np.zeros((max_slots,), np.int32)
        self.deferred_defrags = 0

    # -- geometry ----------------------------------------------------------

    @property
    def span(self) -> int:
        """Max positions one slot can hold — the compiled attention width."""
        return self.pages_per_slot * self.page_size

    @property
    def num_free_pages(self) -> int:
        return len(self._free)

    @property
    def token_capacity(self) -> int:
        """Allocatable token positions (the trash page excluded)."""
        return (self.num_pages - 1) * self.page_size

    @property
    def live_tokens(self) -> int:
        return int(self.lengths[self.active].sum())

    @property
    def unique_live_tokens(self) -> int:
        """Live tokens counting each physical page ONCE: per page, the max
        coverage over every slot referencing it. Equals :attr:`live_tokens`
        when nothing is shared; under prefix sharing it is the honest
        occupancy numerator (summing per-slot lengths over-counts aliased
        pages — the ``report()`` occupancy bug this property fixes)."""
        cover = np.zeros((self.num_pages,), np.int64)
        for s in range(self.max_slots):
            if not self.active[s]:
                continue
            n = int(self.lengths[s])
            for j, p in enumerate(self._slot_pages[s]):
                c = min(self.page_size, n - j * self.page_size)
                if c > 0:
                    cover[p] = max(cover[p], c)
        return int(cover.sum())

    @property
    def shared_pages(self) -> int:
        """Pages with more than one holder (slots and/or the index)."""
        return int(np.sum(self._refcount > 1))

    @property
    def index_pages(self) -> int:
        """Pages pinned by at least one prefix-index node."""
        return int(np.sum(self._index_holds > 0))

    @property
    def reclaimable_index_pages(self) -> int:
        """Pages held ONLY by the index — :meth:`ensure` frees these
        LRU-first under pressure, so admission feasibility may count them
        as available."""
        return int(np.sum((self._refcount == 1) & (self._index_holds == 1)))

    def pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    # -- slot lifecycle ----------------------------------------------------

    def alloc_slot(self) -> int:
        """Claim the lowest free slot (deterministic admit order)."""
        for s in range(self.max_slots):
            if not self.active[s]:
                self.active[s] = True
                self.lengths[s] = 0
                return s
        raise OutOfSlots(f"all {self.max_slots} slots active")

    def ensure(self, slot: int, new_length: int) -> None:
        """Grow ``slot``'s page list to cover ``new_length`` positions,
        allocating pages from the free list. Under pool pressure, pages held
        ONLY by the prefix index (refcount would drop to 0) are reclaimed
        LRU-first before giving up. Raises :class:`OutOfPages` (allocating
        nothing) when the pool still cannot cover the growth."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        if new_length > self.span:
            raise ValueError(f"length {new_length} exceeds slot span "
                             f"{self.span}")
        need = self.pages_for(new_length) - len(self._slot_pages[slot])
        if need <= 0:
            return
        if need > len(self._free):
            self._reclaim_index_pages(need - len(self._free))
        if need > len(self._free):
            raise OutOfPages(
                f"slot {slot} needs {need} page(s), {len(self._free)} free")
        for _ in range(need):
            p = self._free.pop()
            self._owner[p] = slot
            self._refcount[p] = 1
            self.page_table[slot, len(self._slot_pages[slot])] = p
            self._slot_pages[slot].append(p)

    def free_slot(self, slot: int) -> None:
        """Release a slot; each of its pages drops one reference and returns
        to the free list only at refcount 0 (reverse allocation order, so the
        free list stays LIFO-deterministic). Shared pages survive for their
        other holders. The page contents are left stale — masked attention
        never reads past a slot's length."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        if self._slot_holds[slot]:
            raise ValueError(
                f"slot {slot} is held for an in-flight migration "
                f"({int(self._slot_holds[slot])} hold(s)); release the hold "
                f"before freeing")
        for p in reversed(self._slot_pages[slot]):
            self._release_ref(p)
        self._slot_pages[slot] = []
        self.page_table[slot] = 0
        self.lengths[slot] = 0
        self.active[slot] = False

    # -- migration-handoff holds -------------------------------------------

    def hold_slot(self, slot: int) -> None:
        """Pin ``slot`` for an in-flight page handoff: while at least one
        hold is out, :meth:`free_slot` refuses the slot and :meth:`defrag`
        defers entirely (returns 0 and bumps ``deferred_defrags``) — nothing
        may move or recycle the pages a migration's flat-index snapshot
        references, so the transfer can retry/hedge against stable source
        bytes. Prefix-index pins are refcounts and survive regardless."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        self._slot_holds[slot] += 1

    def release_slot_hold(self, slot: int) -> None:
        """Drop one migration hold on ``slot`` (see :meth:`hold_slot`)."""
        if self._slot_holds[slot] <= 0:
            raise ValueError(f"slot {slot} has no outstanding hold")
        self._slot_holds[slot] -= 1

    @property
    def held_slots(self) -> list:
        return [s for s in range(self.max_slots) if self._slot_holds[s] > 0]

    # -- reference counting / prefix sharing -------------------------------

    def _release_ref(self, p: int) -> None:
        """Drop one reference to page ``p``; free it at refcount 0."""
        assert self._refcount[p] > 0, f"refcount underflow on page {p}"
        self._refcount[p] -= 1
        if self._refcount[p] == 0:
            self._owner[p] = FREE
            self._free.append(p)
        else:
            self._recompute_owner(p)

    def _recompute_owner(self, p: int) -> None:
        """Keep the owner sentinel precise after a reference change: the
        single referencing slot when exclusive, SHARED otherwise."""
        if self._refcount[p] == 0:
            self._owner[p] = FREE
            return
        holders = [s for s in range(self.max_slots)
                   if p in self._slot_pages[s]]
        if len(holders) == 1 and self._index_holds[p] == 0:
            self._owner[p] = holders[0]
        else:
            self._owner[p] = SHARED

    def _drop_index_hold(self, p: int) -> None:
        assert self._index_holds[p] > 0
        self._index_holds[p] -= 1
        self._release_ref(p)

    def _add_index_hold(self, p: int) -> None:
        self._index_holds[p] += 1
        self._refcount[p] += 1
        self._owner[p] = SHARED

    def _evict_index_leaf(self, node) -> None:
        self.prefix.remove(node)
        self.prefix_counters["index_evictions"] += 1
        self._drop_index_hold(node.page)

    def _reclaim_index_pages(self, want: int) -> int:
        """Free up to ``want`` pages by evicting LRU index leaves whose page
        is held ONLY by the index (refcount 1 → dropping the hold frees it).
        Repeats so a freed leaf exposes its now-leaf parent. Returns the
        number of pages actually freed."""
        if self.prefix is None:
            return 0
        freed = 0
        while freed < want:
            candidates = [n for n in self.prefix.leaves()
                          if self._refcount[n.page] == 1]
            if not candidates:
                break
            victim = min(candidates, key=lambda n: (n.stamp, n.page))
            self._evict_index_leaf(victim)
            freed += 1
        self.prefix_counters["reclaimed_pages"] += freed
        return freed

    def probe_prefix(self, tokens, max_tokens: Optional[int] = None) -> dict:
        """Dry-run :meth:`share_prefix`: what WOULD an admit reuse?
        Returns {"tokens": claimable prefix length, "pages": index pages a
        match would map, "forks": COW forks the suffix write would trigger
        (1 when the match ends mid-page)} — the admit feasibility check uses
        this to count pages the slot will NOT need from the free list."""
        if self.prefix is None:
            return {"tokens": 0, "pages": 0, "forks": 0}
        limit = (len(tokens) if max_tokens is None
                 else min(int(max_tokens), len(tokens)))
        claimed, pages = 0, 0
        for node, claim in self.prefix.match(tokens):
            take = min(claim, limit - claimed)
            if take <= 0:
                break
            claimed += take
            pages += 1
        if claimed < (self.prefix_cfg.min_shared_block
                      if self.prefix_cfg else 1):
            return {"tokens": 0, "pages": 0, "forks": 0}
        return {"tokens": claimed, "pages": pages,
                "forks": 1 if claimed % self.page_size else 0}

    def share_prefix(self, slot: int, tokens,
                     max_tokens: Optional[int] = None) -> int:
        """Map the longest indexed prefix of ``tokens`` into a FRESH slot's
        page table with zero data movement: each matched index page gains one
        reference and lands in the slot's next table row; the slot's length
        becomes the claimed token count. ``max_tokens`` caps the claim (the
        batcher passes S-1 so at least one suffix token remains to produce
        the first sampled logits). Returns the claimed length (0 = miss or
        below ``min_shared_block`` — the slot is untouched)."""
        if self.prefix is None:
            return 0
        if not self.active[slot] or self._slot_pages[slot]:
            raise ValueError(
                f"share_prefix needs a fresh active slot; slot {slot} "
                f"already owns {len(self._slot_pages[slot])} page(s)")
        limit = (len(tokens) if max_tokens is None
                 else min(int(max_tokens), len(tokens)))
        matched = self.prefix.match(tokens)
        claimed = 0
        mapped: list = []
        for node, claim in matched:
            take = min(claim, limit - claimed)
            if take <= 0:
                break
            claimed += take
            mapped.append(node)
        if claimed < (self.prefix_cfg.min_shared_block
                      if self.prefix_cfg else 1):
            self.prefix_counters["misses"] += 1
            return 0
        for node in mapped:
            p = node.page
            self._refcount[p] += 1
            self._owner[p] = SHARED
            self.page_table[slot, len(self._slot_pages[slot])] = p
            self._slot_pages[slot].append(p)
            self.prefix.touch(node)
        self.lengths[slot] = claimed
        self.prefix_counters["hits"] += 1
        self.prefix_counters["saved_tokens"] += claimed
        return claimed

    def _index_make_room(self, protect: set) -> bool:
        """Honor ``max_index_pages``: evict LRU leaves (never ``protect``,
        the registration path in flight) until a node fits. False = every
        evictable leaf is protected, caller should stop registering."""
        cap = self.prefix_cfg.max_index_pages if self.prefix_cfg else 0
        if cap <= 0:
            return True
        while self.prefix.num_nodes >= cap:
            candidates = [n for n in self.prefix.leaves()
                          if n not in protect]
            if not candidates:
                return False
            self._evict_index_leaf(
                min(candidates, key=lambda n: (n.stamp, n.page)))
        return True

    def register_prefix(self, slot: int, tokens) -> int:
        """Publish ``slot``'s prompt pages into the index so later admits can
        share them: one full-block node per fully-covered page, plus one
        partial leaf for the tail. Blocks already indexed (a donor's, or the
        shared pages this very slot mapped) are LRU-touched, not re-pinned.
        Newly indexed pages gain an index reference — the slot's own first
        decode write into its partial page will COW-fork, leaving the
        registered bytes immutable. Returns the number of nodes added."""
        if self.prefix is None:
            return 0
        ps = self.page_size
        pages = self._slot_pages[slot]
        node = self.prefix.root
        added = 0
        walked: set = set()
        j = 0
        while (j + 1) * ps <= len(tokens) and j < len(pages):
            key = tuple(int(t) for t in tokens[j * ps:(j + 1) * ps])
            child = node.children.get(key)
            if child is None:
                if not self._index_make_room(walked):
                    return added
                child = self.prefix.insert_full(node, key, pages[j])
                self._add_index_hold(pages[j])
                added += 1
            else:
                self.prefix.touch(child)
            walked.add(child)
            node = child
            j += 1
        tail = tuple(int(t) for t in tokens[j * ps:])
        if tail and j < len(pages):
            for cand in node.partials:
                if cand.tokens == tail:
                    self.prefix.touch(cand)
                    return added
            if not self._index_make_room(walked):
                return added
            self.prefix.insert_partial(node, tail, pages[j])
            self._add_index_hold(pages[j])
            added += 1
        return added

    def release_prefix(self, tokens=None) -> int:
        """Drop index pins: the whole index (``tokens=None``) or the deepest
        exclusive suffix of one registered path. Pages whose refcount hits 0
        return to the free list. Returns the number of nodes released."""
        if self.prefix is None:
            return 0
        if tokens is None:
            dropped = 0
            while True:
                leaves = self.prefix.leaves()
                if not leaves:
                    break
                for leaf in leaves:
                    self._evict_index_leaf(leaf)
                    dropped += 1
            return dropped
        chain = [node for node, _ in self.prefix.match(tokens)]
        dropped = 0
        for node in reversed(chain):
            if not node.is_leaf:
                break
            self._evict_index_leaf(node)
            dropped += 1
        return dropped

    # -- copy-on-write -----------------------------------------------------

    def fork_page(self, slot: int, page_index: int) -> tuple[int, int]:
        """COW: give ``slot`` a private copy-slot for its ``page_index``-th
        page. Allocates a fresh page, repoints the slot's table row, and
        drops one reference on the shared original (every other holder keeps
        it). Returns (old_page, new_page) — the DEVICE copy is the caller's
        job (:meth:`ensure_writable` does it for a materialized pool; the
        split batcher routes the pair through the runtime's per-stage
        pools)."""
        old = self._slot_pages[slot][page_index]
        assert self._refcount[old] > 1, \
            f"fork_page on exclusively-owned page {old}"
        if not self._free:
            self._reclaim_index_pages(1)
        if not self._free:
            raise OutOfPages(
                f"COW fork for slot {slot} needs a free page, 0 free")
        new = self._free.pop()
        self._refcount[new] = 1
        self._owner[new] = slot
        self._refcount[old] -= 1
        self._recompute_owner(old)
        self._slot_pages[slot][page_index] = new
        self.page_table[slot, page_index] = new
        self.prefix_counters["cow_forks"] += 1
        return old, new

    def prepare_write(self, slot: int, new_length: int,
                      start: Optional[int] = None) -> list:
        """Fork every SHARED page the write range
        ``[lengths[slot], new_length)`` touches (bookkeeping only; ``start``
        overrides the range's left edge — :meth:`adopt` rewrites from 0).
        Returns the (old, new) copy list the device pools must apply before
        any row in the range is written."""
        left = int(self.lengths[slot]) if start is None else int(start)
        start = left // self.page_size
        stop = min(self.pages_for(new_length), len(self._slot_pages[slot]))
        forks = [j for j in range(start, stop)
                 if self._refcount[self._slot_pages[slot][j]] > 1]
        # all-or-nothing: a fork that fails MID-loop would leave earlier
        # forks' table rows pointing at pages whose device copy never ran
        if len(forks) > len(self._free):
            self._reclaim_index_pages(len(forks) - len(self._free))
        if len(forks) > len(self._free):
            raise OutOfPages(
                f"slot {slot} needs {len(forks)} COW fork(s), "
                f"{len(self._free)} page(s) free")
        return [self.fork_page(slot, j) for j in forks]

    def ensure_writable(self, slot: int, new_length: int) -> list:
        """:meth:`ensure` + COW: after this, every page covering
        ``[lengths[slot], new_length)`` is exclusively owned by ``slot`` and
        safe to write in place. On a materialized pool the page copies run
        here; bookkeeping-only callers (the split batcher) get the (old, new)
        pairs back and must apply them to their own per-stage pools."""
        self.ensure(slot, new_length)
        pairs = self.prepare_write(slot, new_length)
        if pairs and self.pool is not None:
            src = jnp.asarray([o for o, _ in pairs], jnp.int32)
            dst = jnp.asarray([n for _, n in pairs], jnp.int32)
            if self.kv_codec == "fp":
                k, v = _copy_pages_impl(self.pool.k, self.pool.v, src, dst)
                self.pool = PagePool(k, v)
            else:
                # byte move: the fork copies codes AND scales untouched, so
                # the private page is byte-identical to the shared original
                self.pool = QuantPagePool(
                    *_copy_pool_pages_impl(tuple(self.pool), src, dst))
        return pairs

    def device_tables(self) -> tuple[jnp.ndarray, jnp.ndarray]:
        """(page_table (max_slots, pages_per_slot), lengths (max_slots,)) as
        device int32 arrays — the traced inputs of the compiled step."""
        return (jnp.asarray(self.page_table),
                jnp.asarray(self.lengths, jnp.int32))

    # -- data movement -----------------------------------------------------

    def _require_pool(self, what: str) -> None:
        if self.pool is None:
            raise ValueError(f"{what} needs a materialized pool; this cache "
                             f"was built with materialize=False "
                             f"(bookkeeping-only)")

    def _flat_indices(self, slot: int, n: int) -> np.ndarray:
        pos = np.arange(n)
        return (self.page_table[slot, pos // self.page_size]
                * self.page_size + pos % self.page_size).astype(np.int32)

    def adopt(self, slot: int, k_seq, v_seq, length: int) -> None:
        """Write a contiguous (L, length, KV, hd) post-rotary K/V prefix
        (a prefill's cache, or a restored checkpoint) into ``slot``'s pages
        and set its length. Allocates pages as needed; any shared page in
        the range is COW-forked first (no device copy — every row the fork
        exposes is overwritten here, and rows past ``length`` stay masked)."""
        self._require_pool("adopt")
        self.ensure(slot, length)
        self.prepare_write(slot, length, start=0)
        dest = jnp.asarray(self._flat_indices(slot, length))
        if self.kv_codec == "fp":
            k, v = _adopt_impl(self.pool.k, self.pool.v, k_seq, v_seq, dest)
            self.pool = PagePool(k, v)
        else:
            self.pool = _adopt_quant_impl(self.pool, jnp.asarray(k_seq),
                                          jnp.asarray(v_seq), dest,
                                          kv_codec=self.kv_codec)
        self.lengths[slot] = length

    def adopt_rows(self, slot: int, k_seq, v_seq,
                   start: int, stop: int) -> None:
        """Suffix variant of :meth:`adopt`: write (L, stop-start, KV, hd)
        post-rotary K/V into rows ``[start, stop)`` of ``slot`` — the
        prefix-sharing admit path lands ONLY the unmatched suffix here, the
        shared rows below ``start`` stay aliased. ``start`` must equal the
        slot's current length (the shared-prefix claim)."""
        self._require_pool("adopt_rows")
        if start != int(self.lengths[slot]):
            raise ValueError(f"adopt_rows start {start} != slot {slot} "
                             f"length {int(self.lengths[slot])}")
        self.ensure_writable(slot, stop)
        dest = jnp.asarray(self._flat_indices(slot, stop)[start:])
        if self.kv_codec == "fp":
            k, v = _adopt_impl(self.pool.k, self.pool.v, k_seq, v_seq, dest)
            self.pool = PagePool(k, v)
        else:
            self.pool = _adopt_quant_impl(self.pool, jnp.asarray(k_seq),
                                          jnp.asarray(v_seq), dest,
                                          kv_codec=self.kv_codec)
        self.lengths[slot] = stop

    def adopt_packed(self, slot: int, k_codes, v_codes, k_scale, v_scale,
                     length: int) -> None:
        """Write already-packed (L, length, KV, hdc) codes + (L, length, KV)
        scales into ``slot`` — the restore/readmit path for quantized
        checkpoints. No requantize happens, so the pool bytes equal the
        gathered bytes exactly, across any pool geometry."""
        self._require_pool("adopt_packed")
        if self.kv_codec == "fp":
            raise KVTierMismatchError(
                offered="quantized", pool=self.kv_codec,
                where="adopt_packed",
                detail="packed payloads are for quantized tiers; fp pools "
                       "adopt fp rows via adopt()")
        self.ensure(slot, length)
        self.prepare_write(slot, length, start=0)
        dest = jnp.asarray(self._flat_indices(slot, length))
        self.pool = _adopt_packed_impl(
            self.pool, jnp.asarray(k_codes), jnp.asarray(v_codes),
            jnp.asarray(k_scale), jnp.asarray(v_scale), dest)
        self.lengths[slot] = length

    def gather_slot(self, slot: int) -> dict:
        """Read ``slot``'s K/V back as the contiguous host state dict the
        recovery checkpoint stores: {"k": (L, length, KV, hd), "v": ...,
        "length"} — byte-identical to the contiguous cache prefix on the fp
        tier; on quantized tiers the rows come back DEQUANTIZED to fp32
        (the suffix-prefill compute path — use :meth:`gather_slot_packed`
        when the bytes themselves must survive)."""
        self._require_pool("gather_slot")
        n = int(self.lengths[slot])
        idx = jnp.asarray(self._flat_indices(slot, max(n, 1)))
        if self.kv_codec == "fp":
            k, v = _gather_impl(self.pool.k, self.pool.v, idx)
        else:
            k, v = _gather_quant_impl(self.pool, idx, kv_codec=self.kv_codec)
        return {"k": np.asarray(k)[:, :n], "v": np.asarray(v)[:, :n],
                "length": np.asarray(n, np.int32)}

    def gather_slot_packed(self, slot: int) -> dict:
        """Quantized-tier eviction/checkpoint form: {"k_codes", "v_codes",
        "k_scale", "v_scale", "length"} host arrays — raw pool bytes, so
        gather -> adopt_packed round-trips bit-exactly by construction."""
        self._require_pool("gather_slot_packed")
        if self.kv_codec == "fp":
            raise ValueError("gather_slot_packed is for quantized tiers; "
                             "fp pools use gather_slot()")
        n = int(self.lengths[slot])
        idx = jnp.asarray(self._flat_indices(slot, max(n, 1)))
        kc, vc, ks, vs = _gather_packed_impl(self.pool, idx)
        return {"k_codes": np.asarray(kc)[:, :n],
                "v_codes": np.asarray(vc)[:, :n],
                "k_scale": np.asarray(ks)[:, :n],
                "v_scale": np.asarray(vs)[:, :n],
                "length": np.asarray(n, np.int32)}

    def _check_row_range(self, slot: int, start: int, stop: int) -> None:
        if not 0 <= start < stop <= int(self.lengths[slot]):
            raise ValueError(
                f"row range [{start}, {stop}) out of slot {slot}'s "
                f"length {int(self.lengths[slot])}")

    def gather_slot_rows(self, slot: int, start: int, stop: int) -> dict:
        """Row range ``[start, stop)`` of :meth:`gather_slot` — the per-page
        migration chunk (a handoff seals, ships, and verifies one page at a
        time; under :meth:`hold_slot` the flat indices stay stable across
        the whole ranged walk)."""
        self._require_pool("gather_slot_rows")
        self._check_row_range(slot, start, stop)
        idx = jnp.asarray(self._flat_indices(slot, stop)[start:])
        if self.kv_codec == "fp":
            k, v = _gather_impl(self.pool.k, self.pool.v, idx)
        else:
            k, v = _gather_quant_impl(self.pool, idx, kv_codec=self.kv_codec)
        return {"k": np.asarray(k), "v": np.asarray(v)}

    def gather_slot_rows_packed(self, slot: int, start: int,
                                stop: int) -> dict:
        """Row range ``[start, stop)`` of :meth:`gather_slot_packed` — raw
        pool bytes for one migrated page, so the packed adopt on the far
        side is a byte move."""
        self._require_pool("gather_slot_rows_packed")
        if self.kv_codec == "fp":
            raise ValueError("gather_slot_rows_packed is for quantized "
                             "tiers; fp pools use gather_slot_rows()")
        self._check_row_range(slot, start, stop)
        idx = jnp.asarray(self._flat_indices(slot, stop)[start:])
        kc, vc, ks, vs = _gather_packed_impl(self.pool, idx)
        return {"k_codes": np.asarray(kc), "v_codes": np.asarray(vc),
                "k_scale": np.asarray(ks), "v_scale": np.asarray(vs)}

    def defrag(self) -> int:
        """Compact allocated pages to the low end of the pool (slot order,
        trash page fixed at 0) and rebuild the free list above them. Returns
        the number of pages that moved. One donated device gather; page
        tables are rewritten to match, so every slot's logical content is
        unchanged. Deferred (returns 0) while any slot holds a migration
        pin — a compaction would invalidate the in-flight transfer's
        flat-index snapshot."""
        self._require_pool("defrag")
        if self._slot_holds.any():
            self.deferred_defrags += 1
            return 0
        # src (new -> old) must be a TRUE permutation: after alloc/grow/free
        # churn an owned page's compacted destination can be a currently-free
        # page with a HIGHER id (e.g. slot pages [[4],[2],[1]] with page 3
        # free), so inverting an old->new map would collide with the free
        # page's identity entry and gather garbage into the destination.
        # Place referenced pages at their destinations first, then spread the
        # leftover old pages over the remaining destinations. A SHARED page
        # gets its destination on FIRST encounter and every later holder —
        # other slots' table rows, index nodes — repoints to that same id,
        # so it moves exactly once.
        src = np.zeros((self.num_pages,), np.int32)  # new -> old; src[0] = 0
        new_of: dict = {}
        moved = 0
        nxt = 1

        def place(p: int) -> int:
            nonlocal moved, nxt
            if p in new_of:
                return new_of[p]
            src[nxt] = p
            if p != nxt:
                moved += 1
            new_of[p] = nxt
            nxt += 1
            return new_of[p]

        for s in range(self.max_slots):
            pages = self._slot_pages[s]
            for j, p in enumerate(pages):
                pages[j] = place(p)
                self.page_table[s, j] = pages[j]
        if self.prefix is not None:
            for node in self.prefix.iter_nodes():
                node.page = place(node.page)
        placed = set(int(x) for x in src[:nxt])
        src[nxt:] = [p for p in range(1, self.num_pages) if p not in placed]
        # bookkeeping arrays ride the same permutation (free pages carry
        # FREE/0/0, so the gather is correct for the whole range).
        self._owner = self._owner[src].copy()
        self._refcount = self._refcount[src].copy()
        self._index_holds = self._index_holds[src].copy()
        self._free = list(range(self.num_pages - 1, nxt - 1, -1))
        if moved:
            if self.kv_codec == "fp":
                k, v = _permute_impl(self.pool.k, self.pool.v,
                                     jnp.asarray(src))
                self.pool = PagePool(k, v)
            else:
                # pages move as bytes: codes and scales ride the same
                # permutation, nothing requantizes
                self.pool = QuantPagePool(
                    *_permute_pool_impl(tuple(self.pool), jnp.asarray(src)))
        return moved

    # -- serialization -----------------------------------------------------

    def state_dict(self) -> dict:
        """Whole-cache snapshot as host numpy arrays — the checkpoint form.
        (Per-slot checkpoints use :meth:`gather_slot` instead, which is
        geometry-independent.)"""
        self._require_pool("state_dict")
        if self.kv_codec == "fp":
            # pre-quantization key set, unchanged: old checkpoints and fp
            # pools stay mutually loadable
            state = {"k": np.asarray(self.pool.k),
                     "v": np.asarray(self.pool.v)}
        else:
            state = {"kv_codec": self.kv_codec,
                     "k_codes": np.asarray(self.pool.k),
                     "v_codes": np.asarray(self.pool.v),
                     "k_scale": np.asarray(self.pool.k_scale),
                     "v_scale": np.asarray(self.pool.v_scale)}
        state.update({"page_table": self.page_table.copy(),
                      "lengths": self.lengths.copy(),
                      "active": self.active.copy(),
                      "free": np.asarray(self._free, np.int32),
                      "refcount": self._refcount.copy(),
                      "index_holds": self._index_holds.copy()})
        if self.prefix is not None:
            state["prefix_index"] = self.prefix.to_array()
        return state

    def load_state_dict(self, state: dict) -> None:
        """Restore :meth:`state_dict` output bit-exactly (same geometry).
        Refcounts, index holds, and the serialized radix index round-trip
        when present; pre-sharing checkpoints (no ``refcount`` key) derive
        exclusive refcounts from the slot tables, so restore never
        double-frees or leaks a page either way."""
        self._require_pool("load_state_dict")
        ck = state.get("kv_codec", "fp")
        if ck != self.kv_codec:
            # REFUSAL, not transcode: silently requantizing (or inflating)
            # a whole pool would change every page's bytes under checkpoints
            # that promise bit-exact round-trips — the caller must build a
            # cache at the checkpoint's tier instead.
            raise KVTierMismatchError(offered=ck, pool=self.kv_codec,
                                      where="load_state_dict")
        if self.kv_codec == "fp":
            if state["k"].shape != self.pool.k.shape:
                raise ValueError(
                    f"pool shape mismatch: checkpoint {state['k'].shape} vs "
                    f"cache {self.pool.k.shape}")
            self.pool = PagePool(jnp.asarray(state["k"]),
                                 jnp.asarray(state["v"]))
        else:
            if state["k_codes"].shape != self.pool.k.shape:
                raise ValueError(
                    f"pool shape mismatch: checkpoint "
                    f"{state['k_codes'].shape} vs {self.pool.k.shape}")
            self.pool = QuantPagePool(jnp.asarray(state["k_codes"]),
                                      jnp.asarray(state["v_codes"]),
                                      jnp.asarray(state["k_scale"]),
                                      jnp.asarray(state["v_scale"]))
        self.page_table = np.asarray(state["page_table"], np.int32).copy()
        self.lengths = np.asarray(state["lengths"], np.int32).copy()
        self.active = np.asarray(state["active"], bool).copy()
        self._free = [int(p) for p in state["free"]]
        self._slot_pages = [[] for _ in range(self.max_slots)]
        for s in range(self.max_slots):
            if not self.active[s]:
                continue
            n = self.pages_for(int(self.lengths[s]))
            self._slot_pages[s] = [int(p) for p in self.page_table[s, :n]]
        if "refcount" in state:
            self._refcount = np.asarray(state["refcount"], np.int32).copy()
            self._index_holds = np.asarray(state["index_holds"],
                                           np.int32).copy()
        else:
            self._refcount = np.zeros((self.num_pages,), np.int32)
            self._index_holds = np.zeros((self.num_pages,), np.int32)
            for pages in self._slot_pages:
                for p in pages:
                    self._refcount[p] += 1
        if self.prefix is not None:
            self.prefix = PrefixIndex(self.page_size)
            if state.get("prefix_index") is not None:
                self.prefix.load_array(np.asarray(state["prefix_index"]))
        elif self._index_holds.any():
            # sharing-era checkpoint restored into a prefix-disabled cache:
            # the index is gone, so its holds must not pin (or leak) pages.
            for p in np.nonzero(self._index_holds)[0]:
                self._refcount[p] -= self._index_holds[p]
                self._index_holds[p] = 0
                if self._refcount[p] == 0:
                    self._free.append(int(p))
        self._owner = np.full((self.num_pages,), FREE, np.int32)
        for p in range(1, self.num_pages):
            if self._refcount[p] > 0:
                self._recompute_owner(p)

    # -- invariants --------------------------------------------------------

    def check_invariants(self) -> None:
        """Raise AssertionError on any aliasing/leak/ownership/refcount
        violation — the test suite calls this after every mutation."""
        assert 0 not in self._free, "trash page 0 on the free list"
        assert self._owner[0] == FREE, "trash page 0 owned by a slot"
        assert self._refcount[0] == 0, "trash page 0 referenced"
        # ground truth: refcount == slot-table references + index holds.
        expect = np.zeros((self.num_pages,), np.int32)
        holders: list = [[] for _ in range(self.num_pages)]
        for s, pages in enumerate(self._slot_pages):
            assert len(pages) == len(set(pages)), \
                f"slot {s} references a page twice: {pages}"
            for p in pages:
                expect[p] += 1
                holders[p].append(s)
        index_holds = np.zeros((self.num_pages,), np.int32)
        if self.prefix is not None:
            for node in self.prefix.iter_nodes():
                index_holds[node.page] += 1
                if node.full:
                    assert len(node.tokens) == self.page_size, \
                        f"full index node with {len(node.tokens)} tokens"
                else:
                    assert 0 < len(node.tokens) < self.page_size, \
                        f"partial index node with {len(node.tokens)} tokens"
                    assert not node.children and not node.partials, \
                        "partial index node has children"
        expect += index_holds
        assert (self._index_holds == index_holds).all(), \
            f"index holds drifted: {self._index_holds} vs {index_holds}"
        assert (self._refcount == expect).all(), \
            f"refcounts drifted: {self._refcount} vs {expect}"
        referenced = set(int(p) for p in np.nonzero(expect)[0])
        assert not (referenced & set(self._free)), \
            "page both referenced and free"
        assert referenced | set(self._free) == \
            set(range(1, self.num_pages)), \
            "page leaked (neither referenced nor free)"
        for p in range(1, self.num_pages):
            if expect[p] == 0:
                assert self._owner[p] == FREE, f"free page {p} has an owner"
            elif expect[p] == 1 and len(holders[p]) == 1:
                assert self._owner[p] == holders[p][0], \
                    f"exclusive page {p} owner {self._owner[p]} != " \
                    f"slot {holders[p][0]}"
            else:
                assert self._owner[p] == SHARED, \
                    f"shared page {p} owner {self._owner[p]} != SHARED"
        for s in range(self.max_slots):
            if self.active[s]:
                assert len(self._slot_pages[s]) * self.page_size >= \
                    self.lengths[s], f"slot {s} pages do not cover its length"
                for j, p in enumerate(self._slot_pages[s]):
                    assert self.page_table[s, j] == p
            else:
                assert not self._slot_pages[s], f"inactive slot {s} owns pages"
                assert (self.page_table[s] == 0).all()
                assert self.lengths[s] == 0
                assert self._slot_holds[s] == 0, \
                    f"inactive slot {s} carries a migration hold"

    def prefix_report(self) -> dict:
        """Host-side sharing stats for ``ContinuousBatcher.report()`` and
        the obs gauges. Cheap — no device sync."""
        c = self.prefix_counters
        total = c["hits"] + c["misses"]
        return {"enabled": self.prefix is not None,
                "hits": c["hits"], "misses": c["misses"],
                "hit_rate": (c["hits"] / total) if total else 0.0,
                "saved_tokens": c["saved_tokens"],
                "cow_forks": c["cow_forks"],
                "index_evictions": c["index_evictions"],
                "reclaimed_pages": c["reclaimed_pages"],
                "shared_pages": int(self.shared_pages),
                "index_pages": int(self.index_pages),
                "index_nodes": (self.prefix.num_nodes
                                if self.prefix is not None else 0)}


# ---------------------------------------------------------------------------
# the ragged decode step: one position for EVERY slot, per-slot positions,
# one compiled executable per pool geometry.
# ---------------------------------------------------------------------------


def _apply_rotary_rows(x: jnp.ndarray, cos_b: jnp.ndarray,
                       sin_b: jnp.ndarray, rot: int) -> jnp.ndarray:
    """``apply_rotary`` with a PER-SLOT table row: x (B, 1, H, hd), cos/sin
    (B, rot) gathered at each slot's own position. Elementwise ops and
    values match the contiguous path's single sliced row exactly."""
    c = cos_b[:, None, None, :].astype(x.dtype)
    s = sin_b[:, None, None, :].astype(x.dtype)
    if rot == x.shape[-1]:
        return x * c + _rotate_half(x) * s
    x_rot, x_pass = x[..., :rot], x[..., rot:]
    x_rot = x_rot * c + _rotate_half(x_rot) * s
    return jnp.concatenate([x_rot, x_pass], axis=-1)


def _attention_decode_paged(cfg: ModelConfig, lp: dict, x: jnp.ndarray,
                            cos_b, sin_b, k_pages, v_pages,
                            page_table, lengths,
                            tp_axis: Optional[str] = None):
    """The paged twin of ``transformer._attention_decode``: project the
    (B, 1, D) hidden, rotate each slot at ITS position, scatter the new K/V
    into each slot's current page, then ragged-attend against the gathered
    pages. k/v_pages are ONE layer's (num_pages, page_size, KV, hd) pool."""
    b, s1, d = x.shape
    hd = cfg.head_dim
    h, kv = lp["wq"].shape[-1] // hd, lp["wk"].shape[-1] // hd
    q = (x @ lp["wq"]).reshape(b, s1, h, hd)
    k = (x @ lp["wk"]).reshape(b, s1, kv, hd)
    v = (x @ lp["wv"]).reshape(b, s1, kv, hd)
    if "bq" in lp:
        q = q + lp["bq"].reshape(h, hd)
        k = k + lp["bk"].reshape(kv, hd)
        v = v + lp["bv"].reshape(kv, hd)
    q = _apply_rotary_rows(q, cos_b, sin_b, cfg.rotary_dim)
    k = _apply_rotary_rows(k, cos_b, sin_b, cfg.rotary_dim)
    pn, ps = k_pages.shape[0], k_pages.shape[1]
    # slot i's new token lands in its (length // page_size)-th page at offset
    # length % page_size; inactive slots (all-zero table rows) land in the
    # trash page, where duplicate scatter indices are harmless garbage
    dest = (page_table[jnp.arange(b), lengths // ps] * ps
            + lengths % ps)  # (B,)
    tail = k_pages.shape[2:]
    k_pages = k_pages.reshape(pn * ps, *tail).at[dest].set(
        k[:, 0].astype(k_pages.dtype)).reshape(pn, ps, *tail)
    v_pages = v_pages.reshape(pn * ps, *tail).at[dest].set(
        v[:, 0].astype(v_pages.dtype)).reshape(pn, ps, *tail)

    from .flash_attention import paged_decode_attention

    out = paged_decode_attention(q, k_pages, v_pages, page_table, lengths + 1)
    out = out.reshape(b, s1, h * hd) @ lp["wo"]
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    if "bo" in lp:
        out = out + lp["bo"]
    return out, k_pages, v_pages


def block_decode_paged(cfg: ModelConfig, lp: dict, hidden: jnp.ndarray,
                       cos_b, sin_b, k_pages, v_pages, page_table, lengths,
                       tp_axis: Optional[str] = None):
    """The paged twin of ``transformer.block_decode`` for one layer:
    same norm/residual/MLP structure, paged attention core."""
    if cfg.family == "gpt_neox":
        attn_in = _layernorm(hidden, lp["ln1_scale"], lp["ln1_bias"],
                             cfg.norm_eps)
        attn_out, k_pages, v_pages = _attention_decode_paged(
            cfg, lp, attn_in, cos_b, sin_b, k_pages, v_pages,
            page_table, lengths, tp_axis)
        mlp_in = _layernorm(hidden, lp["ln2_scale"], lp["ln2_bias"],
                            cfg.norm_eps)
        return (hidden + attn_out + mlp(cfg, lp, mlp_in, tp_axis),
                k_pages, v_pages)
    attn_in = _rmsnorm(hidden, lp["ln1_scale"], cfg.norm_eps)
    attn_out, k_pages, v_pages = _attention_decode_paged(
        cfg, lp, attn_in, cos_b, sin_b, k_pages, v_pages,
        page_table, lengths, tp_axis)
    hidden = hidden + attn_out
    mlp_in = _rmsnorm(hidden, lp["ln2_scale"], cfg.norm_eps)
    return hidden + mlp(cfg, lp, mlp_in, tp_axis), k_pages, v_pages


@graph_contract("paged.decode_step", collectives={},
                donate=lambda ctx: ctx.get("donate_min", 2))
def paged_decode_step(cfg: ModelConfig, params: dict,
                      pool_k: jnp.ndarray, pool_v: jnp.ndarray,
                      page_table: jnp.ndarray, lengths: jnp.ndarray,
                      token_ids: jnp.ndarray, *,
                      compute_dtype: Optional[jnp.dtype] = None):
    """Append one position to EVERY slot of a paged pool in one pass.

    pool_k/pool_v: (L, num_pages, page_size, KV, hd); page_table
    (max_slots, pages_per_slot) and lengths (max_slots,) are TRACED — one
    executable per pool geometry serves every admit/evict/fill state.
    token_ids: (max_slots,) int32 (inactive slots pass any valid token; their
    writes land in the trash page). Returns (logits (max_slots, V) fp32,
    pool_k, pool_v).

    Per-slot positions: the RoPE row, the page write offset, and the
    attention mask all index by each slot's own ``lengths[i]`` — the ragged
    generalization of ``decode_step``'s single ``cache.length``; per-slot
    math is bit-identical to the contiguous path (see module docstring).
    """
    params = _cast_params(params, compute_dtype)
    if token_ids.ndim == 1:
        token_ids = token_ids[:, None]
    hidden = embed(params, token_ids)  # (B, 1, D)
    span = page_table.shape[1] * pool_k.shape[2]  # pages_per_slot * page_size
    cos, sin = precompute_rope(cfg, span)
    cos_b = cos[lengths]  # (B, rot) — each slot's own row
    sin_b = sin[lengths]

    def body(h, xs):
        lp, kp, vp = xs
        h, kp, vp = block_decode_paged(cfg, lp, h, cos_b, sin_b, kp, vp,
                                       page_table, lengths)
        return h, (kp, vp)

    hidden, (k_new, v_new) = jax.lax.scan(
        body, hidden, (params["layers"], pool_k, pool_v))
    logits = unembed(cfg, params, hidden)[:, -1]  # (B, V) fp32
    return logits, k_new, v_new


def _attention_decode_paged_quant(cfg: ModelConfig, lp: dict, x: jnp.ndarray,
                                  cos_b, sin_b, k_pages, v_pages,
                                  k_scale, v_scale, page_table, lengths,
                                  kv_codec: str,
                                  tp_axis: Optional[str] = None):
    """Quantized-pool twin of :func:`_attention_decode_paged`: the freshly
    projected K/V row quantizes ON APPEND (codes + its own per-row scales
    scatter into the pool — neighbouring rows are untouched, which is why
    scales are per row and not per page), then the ragged attention
    dequantizes in-kernel. The current token therefore attends its OWN
    quantized K/V, consistent with what every later step will read."""
    b, s1, d = x.shape
    hd = cfg.head_dim
    h, kv = lp["wq"].shape[-1] // hd, lp["wk"].shape[-1] // hd
    q = (x @ lp["wq"]).reshape(b, s1, h, hd)
    k = (x @ lp["wk"]).reshape(b, s1, kv, hd)
    v = (x @ lp["wv"]).reshape(b, s1, kv, hd)
    if "bq" in lp:
        q = q + lp["bq"].reshape(h, hd)
        k = k + lp["bk"].reshape(kv, hd)
        v = v + lp["bv"].reshape(kv, hd)
    q = _apply_rotary_rows(q, cos_b, sin_b, cfg.rotary_dim)
    k = _apply_rotary_rows(k, cos_b, sin_b, cfg.rotary_dim)

    from .flash_attention import paged_decode_attention_quant, quantize_kv_rows

    qk, sk = quantize_kv_rows(k[:, 0], kv_codec)  # (B, KV, hdc), (B, KV)
    qv, sv = quantize_kv_rows(v[:, 0], kv_codec)
    pn, ps = k_pages.shape[0], k_pages.shape[1]
    dest = (page_table[jnp.arange(b), lengths // ps] * ps
            + lengths % ps)  # (B,)
    ctail = k_pages.shape[2:]
    k_pages = k_pages.reshape(pn * ps, *ctail).at[dest].set(
        qk.astype(k_pages.dtype)).reshape(pn, ps, *ctail)
    v_pages = v_pages.reshape(pn * ps, *ctail).at[dest].set(
        qv.astype(v_pages.dtype)).reshape(pn, ps, *ctail)
    k_scale = k_scale.reshape(pn * ps, kv).at[dest].set(
        sk).reshape(pn, ps, kv)
    v_scale = v_scale.reshape(pn * ps, kv).at[dest].set(
        sv).reshape(pn, ps, kv)

    out = paged_decode_attention_quant(q, k_pages, v_pages, k_scale, v_scale,
                                       page_table, lengths + 1,
                                       kv_codec=kv_codec)
    out = out.astype(x.dtype).reshape(b, s1, h * hd) @ lp["wo"]
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    if "bo" in lp:
        out = out + lp["bo"]
    return out, k_pages, v_pages, k_scale, v_scale


def block_decode_paged_quant(cfg: ModelConfig, lp: dict, hidden: jnp.ndarray,
                             cos_b, sin_b, k_pages, v_pages,
                             k_scale, v_scale, page_table, lengths,
                             kv_codec: str,
                             tp_axis: Optional[str] = None):
    """One layer of the quantized paged decode: same norm/residual/MLP
    structure as :func:`block_decode_paged`, quantized attention core."""
    if cfg.family == "gpt_neox":
        attn_in = _layernorm(hidden, lp["ln1_scale"], lp["ln1_bias"],
                             cfg.norm_eps)
        attn_out, k_pages, v_pages, k_scale, v_scale = (
            _attention_decode_paged_quant(
                cfg, lp, attn_in, cos_b, sin_b, k_pages, v_pages,
                k_scale, v_scale, page_table, lengths, kv_codec, tp_axis))
        mlp_in = _layernorm(hidden, lp["ln2_scale"], lp["ln2_bias"],
                            cfg.norm_eps)
        return (hidden + attn_out + mlp(cfg, lp, mlp_in, tp_axis),
                k_pages, v_pages, k_scale, v_scale)
    attn_in = _rmsnorm(hidden, lp["ln1_scale"], cfg.norm_eps)
    attn_out, k_pages, v_pages, k_scale, v_scale = (
        _attention_decode_paged_quant(
            cfg, lp, attn_in, cos_b, sin_b, k_pages, v_pages,
            k_scale, v_scale, page_table, lengths, kv_codec, tp_axis))
    hidden = hidden + attn_out
    mlp_in = _rmsnorm(hidden, lp["ln2_scale"], cfg.norm_eps)
    return (hidden + mlp(cfg, lp, mlp_in, tp_axis),
            k_pages, v_pages, k_scale, v_scale)


@graph_contract("paged.decode_step_quant", collectives={},
                donate=lambda ctx: ctx.get("donate_min", 4))
def paged_decode_step_quant(cfg: ModelConfig, params: dict,
                            pool_k: jnp.ndarray, pool_v: jnp.ndarray,
                            pool_k_scale: jnp.ndarray,
                            pool_v_scale: jnp.ndarray,
                            page_table: jnp.ndarray, lengths: jnp.ndarray,
                            token_ids: jnp.ndarray, *, kv_codec: str,
                            compute_dtype: Optional[jnp.dtype] = None):
    """Quantized-pool twin of :func:`paged_decode_step`: a SEPARATE
    entrypoint, not a branch — the fp tier keeps tracing the exact
    pre-quantization graph (the disabled-build identity the lint layer
    pins), and this one carries the four QuantPagePool arrays through the
    layer scan. Returns (logits (max_slots, V) fp32, pool_k, pool_v,
    pool_k_scale, pool_v_scale)."""
    params = _cast_params(params, compute_dtype)
    if token_ids.ndim == 1:
        token_ids = token_ids[:, None]
    hidden = embed(params, token_ids)  # (B, 1, D)
    span = page_table.shape[1] * pool_k.shape[2]  # pages_per_slot * page_size
    cos, sin = precompute_rope(cfg, span)
    cos_b = cos[lengths]  # (B, rot) — each slot's own row
    sin_b = sin[lengths]

    def body(h, xs):
        lp, kp, vp, ks, vs = xs
        h, kp, vp, ks, vs = block_decode_paged_quant(
            cfg, lp, h, cos_b, sin_b, kp, vp, ks, vs, page_table, lengths,
            kv_codec)
        return h, (kp, vp, ks, vs)

    hidden, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
        body, hidden, (params["layers"], pool_k, pool_v,
                       pool_k_scale, pool_v_scale))
    logits = unembed(cfg, params, hidden)[:, -1]  # (B, V) fp32
    return logits, k_new, v_new, ks_new, vs_new
