"""Page-table KV cache for continuous batching.

The monolithic :class:`~edgellm_tpu.models.transformer.KVCache` gives every
request a private ``(B, capacity)`` buffer sized for the worst case, so a
mixed-length request stream either pads every cache to the longest stream or
recompiles per shape — ROADMAP item 1's gap between "a compiled generate()"
and "a service". This module replaces the monolith with the paged layout of
*Ragged Paged Attention* (PAPERS.md): one shared pool of fixed-size pages,

    k, v: (L, num_pages, page_size, KV, hd)

and a small host-side allocator that maps each stream (a *slot*) to an
ordered list of pages. Logical position ``p`` of slot ``i`` lives at
``page_table[i, p // page_size]`` offset ``p % page_size``. The page table
and per-slot lengths ride through the jitted step as traced int32 arrays, so
ONE executable serves every admit/evict/fill configuration of a given pool
geometry — the continuous-batching scheduler (``serve/batching.py``) admits
and evicts mid-flight without a single retrace.

Conventions that keep the paged step bit-identical to the contiguous one:

- page 0 is the TRASH page: never allocated, written by inactive slots (their
  page-table rows are all zero). Its contents are garbage but always finite
  (inactive rows run real token-0 math), so masked attention positions
  contribute exactly 0 to every softmax.
- pages store POST-ROTARY keys at ``num_kv_heads`` width, the same values the
  contiguous cache stores; gathering a slot's pages in order reproduces that
  slot's contiguous cache prefix byte-for-byte.
- the per-slot RoPE row, attention mask, and sampling fold_in sequence match
  ``decode_step``/``generate`` exactly, and attention softmax is invariant to
  the amount of masked padding — so a slot's tokens are bit-identical to
  running it alone (``tests/test_batching.py`` asserts this, and the
  ``batching.decode-step-identity`` graphlint contract re-checks it on every
  lint run).

Donation: the jitted step and adopt/defrag helpers donate the pool buffers,
so the (L, num_pages, page_size) arrays update in place — the
``paged.decode_step`` graph contract asserts the aliasing survives lowering.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..lint import graph_contract
from .configs import ModelConfig
from .transformer import (_cast_params, _layernorm, _rmsnorm, _rotate_half,
                          embed, mlp, precompute_rope, unembed)

#: slot id a page belongs to when it is on the free list
FREE = -1


class OutOfPages(RuntimeError):
    """The pool has no free page for a slot that must grow — the scheduler's
    signal to evict (or refuse to admit) a stream."""


class OutOfSlots(RuntimeError):
    """Every slot of the compiled step shape is occupied."""


class PagePool(NamedTuple):
    """Device-side page pool: post-rotary K/V at ``num_kv_heads`` width.

    k, v: (L, num_pages, page_size, KV, hd). Page 0 is the reserved trash
    page (see module docstring)."""

    k: jnp.ndarray
    v: jnp.ndarray

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[2]


def init_pool(cfg: ModelConfig, num_pages: int, page_size: int,
              dtype=jnp.float32) -> PagePool:
    """An all-zero pool; ``num_pages`` INCLUDES the reserved trash page 0,
    so ``num_pages - 1`` pages are allocatable."""
    if num_pages < 2:
        raise ValueError(f"num_pages must be >= 2 (page 0 is reserved), "
                         f"got {num_pages}")
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    shape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads,
             cfg.head_dim)
    return PagePool(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


# ---------------------------------------------------------------------------
# jitted pool surgery: adopt a contiguous prefix, gather one back, permute
# pages for defrag. All donate the pool so surgery is in-place.
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _adopt_impl(pool_k, pool_v, k_seq, v_seq, dest):
    """Scatter a contiguous (L, S, KV, hd) K/V prefix into the pool rows
    named by ``dest`` (S,) — flat indices into the (num_pages * page_size)
    token axis. S is static per call (one executable per adopted length)."""
    l, pn, ps = pool_k.shape[:3]
    tail = pool_k.shape[3:]
    fk = pool_k.reshape(l, pn * ps, *tail).at[:, dest].set(
        k_seq.astype(pool_k.dtype))
    fv = pool_v.reshape(l, pn * ps, *tail).at[:, dest].set(
        v_seq.astype(pool_v.dtype))
    return fk.reshape(pool_k.shape), fv.reshape(pool_v.shape)


@jax.jit
def _gather_impl(pool_k, pool_v, idx):
    """Read the pool rows named by ``idx`` (span,) back as contiguous
    (L, span, KV, hd) arrays — the checkpoint/eviction serialization path."""
    l, pn, ps = pool_k.shape[:3]
    tail = pool_k.shape[3:]
    return (pool_k.reshape(l, pn * ps, *tail)[:, idx],
            pool_v.reshape(l, pn * ps, *tail)[:, idx])


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _permute_impl(pool_k, pool_v, src):
    """new_pool[p] = old_pool[src[p]] — the defrag move, one gather."""
    return pool_k[:, src], pool_v[:, src]


class PagedKVCache:
    """Host-side allocator + device pool for up to ``max_slots`` concurrent
    streams of up to ``pages_per_slot * page_size`` tokens each.

    The device state is ``self.pool`` (swapped wholesale after each donated
    step/adopt/defrag); the host state is the page table, per-slot lengths,
    the free list, and per-page ownership. ``device_tables()`` materializes
    the traced int32 inputs of the compiled step. All mutating methods keep
    :meth:`check_invariants` true: no page owned twice, no page leaked, the
    trash page never allocated.
    """

    def __init__(self, cfg: ModelConfig, *, num_pages: int, page_size: int,
                 max_slots: int, pages_per_slot: int, dtype=jnp.float32,
                 materialize: bool = True):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if pages_per_slot < 1:
            raise ValueError(
                f"pages_per_slot must be >= 1, got {pages_per_slot}")
        self.cfg = cfg
        # materialize=False: bookkeeping-only mode — the page table, free
        # list, and ownership machinery without a local device pool. The
        # split runtime uses this: its pools live per-stage on the mesh
        # (SplitRuntime.init_paged_pool), only the allocator is shared.
        self.pool = (init_pool(cfg, num_pages, page_size, dtype)
                     if materialize else None)
        self.page_size = page_size
        self.num_pages = num_pages
        self.max_slots = max_slots
        self.pages_per_slot = pages_per_slot
        self.page_table = np.zeros((max_slots, pages_per_slot), np.int32)
        self.lengths = np.zeros((max_slots,), np.int32)
        self.active = np.zeros((max_slots,), bool)
        # LIFO free list, low pages first out — deterministic layouts
        self._free = list(range(num_pages - 1, 0, -1))
        self._slot_pages: list[list[int]] = [[] for _ in range(max_slots)]
        self._owner = np.full((num_pages,), FREE, np.int32)  # page -> slot

    # -- geometry ----------------------------------------------------------

    @property
    def span(self) -> int:
        """Max positions one slot can hold — the compiled attention width."""
        return self.pages_per_slot * self.page_size

    @property
    def num_free_pages(self) -> int:
        return len(self._free)

    @property
    def token_capacity(self) -> int:
        """Allocatable token positions (the trash page excluded)."""
        return (self.num_pages - 1) * self.page_size

    @property
    def live_tokens(self) -> int:
        return int(self.lengths[self.active].sum())

    def pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    # -- slot lifecycle ----------------------------------------------------

    def alloc_slot(self) -> int:
        """Claim the lowest free slot (deterministic admit order)."""
        for s in range(self.max_slots):
            if not self.active[s]:
                self.active[s] = True
                self.lengths[s] = 0
                return s
        raise OutOfSlots(f"all {self.max_slots} slots active")

    def ensure(self, slot: int, new_length: int) -> None:
        """Grow ``slot``'s page list to cover ``new_length`` positions,
        allocating pages from the free list. Raises :class:`OutOfPages`
        (allocating nothing) when the pool cannot cover the growth."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        if new_length > self.span:
            raise ValueError(f"length {new_length} exceeds slot span "
                             f"{self.span}")
        need = self.pages_for(new_length) - len(self._slot_pages[slot])
        if need <= 0:
            return
        if need > len(self._free):
            raise OutOfPages(
                f"slot {slot} needs {need} page(s), {len(self._free)} free")
        for _ in range(need):
            p = self._free.pop()
            self._owner[p] = slot
            self.page_table[slot, len(self._slot_pages[slot])] = p
            self._slot_pages[slot].append(p)

    def free_slot(self, slot: int) -> None:
        """Release a slot and return its pages (reverse allocation order, so
        the free list stays LIFO-deterministic). The page contents are left
        stale — masked attention never reads past a slot's length."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        for p in reversed(self._slot_pages[slot]):
            self._owner[p] = FREE
            self._free.append(p)
        self._slot_pages[slot] = []
        self.page_table[slot] = 0
        self.lengths[slot] = 0
        self.active[slot] = False

    def device_tables(self) -> tuple[jnp.ndarray, jnp.ndarray]:
        """(page_table (max_slots, pages_per_slot), lengths (max_slots,)) as
        device int32 arrays — the traced inputs of the compiled step."""
        return (jnp.asarray(self.page_table),
                jnp.asarray(self.lengths, jnp.int32))

    # -- data movement -----------------------------------------------------

    def _require_pool(self, what: str) -> None:
        if self.pool is None:
            raise ValueError(f"{what} needs a materialized pool; this cache "
                             f"was built with materialize=False "
                             f"(bookkeeping-only)")

    def _flat_indices(self, slot: int, n: int) -> np.ndarray:
        pos = np.arange(n)
        return (self.page_table[slot, pos // self.page_size]
                * self.page_size + pos % self.page_size).astype(np.int32)

    def adopt(self, slot: int, k_seq, v_seq, length: int) -> None:
        """Write a contiguous (L, length, KV, hd) post-rotary K/V prefix
        (a prefill's cache, or a restored checkpoint) into ``slot``'s pages
        and set its length. Allocates pages as needed."""
        self._require_pool("adopt")
        self.ensure(slot, length)
        dest = jnp.asarray(self._flat_indices(slot, length))
        k, v = _adopt_impl(self.pool.k, self.pool.v, k_seq, v_seq, dest)
        self.pool = PagePool(k, v)
        self.lengths[slot] = length

    def gather_slot(self, slot: int) -> dict:
        """Read ``slot``'s K/V back as the contiguous host state dict the
        recovery checkpoint stores: {"k": (L, length, KV, hd), "v": ...,
        "length"} — byte-identical to the contiguous cache prefix."""
        self._require_pool("gather_slot")
        n = int(self.lengths[slot])
        idx = jnp.asarray(self._flat_indices(slot, max(n, 1)))
        k, v = _gather_impl(self.pool.k, self.pool.v, idx)
        return {"k": np.asarray(k)[:, :n], "v": np.asarray(v)[:, :n],
                "length": np.asarray(n, np.int32)}

    def defrag(self) -> int:
        """Compact allocated pages to the low end of the pool (slot order,
        trash page fixed at 0) and rebuild the free list above them. Returns
        the number of pages that moved. One donated device gather; page
        tables are rewritten to match, so every slot's logical content is
        unchanged."""
        self._require_pool("defrag")
        # src (new -> old) must be a TRUE permutation: after alloc/grow/free
        # churn an owned page's compacted destination can be a currently-free
        # page with a HIGHER id (e.g. slot pages [[4],[2],[1]] with page 3
        # free), so inverting an old->new map would collide with the free
        # page's identity entry and gather garbage into the destination.
        # Place owned pages at their destinations first, then spread the
        # leftover old pages over the remaining destinations.
        src = np.zeros((self.num_pages,), np.int32)  # new -> old; src[0] = 0
        moved = 0
        nxt = 1
        for s in range(self.max_slots):
            pages = self._slot_pages[s]
            for j, p in enumerate(pages):
                src[nxt] = p
                if p != nxt:
                    moved += 1
                pages[j] = nxt
                self.page_table[s, j] = nxt
                self._owner[nxt] = s
                nxt += 1
        placed = set(int(x) for x in src[:nxt])
        src[nxt:] = [p for p in range(1, self.num_pages) if p not in placed]
        for p in range(nxt, self.num_pages):
            self._owner[p] = FREE
        self._free = list(range(self.num_pages - 1, nxt - 1, -1))
        if moved:
            k, v = _permute_impl(self.pool.k, self.pool.v, jnp.asarray(src))
            self.pool = PagePool(k, v)
        return moved

    # -- serialization -----------------------------------------------------

    def state_dict(self) -> dict:
        """Whole-cache snapshot as host numpy arrays — the checkpoint form.
        (Per-slot checkpoints use :meth:`gather_slot` instead, which is
        geometry-independent.)"""
        self._require_pool("state_dict")
        return {"k": np.asarray(self.pool.k), "v": np.asarray(self.pool.v),
                "page_table": self.page_table.copy(),
                "lengths": self.lengths.copy(),
                "active": self.active.copy(),
                "free": np.asarray(self._free, np.int32)}

    def load_state_dict(self, state: dict) -> None:
        """Restore :meth:`state_dict` output bit-exactly (same geometry)."""
        self._require_pool("load_state_dict")
        if state["k"].shape != self.pool.k.shape:
            raise ValueError(
                f"pool shape mismatch: checkpoint {state['k'].shape} vs "
                f"cache {self.pool.k.shape}")
        self.pool = PagePool(jnp.asarray(state["k"]),
                             jnp.asarray(state["v"]))
        self.page_table = np.asarray(state["page_table"], np.int32).copy()
        self.lengths = np.asarray(state["lengths"], np.int32).copy()
        self.active = np.asarray(state["active"], bool).copy()
        self._free = [int(p) for p in state["free"]]
        self._owner = np.full((self.num_pages,), FREE, np.int32)
        self._slot_pages = [[] for _ in range(self.max_slots)]
        for s in range(self.max_slots):
            if not self.active[s]:
                continue
            n = self.pages_for(int(self.lengths[s]))
            self._slot_pages[s] = [int(p) for p in self.page_table[s, :n]]
            for p in self._slot_pages[s]:
                self._owner[p] = s

    # -- invariants --------------------------------------------------------

    def check_invariants(self) -> None:
        """Raise AssertionError on any aliasing/leak/ownership violation —
        the test suite calls this after every mutation."""
        assert 0 not in self._free, "trash page 0 on the free list"
        assert self._owner[0] == FREE, "trash page 0 owned by a slot"
        owned = [p for pages in self._slot_pages for p in pages]
        assert len(owned) == len(set(owned)), \
            f"page owned twice: {sorted(owned)}"
        assert not (set(owned) & set(self._free)), "page both owned and free"
        assert set(owned) | set(self._free) == set(range(1, self.num_pages)), \
            "page leaked (neither owned nor free)"
        for s in range(self.max_slots):
            if self.active[s]:
                assert len(self._slot_pages[s]) * self.page_size >= \
                    self.lengths[s], f"slot {s} pages do not cover its length"
                for j, p in enumerate(self._slot_pages[s]):
                    assert self._owner[p] == s
                    assert self.page_table[s, j] == p
            else:
                assert not self._slot_pages[s], f"inactive slot {s} owns pages"
                assert (self.page_table[s] == 0).all()
                assert self.lengths[s] == 0


# ---------------------------------------------------------------------------
# the ragged decode step: one position for EVERY slot, per-slot positions,
# one compiled executable per pool geometry.
# ---------------------------------------------------------------------------


def _apply_rotary_rows(x: jnp.ndarray, cos_b: jnp.ndarray,
                       sin_b: jnp.ndarray, rot: int) -> jnp.ndarray:
    """``apply_rotary`` with a PER-SLOT table row: x (B, 1, H, hd), cos/sin
    (B, rot) gathered at each slot's own position. Elementwise ops and
    values match the contiguous path's single sliced row exactly."""
    c = cos_b[:, None, None, :].astype(x.dtype)
    s = sin_b[:, None, None, :].astype(x.dtype)
    if rot == x.shape[-1]:
        return x * c + _rotate_half(x) * s
    x_rot, x_pass = x[..., :rot], x[..., rot:]
    x_rot = x_rot * c + _rotate_half(x_rot) * s
    return jnp.concatenate([x_rot, x_pass], axis=-1)


def _attention_decode_paged(cfg: ModelConfig, lp: dict, x: jnp.ndarray,
                            cos_b, sin_b, k_pages, v_pages,
                            page_table, lengths,
                            tp_axis: Optional[str] = None):
    """The paged twin of ``transformer._attention_decode``: project the
    (B, 1, D) hidden, rotate each slot at ITS position, scatter the new K/V
    into each slot's current page, then ragged-attend against the gathered
    pages. k/v_pages are ONE layer's (num_pages, page_size, KV, hd) pool."""
    b, s1, d = x.shape
    hd = cfg.head_dim
    h, kv = lp["wq"].shape[-1] // hd, lp["wk"].shape[-1] // hd
    q = (x @ lp["wq"]).reshape(b, s1, h, hd)
    k = (x @ lp["wk"]).reshape(b, s1, kv, hd)
    v = (x @ lp["wv"]).reshape(b, s1, kv, hd)
    if "bq" in lp:
        q = q + lp["bq"].reshape(h, hd)
        k = k + lp["bk"].reshape(kv, hd)
        v = v + lp["bv"].reshape(kv, hd)
    q = _apply_rotary_rows(q, cos_b, sin_b, cfg.rotary_dim)
    k = _apply_rotary_rows(k, cos_b, sin_b, cfg.rotary_dim)
    pn, ps = k_pages.shape[0], k_pages.shape[1]
    # slot i's new token lands in its (length // page_size)-th page at offset
    # length % page_size; inactive slots (all-zero table rows) land in the
    # trash page, where duplicate scatter indices are harmless garbage
    dest = (page_table[jnp.arange(b), lengths // ps] * ps
            + lengths % ps)  # (B,)
    tail = k_pages.shape[2:]
    k_pages = k_pages.reshape(pn * ps, *tail).at[dest].set(
        k[:, 0].astype(k_pages.dtype)).reshape(pn, ps, *tail)
    v_pages = v_pages.reshape(pn * ps, *tail).at[dest].set(
        v[:, 0].astype(v_pages.dtype)).reshape(pn, ps, *tail)

    from .flash_attention import paged_decode_attention

    out = paged_decode_attention(q, k_pages, v_pages, page_table, lengths + 1)
    out = out.reshape(b, s1, h * hd) @ lp["wo"]
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    if "bo" in lp:
        out = out + lp["bo"]
    return out, k_pages, v_pages


def block_decode_paged(cfg: ModelConfig, lp: dict, hidden: jnp.ndarray,
                       cos_b, sin_b, k_pages, v_pages, page_table, lengths,
                       tp_axis: Optional[str] = None):
    """The paged twin of ``transformer.block_decode`` for one layer:
    same norm/residual/MLP structure, paged attention core."""
    if cfg.family == "gpt_neox":
        attn_in = _layernorm(hidden, lp["ln1_scale"], lp["ln1_bias"],
                             cfg.norm_eps)
        attn_out, k_pages, v_pages = _attention_decode_paged(
            cfg, lp, attn_in, cos_b, sin_b, k_pages, v_pages,
            page_table, lengths, tp_axis)
        mlp_in = _layernorm(hidden, lp["ln2_scale"], lp["ln2_bias"],
                            cfg.norm_eps)
        return (hidden + attn_out + mlp(cfg, lp, mlp_in, tp_axis),
                k_pages, v_pages)
    attn_in = _rmsnorm(hidden, lp["ln1_scale"], cfg.norm_eps)
    attn_out, k_pages, v_pages = _attention_decode_paged(
        cfg, lp, attn_in, cos_b, sin_b, k_pages, v_pages,
        page_table, lengths, tp_axis)
    hidden = hidden + attn_out
    mlp_in = _rmsnorm(hidden, lp["ln2_scale"], cfg.norm_eps)
    return hidden + mlp(cfg, lp, mlp_in, tp_axis), k_pages, v_pages


@graph_contract("paged.decode_step", collectives={},
                donate=lambda ctx: ctx.get("donate_min", 2))
def paged_decode_step(cfg: ModelConfig, params: dict,
                      pool_k: jnp.ndarray, pool_v: jnp.ndarray,
                      page_table: jnp.ndarray, lengths: jnp.ndarray,
                      token_ids: jnp.ndarray, *,
                      compute_dtype: Optional[jnp.dtype] = None):
    """Append one position to EVERY slot of a paged pool in one pass.

    pool_k/pool_v: (L, num_pages, page_size, KV, hd); page_table
    (max_slots, pages_per_slot) and lengths (max_slots,) are TRACED — one
    executable per pool geometry serves every admit/evict/fill state.
    token_ids: (max_slots,) int32 (inactive slots pass any valid token; their
    writes land in the trash page). Returns (logits (max_slots, V) fp32,
    pool_k, pool_v).

    Per-slot positions: the RoPE row, the page write offset, and the
    attention mask all index by each slot's own ``lengths[i]`` — the ragged
    generalization of ``decode_step``'s single ``cache.length``; per-slot
    math is bit-identical to the contiguous path (see module docstring).
    """
    params = _cast_params(params, compute_dtype)
    if token_ids.ndim == 1:
        token_ids = token_ids[:, None]
    hidden = embed(params, token_ids)  # (B, 1, D)
    span = page_table.shape[1] * pool_k.shape[2]  # pages_per_slot * page_size
    cos, sin = precompute_rope(cfg, span)
    cos_b = cos[lengths]  # (B, rot) — each slot's own row
    sin_b = sin[lengths]

    def body(h, xs):
        lp, kp, vp = xs
        h, kp, vp = block_decode_paged(cfg, lp, h, cos_b, sin_b, kp, vp,
                                       page_table, lengths)
        return h, (kp, vp)

    hidden, (k_new, v_new) = jax.lax.scan(
        body, hidden, (params["layers"], pool_k, pool_v))
    logits = unembed(cfg, params, hidden)[:, -1]  # (B, V) fp32
    return logits, k_new, v_new
