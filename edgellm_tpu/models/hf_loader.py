"""Convert HuggingFace checkpoints (torch state_dicts) into the stacked pytree layout.

The reference leans on ``AutoModelForCausalLM.from_pretrained`` at runtime and keeps
*two* live torch model instances per experiment (``pythia_model.py:25``,
``last_row_exp.py:66-70``). Here conversion happens once: a torch state_dict (from a
downloaded checkpoint, or a randomly-initialized ``transformers`` model in offline
test environments) becomes a single JAX pytree with layers stacked on axis 0, ready
to be sharded along a pipeline-stage mesh axis.

Layout notes:
- torch ``nn.Linear.weight`` is (out, in); we store (in, out) so the forward is
  ``x @ W``.
- GPT-NeoX fuses QKV with per-head interleaving: ``query_key_value.weight`` viewed
  as (num_heads, 3*head_dim, in) splits into q/k/v as the three head_dim-blocks of
  each head's rows (matches HF's ``qkv.view(..., num_heads, 3*head_size)`` split).
"""
from __future__ import annotations

import os
import time

import numpy as np
import jax.numpy as jnp

from .configs import ModelConfig


def fetch_with_retry(url: str, dest: str, *, max_retries: int = 4,
                     timeout: float = 30.0, backoff: float = 1.0,
                     _sleep=time.sleep) -> str:
    """Download ``url`` to ``dest`` with bounded retries and exponential
    backoff — the edge-network counterpart of the wire-fault layer: flaky
    checkpoint links get ``max_retries`` re-attempts (waiting ``backoff * 2**n``
    seconds between them), permanent HTTP client errors (4xx) fail immediately,
    and the final error says exactly what to do next. The download lands in a
    temp file and is renamed into place, so a cut connection never leaves a
    truncated ``dest`` behind. stdlib urllib only — no new dependencies."""
    import urllib.error
    import urllib.request

    if max_retries < 0:
        raise ValueError(f"max_retries must be >= 0, got {max_retries}")
    os.makedirs(os.path.dirname(os.path.abspath(dest)), exist_ok=True)
    tmp = dest + ".part"
    last_err = None
    for attempt in range(max_retries + 1):
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r, \
                    open(tmp, "wb") as f:
                while chunk := r.read(1 << 20):
                    f.write(chunk)
            os.replace(tmp, dest)
            return dest
        except urllib.error.HTTPError as e:
            if e.code < 500:  # 4xx is permanent; retrying can't fix a 404
                raise RuntimeError(
                    f"fetch of {url} failed permanently (HTTP {e.code} "
                    f"{e.reason}); check the URL/revision, or download the "
                    f"file manually and pass its local path") from e
            last_err = e
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            last_err = e
        if attempt < max_retries:
            _sleep(backoff * (2 ** attempt))
    raise RuntimeError(
        f"fetch of {url} failed after {max_retries + 1} attempts "
        f"(last error: {last_err}); the link may be down — retry later, or "
        f"download the file manually and pass its local path") from last_err


def _np(t):
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, dtype=np.float32)


def _stack(sd, template: str, n: int, transform):
    return jnp.asarray(np.stack([transform(_np(sd[template.format(i=i)])) for i in range(n)]))


def _split_neox_qkv(w: np.ndarray, cfg: ModelConfig):
    """(3D, in)-shaped fused weight -> (q, k, v) each (in, D)."""
    h, hd = cfg.num_heads, cfg.head_dim
    per_head = w.reshape(h, 3, hd, -1)
    return tuple(per_head[:, j].reshape(h * hd, -1).T for j in range(3))


def _split_neox_qkv_bias(b: np.ndarray, cfg: ModelConfig):
    h, hd = cfg.num_heads, cfg.head_dim
    per_head = b.reshape(h, 3, hd)
    return tuple(per_head[:, j].reshape(h * hd) for j in range(3))


def params_from_state_dict(cfg: ModelConfig, sd: dict) -> dict:
    """Build the framework's parameter pytree from a HF torch state_dict."""
    if cfg.family == "gpt_neox":
        return _neox_params(cfg, sd)
    return _qwen2_params(cfg, sd)


def _neox_params(cfg: ModelConfig, sd: dict) -> dict:
    L = cfg.num_layers
    qs, ks, vs, qbs, kbs, vbs = [], [], [], [], [], []
    for i in range(L):
        w = _np(sd[f"gpt_neox.layers.{i}.attention.query_key_value.weight"])
        b = _np(sd[f"gpt_neox.layers.{i}.attention.query_key_value.bias"])
        q, k, v = _split_neox_qkv(w, cfg)
        qb, kb, vb = _split_neox_qkv_bias(b, cfg)
        qs.append(q); ks.append(k); vs.append(v)
        qbs.append(qb); kbs.append(kb); vbs.append(vb)
    lt = "gpt_neox.layers.{i}."
    layers = {
        "wq": jnp.asarray(np.stack(qs)), "wk": jnp.asarray(np.stack(ks)),
        "wv": jnp.asarray(np.stack(vs)),
        "bq": jnp.asarray(np.stack(qbs)), "bk": jnp.asarray(np.stack(kbs)),
        "bv": jnp.asarray(np.stack(vbs)),
        "wo": _stack(sd, lt + "attention.dense.weight", L, lambda w: w.T),
        "bo": _stack(sd, lt + "attention.dense.bias", L, lambda b: b),
        "ln1_scale": _stack(sd, lt + "input_layernorm.weight", L, lambda w: w),
        "ln1_bias": _stack(sd, lt + "input_layernorm.bias", L, lambda w: w),
        "ln2_scale": _stack(sd, lt + "post_attention_layernorm.weight", L, lambda w: w),
        "ln2_bias": _stack(sd, lt + "post_attention_layernorm.bias", L, lambda w: w),
        "w_in": _stack(sd, lt + "mlp.dense_h_to_4h.weight", L, lambda w: w.T),
        "b_in": _stack(sd, lt + "mlp.dense_h_to_4h.bias", L, lambda b: b),
        "w_out": _stack(sd, lt + "mlp.dense_4h_to_h.weight", L, lambda w: w.T),
        "b_out": _stack(sd, lt + "mlp.dense_4h_to_h.bias", L, lambda b: b),
    }
    params = {
        "embed": jnp.asarray(_np(sd["gpt_neox.embed_in.weight"])),
        "layers": layers,
        "final_norm_scale": jnp.asarray(_np(sd["gpt_neox.final_layer_norm.weight"])),
        "final_norm_bias": jnp.asarray(_np(sd["gpt_neox.final_layer_norm.bias"])),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(_np(sd["embed_out.weight"]).T)
    return params


def _qwen2_params(cfg: ModelConfig, sd: dict) -> dict:
    """Qwen2 and Llama share HF key names; Llama simply has no QKV biases."""
    L = cfg.num_layers
    lt = "model.layers.{i}."
    layers = {
        "wq": _stack(sd, lt + "self_attn.q_proj.weight", L, lambda w: w.T),
        "wk": _stack(sd, lt + "self_attn.k_proj.weight", L, lambda w: w.T),
        "wv": _stack(sd, lt + "self_attn.v_proj.weight", L, lambda w: w.T),
        "wo": _stack(sd, lt + "self_attn.o_proj.weight", L, lambda w: w.T),
        "ln1_scale": _stack(sd, lt + "input_layernorm.weight", L, lambda w: w),
        "ln2_scale": _stack(sd, lt + "post_attention_layernorm.weight", L, lambda w: w),
        "w_gate": _stack(sd, lt + "mlp.gate_proj.weight", L, lambda w: w.T),
        "w_up": _stack(sd, lt + "mlp.up_proj.weight", L, lambda w: w.T),
        "w_down": _stack(sd, lt + "mlp.down_proj.weight", L, lambda w: w.T),
    }
    if cfg.qkv_bias:
        layers.update({
            "bq": _stack(sd, lt + "self_attn.q_proj.bias", L, lambda b: b),
            "bk": _stack(sd, lt + "self_attn.k_proj.bias", L, lambda b: b),
            "bv": _stack(sd, lt + "self_attn.v_proj.bias", L, lambda b: b),
        })
    params = {
        "embed": jnp.asarray(_np(sd["model.embed_tokens.weight"])),
        "layers": layers,
        "final_norm_scale": jnp.asarray(_np(sd["model.norm.weight"])),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(_np(sd["lm_head.weight"]).T)
    return params


def config_from_hf(hf_config) -> ModelConfig:
    """Map a transformers config object to a ModelConfig."""
    mt = hf_config.model_type
    if mt == "gpt_neox":
        if not getattr(hf_config, "use_parallel_residual", True):
            raise ValueError("gpt_neox with use_parallel_residual=False is not supported")
        if getattr(hf_config, "hidden_act", "gelu") != "gelu":
            raise ValueError(f"gpt_neox hidden_act={hf_config.hidden_act!r} not supported (gelu only)")
        if not getattr(hf_config, "attention_bias", True):
            raise ValueError("gpt_neox with attention_bias=False is not supported")
        if getattr(hf_config, "rope_scaling", None):
            raise ValueError("gpt_neox rope_scaling is not supported (vanilla RoPE only)")
        return ModelConfig(
            family="gpt_neox",
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=hf_config.num_attention_heads,
            intermediate_size=hf_config.intermediate_size,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_eps=hf_config.layer_norm_eps,
            rope_theta=getattr(hf_config, "rotary_emb_base", 10000.0),
            rotary_pct=hf_config.rotary_pct,
            tie_word_embeddings=hf_config.tie_word_embeddings,
        )
    if mt == "llama":
        scaling = getattr(hf_config, "rope_scaling", None)
        rope_scaling = None
        if scaling:
            kind = scaling.get("rope_type", scaling.get("type"))
            if kind != "llama3":
                raise ValueError(f"llama rope_scaling type {kind!r} is not "
                                 f"supported (llama3 or none)")
            rope_scaling = ("llama3", float(scaling["factor"]),
                            float(scaling["low_freq_factor"]),
                            float(scaling["high_freq_factor"]),
                            int(scaling["original_max_position_embeddings"]))
        if getattr(hf_config, "attention_bias", False):
            raise ValueError("llama with attention_bias=True is not supported")
        hd = getattr(hf_config, "head_dim", None)
        if hd and hd * hf_config.num_attention_heads != hf_config.hidden_size:
            raise ValueError("llama with head_dim != hidden_size/num_heads is "
                             "not supported")
        return ModelConfig(
            family="llama",
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=hf_config.num_key_value_heads,
            intermediate_size=hf_config.intermediate_size,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_eps=hf_config.rms_norm_eps,
            rope_theta=hf_config.rope_theta,
            tie_word_embeddings=hf_config.tie_word_embeddings,
            rope_scaling=rope_scaling,
        )
    if mt == "qwen2":
        if getattr(hf_config, "rope_scaling", None):
            raise ValueError("qwen2 rope_scaling is not supported (vanilla RoPE only)")
        if getattr(hf_config, "use_sliding_window", False):
            raise ValueError("qwen2 sliding-window attention is not supported")
        return ModelConfig(
            family="qwen2",
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=hf_config.num_key_value_heads,
            intermediate_size=hf_config.intermediate_size,
            max_position_embeddings=hf_config.max_position_embeddings,
            norm_eps=hf_config.rms_norm_eps,
            rope_theta=hf_config.rope_theta,
            tie_word_embeddings=hf_config.tie_word_embeddings,
        )
    raise ValueError(f"unsupported model_type: {mt}")
