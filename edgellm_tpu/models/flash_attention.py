"""Whole-sequence-in-VMEM causal attention kernel for small-head models.

Why this exists: the flagship qwen2-0.5b has ``head_dim=64`` — half the MXU
lane width — and at the sweep's shapes (S=512, B*R up to 256 rows) XLA's
fused ``jax.nn.dot_product_attention`` measures ~18 TF/s on the v5e while the
same chip does 194 TF/s on big matmuls; the generic Pallas flash/splash
kernels (built for long S, hd>=128) measure slower still. This kernel takes
the opposite design point: at S <= 1024 the ENTIRE (S, S) score matrix of one
(batch, head) pair fits VMEM, so each grid step computes
scores -> causal mask -> softmax -> PV in one pass with zero HBM traffic for
intermediates — no flash blocking, no online-softmax recurrence.

Measured design notes (differential-scan timings on the v5e, round 4):

- the big (S, hd) x (hd, S) ops are what the MXU wants: in-kernel fori flash
  tiling measured 27 TF/s (T=2) / 14 TF/s (T=4), and a 2-way causal split
  (25% fewer flops but 2x smaller matmuls) measured 33 TF/s — all SLOWER
  than the 43-46 TF/s untiled full square, so the causal upper triangle is
  deliberately computed and masked;
- all ``rep = H // KV`` query heads of one KV group run per grid step: K/V
  are fetched once per group (the GQA broadcast costs no HBM traffic) and
  the longer step amortizes grid overhead (43.5 -> 45.9 TF/s);
- per-matmul anatomy: QK alone 34 TF/s, PV alone 31 TF/s, both overlap to
  ~45-50 — the kernel is MXU-bound at the hd=64 padding limit, softmax adds
  only ~15%;
- q and the output stay PACKED as (B, S, H*hd) — the natural projection
  layout — with heads as static column slices of the block, so the two big
  (B, S, H, hd) <-> (B, H, S, hd) transposes never exist (38.3 -> 43.5 TF/s
  end-to-end at the sweep's 256-row batches); only the KV/H-fold smaller K/V
  are transposed.

Net: ~2.4x XLA's fused attention at the flagship shapes (43.5 TF/s vs 18.4
at B=256), measured end-to-end from the model's layout.

The stats variant additionally emits the column-sum and last-query-row
statistics the importance metrics consume (``AttnStats``), read directly off
the in-VMEM probability matrix — the fused replacement for the blocked-scan
stats capture in ``transformer.attention`` (reference constraint: a SECOND
eager model instance just to get attention maps,
``Experiments/Pythia-70M/last_row_exp.py:66-70``).
"""
from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

#: one head's in-flight score/prob matrices must fit VMEM alongside the
#: double-buffered blocks; S=1024 (4 MB fp32 scores) compile- and run-checked
#: on the v5e (only one head's matrices are live at a time — Mosaic schedules
#: the rest), S=2048 (16 MB) cannot fit
MAX_WHOLE_S = 1024
#: widest packed q/out row validated on silicon: dh=896 (flagship, 2.4x XLA)
#: and dh=1536 (qwen2-1.5b hd=128, 3.45x XLA) compile and win; dh=2048
#: (llama-1b, 32 heads) exceeds scoped VMEM by ~2 MB at S=512 — wider models
#: stay on XLA's fused path like the codec kernels stay unsubstituted until
#: a win is measured
MAX_PACKED_DH = 1536


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def kernel_eligible(seq: int, model_dim: int,
                    backend_check: bool = True) -> bool:
    """True when the whole-S kernel should handle this (S, H*hd) shape by
    default: TPU backend, sequence short enough for in-VMEM scores, packed
    row within the silicon-validated width. EDGELLM_ATTN forces the kernel
    (=pallas) or the XLA path (=xla) on any backend — the force still honors
    the VMEM-driven shape limits."""
    flag = os.environ.get("EDGELLM_ATTN")
    fits = seq <= MAX_WHOLE_S and model_dim <= MAX_PACKED_DH
    if flag == "xla":
        return False
    if flag == "pallas":
        return fits
    return fits and (not backend_check or jax.default_backend() == "tpu")


def _head_attn(q, k, v):
    """One head's causal attention, entirely in VMEM -> (out, probs)."""
    s, hd = q.shape
    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * (1.0 / np.sqrt(hd))
    row = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
    scores = jnp.where(row >= col, scores, -1e30)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    out = jax.lax.dot_general(
        p.astype(q.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return out.astype(q.dtype), p


def _attn_packed_kernel(q_ref, k_ref, v_ref, o_ref, *, hd):
    """Grid (B,): one batch row, every head, PACKED (S, H*hd) q/out layout.

    The packed layout is the natural shape of the QKV projection output, so
    the (B, S, H, hd) -> (B, H, S, hd) transpose of q and of the output —
    hundreds of MB each way per layer at the sweep's 256-row batches — never
    exists; each head is a STATIC column slice of the block. K/V still use
    the (B, KV, S, hd) layout (their transpose is KV/H-fold smaller)."""
    kv = k_ref.shape[1]
    rep = (q_ref.shape[2] // hd) // kv
    for j in range(kv):
        k = k_ref[0, j]
        v = v_ref[0, j]
        for g in range(rep):
            c0 = (j * rep + g) * hd
            out, _ = _head_attn(q_ref[0, :, c0:c0 + hd], k, v)
            o_ref[0, :, c0:c0 + hd] = out.astype(o_ref.dtype)


def _attn_packed_stats_kernel(q_ref, k_ref, v_ref, o_ref, col_ref, last_ref,
                              *, hd):
    kv = k_ref.shape[1]
    rep = (q_ref.shape[2] // hd) // kv
    s = k_ref.shape[2]
    for j in range(kv):
        k = k_ref[0, j]
        v = v_ref[0, j]
        for g in range(rep):
            c0 = (j * rep + g) * hd
            out, p = _head_attn(q_ref[0, :, c0:c0 + hd], k, v)
            o_ref[0, :, c0:c0 + hd] = out.astype(o_ref.dtype)
            col_ref[0, j * rep + g, 0] = jnp.sum(p, axis=0) * (1.0 / s)
            last_ref[0, j * rep + g, 0] = p[s - 1, :]


@functools.partial(jax.jit, static_argnames=("hd", "interpret"))
def _attn_packed(q2, kt, vt, hd: int, interpret: bool):
    """q2 (B, S, H*hd) packed; kt/vt (B, KV, S, hd) -> out (B, S, H*hd)."""
    b, s, dh = q2.shape
    kv = kt.shape[1]
    spec_q = pl.BlockSpec((1, s, dh), lambda i: (i, 0, 0))
    spec_kv = pl.BlockSpec((1, kv, s, hd), lambda i: (i, 0, 0, 0))
    return pl.pallas_call(
        functools.partial(_attn_packed_kernel, hd=hd),
        grid=(b,),
        in_specs=[spec_q, spec_kv, spec_kv],
        out_specs=spec_q,
        out_shape=jax.ShapeDtypeStruct((b, s, dh), q2.dtype),
        interpret=interpret,
    )(q2, kt, vt)


@functools.partial(jax.jit, static_argnames=("hd", "interpret"))
def _attn_packed_stats(q2, kt, vt, hd: int, interpret: bool):
    b, s, dh = q2.shape
    kv = kt.shape[1]
    h = dh // hd
    spec_q = pl.BlockSpec((1, s, dh), lambda i: (i, 0, 0))
    spec_kv = pl.BlockSpec((1, kv, s, hd), lambda i: (i, 0, 0, 0))
    spec_s = pl.BlockSpec((1, h, 1, s), lambda i: (i, 0, 0, 0))
    out, col, last = pl.pallas_call(
        functools.partial(_attn_packed_stats_kernel, hd=hd),
        grid=(b,),
        in_specs=[spec_q, spec_kv, spec_kv],
        out_specs=[spec_q, spec_s, spec_s],
        out_shape=[
            jax.ShapeDtypeStruct((b, s, dh), q2.dtype),
            jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
            jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
        ],
        interpret=interpret,
    )(q2, kt, vt)
    return out, col[:, :, 0, :], last[:, :, 0, :]


def causal_attention(q, k, v, *, interpret: bool | None = None):
    """Causal attention from the model's (B, S, H, hd) layout; K/V may carry
    fewer (grouped-query) heads. Returns (B, S, H, hd).

    q rides through the kernel PACKED as (B, S, H*hd) — a free reshape of the
    projection output, no transpose; only the small K/V get transposed."""
    if interpret is None:
        interpret = _use_interpret()
    b, s, h, hd = q.shape
    out = _attn_packed(q.reshape(b, s, h * hd),
                       jnp.transpose(k, (0, 2, 1, 3)),
                       jnp.transpose(v, (0, 2, 1, 3)), hd, interpret)
    return out.reshape(b, s, h, hd)


def causal_attention_stats(q, k, v, *, interpret: bool | None = None):
    """Causal attention + (col_sum/S, last_row) stats, from (B, S, H, hd).
    Returns (out (B, S, H, hd), (col_sum (B, H, S), last_row (B, H, S)))."""
    if interpret is None:
        interpret = _use_interpret()
    b, s, h, hd = q.shape
    out, col, last = _attn_packed_stats(q.reshape(b, s, h * hd),
                                        jnp.transpose(k, (0, 2, 1, 3)),
                                        jnp.transpose(v, (0, 2, 1, 3)),
                                        hd, interpret)
    return out.reshape(b, s, h, hd), (col, last)
