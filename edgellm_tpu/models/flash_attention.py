"""Whole-sequence-in-VMEM causal attention kernel for small-head models.

Why this exists: the flagship qwen2-0.5b has ``head_dim=64`` — half the MXU
lane width — and at the sweep's shapes (S=512, B*R up to 256 rows) XLA's
fused ``jax.nn.dot_product_attention`` measures ~18 TF/s on the v5e while the
same chip does 194 TF/s on big matmuls; the generic Pallas flash/splash
kernels (built for long S, hd>=128) measure slower still. This kernel takes
the opposite design point: at S <= 1024 the ENTIRE (S, S) score matrix of one
(batch, head) pair fits VMEM, so each grid step computes
scores -> causal mask -> softmax -> PV in one pass with zero HBM traffic for
intermediates — no flash blocking, no online-softmax recurrence.

Measured design notes (differential-scan timings on the v5e, round 4):

- the big (S, hd) x (hd, S) ops are what the MXU wants: in-kernel fori flash
  tiling measured 27 TF/s (T=2) / 14 TF/s (T=4), and a 2-way causal split
  (25% fewer flops but 2x smaller matmuls) measured 33 TF/s — all SLOWER
  than the 43-46 TF/s untiled full square, so the causal upper triangle is
  deliberately computed and masked;
- all ``rep = H // KV`` query heads of one KV group run per grid step: K/V
  are fetched once per group (the GQA broadcast costs no HBM traffic) and
  the longer step amortizes grid overhead (43.5 -> 45.9 TF/s);
- per-matmul anatomy: QK alone 34 TF/s, PV alone 31 TF/s, both overlap to
  ~45-50 — the kernel is MXU-bound at the hd=64 padding limit, softmax adds
  only ~15%;
- q and the output stay PACKED as (B, S, H*hd) — the natural projection
  layout — with heads as static column slices of the block, so the two big
  (B, S, H, hd) <-> (B, H, S, hd) transposes never exist (38.3 -> 43.5 TF/s
  end-to-end at the sweep's 256-row batches); only the KV/H-fold smaller K/V
  are transposed.

Net: ~2.4x XLA's fused attention at the flagship shapes (43.5 TF/s vs 18.4
at B=256), measured end-to-end from the model's layout.

Round 5 extends the envelope with a second, BLOCKED kernel (same design
language, two independent splits — see ``_attn_blocked_kernel``) covering
the reference's own Pythia evaluation window (S=2048,
``Experiments/Pythia-70M/initial_exp.py:86``) and wide packed rows
(llama-1b's 2048). Measured on the v5e (``tools/attn_probe.py``,
interleaved-pair median vs XLA's fused attention, bf16):

===================  ======================  ========  =======  =========
shape                plan                    Pallas    XLA      speedup
===================  ======================  ========  =======  =========
pythia-70m  S=2048   blocked (qb512, hps8)   59 TF/s   21 TF/s  2.81x
qwen2-0.5b  S=2048   blocked (qb512, hps14)  56 TF/s   22 TF/s  2.51x
llama-1b    S=512    blocked (qb512, hps16)  52 TF/s   20 TF/s  2.65x
qwen2-0.5b  S=512    whole-S (regression)    54 TF/s   20 TF/s  2.77x
qwen2-1.5b  S=512    whole-S (regression)    88 TF/s   20 TF/s  4.31x
===================  ======================  ========  =======  =========

The stats variants measure within 3-5% of the plain kernels at every shape
(fused stats capture stays ~free); blocked-kernel outputs match the dense
formulation to bf16 tolerance and its stats to <=2e-9 on silicon.

The stats variant additionally emits the column-sum and last-query-row
statistics the importance metrics consume (``AttnStats``), read directly off
the in-VMEM probability matrix — the fused replacement for the blocked-scan
stats capture in ``transformer.attention`` (reference constraint: a SECOND
eager model instance just to get attention maps,
``Experiments/Pythia-70M/last_row_exp.py:66-70``).
"""
from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

#: one head's in-flight score/prob matrices must fit VMEM alongside the
#: double-buffered blocks; S=1024 (4 MB fp32 scores) compile- and run-checked
#: on the v5e (only one head's matrices are live at a time — Mosaic schedules
#: the rest), S=2048 (16 MB) cannot fit — longer sequences take the
#: query-blocked kernel instead
MAX_WHOLE_S = 1024
#: widest packed q/out row validated on silicon for the whole-S all-heads
#: kernel: dh=896 (flagship, 2.4x XLA) and dh=1536 (qwen2-1.5b hd=128,
#: 3.45x XLA); wider rows (llama-1b's 2048) take the head-group-split
#: blocked kernel, which keeps only ``hps*hd`` packed columns live per step
MAX_PACKED_DH = 1536
#: query-block rows for the blocked kernel at S > MAX_WHOLE_S: a 512-row
#: block's scores are 512 x S fp32 = 4 MB at S=2048 — same VMEM budget the
#: whole-S kernel was validated at. Rows stay COMPLETE (every key visible),
#: so per-row softmax is exact and stats capture needs no online rescaling.
QBLOCK = 512
#: longest sequence for the blocked kernel (S=2048 covers the reference's
#: own Pythia evaluation window, Experiments/Pythia-70M/initial_exp.py:86,
#: and the repo's long-context ring config)
MAX_BLOCKED_S = 2048
#: head dims compile- and run-checked on silicon (ADVICE r4: an unvalidated
#: hd such as 80 must fall back to XLA, not silently take the kernel)
VALIDATED_HD = (64, 128)
#: largest per-step resident K (and V) block for the blocked kernel —
#: kvps * S * hd * 2 bytes. 2 MB is the silicon-validated worst case
#: (pythia-70m MHA at S=2048: 8 KV heads x 2048 x 64 bf16); wider MHA
#: groups shrink hps until the K/V blocks fit, rather than compiling a
#: never-validated VMEM footprint on the default path
MAX_KV_BYTES = 2 * 1024 * 1024
#: paged-decode gate: total K+V bytes ONE slot's span can reference
#: (2 * span * KV * hd * itemsize). The paged kernel streams one page per
#: grid step, so its resident footprint is tiny, but the whole span still
#: rides through HBM every step — past this budget the step is so deep into
#: the bandwidth roofline that kernel dispatch cannot win and the gate
#: refuses rather than extrapolate (same philosophy as MAX_KV_BYTES)
MAX_PAGED_KV_BYTES = 4 * 1024 * 1024


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _shape_plan(s: int, h: int, kv: int, hd: int, itemsize: int = 2):
    """Which kernel handles an (S, H, KV, hd) attention shape, ignoring
    backend/eligibility gating: ``("whole", None)`` — the all-heads-per-step
    whole-S kernel; ``("blocked", (qb, hps))`` — the query-blocked,
    head-group-split kernel with ``qb`` query rows and ``hps`` heads per grid
    step; ``None`` — no kernel covers the shape (XLA fused path).
    ``itemsize`` is the activation dtype's bytes (2 = bf16, the validated
    default); fp32 halves the K/V budget so the gate tracks the REAL
    resident footprint, not a bf16 assumption.

    Raises on ragged GQA (``h % kv``): both kernels iterate whole KV groups,
    so a ragged layout would silently leave head columns unwritten — callers
    that want a soft fallback gate through :func:`kernel_plan`."""
    if h % kv:
        raise ValueError(f"kernels need head-aligned GQA, got H={h}, KV={kv}")
    dh = h * hd
    # the whole-S envelope constants were validated at bf16; wider activation
    # dtypes double the resident score/probs and packed-row bytes, so the
    # eligibility window shrinks with itemsize (ADVICE r5 #1) — shapes that
    # fall out land on the blocked branch, whose hps search already budgets
    # the resident K/V blocks by itemsize
    scale = max(itemsize, 2) // 2
    if s <= MAX_WHOLE_S // scale and dh <= MAX_PACKED_DH // scale:
        return ("whole", None)
    if s > MAX_BLOCKED_S:
        return None
    qb = s if s <= MAX_WHOLE_S else QBLOCK
    if s % qb:
        return None
    rep = h // kv
    # largest head group that divides H, keeps KV groups whole (multiple of
    # rep), fits the validated packed width, AND keeps the per-step resident
    # K/V blocks inside the silicon-validated footprint
    hps = next((c for c in range(h, 0, -1)
                if h % c == 0 and c % rep == 0 and c * hd <= MAX_PACKED_DH
                and (c // rep) * s * hd * itemsize <= MAX_KV_BYTES),
               None)
    if hps is None:
        return None
    return ("blocked", (qb, hps))


def kernel_plan(s: int, h: int, kv: int, hd: int,
                backend_check: bool = True, itemsize: int = 2):
    """The kernel plan for this shape when the Pallas path should handle it
    by default, else None (XLA fused path): TPU backend, silicon-validated
    head_dim, head-aligned GQA, and a shape one of the two kernels covers.
    EDGELLM_ATTN forces the kernel (=pallas) or the XLA path (=xla) on any
    backend — the force still honors the VMEM-driven shape limits."""
    flag = os.environ.get("EDGELLM_ATTN")
    if flag == "xla":
        return None
    if hd not in VALIDATED_HD or h % kv:
        return None
    if flag != "pallas" and backend_check and jax.default_backend() != "tpu":
        return None
    return _shape_plan(s, h, kv, hd, itemsize)


def kernel_eligible(seq: int, model_dim: int,
                    backend_check: bool = True,
                    num_heads: int | None = None,
                    num_kv_heads: int | None = None) -> bool:
    """True when a Pallas kernel handles this (S, H*hd) shape by default.

    Callers must pass the real head layout: the historical hd=64 MHA
    inference is DEPRECATED (ADVICE r5 #2) because it disagrees with real
    dispatch for hd=128 and GQA presets — real dispatch is
    :func:`kernel_plan` on (S, H, KV, hd)."""
    if num_heads is None:
        import warnings

        warnings.warn(
            "kernel_eligible without num_heads/num_kv_heads infers an hd=64 "
            "MHA layout, which can disagree with real dispatch for hd=128/GQA "
            "presets; pass the head counts or use kernel_plan directly",
            DeprecationWarning, stacklevel=2)
        num_heads = max(model_dim // 64, 1)
    if num_kv_heads is None:
        num_kv_heads = num_heads
    hd = model_dim // num_heads
    return kernel_plan(seq, num_heads, num_kv_heads, hd,
                       backend_check=backend_check) is not None


def _head_attn(q, k, v, row0=0):
    """One head's causal attention for a (possibly partial) block of query
    rows against the FULL key set, entirely in VMEM -> (out, probs).
    ``row0`` is the global position of the first query row; every row is
    complete (all keys present), so the per-row softmax is exact."""
    sq, hd = q.shape
    sk = k.shape[0]
    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * (1.0 / np.sqrt(hd))
    row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0) + row0
    col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    scores = jnp.where(row >= col, scores, -1e30)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    out = jax.lax.dot_general(
        p.astype(q.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return out.astype(q.dtype), p


def _attn_packed_kernel(q_ref, k_ref, v_ref, o_ref, *, hd):
    """Grid (B,): one batch row, every head, PACKED (S, H*hd) q/out layout.

    NOTE: the whole-S family (this kernel + its stats twin) is the qb=S,
    hps=H special case of the blocked family below — any fix to masking,
    dtype casting, or stats capture must land in BOTH. They stay separate
    until a silicon probe confirms the blocked kernel's 3-D grid costs
    nothing at the validated whole-S shapes (the round-4 measurements that
    earned this kernel were taken on the 1-D grid; collapsing without that
    probe would silently re-litigate them).

    The packed layout is the natural shape of the QKV projection output, so
    the (B, S, H, hd) -> (B, H, S, hd) transpose of q and of the output —
    hundreds of MB each way per layer at the sweep's 256-row batches — never
    exists; each head is a STATIC column slice of the block. K/V still use
    the (B, KV, S, hd) layout (their transpose is KV/H-fold smaller)."""
    kv = k_ref.shape[1]
    rep = (q_ref.shape[2] // hd) // kv
    for j in range(kv):
        k = k_ref[0, j]
        v = v_ref[0, j]
        for g in range(rep):
            c0 = (j * rep + g) * hd
            out, _ = _head_attn(q_ref[0, :, c0:c0 + hd], k, v)
            o_ref[0, :, c0:c0 + hd] = out.astype(o_ref.dtype)


def _attn_packed_stats_kernel(q_ref, k_ref, v_ref, o_ref, col_ref, last_ref,
                              *, hd):
    kv = k_ref.shape[1]
    rep = (q_ref.shape[2] // hd) // kv
    s = k_ref.shape[2]
    for j in range(kv):
        k = k_ref[0, j]
        v = v_ref[0, j]
        for g in range(rep):
            c0 = (j * rep + g) * hd
            out, p = _head_attn(q_ref[0, :, c0:c0 + hd], k, v)
            o_ref[0, :, c0:c0 + hd] = out.astype(o_ref.dtype)
            col_ref[0, j * rep + g, 0] = jnp.sum(p, axis=0) * (1.0 / s)
            last_ref[0, j * rep + g, 0] = p[s - 1, :]


@functools.partial(jax.jit, static_argnames=("hd", "interpret"))
def _attn_packed(q2, kt, vt, hd: int, interpret: bool):
    """q2 (B, S, H*hd) packed; kt/vt (B, KV, S, hd) -> out (B, S, H*hd)."""
    b, s, dh = q2.shape
    kv = kt.shape[1]
    spec_q = pl.BlockSpec((1, s, dh), lambda i: (i, 0, 0))
    spec_kv = pl.BlockSpec((1, kv, s, hd), lambda i: (i, 0, 0, 0))
    return pl.pallas_call(
        functools.partial(_attn_packed_kernel, hd=hd),
        grid=(b,),
        in_specs=[spec_q, spec_kv, spec_kv],
        out_specs=spec_q,
        out_shape=jax.ShapeDtypeStruct((b, s, dh), q2.dtype),
        interpret=interpret,
    )(q2, kt, vt)


@functools.partial(jax.jit, static_argnames=("hd", "interpret"))
def _attn_packed_stats(q2, kt, vt, hd: int, interpret: bool):
    b, s, dh = q2.shape
    kv = kt.shape[1]
    h = dh // hd
    spec_q = pl.BlockSpec((1, s, dh), lambda i: (i, 0, 0))
    spec_kv = pl.BlockSpec((1, kv, s, hd), lambda i: (i, 0, 0, 0))
    spec_s = pl.BlockSpec((1, h, 1, s), lambda i: (i, 0, 0, 0))
    out, col, last = pl.pallas_call(
        functools.partial(_attn_packed_stats_kernel, hd=hd),
        grid=(b,),
        in_specs=[spec_q, spec_kv, spec_kv],
        out_specs=[spec_q, spec_s, spec_s],
        out_shape=[
            jax.ShapeDtypeStruct((b, s, dh), q2.dtype),
            jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
            jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
        ],
        interpret=interpret,
    )(q2, kt, vt)
    return out, col[:, :, 0, :], last[:, :, 0, :]


def _attn_blocked_kernel(q_ref, k_ref, v_ref, o_ref, *, hd):
    """Grid (B, H//hps, S//qb): one query block x one head group per step.

    Two independent splits extend the whole-S kernel's envelope:

    - query blocking (qb < S): only a (qb, S) score slab is live — 4 MB fp32
      at the validated qb=512/S=2048 point — while the FULL K/V of the head
      group stays resident, so every query row still sees all its keys and
      the per-row softmax is exact (no online-softmax recurrence, no
      flash-style rescaling);
    - head-group splitting (hps < H): only ``hps*hd`` packed q/out columns
      ride per step, bringing wide rows (llama-1b's 2048) inside the
      envelope. Groups are KV-aligned (hps a multiple of rep), so K/V are
      still fetched once per GQA group.

    The causal upper triangle is computed and masked, exactly like the
    whole-S kernel — measured on the v5e (round 4): the big (qb, hd) x
    (hd, S) ops beat any in-kernel tiling that skips masked work."""
    t = pl.program_id(2)
    qb = q_ref.shape[1]
    kvps = k_ref.shape[1]
    rep = (q_ref.shape[2] // hd) // kvps
    for j in range(kvps):
        k = k_ref[0, j]
        v = v_ref[0, j]
        for g in range(rep):
            c0 = (j * rep + g) * hd
            out, _ = _head_attn(q_ref[0, :, c0:c0 + hd], k, v, row0=t * qb)
            o_ref[0, :, c0:c0 + hd] = out.astype(o_ref.dtype)


def _attn_blocked_stats_kernel(q_ref, k_ref, v_ref, o_ref, col_ref, last_ref,
                               *, hd, nt):
    """Blocked kernel + stats. col/last blocks are indexed (i, j) — constant
    in the innermost grid dim t — so the same VMEM block is revisited across
    consecutive query blocks: col accumulates (init at t=0), last_row is
    written by the final block (global row S-1 lives there). Rows are
    complete per block, so both stats are exact, not rescaled estimates."""
    t = pl.program_id(2)
    qb = q_ref.shape[1]
    kvps = k_ref.shape[1]
    s = k_ref.shape[2]
    rep = (q_ref.shape[2] // hd) // kvps
    for j in range(kvps):
        k = k_ref[0, j]
        v = v_ref[0, j]
        for g in range(rep):
            c0 = (j * rep + g) * hd
            out, p = _head_attn(q_ref[0, :, c0:c0 + hd], k, v, row0=t * qb)
            o_ref[0, :, c0:c0 + hd] = out.astype(o_ref.dtype)
            hl = j * rep + g
            part = jnp.sum(p, axis=0) * (1.0 / s)

            @pl.when(t == 0)
            def _init():
                col_ref[0, hl, 0] = part

            @pl.when(t > 0)
            def _accum():
                col_ref[0, hl, 0] = col_ref[0, hl, 0] + part

            @pl.when(t == nt - 1)
            def _last():
                last_ref[0, hl, 0] = p[qb - 1, :]


@functools.partial(jax.jit, static_argnames=("hd", "qb", "hps", "interpret"))
def _attn_blocked(q2, kt, vt, hd: int, qb: int, hps: int, interpret: bool):
    b, s, dh = q2.shape
    kv = kt.shape[1]
    rep = (dh // hd) // kv
    kvps = hps // rep
    grid = (b, (dh // hd) // hps, s // qb)
    spec_q = pl.BlockSpec((1, qb, hps * hd), lambda i, j, t: (i, t, j))
    spec_kv = pl.BlockSpec((1, kvps, s, hd), lambda i, j, t: (i, j, 0, 0))
    return pl.pallas_call(
        functools.partial(_attn_blocked_kernel, hd=hd),
        grid=grid,
        in_specs=[spec_q, spec_kv, spec_kv],
        out_specs=spec_q,
        out_shape=jax.ShapeDtypeStruct((b, s, dh), q2.dtype),
        interpret=interpret,
    )(q2, kt, vt)


@functools.partial(jax.jit, static_argnames=("hd", "qb", "hps", "interpret"))
def _attn_blocked_stats(q2, kt, vt, hd: int, qb: int, hps: int,
                        interpret: bool):
    b, s, dh = q2.shape
    kv = kt.shape[1]
    h = dh // hd
    rep = h // kv
    kvps = hps // rep
    nt = s // qb
    grid = (b, h // hps, nt)
    spec_q = pl.BlockSpec((1, qb, hps * hd), lambda i, j, t: (i, t, j))
    spec_kv = pl.BlockSpec((1, kvps, s, hd), lambda i, j, t: (i, j, 0, 0))
    spec_s = pl.BlockSpec((1, hps, 1, s), lambda i, j, t: (i, j, 0, 0))
    out, col, last = pl.pallas_call(
        functools.partial(_attn_blocked_stats_kernel, hd=hd, nt=nt),
        grid=grid,
        in_specs=[spec_q, spec_kv, spec_kv],
        out_specs=[spec_q, spec_s, spec_s],
        out_shape=[
            jax.ShapeDtypeStruct((b, s, dh), q2.dtype),
            jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
            jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
        ],
        interpret=interpret,
    )(q2, kt, vt)
    return out, col[:, :, 0, :], last[:, :, 0, :]


def _resolve(q, k, plan):
    b, s, h, hd = q.shape
    if plan is None:
        plan = _shape_plan(s, h, k.shape[2], hd,
                           itemsize=jnp.dtype(q.dtype).itemsize)
        if plan is None:
            raise ValueError(
                f"no kernel covers S={s}, H={h}, KV={k.shape[2]}, hd={hd}")
    return plan


def causal_attention(q, k, v, *, interpret: bool | None = None, plan=None):
    """Causal attention from the model's (B, S, H, hd) layout; K/V may carry
    fewer (grouped-query) heads. Returns (B, S, H, hd).

    q rides through the kernel PACKED as (B, S, H*hd) — a free reshape of the
    projection output, no transpose; only the small K/V get transposed.
    ``plan`` (from :func:`kernel_plan`) picks whole-S vs blocked; resolved
    from the shape when omitted."""
    if interpret is None:
        interpret = _use_interpret()
    kind, args = _resolve(q, k, plan)
    b, s, h, hd = q.shape
    q2 = q.reshape(b, s, h * hd)
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    if kind == "whole":
        out = _attn_packed(q2, kt, vt, hd, interpret)
    else:
        out = _attn_blocked(q2, kt, vt, hd, args[0], args[1], interpret)
    return out.reshape(b, s, h, hd)


def causal_attention_stats(q, k, v, *, interpret: bool | None = None,
                           plan=None):
    """Causal attention + (col_sum/S, last_row) stats, from (B, S, H, hd).
    Returns (out (B, S, H, hd), (col_sum (B, H, S), last_row (B, H, S)))."""
    if interpret is None:
        interpret = _use_interpret()
    kind, args = _resolve(q, k, plan)
    b, s, h, hd = q.shape
    q2 = q.reshape(b, s, h * hd)
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    if kind == "whole":
        out, col, last = _attn_packed_stats(q2, kt, vt, hd, interpret)
    else:
        out, col, last = _attn_blocked_stats(q2, kt, vt, hd, args[0], args[1],
                                             interpret)
    return out.reshape(b, s, h, hd), (col, last)


# ---------------------------------------------------------------------------
# Decode attention: q_len=1 against a length-masked KV cache.
# ---------------------------------------------------------------------------


#: KV-at-rest storage tiers for the paged pool (models/paged_kv.py): pages
#: hold packed int codes plus one fp32 scale per (token row, KV head), the
#: same per-channel shapes the wire codecs compress — applied at rest.
#: "fp" is the uncompressed tier and builds the exact pre-quantization graph.
KV_REST_TIERS = ("fp", "int8_per_channel", "int4_per_channel")


def _kv_quant_spec(kv_codec: str) -> float:
    """Integer span of a quantized KV tier (codes live in [-qmax, qmax])."""
    if kv_codec == "int8_per_channel":
        return 127.0
    if kv_codec == "int4_per_channel":
        return 7.0
    raise ValueError(f"unknown KV-at-rest tier {kv_codec!r}; quantized "
                     f"options: {[t for t in KV_REST_TIERS if t != 'fp']}")


def quantize_kv_rows(x, kv_codec: str):
    """Quantize K or V rows per (token, KV head) over the ``hd`` lanes:
    x (..., KV, hd) -> (codes, scales (..., KV) fp32).

    The scale is each row's absmax — one fp32 per row per head, so a page
    append touches only its own row's codes and scale (whole-page scales
    would force a page requantize on every decode write). int8 codes are
    (..., KV, hd) int8; int4 codes pack lane ``i`` with lane ``i + hd/2``
    into one uint8 (..., KV, hd//2), the contiguous-half pairing the wire
    codecs use. An all-zero row quantizes to zero codes with scale 0, which
    dequantizes back to exact zeros (the trash page stays finite)."""
    qmax = _kv_quant_spec(kv_codec)
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    safe = jnp.where(amax > 0, amax, 1.0)
    codes = jnp.round(xf / safe[..., None] * qmax).astype(jnp.int8)
    if kv_codec == "int4_per_channel":
        half = x.shape[-1] // 2
        u = (codes + 8).astype(jnp.uint8)  # [-8, 7] -> [0, 15]
        codes = u[..., :half] | (u[..., half:] << 4)
    return codes, amax


def dequantize_kv_rows(codes, scales, kv_codec: str, dtype=jnp.float32):
    """Invert :func:`quantize_kv_rows`: codes (..., KV, hdc) + scales
    (..., KV) -> (..., KV, hd) in ``dtype``. The XLA gather fallback and the
    reference path of the numerical-equivalence contract both run exactly
    this expression, so gather-then-dequantize equals dequantize-then-gather
    bit for bit (the op is elementwise per row)."""
    qmax = _kv_quant_spec(kv_codec)
    if kv_codec == "int4_per_channel":
        lo = (codes & 0xF).astype(jnp.int8) - 8
        hi = ((codes >> 4) & 0xF).astype(jnp.int8) - 8
        c = jnp.concatenate([lo, hi], axis=-1).astype(jnp.float32)
    else:
        c = codes.astype(jnp.float32)
    return (c * (scales[..., None] / qmax)).astype(dtype)


def decode_plan(capacity: int, h: int, kv: int, hd: int,
                itemsize: int = 2, pages: tuple[int, int] | None = None,
                kv_codec: str | None = None):
    """Kernel plan for the q_len=1 decode shape — mirrors :func:`kernel_plan`
    so the probe-cache substitution policy carries over unchanged.

    CONTIGUOUS caches (``pages=None``) always return ``None``: one query row
    leaves the MXU idle and the step is HBM-bound on the K/V cache read, a
    regime where XLA's fused dot-product path is already at the bandwidth
    roofline — there is no measured win to encode, and an unvalidated kernel
    must not dispatch by default (the same rule ``VALIDATED_HD`` enforces for
    the prefill kernels).

    PAGED caches (``pages=(pages_per_slot, page_size)``) are different: XLA
    sees a gather-then-attend, materializing every slot's full span in HBM
    each step, while the Pallas kernel scalar-prefetches the page table and
    streams each slot's pages directly (Ragged Paged Attention, PAPERS.md) —
    a genuinely new data path, not a re-tiling of one XLA already has. It
    still dispatches only when EARNED, per the probe-cache rule: by default
    the plan requires TPU backend AND a recorded
    ``measured_win("paged_decode_attention")`` from ``tools/probe_kernels``;
    ``EDGELLM_ATTN=pallas`` forces it on any backend (interpret mode off-TPU,
    which is how tier-1 exercises the kernel); ``EDGELLM_ATTN=xla`` forces
    the gather fallback. The ``itemsize`` scaling tracks the real
    bytes-per-step the way the prefill gates do.

    ``kv_codec`` names a quantized at-rest tier (:data:`KV_REST_TIERS`): the
    byte budget then counts the REAL per-row footprint (packed codes plus one
    fp32 scale per KV head, per K and per V), the plan kind becomes
    ``"paged_quant"`` (the in-kernel-dequant kernel), the probe-cache key is
    per-tier (``paged_decode_attention.<tier>`` — a win measured for the fp
    kernel says nothing about the dequant one), and on real silicon the page
    size must tile the int8 sublane minimum (32; fp32 pages tile at 8 —
    interpret mode has no tiling, so the forced-flag CI path keeps ps % 8)."""
    flag = os.environ.get("EDGELLM_ATTN")
    if flag == "xla":
        return None
    if hd not in VALIDATED_HD or h % kv:
        return None
    if pages is None:
        # no contiguous decode kernel validated: XLA fallback for all shapes
        return None
    quant = kv_codec is not None and kv_codec != "fp"
    if quant:
        _kv_quant_spec(kv_codec)  # fail fast on an unknown tier name
        if hd % 2:
            return None  # int4 packing pairs lanes across hd/2
    pps, ps = pages
    if pps * ps != capacity:
        return None
    # page rows land in the sublane dim of the (ps, KV*hd) page block; keep
    # them register-aligned, and keep the span inside the validated window
    align = 32 if quant and jax.default_backend() == "tpu" else 8
    if ps % align or capacity > MAX_BLOCKED_S:
        return None
    code_bytes = (hd * itemsize if not quant
                  else (hd if kv_codec == "int8_per_channel" else hd // 2) + 4)
    if 2 * capacity * kv * code_bytes > MAX_PAGED_KV_BYTES:
        return None
    kind = ("paged_quant", (pps, ps)) if quant else ("paged", (pps, ps))
    if flag == "pallas":
        return kind
    if jax.default_backend() != "tpu":
        return None
    from ..codecs import probe_cache

    probe_key = (f"paged_decode_attention.{kv_codec}" if quant
                 else "paged_decode_attention")
    if probe_cache.measured_win(probe_key) is True:
        return kind
    return None


def decode_attention(q, k_cache, v_cache, length):
    """Single-position attention against a cache: q (B, 1, H, hd) vs
    k/v_cache (B, capacity, KV, hd) of which the first ``length`` positions
    are valid (``length`` is traced — one executable per capacity). ``length``
    may be a scalar (one fill level for the whole batch — the contiguous
    decode path) or a (B,) vector (per-row fill levels — the ragged gather
    fallback of :func:`paged_decode_attention`); the scalar graph is
    unchanged by the vector extension.
    Returns (B, 1, H, hd) in q's dtype; softmax in fp32.

    GQA broadcasting happens here, not in the cache: the per-group einsum
    reads each KV head once and applies it to its ``rep`` query heads, so
    the cache stays at num_kv_heads width (the whole point of GQA at decode
    time — the cache read IS the bottleneck).
    """
    b, s1, h, hd = q.shape
    kv = k_cache.shape[2]
    rep = h // kv
    if s1 != 1:
        raise ValueError(f"decode_attention is q_len=1 only, got q_len={s1}")
    if h % kv:
        raise ValueError(f"ragged GQA: H={h}, KV={kv}")
    # consult the kernel plan exactly like the prefill dispatch does; None for
    # every shape today (no validated decode kernel), so the XLA fallback
    # below is the only implementation
    plan = decode_plan(k_cache.shape[1], h, kv, hd,
                       itemsize=jnp.dtype(q.dtype).itemsize)
    assert plan is None
    # head j*rep+g attends KV group j — the same packing convention as the
    # prefill kernels' column slices (c0 = (j*rep+g)*hd)
    qg = q[:, 0].reshape(b, kv, rep, hd)
    scores = jnp.einsum("bgrd,bcgd->bgrc", qg, k_cache,
                        preferred_element_type=jnp.float32)
    scores = scores * (1.0 / np.sqrt(hd))
    if jnp.ndim(length):
        # ragged: row i masks at its own lengths[i]
        valid = jnp.arange(k_cache.shape[1])[None, :] < length[:, None]
        scores = jnp.where(valid[:, None, None, :], scores,
                           jnp.finfo(jnp.float32).min)
    else:
        valid = jnp.arange(k_cache.shape[1]) < length  # (capacity,)
        scores = jnp.where(valid[None, None, None, :], scores,
                           jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bgrc,bcgd->bgrd", probs.astype(q.dtype), v_cache,
                     preferred_element_type=jnp.float32).astype(q.dtype)
    return out.reshape(b, 1, h, hd)


def verify_attention(q, k_cache, v_cache, length):
    """q_len=k attention against a cache for speculative verify: q
    (B, K, H, hd) holds K consecutive positions whose K/V were just written
    at cache rows ``length .. length+K-1``, so query row j attends cache
    positions ``[0, length + j]`` — the per-query causal mask is the only
    difference from :func:`decode_attention`, whose einsum/mask/softmax
    structure this clones with the K axis kept. ``length`` is a traced
    scalar (the pre-write fill level). At K=1 this reduces exactly to
    ``decode_attention(q, k_cache, v_cache, length + 1)``.
    Returns (B, K, H, hd) in q's dtype; softmax in fp32.
    """
    b, kq, h, hd = q.shape
    kv = k_cache.shape[2]
    rep = h // kv
    if h % kv:
        raise ValueError(f"ragged GQA: H={h}, KV={kv}")
    # no kernel plan to consult: the verify shape is (tiny K) x (cache read),
    # the same HBM-bound regime where decode_plan returns None for contiguous
    # caches — XLA's fused path is the only implementation
    qg = q.reshape(b, kq, kv, rep, hd)
    scores = jnp.einsum("bqgrd,bcgd->bqgrc", qg, k_cache,
                        preferred_element_type=jnp.float32)
    scores = scores * (1.0 / np.sqrt(hd))
    # query row j sees positions < length + j + 1 (its own row included)
    valid = (jnp.arange(k_cache.shape[1])[None, :]
             < (length + jnp.arange(kq)[:, None] + 1))  # (K, capacity)
    scores = jnp.where(valid[None, :, None, None, :], scores,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bqgrc,bcgd->bqgrd", probs.astype(q.dtype), v_cache,
                     preferred_element_type=jnp.float32).astype(q.dtype)
    return out.reshape(b, kq, h, hd)


# ---------------------------------------------------------------------------
# Paged ragged decode attention: q_len=1 per slot against that slot's page
# list. Pallas kernel on TPU (plan-gated), XLA gather fallback everywhere.
# ---------------------------------------------------------------------------


def _paged_decode_kernel(pt_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, hd, ps, pps):
    """Grid (B, pages_per_slot): one slot x one of its pages per step.

    The page table and lengths arrive as SCALAR-PREFETCH operands, so the
    k/v BlockSpec index maps read ``pt[i*pps + j]`` and Mosaic's pipeline
    DMAs exactly that page — the Ragged Paged Attention trick: no manual
    copies, no gather materializing the span in HBM. The TPU grid iterates
    the last dim fastest, so the fp32 m/l/acc VMEM scratch carries the
    online-softmax state of slot ``i`` across its ``pps`` page steps: reset
    at j=0, accumulate on pages that intersect the slot's length (whole-page
    skip via ``pl.when`` — unallocated table entries point at the trash page
    and are never read), emit acc/l at j=pps-1.

    Unlike the prefill kernels (exact per-row softmax), this IS the
    online-softmax recurrence, so the output matches the XLA fallback to
    dtype tolerance, not bitwise — which is why the serve layer's
    bit-identity story runs on the fallback unless a probe win flips the
    plan (see decode_plan)."""
    i = pl.program_id(0)
    j = pl.program_id(1)
    kv = k_ref.shape[2] // hd
    h = q_ref.shape[1] // hd
    rep = h // kv
    length = lens_ref[i]

    @pl.when(j == 0)
    def _reset():
        m_scr[...] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when(j * ps < length)
    def _compute():
        pos = j * ps + jax.lax.broadcasted_iota(jnp.int32, (1, ps), 1)
        for g in range(kv):
            k = k_ref[0, :, g * hd:(g + 1) * hd]  # (ps, hd)
            v = v_ref[0, :, g * hd:(g + 1) * hd]
            for r in range(rep):
                hidx = g * rep + r
                qh = q_ref[0, hidx * hd:(hidx + 1) * hd].reshape(1, hd)
                s = jax.lax.dot_general(
                    qh, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * (1.0 / np.sqrt(hd))
                s = jnp.where(pos < length, s, -jnp.inf)
                m_old = m_scr[hidx, 0]
                m_new = jnp.maximum(m_old, jnp.max(s))
                p = jnp.exp(s - m_new)  # (1, ps); masked cols exp(-inf) = 0
                corr = jnp.exp(m_old - m_new)
                m_scr[hidx, 0] = m_new
                l_scr[hidx, 0] = l_scr[hidx, 0] * corr + jnp.sum(p)
                pv = jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                acc_scr[hidx, :] = acc_scr[hidx, :] * corr + pv[0]

    @pl.when(j == pps - 1)
    def _emit():
        # lengths >= 1 always (the step's own token), so l > 0
        out = acc_scr[...] / l_scr[...]
        o_ref[...] = out.reshape(1, h * hd).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("hd", "pps", "interpret"))
def _paged_attn(q2, kf, vf, pt_flat, lens, hd: int, pps: int,
                interpret: bool):
    """q2 (B, H*hd); kf/vf (num_pages, page_size, KV*hd); pt_flat (B*pps,)
    int32; lens (B,) int32 -> (B, H*hd)."""
    from jax.experimental.pallas import tpu as pltpu

    b, dh = q2.shape
    ps, kvd = kf.shape[1], kf.shape[2]
    h = dh // hd
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, pps),
        in_specs=[
            pl.BlockSpec((1, dh), lambda i, j, pt, ln: (i, 0)),
            pl.BlockSpec((1, ps, kvd),
                         lambda i, j, pt, ln: (pt[i * pps + j], 0, 0)),
            pl.BlockSpec((1, ps, kvd),
                         lambda i, j, pt, ln: (pt[i * pps + j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, dh), lambda i, j, pt, ln: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_decode_kernel, hd=hd, ps=ps, pps=pps),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, dh), q2.dtype),
        interpret=interpret,
    )(pt_flat, lens, q2, kf, vf)


def paged_decode_attention(q, k_pages, v_pages, page_table, lengths):
    """Ragged single-position attention against a paged pool: q (B, 1, H, hd)
    per slot; k/v_pages (num_pages, page_size, KV, hd) — ONE layer's shared
    pool; page_table (B, pages_per_slot) int32 names each slot's pages in
    logical order (0 = the trash page for unallocated tails); lengths (B,)
    int32 counts each slot's valid positions INCLUDING the one this step
    wrote. Returns (B, 1, H, hd) in q's dtype; softmax in fp32.

    Dispatch mirrors the prefill kernels: :func:`decode_plan` (with
    ``pages=``) earns the Pallas kernel via probe-cache win or
    ``EDGELLM_ATTN=pallas`` force; otherwise the XLA fallback gathers each
    slot's span contiguous and reuses :func:`decode_attention` with vector
    lengths — trash-page garbage lands only in masked positions, where
    softmax of ``finfo.min`` contributes exactly 0."""
    b, s1, h, hd = q.shape
    pn, ps, kv, _ = k_pages.shape
    pps = page_table.shape[1]
    span = pps * ps
    if s1 != 1:
        raise ValueError(f"paged decode is q_len=1 only, got q_len={s1}")
    if h % kv:
        raise ValueError(f"ragged GQA: H={h}, KV={kv}")
    plan = decode_plan(span, h, kv, hd,
                       itemsize=jnp.dtype(q.dtype).itemsize,
                       pages=(pps, ps))
    if plan is not None:
        q2 = q.reshape(b, h * hd)
        kf = k_pages.reshape(pn, ps, kv * hd)
        vf = v_pages.reshape(pn, ps, kv * hd)
        out = _paged_attn(q2, kf, vf, page_table.reshape(-1),
                          lengths.astype(jnp.int32), hd, pps,
                          _use_interpret())
        return out.reshape(b, 1, h, hd)
    idx = (page_table[:, :, None] * ps
           + jnp.arange(ps)[None, None, :]).reshape(b, span)
    kg = k_pages.reshape(pn * ps, kv, hd)[idx]
    vg = v_pages.reshape(pn * ps, kv, hd)[idx]
    return decode_attention(q, kg, vg, lengths)


def _paged_decode_quant_kernel(pt_ref, lens_ref, q_ref, k_ref, v_ref,
                               ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr,
                               *, hd, ps, pps, bits):
    """Quantized-page twin of :func:`_paged_decode_kernel`: pages arrive as
    packed int codes plus per-row scales and are dequantized IN VMEM, per
    page, inside the grid step — decode never materializes an fp copy of the
    pool in HBM. Two extra scalar-prefetch-indexed operands carry the
    (page_size, KV) fp32 scale blocks for K and V; the BlockSpec index map is
    the same ``pt[i*pps + j]`` page walk.

    ``bits`` is static: 8 reads (ps, KV*hd) int8 codes directly; 4 reads
    (ps, KV*hd/2) packed uint8 and splits nibbles with int32 shifts (lane i
    pairs with lane i + hd/2, matching quantize_kv_rows), widening each
    group's half-block to (ps, hd) before the dot. All dequant math and both
    dots run in fp32 — the codes' dynamic range is tiny, and q may be a
    different dtype than the pool."""
    i = pl.program_id(0)
    j = pl.program_id(1)
    hdc = hd // 2 if bits == 4 else hd
    kv = k_ref.shape[2] // hdc
    h = q_ref.shape[1] // hd
    rep = h // kv
    length = lens_ref[i]
    inv_qmax = 1.0 / (7.0 if bits == 4 else 127.0)

    @pl.when(j == 0)
    def _reset():
        m_scr[...] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when(j * ps < length)
    def _compute():
        pos = j * ps + jax.lax.broadcasted_iota(jnp.int32, (1, ps), 1)
        for g in range(kv):
            kc = k_ref[0, :, g * hdc:(g + 1) * hdc]  # (ps, hdc) int codes
            vc = v_ref[0, :, g * hdc:(g + 1) * hdc]
            ksc = ks_ref[0, :, g:g + 1] * inv_qmax   # (ps, 1) fp32
            vsc = vs_ref[0, :, g:g + 1] * inv_qmax
            if bits == 4:
                k32 = kc.astype(jnp.int32)
                kq = jnp.concatenate(
                    [(k32 & 0xF) - 8, ((k32 >> 4) & 0xF) - 8], axis=1)
                v32 = vc.astype(jnp.int32)
                vq = jnp.concatenate(
                    [(v32 & 0xF) - 8, ((v32 >> 4) & 0xF) - 8], axis=1)
            else:
                kq = kc.astype(jnp.int32)
                vq = vc.astype(jnp.int32)
            k = kq.astype(jnp.float32) * ksc  # (ps, hd) dequantized
            v = vq.astype(jnp.float32) * vsc
            for r in range(rep):
                hidx = g * rep + r
                qh = q_ref[0, hidx * hd:(hidx + 1) * hd].reshape(1, hd)
                s = jax.lax.dot_general(
                    qh.astype(jnp.float32), k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * (1.0 / np.sqrt(hd))
                s = jnp.where(pos < length, s, -jnp.inf)
                m_old = m_scr[hidx, 0]
                m_new = jnp.maximum(m_old, jnp.max(s))
                p = jnp.exp(s - m_new)
                corr = jnp.exp(m_old - m_new)
                m_scr[hidx, 0] = m_new
                l_scr[hidx, 0] = l_scr[hidx, 0] * corr + jnp.sum(p)
                pv = jax.lax.dot_general(
                    p, v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                acc_scr[hidx, :] = acc_scr[hidx, :] * corr + pv[0]

    @pl.when(j == pps - 1)
    def _emit():
        out = acc_scr[...] / l_scr[...]
        o_ref[...] = out.reshape(1, h * hd).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("hd", "pps", "bits", "interpret"))
def _paged_attn_quant(q2, kf, vf, ksf, vsf, pt_flat, lens, hd: int, pps: int,
                      bits: int, interpret: bool):
    """q2 (B, H*hd); kf/vf (num_pages, page_size, KV*hdc) packed codes;
    ksf/vsf (num_pages, page_size, KV) fp32 scales; pt_flat (B*pps,) int32;
    lens (B,) int32 -> (B, H*hd)."""
    from jax.experimental.pallas import tpu as pltpu

    b, dh = q2.shape
    ps, kvc = kf.shape[1], kf.shape[2]
    kv = ksf.shape[2]
    h = dh // hd
    page_map = lambda i, j, pt, ln: (pt[i * pps + j], 0, 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, pps),
        in_specs=[
            pl.BlockSpec((1, dh), lambda i, j, pt, ln: (i, 0)),
            pl.BlockSpec((1, ps, kvc), page_map),
            pl.BlockSpec((1, ps, kvc), page_map),
            pl.BlockSpec((1, ps, kv), page_map),
            pl.BlockSpec((1, ps, kv), page_map),
        ],
        out_specs=pl.BlockSpec((1, dh), lambda i, j, pt, ln: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_decode_quant_kernel,
                          hd=hd, ps=ps, pps=pps, bits=bits),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, dh), q2.dtype),
        interpret=interpret,
    )(pt_flat, lens, q2, kf, vf, ksf, vsf)


def paged_decode_attention_quant(q, k_pages, v_pages, k_scale, v_scale,
                                 page_table, lengths, *, kv_codec):
    """Quantized-pool twin of :func:`paged_decode_attention`: k/v_pages hold
    packed int codes (num_pages, page_size, KV, hdc) — hdc = hd for int8,
    hd/2 for packed int4 — and k/v_scale (num_pages, page_size, KV) fp32
    per-row absmax scales, the layout quantize_kv_rows writes. Dispatch is
    the same plan gate with ``kv_codec`` (per-tier probe key); the Pallas
    path dequantizes in VMEM, and the XLA fallback gathers codes+scales by
    page table THEN dequantizes — elementwise per row, so it is exactly
    equal to dequantizing the whole pool first (the numerical-equivalence
    contract the lint layer executes)."""
    b, s1, h, hd_q = q.shape
    pn, ps, kv, hdc = k_pages.shape
    hd = hdc * 2 if kv_codec == "int4_per_channel" else hdc
    pps = page_table.shape[1]
    span = pps * ps
    if s1 != 1:
        raise ValueError(f"paged decode is q_len=1 only, got q_len={s1}")
    if hd != hd_q:
        raise ValueError(f"code width {hdc} does not match q head_dim "
                         f"{hd_q} for tier {kv_codec!r}")
    if h % kv:
        raise ValueError(f"ragged GQA: H={h}, KV={kv}")
    plan = decode_plan(span, h, kv, hd,
                       itemsize=jnp.dtype(q.dtype).itemsize,
                       pages=(pps, ps), kv_codec=kv_codec)
    if plan is not None:
        bits = 4 if kv_codec == "int4_per_channel" else 8
        q2 = q.reshape(b, h * hd)
        kf = k_pages.reshape(pn, ps, kv * hdc)
        vf = v_pages.reshape(pn, ps, kv * hdc)
        out = _paged_attn_quant(q2, kf, vf, k_scale, v_scale,
                                page_table.reshape(-1),
                                lengths.astype(jnp.int32), hd, pps, bits,
                                _use_interpret())
        return out.reshape(b, 1, h, hd)
    idx = (page_table[:, :, None] * ps
           + jnp.arange(ps)[None, None, :]).reshape(b, span)
    kg = dequantize_kv_rows(k_pages.reshape(pn * ps, kv, hdc)[idx],
                            k_scale.reshape(pn * ps, kv)[idx],
                            kv_codec, q.dtype)
    vg = dequantize_kv_rows(v_pages.reshape(pn * ps, kv, hdc)[idx],
                            v_scale.reshape(pn * ps, kv)[idx],
                            kv_codec, q.dtype)
    return decode_attention(q, kg, vg, lengths)
