"""Model architecture configs for the supported causal-LM families.

The reference hardcodes two HuggingFace checkpoints — ``EleutherAI/pythia-70m``
(``/root/reference/Experiments/Pythia-70M/pythia_model.py:25``) and
``Qwen/Qwen2-0.5B`` (``Experiments/Qwen2-0.5B/qwen_layer_wise.py:17``).  Here the
architecture is an explicit config so any GPT-NeoX- or Qwen2-family size runs,
including the Qwen2-1.5B 3-hop target (BASELINE.json configs[4]) and tiny
randomly-initialized variants used by the test suite (the environment has no
network access to pull pretrained weights).
"""
from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters for one causal LM.

    ``family`` selects the block wiring:
      - ``"gpt_neox"``: parallel-residual blocks, LayerNorm (+bias), fused GELU MLP,
        partial rotary (``rotary_pct``), biases on all linears. Pythia models.
      - ``"qwen2"``: sequential-residual blocks, RMSNorm, SwiGLU MLP, full rotary,
        QKV biases but bias-free o/gate/up/down projections, grouped-query attention.
      - ``"llama"``: identical wiring to qwen2 with no biases anywhere
        (Llama-2/3 models; beyond the reference's two families).
    """

    family: str
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    intermediate_size: int
    max_position_embeddings: int
    norm_eps: float
    rope_theta: float = 10000.0
    rotary_pct: float = 1.0
    tie_word_embeddings: bool = False
    #: llama3 RoPE frequency rescaling, or None for vanilla RoPE. Tuple form
    #: ("llama3", factor, low_freq_factor, high_freq_factor,
    #: original_max_position_embeddings) — hashable for the frozen config.
    rope_scaling: Optional[tuple] = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def rotary_dim(self) -> int:
        return int(self.head_dim * self.rotary_pct)

    @property
    def qkv_bias(self) -> bool:
        return self.family in ("gpt_neox", "qwen2")

    def __post_init__(self):
        if self.family not in ("gpt_neox", "qwen2", "llama"):
            raise ValueError(f"unknown family: {self.family}")
        if self.hidden_size % self.num_heads:
            raise ValueError("num_heads must evenly divide hidden_size")
        if self.num_heads % self.num_kv_heads:
            raise ValueError("num_kv_heads must evenly divide num_heads")


# EleutherAI/pythia-70m — facts per SURVEY.md section 2.1 (6 layers, d=512, 8 heads,
# FFN 2048 GELU, vocab 50304, LayerNorm, rotary_pct 0.25, window 2048).
PYTHIA_70M = ModelConfig(
    family="gpt_neox",
    vocab_size=50304,
    hidden_size=512,
    num_layers=6,
    num_heads=8,
    num_kv_heads=8,
    intermediate_size=2048,
    max_position_embeddings=2048,
    norm_eps=1e-5,
    rope_theta=10000.0,
    rotary_pct=0.25,
)

# Qwen/Qwen2-0.5B — 24 layers, d=896, 14 q heads / 2 kv heads (GQA), FFN 4864,
# vocab 151936, RMSNorm eps 1e-6 (SURVEY.md section 2.1 / notebook module dumps).
QWEN2_0_5B = ModelConfig(
    family="qwen2",
    vocab_size=151936,
    hidden_size=896,
    num_layers=24,
    num_heads=14,
    num_kv_heads=2,
    intermediate_size=4864,
    max_position_embeddings=131072,
    norm_eps=1e-6,
    rope_theta=1000000.0,
    tie_word_embeddings=True,
)

# Qwen/Qwen2-1.5B — the 3-device multi-hop split target (BASELINE.json configs[4]).
QWEN2_1_5B = ModelConfig(
    family="qwen2",
    vocab_size=151936,
    hidden_size=1536,
    num_layers=28,
    num_heads=12,
    num_kv_heads=2,
    intermediate_size=8960,
    max_position_embeddings=131072,
    norm_eps=1e-6,
    rope_theta=1000000.0,
    tie_word_embeddings=True,
)

# meta-llama/Llama-3.2-1B — beyond-parity family (edge-sized Llama). Ships
# llama3 RoPE rescaling (factor 32 over an 8192-token original window).
LLAMA_3_2_1B = ModelConfig(
    family="llama",
    vocab_size=128256,
    hidden_size=2048,
    num_layers=16,
    num_heads=32,
    num_kv_heads=8,
    intermediate_size=8192,
    max_position_embeddings=131072,
    norm_eps=1e-5,
    rope_theta=500000.0,
    tie_word_embeddings=True,
    rope_scaling=("llama3", 32.0, 1.0, 4.0, 8192),
)


def tiny_config(family: str, *, num_layers: int = 4, hidden_size: int = 64,
                num_heads: int = 4, num_kv_heads: int | None = None,
                vocab_size: int = 256, intermediate_size: int | None = None) -> ModelConfig:
    """Small random-init config for tests (no pretrained weights in this environment)."""
    if num_kv_heads is None:
        num_kv_heads = 2 if family in ("qwen2", "llama") else num_heads
    if intermediate_size is None:
        intermediate_size = hidden_size * 4
    return ModelConfig(
        family=family,
        vocab_size=vocab_size,
        hidden_size=hidden_size,
        num_layers=num_layers,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        intermediate_size=intermediate_size,
        max_position_embeddings=512,
        norm_eps=1e-5 if family == "gpt_neox" else 1e-6,
        rope_theta=10000.0 if family == "gpt_neox" else 1000000.0,
        rotary_pct=0.25 if family == "gpt_neox" else 1.0,
        tie_word_embeddings=family in ("qwen2", "llama"),
    )


PRESETS = {
    "pythia-70m": PYTHIA_70M,
    "qwen2-0.5b": QWEN2_0_5B,
    "qwen2-1.5b": QWEN2_1_5B,
    "llama-3.2-1b": LLAMA_3_2_1B,
    # CI/smoke-scale variants (random init, no pretrained weights needed)
    "tiny-neox": tiny_config("gpt_neox"),
    "tiny-qwen2": tiny_config("qwen2", num_layers=6),
    "tiny-llama": tiny_config("llama", num_layers=6),
}
