"""The overload-robust serving front over ``generate`` / ``generate_split``.

Everything below this module serves ONE generation at a time: the decode
loops (``serve.decode``) drive the compile-once executables, the resilience
ladder survives link corruption, the recovery layer survives stage loss.
:class:`ServeFront` is the request lifecycle around them — the layer that
decides *whether* a generation should run at all, *which* backend runs it,
and *what quality* it gets under pressure:

    submit(Request) ── admission ──> bounded priority queue ── drain() ──>
      route (circuit breakers + retry budget) ──> generate / generate_split
        └─ failover (stage loss -> replan or local fallback, once) ─┘
                      └──> RequestRecord (typed outcome)

Design rules, in order:

- **Reject early, never silently.** Every refusal happens at submit with a
  typed reason (``queue_full``, ``deadline_infeasible``, ``circuit_open``,
  ``retry_budget_exhausted``) and lands in a :class:`RequestRecord` — a
  rejected request costs zero device work.
- **One request, one generate call.** Admitted requests are NOT batched
  together: cross-request batching changes each row's position under the
  per-step ``fold_in`` sampling keys and silently breaks per-request
  reproducibility. Bucketing is *capacity rounding* instead — capacities
  snap up to ``capacity_round`` multiples so a steady request mix reuses
  the same (batch, capacity) executables jit-miss-free (the record carries
  the per-call miss delta so tests assert it).
- **The graph is untouched.** The front is host-side orchestration only; a
  default-config front traces the exact ``decode.step`` jaxpr ``generate``
  traces (the ``frontend.decode-step-identity`` graphlint contract proves
  it byte-identically).
- **Degrade quality before dropping work.** Overload walks the
  :class:`~edgellm_tpu.serve.overload.BrownoutController` ladder (codec
  tier bias, hedging off, token caps, priority shed) with dwell hysteresis;
  failures open :class:`~edgellm_tpu.serve.overload.CircuitBreaker`s and
  route around the sick path (replanned split or single-device fallback)
  instead of queueing doomed work behind it.

Outcome taxonomy (see ``serve.overload``): ``completed`` is reserved for
requests whose tokens are exact — verified transport, no substituted
payloads, no mid-flight failover — so the soak harness can hold every
``completed`` request to bit-identity against a fault-free reference. A
request finished on a degraded *route* is still ``completed`` (the route is
in ``backend``/``plan``); a request rescued mid-flight is ``failed_over``;
a request whose ladder substituted a payload is ``failed``.
"""
from __future__ import annotations

import dataclasses
import heapq
import os
import threading
from typing import Any, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..obs import context as obs_context
from ..obs.flight import flight_dump_for, get_flight_recorder
from ..obs.metrics import Histogram, get_registry, record_prefix_stats
from ..obs.server import ObsServer
from ..obs.tracing import span as obs_span
from ..utils.clock import MONOTONIC, Clock
from ..utils.concurrency import guarded_by
from .decode import generate, generate_split
from .overload import (COMPLETED, FAILED, FAILED_OVER, REJECTED, SHED,
                       TIMED_OUT, AdmissionController, AdmissionError,
                       BrownoutController, CircuitBreaker, RetryBudget,
                       ServeFrontConfigError)
from .overload import (AdmissionConfig, BreakerConfig, BrownoutConfig,
                       RetryBudgetConfig)
from .recovery import DecodeTimeout, RecoveryConfig, StageLostError

__all__ = ["Request", "RequestRecord", "ServeFrontConfig", "ServeFront"]


@dataclasses.dataclass(frozen=True)
class Request:
    """One unit of admitted work.

    ``prompt_ids`` is (S,) or (B, S) int token ids; ``priority`` orders the
    queue (higher first) and feeds brownout shedding; ``deadline_s`` is
    relative to submit time (None = best-effort, never rejected for time);
    ``rng_seed`` pins the sampling stream so the same request replays
    token-identically anywhere."""

    prompt_ids: Any
    max_new_tokens: int = 16
    priority: int = 1
    deadline_s: Optional[float] = None
    temperature: float = 0.0
    rng_seed: int = 0

    def __post_init__(self):
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {self.deadline_s}")
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")


@dataclasses.dataclass
class RequestRecord:
    """The per-request outcome record ``ServeFront`` emits — the audit unit
    the soak harness, the obs registry, and ``--serve-report`` consume."""

    request_id: int
    outcome: str
    reason: str
    backend: Optional[str]          # "split" | "local" | "batched"
                                    # | None (never ran)
    priority: int
    submitted_at: float
    started_at: Optional[float]
    finished_at: Optional[float]
    queue_wait_s: Optional[float]
    ttft_s: Optional[float]         # submit -> first token (wait + prefill)
    service_s: Optional[float]      # measured prefill + decode wall
    latency_s: Optional[float]      # queue_wait + service
    deadline_s: Optional[float]
    deadline_met: Optional[bool]
    prompt_tokens: int
    requested_tokens: int
    granted_tokens: Optional[int]   # after brownout token caps
    capacity: Optional[int]         # bucketed cache capacity
    batch: int
    plan: Optional[dict]            # {"mode", "cuts", "hop_codecs"}
    brownout_level: int
    retries_charged: int
    jit_misses: Optional[int]       # decode-step executables compiled by
                                    # this call (local backend only)
    tokens: Optional[np.ndarray]    # (B, granted_tokens) or None
    recovery: Optional[dict]        # recovery counters, when the loop ran

    def as_dict(self) -> dict:
        """JSON-safe view (tokens elided — they are data, not telemetry)."""
        d = dataclasses.asdict(self)
        d["tokens"] = None if self.tokens is None else list(
            np.asarray(self.tokens).shape)
        return d


@dataclasses.dataclass(frozen=True)
class ServeFrontConfig:
    """Everything the front's controllers need, in one frozen bundle.

    ``capacity_round`` is the bucketing quantum: per-request cache
    capacities round up to its multiples so the steady-state request mix
    maps onto a handful of (batch, capacity) executables.
    ``step_deadline_s`` arms the per-request watchdog;
    ``checkpoint_dir``/``checkpoint_every`` arm per-request
    :class:`~edgellm_tpu.serve.recovery.DecodeCheckpoint` snapshots (the
    file is ``req<id>.ckpt`` under the dir). ``local_fallback`` allows
    routing to single-device ``generate`` when the split path is broken;
    ``replan_on_stage_loss`` allows rebuilding the split onto the surviving
    stages (needs >= 2 survivors). With all four at their defaults the
    front adds no recovery orchestration at all — admitted requests run the
    exact direct ``generate`` path."""

    admission: AdmissionConfig = dataclasses.field(
        default_factory=AdmissionConfig)
    breaker: BreakerConfig = dataclasses.field(default_factory=BreakerConfig)
    brownout: BrownoutConfig = dataclasses.field(
        default_factory=BrownoutConfig)
    retry_budget: RetryBudgetConfig = dataclasses.field(
        default_factory=RetryBudgetConfig)
    capacity_round: int = 16
    max_new_tokens_cap: Optional[int] = None
    step_deadline_s: Optional[float] = None
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0
    local_fallback: bool = True
    replan_on_stage_loss: bool = True
    #: keep every RequestRecord in ``records`` (the post-hoc audit surface).
    #: False drops terminal records after they are returned from drain and
    #: folded into the running aggregates — a 10⁶-request soak stays
    #: memory-flat while ``report()`` stays exact on counts and ~exact on
    #: percentiles (log-bucketed histograms)
    record_history: bool = True

    def __post_init__(self):
        if (isinstance(self.capacity_round, bool)
                or not isinstance(self.capacity_round, int)
                or self.capacity_round < 1):
            raise ServeFrontConfigError(
                f"capacity_round must be an integer >= 1, "
                f"got {self.capacity_round!r}")
        if self.max_new_tokens_cap is not None and self.max_new_tokens_cap < 1:
            raise ServeFrontConfigError(
                f"max_new_tokens_cap must be >= 1 or None, "
                f"got {self.max_new_tokens_cap!r}")
        if self.step_deadline_s is not None and self.step_deadline_s <= 0:
            raise ServeFrontConfigError(
                f"step_deadline_s must be > 0 or None, "
                f"got {self.step_deadline_s!r}")
        if self.checkpoint_every < 0:
            raise ServeFrontConfigError(
                f"checkpoint_every must be >= 0, "
                f"got {self.checkpoint_every!r}")


@dataclasses.dataclass
class _Pending:
    """Internal queue entry: the request plus everything priced at submit."""

    rid: int
    req: Request
    prompt: jnp.ndarray             # always (B, S)
    granted: int                    # tokens after brownout caps
    est_s: float                    # priced service time at admission
    submitted_at: float


def _round_up(n: int, quantum: int) -> int:
    return ((n + quantum - 1) // quantum) * quantum


@guarded_by("_submit_lock", fields=["_seq", "_queue", "_backlog_s",
                                    "_inflight_rids", "_agg", "records"])
class ServeFront:
    """The serving front. One instance owns the queue, the controllers, the
    breakers, and (optionally) a split runtime; ``submit`` admits,
    ``drain`` executes in priority order, every terminal state becomes a
    :class:`RequestRecord` in ``records``.

    ``split_ladder`` is an optional sequence of *same-topology* split
    runtimes at decreasing fidelity (e.g. tier 0 with hedging, tier 1
    without): the front serves from index ``link_health.tier +
    brownout.tier_bias`` (clamped), so both the link SLO controller and the
    brownout controller can walk real quality down without the front
    knowing how the tiers were built. With a single ``split_runtime`` the
    tier signals are advisory (reported, not actuated)."""

    def __init__(self, model_cfg: Any, params: dict, *,
                 split_runtime: Any = None,
                 split_ladder: Optional[Sequence[Any]] = None,
                 config: Optional[ServeFrontConfig] = None,
                 link_health: Any = None,
                 compute_dtype: Any = None,
                 batcher: Any = None,
                 speculative: Any = None,
                 clock: Clock = MONOTONIC):
        if split_runtime is not None and split_ladder is not None:
            raise ServeFrontConfigError(
                "pass split_runtime OR split_ladder, not both")
        self.batcher = batcher   # ContinuousBatcher, for drain_batched()
        self.model_cfg = model_cfg
        self.config = config if config is not None else ServeFrontConfig()
        self.clock = clock
        self.compute_dtype = compute_dtype
        self.link_health = link_health
        # SpecConfig for the split backend: every split-served request runs
        # speculative decode (draft + one k-token verify hop per burst);
        # None / disabled leaves generate_split on its vanilla loop
        self.speculative = speculative
        self._params = params
        self.admission = AdmissionController(self.config.admission)
        self.budget = RetryBudget(self.config.retry_budget, clock=clock)
        self.brownout = BrownoutController(self.config.brownout, clock=clock)
        self._queue: list = []      # heap of (-priority, deadline, rid, _Pending)
        self._backlog_s = 0.0       # priced service time sitting in the queue
        self._seq = 0
        # submit-side state (sequence, queue, backlog) mutates under this
        # lock so concurrent submitters never mint duplicate request ids or
        # corrupt the heap; drain stays single-threaded by contract
        self._submit_lock = threading.Lock()
        self._obs_server: Optional[ObsServer] = None
        fl = get_flight_recorder()
        if fl is not None:
            fl.set_context_provider(self._flight_context)
        self.records: list[RequestRecord] = []
        # running aggregates — the memory-flat twin of `records`: every
        # terminal record folds in here (under the submit lock) so report()
        # and health_summary() stay O(1) in served requests even with
        # record_history=False. Histograms self-lock, so they fold outside.
        self._agg: dict = {"requests": 0, "finished": 0, "tokens_out": 0,
                           "met": 0, "with_deadline": 0,
                           "outcomes": {}, "reasons": {}}
        self._ttft_hist = Histogram("serve_ttft_s", lo=1e-6, hi=1e4,
                                    n_buckets=400)
        self._latency_hist = Histogram("serve_latency_s", lo=1e-6, hi=1e4,
                                       n_buckets=400)
        self._inflight_rids: set = set()
        self.failovers = 0
        self._plans: dict = {}      # (batch, capacity) -> call count
        self._rt = None
        self._placed = None
        self._split_names: tuple = ()
        self._ladder = None
        self._ladder_idx = 0
        self._ladder_placed: dict = {}
        self._breakers = {"local": CircuitBreaker("local", self.config.breaker,
                                                  clock=clock)}
        if split_ladder is not None:
            if not split_ladder:
                raise ServeFrontConfigError("split_ladder may not be empty")
            self._ladder = tuple(split_ladder)
            self._install_runtime(self._ladder[0])
        elif split_runtime is not None:
            self._install_runtime(split_runtime)

    # -- runtime management ------------------------------------------------

    def set_split_runtime(self, rt: Any, *, keep_breakers: bool = False) -> None:
        """Swap the split backend (chaos harness: corruption burst on/off;
        ops: a re-provisioned mesh). Clears any ladder — an external swap
        supersedes it. ``keep_breakers`` preserves breaker state across the
        swap (same topology, different fault behaviour); by default the new
        runtime starts with fresh closed breakers."""
        self._ladder = None
        self._ladder_placed = {}
        self._install_runtime(rt, keep_breakers=keep_breakers)

    def _install_runtime(self, rt: Any, *, keep_breakers: bool = False) -> None:
        self._rt = rt
        self._placed = rt.place_params(self._params)
        names = (["split"]
                 + [f"stage{i}" for i in range(rt.split.n_stages)]
                 + [f"link{i}" for i in range(len(rt.split.cuts))])
        if keep_breakers and self._split_names == tuple(names):
            return
        for n in self._split_names:
            self._breakers.pop(n, None)
        self._split_names = tuple(names)
        for n in names:
            self._breakers[n] = CircuitBreaker(n, self.config.breaker,
                                               clock=self.clock)

    def _walk_ladder(self) -> None:
        """Serve from the ladder entry the tier signals point at."""
        if self._ladder is None:
            return
        base = self.link_health.tier if self.link_health is not None else 0
        idx = min(base + self.brownout.tier_bias, len(self._ladder) - 1)
        if idx == self._ladder_idx and self._rt is self._ladder[idx]:
            return
        self._ladder_idx = idx
        rt = self._ladder[idx]
        if idx in self._ladder_placed:
            self._rt, self._placed = rt, self._ladder_placed[idx]
            # same topology by contract: breakers stay
        else:
            self._install_runtime(rt, keep_breakers=True)
            self._ladder_placed[idx] = self._placed

    @property
    def split_runtime(self) -> Any:
        return self._rt

    @property
    def params(self) -> dict:
        """The raw (unplaced) parameter pytree the front serves with — what
        a reference run needs to reproduce a request elsewhere."""
        return self._params

    @property
    def breakers(self) -> dict:
        return dict(self._breakers)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    def probe_prefix(self, prompt_ids) -> int:
        """Affinity lookup for a cluster router: leading tokens of this
        prompt the front's paged pool already holds (0 without a
        prefix-enabled batcher). Pure dry-run — probing N replicas does not
        skew any replica's hit/miss stats."""
        if self.batcher is None:
            return 0
        return self.batcher.probe_prefix(prompt_ids)

    def load_fraction(self) -> float:
        """Scalar load pressure in [0, 1]: queue fullness against the
        admission bound, or the brownout ladder position — whichever is
        higher. The cluster autoscaler's per-replica input."""
        depth = len(self._queue) / self.admission.cfg.max_queue_depth
        level = self.brownout.level / max(1, self.brownout.cfg.max_level)
        return float(min(1.0, max(depth, level)))

    # -- submit ------------------------------------------------------------

    def submit(self, req: Request) -> int:
        """Admit (or reject/shed, recorded) one request; returns its id.
        Thread-safe: the id sequence and the queue mutate under a lock, and
        the admission work runs inside a ``serve.submit`` span bound to the
        request's trace context (so every nested span/metric carries the
        request id)."""
        rid, _ = self.submit_ex(req)
        return rid

    def submit_ex(self, req: Request) -> tuple:
        """:meth:`submit` plus the submit-time refusal in-band: returns
        ``(rid, record)`` where ``record`` is the terminal
        :class:`RequestRecord` when the request was rejected or shed at
        admission, or None when it was queued. A cluster router needs the
        refusal as a return value — fishing it out of ``records`` is racy
        and impossible under ``record_history=False``."""
        now = self.clock()
        with self._submit_lock:
            self._seq += 1
            rid = self._seq
        fl = get_flight_recorder()
        if fl is not None:
            fl.note_request(f"r{rid}", priority=int(req.priority),
                            prompt=int(np.asarray(req.prompt_ids).size),
                            max_new_tokens=int(req.max_new_tokens))
        with obs_context.bind(rid=f"r{rid}"):
            with obs_span("serve.submit", priority=int(req.priority)):
                return rid, self._submit_impl(rid, req, now)

    def _submit_impl(self, rid: int, req: Request,
                     now: float) -> Optional[RequestRecord]:
        depth = len(self._queue)
        self.brownout.observe(depth / self.admission.cfg.max_queue_depth)
        prompt = jnp.asarray(req.prompt_ids)
        if prompt.ndim == 1:
            prompt = prompt[None, :]
        if prompt.ndim != 2:
            raise ValueError(
                f"prompt_ids must be (S,) or (B, S), got {prompt.shape}")
        b, s = prompt.shape
        requested = req.max_new_tokens
        if self.config.max_new_tokens_cap is not None:
            requested = min(requested, self.config.max_new_tokens_cap)
        granted = self.brownout.token_cap(requested)
        if self.brownout.should_shed(req.priority):
            return self._finish(rid, req, b, s, SHED, "brownout_shed", now)
        try:
            self.admission.admit(s, granted, depth, req.deadline_s,
                                 backlog_s=self._backlog_s)
        except AdmissionError as e:
            return self._finish(rid, req, b, s, REJECTED, e.reason, now)
        est = self.admission.estimate_s(s, granted)
        pend = _Pending(rid=rid, req=req, prompt=prompt, granted=granted,
                        est_s=est, submitted_at=now)
        deadline_key = (now + req.deadline_s if req.deadline_s is not None
                        else float("inf"))
        with self._submit_lock:
            heapq.heappush(self._queue,
                           (-req.priority, deadline_key, rid, pend))
            self._backlog_s += est
        return None

    # -- drain -------------------------------------------------------------

    def _pop_pending(self) -> Optional[_Pending]:
        """Pop the highest-priority pending request and re-price the
        backlog, atomically w.r.t. concurrent submitters (None when the
        queue is empty). Execution stays outside the lock."""
        with self._submit_lock:
            if not self._queue:
                return None
            _, _, _, pend = heapq.heappop(self._queue)
            self._backlog_s = max(0.0, self._backlog_s - pend.est_s)
            self._inflight_rids.add(pend.rid)
            return pend

    def drain_pending(self) -> list:
        """Pop EVERY queued (not yet executing) request and hand it back as
        ``[(rid, Request)]`` without recording a terminal outcome — the
        replica-drain hatch: a cluster router re-admits the work on a
        surviving replica under the same seed, so the tokens stay identical
        and nothing is lost or double-counted here."""
        out: list = []
        with self._submit_lock:
            while self._queue:
                _, _, _, pend = heapq.heappop(self._queue)
                out.append((pend.rid, pend.req))
            self._backlog_s = 0.0
        fl = get_flight_recorder()
        if fl is not None:
            for rid, _ in out:
                fl.end_request(f"r{rid}")
        return out

    def drain(self, max_requests: Optional[int] = None) -> list:
        """Execute queued requests in (priority, deadline) order; returns
        the records produced by this call."""
        out: list = []
        while max_requests is None or len(out) < max_requests:
            pend = self._pop_pending()
            if pend is None:
                break
            self.brownout.observe(len(self._queue)
                                  / self.admission.cfg.max_queue_depth)
            out.append(self._execute(pend))
        return out

    def drain_batched(self, max_requests: Optional[int] = None,
                      max_steps: int = 100_000) -> list:
        """Execute queued requests through the continuous batcher — one
        compiled ragged decode step serving every admitted stream — instead
        of one generate call each. Admission, brownout, deadline expiry, and
        the local breaker apply exactly as in :meth:`drain`; each stream's
        tokens are bit-identical to its solo ``generate`` run (the batcher's
        core invariant, asserted by ``tests/test_batching.py``). Requests
        with batch > 1 prompts fall back to the one-shot path — the batcher
        serves single streams. A split-driven batcher (built with
        ``split_runtime=``) serves the same way through
        ``SplitRuntime.decode_step_paged`` — records carry
        ``plan["mode"] == "batched_split"`` plus the cuts/codecs."""
        if self.batcher is None:
            raise ServeFrontConfigError(
                "drain_batched needs a continuous batcher: "
                "ServeFront(..., batcher=ContinuousBatcher(...))")
        out: list = []
        inflight: dict = {}   # sid -> (pend, queue_wait_s, started_at)
        while (max_requests is None
               or len(out) + len(inflight) < max_requests):
            pend = self._pop_pending()
            if pend is None:
                break
            self.brownout.observe(len(self._queue)
                                  / self.admission.cfg.max_queue_depth)
            now = self.clock()
            wait = now - pend.submitted_at
            b, s = pend.prompt.shape
            d = pend.req.deadline_s
            if d is not None and wait >= d:
                out.append(self._finish(pend.rid, pend.req, b, s, TIMED_OUT,
                                        "expired_in_queue", pend.submitted_at,
                                        queue_wait_s=wait))
                continue
            if b != 1:
                out.append(self._execute(pend))
                continue
            if not self._breakers["local"].allow():
                out.append(self._finish(pend.rid, pend.req, b, s, REJECTED,
                                        "circuit_open", pend.submitted_at,
                                        queue_wait_s=wait))
                continue
            try:
                sid = self.batcher.submit(np.asarray(pend.prompt[0]),
                                          pend.granted,
                                          temperature=pend.req.temperature,
                                          rng_seed=pend.req.rng_seed)
            except ValueError:
                # prompt + granted tokens exceed the batcher's slot span — a
                # per-request shape problem, not a backend failure: reject it
                # and keep draining (nothing ties admission limits to the
                # batcher geometry)
                out.append(self._finish(pend.rid, pend.req, b, s, REJECTED,
                                        "exceeds_slot_span",
                                        pend.submitted_at,
                                        queue_wait_s=wait))
                continue
            inflight[sid] = (pend, wait, now)
        if not inflight:
            return out
        t0 = self.clock()
        try:
            results = self.batcher.run(max_steps)
            failure = None
        except Exception as e:  # noqa: BLE001 — a wedged pool / watchdog
            results = self.batcher.results
            failure = e
        wall = self.clock() - t0
        rep = self.batcher.report()
        plan = {"mode": "batched",
                "page_size": self.batcher.bcfg.page_size,
                "num_pages": self.batcher.bcfg.num_pages,
                "max_slots": self.batcher.bcfg.max_slots}
        if rep.get("prefix"):
            # once per drain (the counters carry running totals); the plan
            # carries the headline numbers so per-request records are
            # self-describing in the soak log
            record_prefix_stats(rep["prefix"])
            plan["prefix"] = {
                "hit_rate": rep["prefix"]["hit_rate"],
                "saved_tokens": rep["prefix"]["saved_tokens"],
                "shared_pages": rep["prefix"]["shared_pages"]}
        if getattr(self.batcher, "rt", None) is not None:
            # split-driven batcher: every ragged step crossed the boundary
            # through the quantized hop ladder — record the plan it ran on
            plan["mode"] = "batched_split"
            plan["cuts"] = [int(c) for c in self.batcher.rt.split.cuts]
            plan["hop_codecs"] = [c.name for c in self.batcher.rt.codecs]
        if rep.get("disagg"):
            # disaggregated prefill/decode front: the per-drain record carries
            # the migration scoreboard so a degrade mid-soak is attributable
            # to the drain where it happened
            plan["mode"] = ("disagg_split" if plan["mode"] == "batched_split"
                            else "disagg")
            plan["disagg"] = {
                "degraded": rep["disagg"]["degraded"],
                "degrade_reason": rep["disagg"]["degrade_reason"],
                "migrations": rep["disagg"]["migrations"],
                "recompute_tokens": rep["disagg"]["recompute_tokens"]}
        for sid in sorted(inflight):
            pend, wait, started = inflight[sid]
            b, s = pend.prompt.shape
            toks = results.get(sid)
            # collected either way: finished results must not accumulate in
            # the batcher, and a failed run's leftover streams must not rerun
            # on the next drain with nobody to receive them
            self.batcher.discard(sid)
            if toks is None:
                self._breakers["local"].record_failure()
                reason = (f"batcher:{type(failure).__name__}"
                          if failure is not None else "batcher:incomplete")
                out.append(self._finish(
                    pend.rid, pend.req, b, s, FAILED, reason,
                    pend.submitted_at, queue_wait_s=wait, backend="batched",
                    started_at=started))
                continue
            self._breakers["local"].record_success()
            # service/latency are whole-batch wall time: streams share the
            # step loop, so per-request attribution would be fiction
            out.append(self._finish(
                pend.rid, pend.req, b, s, COMPLETED, "", pend.submitted_at,
                queue_wait_s=wait, backend="batched", started_at=started,
                service_s=wall, latency_s=wait + wall,
                granted_tokens=pend.granted,
                capacity=self.batcher.bcfg.span, plan=plan,
                jit_misses=rep.get("jit_misses"),
                tokens=np.asarray(toks)[None, :]))
        return out

    def _execute(self, p: _Pending) -> RequestRecord:
        """One request's terminal execution, bound to its trace context —
        every hop span the decode loops emit below carries the request id."""
        with obs_context.bind(rid=f"r{p.rid}"):
            with obs_span("serve.execute", priority=int(p.req.priority)):
                return self._execute_impl(p)

    def _execute_impl(self, p: _Pending) -> RequestRecord:
        now = self.clock()
        wait = now - p.submitted_at
        b, s = p.prompt.shape
        d = p.req.deadline_s
        if d is not None and wait >= d:
            return self._finish(p.rid, p.req, b, s, TIMED_OUT,
                                "expired_in_queue", p.submitted_at,
                                queue_wait_s=wait)
        if d is not None and not self.admission.feasible(s, p.granted,
                                                         d - wait):
            return self._finish(p.rid, p.req, b, s, SHED,
                                "deadline_infeasible_in_queue",
                                p.submitted_at, queue_wait_s=wait)
        self._walk_ladder()
        backend, route_note = self._choose_route()
        if backend is None:
            return self._finish(p.rid, p.req, b, s, REJECTED,
                                route_note or "circuit_open",
                                p.submitted_at, queue_wait_s=wait)
        capacity = _round_up(s + p.granted, self.config.capacity_round)
        try:
            toks, stats, retries = self._run(p, backend, capacity)
            attempt2 = False
        except StageLostError as e:
            # post-mortem before routing around (once per instance: the
            # recorder latch absorbs duplicate dump_for calls downstream)
            flight_dump_for(e, rid=p.rid, backend=backend)
            self._on_stage_loss(e.stage)
            backend, retry_note = self._choose_route()
            if backend is None:
                return self._finish(p.rid, p.req, b, s, FAILED,
                                    f"stage_lost:{e.stage}", p.submitted_at,
                                    queue_wait_s=wait, backend=None,
                                    started_at=now)
            try:
                toks, stats, retries = self._run(p, backend, capacity)
                attempt2 = True
                route_note = f"stage_lost:{e.stage}"
            except (StageLostError, DecodeTimeout) as e2:
                flight_dump_for(e2, rid=p.rid, backend=backend)
                reason = (f"stage_lost:{e2.stage}"
                          if isinstance(e2, StageLostError) else "watchdog")
                return self._finish(p.rid, p.req, b, s, FAILED, reason,
                                    p.submitted_at, queue_wait_s=wait,
                                    backend=backend, started_at=now)
        except DecodeTimeout:
            self._breakers[
                "split" if backend == "split" else "local"].record_failure()
            return self._finish(p.rid, p.req, b, s, TIMED_OUT, "watchdog",
                                p.submitted_at, queue_wait_s=wait,
                                backend=backend, started_at=now)

        lc = stats.get("link_counters")
        substituted = (sum(lc.get("substituted", ())) if lc else 0)
        service = stats.get("prefill_s", 0.0) + stats.get("decode_s", 0.0)
        self.admission.record(s, stats.get("prefill_s", 0.0),
                              stats.get("decode_steps", 0),
                              stats.get("decode_s", 0.0))
        if backend == "split":
            if substituted:
                self._breakers["split"].record_failure()
            else:
                self._breakers["split"].record_success()
                for i in range(self._rt.split.n_stages):
                    self._breakers[f"stage{i}"].record_success()
            self._observe_link_burn(lc)
        else:
            self._breakers["local"].record_success()
        if substituted:
            outcome, reason = FAILED, "substituted_payload"
        elif attempt2:
            outcome, reason = FAILED_OVER, route_note
        else:
            outcome, reason = COMPLETED, (route_note or "")
        plan = ({"mode": "split", "cuts": list(self._rt.split.cuts),
                 "hop_codecs": list(self._rt.split.hop_codecs)}
                if backend == "split" else {"mode": "local"})
        key = (b, capacity)
        self._plans[key] = self._plans.get(key, 0) + 1
        return self._finish(
            p.rid, p.req, b, s, outcome, reason, p.submitted_at,
            queue_wait_s=wait, backend=backend, started_at=now,
            ttft_s=wait + stats.get("prefill_s", 0.0), service_s=service,
            latency_s=wait + service, granted_tokens=p.granted,
            capacity=capacity, plan=plan, retries_charged=retries,
            jit_misses=stats.get("decode_step_cache_misses"),
            tokens=np.asarray(toks),
            recovery=stats.get("recovery_counters"))

    # -- routing + backends ------------------------------------------------

    def _choose_route(self):
        """Pick a backend the breakers and the retry budget will fund.
        Returns (backend, note): note names why the primary was skipped."""
        note = None
        if self._rt is not None:
            if all(self._breakers[n].allow() for n in self._split_names):
                if self._rt.faults is not None and self.budget.exhausted():
                    self.budget.deny()
                    note = "retry_budget_exhausted"
                else:
                    return "split", None
            else:
                note = "circuit_open"
            if self.config.local_fallback and self._breakers["local"].allow():
                return "local", note
            return None, note
        if self._breakers["local"].allow():
            return "local", None
        return None, "circuit_open"

    def _recovery_cfg(self, rid: int) -> Optional[RecoveryConfig]:
        """Per-request recovery orchestration, or None (the direct loops)
        when nothing is configured. ``replan=False`` on purpose: mid-call
        replan would be invisible to the front's routing state, so stage
        loss must propagate here."""
        ckpt_dir = self.config.checkpoint_dir
        if ckpt_dir is None and self.config.step_deadline_s is None:
            return None
        path = (os.path.join(ckpt_dir, f"req{rid}.ckpt")
                if ckpt_dir is not None else None)
        return RecoveryConfig(
            checkpoint_path=path,
            checkpoint_every=self.config.checkpoint_every if path else 0,
            deadline_s=self.config.step_deadline_s,
            replan=False, clock=self.clock)

    def _run(self, p: _Pending, backend: str, capacity: int):
        """One generation attempt; returns (tokens, stats, retries_charged)."""
        stats: dict = {}
        key = jax.random.key(p.req.rng_seed)
        rec = self._recovery_cfg(p.rid)
        if backend == "split":
            if getattr(self.speculative, "enabled", False):
                # a verify burst may write k-1 draft rows past the vanilla
                # high-water mark; same deterministic formula per request
                # shape, so plan warming still holds
                capacity = max(capacity, p.prompt.shape[1] + p.granted
                               + self.speculative.k - 2)
            toks = generate_split(
                self._rt, self._placed, p.prompt, p.granted,
                capacity=capacity, temperature=p.req.temperature,
                rng_key=key, fault_step=p.rid, stats=stats, recovery=rec,
                raw_params=self._params, link_health=self.link_health,
                speculative=self.speculative)
        else:
            toks = generate(
                self.model_cfg, self._params, p.prompt, p.granted,
                capacity=capacity, temperature=p.req.temperature,
                rng_key=key, compute_dtype=self.compute_dtype, stats=stats,
                recovery=rec)
        lc = stats.get("link_counters")
        retries = int(sum(lc.get("retried", ()))) if lc else 0
        self.budget.charge(retries)
        return toks, stats, retries

    def _on_stage_loss(self, stage: int) -> None:
        """Trip the breakers, then route around: replan the split onto the
        survivors (>= 2 left) or leave the open breakers to force the local
        fallback. Mirrors the in-loop failover of ``serve.decode``, but at
        the *front* level the replanned runtime persists — every subsequent
        request is served on the new plan instead of re-failing."""
        self.failovers += 1
        if f"stage{stage}" in self._breakers:
            self._breakers[f"stage{stage}"].trip()
        self._breakers["split"].record_failure()
        if not self.config.replan_on_stage_loss or self._rt is None:
            return
        grid = np.asarray(self._rt.mesh.devices)  # (stage, data, model)
        if not (0 <= stage < grid.shape[0]) or grid.shape[0] - 1 < 2:
            return
        survivors = np.delete(grid, stage, axis=0)
        from jax.sharding import Mesh

        from ..parallel.split import SplitRuntime

        cfg = self._rt.cfg
        new_split = self._rt.split.replan(cfg.num_layers, survivors.shape[0])
        new_rt = SplitRuntime(cfg, new_split,
                              Mesh(survivors, ("stage", "data", "model")),
                              faults=self._rt.faults, policy=self._rt.policy,
                              fec=self._rt.fec, hedge=self._rt.hedge)
        self._ladder = None
        self._ladder_placed = {}
        self._install_runtime(new_rt)

    def _observe_link_burn(self, lc: Optional[dict]) -> None:
        """Per-hop burn rates -> per-link breaker signal, priced with the
        link SLO controller's error budget."""
        if lc is None:
            return
        budget = (self.link_health.cfg.error_budget
                  if self.link_health is not None else 0.02)
        hops = lc.get("hops", ())
        det = lc.get("detected", ())
        rep = lc.get("repaired", ())
        for i, h in enumerate(hops):
            name = f"link{i}"
            if name not in self._breakers or not h:
                continue
            unrepaired = (det[i] if i < len(det) else 0) - (
                rep[i] if i < len(rep) else 0)
            self._breakers[name].observe_burn((unrepaired / h) / budget)

    # -- records + reporting -----------------------------------------------

    def _finish(self, rid: int, req: Request, batch: int, prompt_tokens: int,
                outcome: str, reason: str, submitted_at: float, *,
                queue_wait_s: Optional[float] = None,
                backend: Optional[str] = None,
                started_at: Optional[float] = None,
                ttft_s: Optional[float] = None,
                service_s: Optional[float] = None,
                latency_s: Optional[float] = None,
                granted_tokens: Optional[int] = None,
                capacity: Optional[int] = None,
                plan: Optional[dict] = None,
                retries_charged: int = 0,
                jit_misses: Optional[int] = None,
                tokens: Optional[np.ndarray] = None,
                recovery: Optional[dict] = None) -> RequestRecord:
        deadline_met = None
        if req.deadline_s is not None and latency_s is not None:
            deadline_met = latency_s <= req.deadline_s
        finished_at = (started_at + service_s
                       if started_at is not None and service_s is not None
                       else None)
        rec = RequestRecord(
            request_id=rid, outcome=outcome, reason=reason, backend=backend,
            priority=req.priority, submitted_at=submitted_at,
            started_at=started_at, finished_at=finished_at,
            queue_wait_s=queue_wait_s, ttft_s=ttft_s, service_s=service_s,
            latency_s=latency_s, deadline_s=req.deadline_s,
            deadline_met=deadline_met, prompt_tokens=prompt_tokens,
            requested_tokens=req.max_new_tokens,
            granted_tokens=granted_tokens, capacity=capacity, batch=batch,
            plan=plan, brownout_level=self.brownout.level,
            retries_charged=retries_charged, jit_misses=jit_misses,
            tokens=tokens, recovery=recovery)
        # histograms self-lock; folding them outside keeps the submit lock
        # to pure dict/scalar updates
        if outcome in (COMPLETED, FAILED_OVER):
            if ttft_s is not None:
                self._ttft_hist.observe(ttft_s)
            if latency_s is not None:
                self._latency_hist.observe(latency_s)
        with self._submit_lock:
            agg = self._agg
            agg["requests"] += 1
            agg["outcomes"][outcome] = agg["outcomes"].get(outcome, 0) + 1
            if reason:
                agg["reasons"][reason] = agg["reasons"].get(reason, 0) + 1
            if outcome in (COMPLETED, FAILED_OVER):
                agg["finished"] += 1
                if granted_tokens is not None:
                    agg["tokens_out"] += batch * granted_tokens
                if deadline_met is not None:
                    agg["with_deadline"] += 1
                    agg["met"] += int(deadline_met)
            if self.config.record_history:
                self.records.append(rec)
            self._inflight_rids.discard(rid)
        fl = get_flight_recorder()
        if fl is not None:
            fl.end_request(f"r{rid}")
        reg = get_registry()
        if reg.enabled:
            reg.counter("serve_requests_total",
                        "terminal serve outcomes").inc(outcome=outcome)
            if ttft_s is not None:
                reg.histogram("serve_ttft_s", "submit -> first token",
                              lo=1e-4, hi=120.0).observe(ttft_s)
            if latency_s is not None:
                reg.histogram("serve_latency_s", "submit -> last token",
                              lo=1e-4, hi=600.0).observe(latency_s)
            if retries_charged:
                reg.counter("serve_retries_charged_total",
                            "ladder retries charged to the retry budget"
                            ).inc(retries_charged)
            reg.gauge("serve_brownout_level",
                      "current brownout level").set(self.brownout.level)
            reg.gauge("serve_queue_depth",
                      "queued requests").set(len(self._queue))
        return rec

    def report(self) -> dict:
        """Aggregate view over every terminal record so far: outcome/reason
        counts, SLO attainment, TTFT/latency percentiles, controller
        summaries, breaker states, (batch, capacity) plan usage. Computed
        from the running aggregates — O(1) in requests served, so a
        10⁶-request soak can call it freely and ``record_history=False``
        loses nothing but the raw record list. Percentiles come from
        log-bucketed histograms (exact to one bucket's relative width,
        ~2.3% at the default 400-bucket density)."""

        def pct(hist):
            if hist.count == 0:
                return None
            return {"p50": float(hist.quantile(0.50)),
                    "p95": float(hist.quantile(0.95)),
                    "p99": float(hist.quantile(0.99))}

        with self._submit_lock:
            agg = {**self._agg, "outcomes": dict(self._agg["outcomes"]),
                   "reasons": dict(self._agg["reasons"])}
            depth = len(self._queue)
        return {
            "requests": agg["requests"],
            "finished": agg["finished"],
            "tokens_out": agg["tokens_out"],
            "outcomes": agg["outcomes"],
            "reasons": agg["reasons"],
            "slo_attainment": ((agg["met"] / agg["with_deadline"])
                               if agg["with_deadline"] else None),
            "ttft_s": pct(self._ttft_hist),
            "latency_s": pct(self._latency_hist),
            "queue_depth": depth,
            "failovers": self.failovers,
            "admission": self.admission.summary(),
            "retry_budget": self.budget.summary(),
            "brownout": self.brownout.summary(),
            "breakers": {n: b.summary()
                         for n, b in sorted(self._breakers.items())},
            "plans": {f"{b}x{c}": n
                      for (b, c), n in sorted(self._plans.items())},
            # present only when this front drains a prefix-enabled batcher:
            # the live radix-index scoreboard --serve-report prints
            **({"prefix": self.batcher.pool.prefix_report()}
               if (self.batcher is not None
                   and self.batcher.pool.prefix is not None) else {}),
            # present only when this front drains a disaggregated server:
            # degrade state + migration scoreboard for --serve-report and
            # the cluster router's placement probe
            **({"disagg": self.disagg_state()}
               if self.disagg_state() is not None else {}),
        }

    def disagg_state(self) -> Optional[dict]:
        """Degrade state of a disaggregated batcher, or ``None`` for a plain
        colocated front.

        The cluster router probes this before placement: a replica whose
        disagg front has degraded to colocated serving still answers
        correctly (token-identical by construction) but at colocated
        throughput, so it should lose placement preference to healthy
        disaggregated peers.
        """
        b = self.batcher
        if b is None or not hasattr(b, "degrade_reason"):
            return None
        return {"degraded": bool(b.degraded),
                "degrade_reason": b.degrade_reason}

    # -- live telemetry ----------------------------------------------------

    def _flight_context(self) -> dict:
        """What the flight recorder folds into every post-mortem artifact:
        the front's control-plane state at dump time."""
        ctx: dict = {
            "queue_depth": len(self._queue),
            "brownout": self.brownout.summary(),
            "failovers": self.failovers,
            "breakers": {n: b.summary()
                         for n, b in sorted(self._breakers.items())},
        }
        if self.link_health is not None:
            ctx["link_health"] = self.link_health.summary()
        return ctx

    def health_summary(self) -> dict:
        """The ``/healthz`` body: degraded whenever any breaker left the
        closed state or brownout is active, ok otherwise. Read-only — no
        breaker probes, no controller side effects.

        The whole body is ONE consistent snapshot taken under the submit
        lock: a cluster router polls N replicas mid-transition, and without
        the lock it could read the queue after a pop but the record count
        before the finish (a request that exists nowhere), or a brownout
        level from a different instant than the queue depth it supposedly
        explains. Lock order is submit lock → controller locks; no
        controller ever calls back into the front, so the order is acyclic
        (threadlint EG102). ``inflight`` counts popped-but-unfinished
        requests so ``queue_depth + inflight + records`` always accounts for
        every admitted request."""
        with self._submit_lock:
            breakers = {n: b.summary()
                        for n, b in sorted(self._breakers.items())}
            open_names = [n for n, s in breakers.items()
                          if s.get("state") != "closed"]
            level = self.brownout.level
            health: dict = {
                "status": "degraded" if open_names or level else "ok",
                "open_breakers": open_names,
                "brownout_level": level,
                "queue_depth": len(self._queue),
                "inflight": len(self._inflight_rids),
                "records": self._agg["requests"],
                "failovers": self.failovers,
            }
            if self.link_health is not None:
                health["link_health"] = self.link_health.summary()
        return health

    def start_obs_server(self, port: int = 0) -> int:
        """Expose the live telemetry endpoint for this front —
        ``/healthz`` reports :meth:`health_summary` — and point the armed
        flight recorder (if any) at the front's control-plane context.
        Returns the bound port (``port=0`` = OS-assigned)."""
        if self._obs_server is None:
            self._obs_server = ObsServer(port, health_fn=self.health_summary)
            self._obs_server.start()
        fl = get_flight_recorder()
        if fl is not None:
            fl.set_context_provider(self._flight_context)
        port_ = self._obs_server.port
        assert port_ is not None  # started above
        return port_

    def stop_obs_server(self) -> None:
        if self._obs_server is not None:
            self._obs_server.stop()
            self._obs_server = None

    # -- graphlint hook ----------------------------------------------------

    def step_trace_spec(self, batch: int, prompt_len: int,
                        max_new_tokens: int,
                        temperature: float = 0.0) -> dict:
        """The static decode-step parameters this front would trace for a
        request of the given shape — what the ``frontend.decode-step-
        identity`` graphlint contract compares against direct ``generate``.
        ``uses_survivable_loop`` is False iff the front runs the untouched
        direct loop (default config)."""
        requested = max_new_tokens
        if self.config.max_new_tokens_cap is not None:
            requested = min(requested, self.config.max_new_tokens_cap)
        granted = self.brownout.token_cap(requested)
        return {
            "granted_tokens": granted,
            "capacity": _round_up(prompt_len + granted,
                                  self.config.capacity_round),
            "temperature": float(temperature),
            "compute_dtype": self.compute_dtype,
            "uses_survivable_loop": self._recovery_cfg(0) is not None,
        }
