"""Cluster-scale replica router: prefix-affinity placement, per-replica
failure isolation, and drain/respawn lifecycle.

One :class:`~edgellm_tpu.serve.frontend.ServeFront` is one replica: a mesh,
a batcher, a paged pool, and the overload control plane around them. This
module is the layer that makes N of those a *service*:

    ClusterFront.submit(Request)
      ── probe every live replica's radix index (`probe_prefix`) ──>
         route to the longest shared prefix (>= min_affinity_tokens),
         least-loaded fallback, deterministic (load, id) tiebreak
      ── per-replica CircuitBreaker + RetryBudget gate the candidates ──>
         replica.front.submit_ex(...)

    ClusterFront.drain()
      ── round-robin replica drains; every absorbed record feeds that
         replica's breaker ──>
         replica-fatal failure (stage_lost / watchdog / wedged batcher)
           → kill: flight-dump once, drain the queue + checkpoint the
             mid-flight streams (DecodeCheckpoint), re-admit elsewhere
             token-identically (counting recompute tokens), respawn from a
             clean plan after exponential backoff + jitter, re-admit to the
             rotation only after half-open probe requests succeed

Design rules:

- **Zero accepted loss.** Work a replica accepted is never dropped by the
  router: a dead replica's queue re-admits on survivors under the same
  seed (token-identical by construction), mid-flight streams resume from
  their checkpoint, and when no survivor can take a request it parks until
  one can. Only *fresh* submits are refused (``no_live_replica``) when the
  whole fleet is down — honest load shedding, recorded.
- **One sick replica cannot poison the fleet.** Routing consults each
  replica's own breaker and retry budget; a replica that keeps failing
  trips open and stops receiving placements while the rest serve on.
- **Determinism.** Everything runs on the injected clock; respawn jitter
  comes from a seeded RNG; candidate iteration is sorted by replica id.
  The same seed replays the same routing decisions.

The simulated replica (:class:`SimReplicaFront`) duck-types the slice of
the ``ServeFront`` surface the router uses and decodes with a pure
crc-chain token function on the virtual clock — the scale vehicle that
lets ``run_cluster_soak`` push ~10⁶ requests through the *real* router,
breakers, lifecycle, and autoscaler with memory held flat, while real-model
fleets (built by ``run.py``/tests) exercise the identical router code path
end to end.
"""
from __future__ import annotations

import collections
import dataclasses
import os
import struct
import threading
import zlib
from typing import Any, Callable, Optional

import numpy as np

from ..obs.flight import FlightRecorder, flight_dump_for
from ..obs.metrics import get_registry
from ..obs.tracing import span as obs_span
from ..utils.clock import MONOTONIC, Clock
from ..utils.concurrency import guarded_by
from .frontend import Request, RequestRecord
from .overload import (COMPLETED, FAILED, FAILED_OVER, REJECTED, SHED,
                       TIMED_OUT, BreakerConfig, CircuitBreaker,
                       DeadlineExpired, RetryBudget, RetryBudgetConfig,
                       ServeFrontConfigError, StragglerConfig,
                       StragglerDetector)
from .recovery import DecodeCheckpoint

__all__ = [
    "AutoscalerConfig", "ClusterConfig", "ClusterConfigError", "ClusterFront",
    "GrayConfig", "Replica", "ReplicaLostError", "RespawnConfig",
    "SimReplicaConfig", "SimReplicaFront", "drive_cluster",
    "sim_reference_tokens",
    "REPLICA_LIVE", "REPLICA_DEAD", "REPLICA_PROBING",
]

REPLICA_LIVE = "live"
REPLICA_DEAD = "dead"
REPLICA_PROBING = "probing"

#: record reasons that indict the replica, not the request — the router
#: kills and re-admits instead of failing the work
_REPLICA_FATAL_PREFIXES = ("stage_lost", "batcher:")
_REPLICA_FATAL_REASONS = ("watchdog",)


class ClusterConfigError(ServeFrontConfigError):
    """A ClusterConfig (or its nested blocks) failed validation."""


class ReplicaLostError(RuntimeError):
    """A replica left the rotation (chaos kill, fatal failure record). The
    router raises nothing — this type exists so the flight recorder has a
    typed failure instance to dump exactly once per kill."""

    def __init__(self, replica_id: int, reason: str):
        super().__init__(f"replica {replica_id} lost: {reason}")
        self.replica_id = replica_id
        self.reason = reason


@dataclasses.dataclass(frozen=True)
class RespawnConfig:
    """Dead-replica resurrection policy: exponential backoff with seeded
    jitter on the injected clock, then ``half_open_probes`` live requests
    must complete before the replica rejoins the rotation (the breaker
    half-open discipline, applied to a whole replica)."""

    backoff_base_s: float = 0.5
    backoff_factor: float = 2.0
    backoff_max_s: float = 30.0
    jitter_frac: float = 0.1
    jitter_seed: int = 0
    half_open_probes: int = 2

    def __post_init__(self):
        if self.backoff_base_s <= 0 or self.backoff_max_s <= 0:
            raise ClusterConfigError(
                f"backoff_base_s/backoff_max_s must be > 0, got "
                f"{self.backoff_base_s!r}/{self.backoff_max_s!r}")
        if self.backoff_factor < 1.0:
            raise ClusterConfigError(
                f"backoff_factor must be >= 1, got {self.backoff_factor!r}")
        if not 0.0 <= self.jitter_frac <= 1.0:
            raise ClusterConfigError(
                f"jitter_frac must be in [0, 1], got {self.jitter_frac!r}")
        if self.half_open_probes < 1:
            raise ClusterConfigError(
                f"half_open_probes must be >= 1, got "
                f"{self.half_open_probes!r}")


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Simulated autoscaler bounds: driven by the published
    ``edgellm_cluster_pressure`` gauge (mean per-replica ``load_fraction`` —
    queue fullness or brownout ladder position), with min-dwell hysteresis
    so the fleet cannot flap."""

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 8
    scale_up_pressure: float = 0.75
    scale_down_pressure: float = 0.15
    min_dwell_s: float = 30.0

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ClusterConfigError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas!r}/{self.max_replicas!r}")
        if not (0.0 <= self.scale_down_pressure
                < self.scale_up_pressure <= 1.0):
            raise ClusterConfigError(
                f"need 0 <= scale_down_pressure < scale_up_pressure <= 1, "
                f"got {self.scale_down_pressure!r}/"
                f"{self.scale_up_pressure!r}")
        if self.min_dwell_s < 0:
            raise ClusterConfigError(
                f"min_dwell_s must be >= 0, got {self.min_dwell_s!r}")


@dataclasses.dataclass(frozen=True)
class GrayConfig:
    """The gray-failure plane's policy bundle: straggler demotion, request
    hedging, and deadline propagation. A replica whose windowed p95 service
    latency reaches ``p95_multiple`` × the pooled fleet median is demoted
    in the placement sort (it still serves, but loses every tie); a request
    still running after the fleet's ``hedge_delay_quantile`` latency is
    re-placed on a second replica and the first finisher wins, with the
    loser cancelled or discarded exactly-once. ``max_hedge_fraction``
    bounds hedge dispatches relative to primary placements so the backup
    traffic cannot itself brown the fleet out."""

    enabled: bool = False
    p95_multiple: float = 3.0
    hedge_delay_quantile: float = 0.95
    min_dwell_s: float = 5.0
    max_hedge_fraction: float = 0.25
    min_samples: int = 8
    window_s: float = 120.0

    def __post_init__(self):
        if not isinstance(self.enabled, bool):
            raise ClusterConfigError(
                f"enabled must be a bool, got {self.enabled!r}")
        if self.p95_multiple <= 1.0:
            raise ClusterConfigError(
                f"p95_multiple must be > 1, got {self.p95_multiple!r}")
        if not 0.0 < self.hedge_delay_quantile < 1.0:
            raise ClusterConfigError(
                f"hedge_delay_quantile must be in (0, 1), got "
                f"{self.hedge_delay_quantile!r}")
        if self.min_dwell_s < 0:
            raise ClusterConfigError(
                f"min_dwell_s must be >= 0, got {self.min_dwell_s!r}")
        if not 0.0 <= self.max_hedge_fraction <= 1.0:
            raise ClusterConfigError(
                f"max_hedge_fraction must be in [0, 1], got "
                f"{self.max_hedge_fraction!r}")
        if isinstance(self.min_samples, bool) or not isinstance(
                self.min_samples, int) or self.min_samples < 1:
            raise ClusterConfigError(
                f"min_samples must be an int >= 1, got "
                f"{self.min_samples!r}")
        if self.window_s <= 0:
            raise ClusterConfigError(
                f"window_s must be > 0, got {self.window_s!r}")


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """The router's frozen policy bundle. ``min_affinity_tokens`` is the
    prefix-affinity threshold (a shorter match routes least-loaded instead);
    ``max_readmissions`` bounds failure-driven bounces per request before it
    fails terminally (admission refusals on survivors park instead — they
    never lose accepted work). ``flight_dir`` arms one flight recorder per
    replica; ``checkpoint_dir`` spools mid-flight DecodeCheckpoints during a
    replica drain."""

    num_replicas: int = 2
    min_affinity_tokens: int = 4
    probe_prefix: bool = True
    max_readmissions: int = 3
    breaker: BreakerConfig = dataclasses.field(default_factory=BreakerConfig)
    retry_budget: RetryBudgetConfig = dataclasses.field(
        default_factory=RetryBudgetConfig)
    respawn: RespawnConfig = dataclasses.field(default_factory=RespawnConfig)
    autoscaler: AutoscalerConfig = dataclasses.field(
        default_factory=AutoscalerConfig)
    gray: GrayConfig = dataclasses.field(default_factory=GrayConfig)
    flight_dir: Optional[str] = None
    checkpoint_dir: Optional[str] = None

    def __post_init__(self):
        if self.num_replicas < 1:
            raise ClusterConfigError(
                f"num_replicas must be >= 1, got {self.num_replicas!r}")
        if self.min_affinity_tokens < 1:
            raise ClusterConfigError(
                f"min_affinity_tokens must be >= 1, got "
                f"{self.min_affinity_tokens!r}")
        if self.max_readmissions < 0:
            raise ClusterConfigError(
                f"max_readmissions must be >= 0, got "
                f"{self.max_readmissions!r}")
        for field, cls in (("breaker", BreakerConfig),
                           ("retry_budget", RetryBudgetConfig),
                           ("respawn", RespawnConfig),
                           ("autoscaler", AutoscalerConfig),
                           ("gray", GrayConfig)):
            if not isinstance(getattr(self, field), cls):
                raise ClusterConfigError(
                    f"{field} must be a {cls.__name__}, got "
                    f"{type(getattr(self, field)).__name__}")


class Replica:
    """One replica's router-side state: the front, its breaker + retry
    budget, the lifecycle machine (live → dead → probing → live), and
    lifetime counters."""

    def __init__(self, replica_id: int, front: Any, breaker: CircuitBreaker,
                 budget: RetryBudget,
                 flight: Optional[FlightRecorder] = None):
        self.id = replica_id
        self.generation = 0
        self.front = front
        self.breaker = breaker
        self.budget = budget
        self.flight = flight
        self.state = REPLICA_LIVE
        self.died_at: Optional[float] = None
        self.respawn_at: Optional[float] = None
        self.backoff_attempt = 0
        self.probes_sent = 0
        self.probes_ok = 0
        # lifetime counters (survive respawns)
        self.placed = 0
        self.completed = 0
        self.failures = 0
        self.kills = 0
        self.respawns = 0

    def summary(self) -> dict:
        return {
            "state": self.state, "generation": self.generation,
            "placed": self.placed, "completed": self.completed,
            "failures": self.failures, "kills": self.kills,
            "respawns": self.respawns,
            "queue_depth": (self.front.queue_depth
                            if self.front is not None else None),
            "respawn_at": self.respawn_at,
            "breaker": self.breaker.summary(),
            "retry_budget": self.budget.summary(),
            "disagg": self.disagg_state(),
        }

    def disagg_state(self) -> Optional[dict]:
        """The front's disaggregation state (degraded flag + typed reason),
        or ``None`` when the replica serves a plain colocated batcher."""
        probe = getattr(self.front, "disagg_state", None)
        return probe() if callable(probe) else None

    def _disagg_penalty(self) -> int:
        """1 when this replica's disagg front has degraded to colocated
        serving, else 0 — folded into the placement sort keys so healthy
        disaggregated peers win ties and absorb new load first."""
        st = self.disagg_state()
        return 1 if (st is not None and st["degraded"]) else 0


@dataclasses.dataclass
class _Placement:
    """Router-side bookkeeping for one accepted request."""

    crid: int                       # cluster-level request id
    req: Request
    replica_id: int
    local_rid: int
    submitted_at: float
    generation: int = 0             # replica generation the leg was placed on
    resubmits: int = 0
    recompute_tokens: int = 0       # tokens regenerated after scratch readmits
    # hedge leg (gray-failure plane): a second, concurrently running copy
    # of the same request on another replica — first finisher wins
    hedge_replica_id: Optional[int] = None
    hedge_local_rid: Optional[int] = None
    hedge_generation: Optional[int] = None
    hedged_at: Optional[float] = None


@guarded_by("_lock", fields=["_seq", "_loose"])
class ClusterFront:
    """N replicas behind a prefix-affine, failure-isolating router.

    ``factory(replica_id, generation) -> front`` builds a replica front —
    a :class:`~edgellm_tpu.serve.frontend.ServeFront` (real mesh + batcher
    + paged pool; ``run.py`` builds these) or a :class:`SimReplicaFront`
    (the soak's scale vehicle). A respawn calls the factory again with a
    bumped generation: a *clean plan*, no state carried over.

    Threading contract: ``submit`` is thread-safe for id minting and the
    loose-record buffer (the declared lock); routing + drain are
    single-threaded, like ``ServeFront.drain``.
    """

    def __init__(self, factory: Callable[[int, int], Any],
                 config: Optional[ClusterConfig] = None, *,
                 clock: Clock = MONOTONIC):
        self.cfg = config if config is not None else ClusterConfig()
        self.factory = factory
        self.clock = clock
        self._lock = threading.Lock()
        self._seq = 0
        self.replicas: dict[int, Replica] = {}
        self._next_replica_id = self.cfg.num_replicas
        self._placements: dict[int, _Placement] = {}
        self._local_index: dict = {}  # (replica, generation, local_rid) -> crid
        self._parked: list = []       # [(crid, resume_payload | None)]
        self._loose: list = []        # terminal records minted outside drains
        self._jitter_rng = np.random.default_rng(self.cfg.respawn.jitter_seed)
        self._last_scale_at = self.clock()
        self.kills: list = []
        self.autoscale_events: list = []
        self.totals = {"placed": 0, "affinity": 0, "least_loaded": 0,
                       "probe": 0, "readmitted": 0, "recompute_tokens": 0,
                       "no_replica_rejects": 0, "parked_total": 0,
                       "hedges": 0, "hedge_wins_primary": 0,
                       "hedge_wins_hedge": 0, "hedge_cancelled": 0,
                       "hedge_discarded": 0, "hedge_refused": 0,
                       "deadline_expired": 0}
        gray = self.cfg.gray
        self._straggler: Optional[StragglerDetector] = (
            StragglerDetector(
                StragglerConfig(p95_multiple=gray.p95_multiple,
                                window_s=gray.window_s,
                                min_samples=gray.min_samples,
                                min_dwell_s=gray.min_dwell_s),
                clock=self.clock)
            if gray.enabled else None)
        self._gray_flagged: set = set()
        # losing hedge legs whose front could not cancel them: their late
        # records are swallowed on arrival (exactly-once accounting)
        self._hedge_discard: set = set()
        for i in range(self.cfg.num_replicas):
            self.replicas[i] = self._new_replica(i)

    # -- replica construction / lifecycle ----------------------------------

    def _new_replica(self, replica_id: int) -> Replica:
        flight = None
        if self.cfg.flight_dir is not None:
            flight = FlightRecorder(
                os.path.join(self.cfg.flight_dir, f"replica{replica_id}"),
                clock=self.clock)
        return Replica(
            replica_id, self.factory(replica_id, 0),
            CircuitBreaker(f"replica{replica_id}", self.cfg.breaker,
                           clock=self.clock),
            RetryBudget(self.cfg.retry_budget, clock=self.clock),
            flight=flight)

    def kill_replica(self, replica_id: int, reason: str = "chaos") -> None:
        """Operator/chaos entry point: drain + kill one replica now (same
        path a replica-fatal failure record takes)."""
        r = self.replicas.get(replica_id)
        if r is None or r.state == REPLICA_DEAD:
            return
        self._kill(r, reason)

    def _kill(self, r: Replica, reason: str) -> None:
        now = self.clock()
        with obs_span("cluster.kill", replica=r.id, reason=reason):
            # exactly one post-mortem per induced failure: the exception
            # instance carries the recorder latch
            exc = ReplicaLostError(r.id, reason)
            if r.flight is not None:
                # NB: "reason" is dump()'s positional (the exception type
                # name) — the kill cause rides as kill_reason
                r.flight.dump_for(exc, replica=r.id, kill_reason=reason,
                                  generation=r.generation)
            else:
                flight_dump_for(exc, replica=r.id, kill_reason=reason,
                                generation=r.generation)
            front = r.front
            r.state = REPLICA_DEAD   # before re-placement: never a candidate
            r.kills += 1
            r.failures += 1
            r.died_at = now
            r.backoff_attempt += 1
            rs = self.cfg.respawn
            backoff = min(rs.backoff_base_s
                          * rs.backoff_factor ** (r.backoff_attempt - 1),
                          rs.backoff_max_s)
            backoff *= 1.0 + rs.jitter_frac * float(self._jitter_rng.random())
            r.respawn_at = now + backoff
            r.breaker.trip()
            self.kills.append({"replica": r.id, "at_s": now,
                               "reason": reason, "respawn_at": r.respawn_at})
            if front is None:
                return
            r.front = None
            # 1) queued work: nothing computed yet — re-admit from scratch,
            #    token-identical under the same seed, zero recompute
            for local_rid, req in front.drain_pending():
                crid = self._local_index.pop(
                    (r.id, r.generation, local_rid), None)
                if crid is not None and not self._detach_leg(crid, r.id):
                    self._readmit(crid, resume=None)
            # 2) mid-flight work: checkpoint via DecodeCheckpoint and resume
            #    elsewhere (or re-run from scratch, counting the tokens the
            #    dead replica had already produced as recompute)
            ckpt = getattr(front, "checkpoint_inflight", None)
            if ckpt is not None:
                for item in ckpt(self.cfg.checkpoint_dir):
                    crid = self._local_index.pop(
                        (r.id, r.generation, item["local_rid"]), None)
                    if crid is not None and not self._detach_leg(crid, r.id):
                        self._readmit(crid, resume=item)

    def _respawn(self, r: Replica) -> None:
        with obs_span("cluster.respawn", replica=r.id,
                      generation=r.generation + 1):
            r.generation += 1
            r.front = self.factory(r.id, r.generation)
            r.breaker.reset()
            r.budget = RetryBudget(self.cfg.retry_budget, clock=self.clock)
            r.state = REPLICA_PROBING
            r.probes_sent = 0
            r.probes_ok = 0
            r.respawns += 1
            r.respawn_at = None

    def _tick(self) -> None:
        """Lifecycle pass: due respawns, parked re-placement, gauges,
        autoscale. Called from submit and drain — cheap when idle."""
        now = self.clock()
        for rid in sorted(self.replicas):
            r = self.replicas[rid]
            if (r.state == REPLICA_DEAD and r.respawn_at is not None
                    and now >= r.respawn_at):
                self._respawn(r)
        if self._parked:
            # swap the list out before iterating: a failed re-placement
            # re-parks through _absorb, which appends to self._parked — and
            # appending to the list under iteration would retry the same
            # request forever inside this loop
            parked, self._parked = self._parked, []
            # starvation guard: cluster ids mint in arrival order, so the
            # oldest parked request gets first claim on freed capacity no
            # matter how it bounced back into the park list
            parked.sort(key=lambda item: item[0])
            for crid, resume in parked:
                target, _ = self._place(self._placements[crid].req)
                if target is not None:
                    # don't bounce off a saturated survivor every tick —
                    # stay parked until someone has room
                    lf = getattr(target.front, "load_fraction", None)
                    if lf is not None and lf() >= 1.0:
                        target = None
                if target is None:
                    self._parked.append((crid, resume))
                else:
                    self._readmit_to(target, crid, resume)
        if self._straggler is not None:
            self._gray_tick(now)
        self._publish()
        if self.cfg.autoscaler.enabled:
            self._autoscale(now)

    # -- placement ----------------------------------------------------------

    def _candidates(self) -> list:
        """Replicas that may take a fresh placement, sorted by id. A probing
        replica with probe quota left comes FIRST — it needs live traffic to
        prove itself (the half-open discipline)."""
        probing, live = [], []
        for rid in sorted(self.replicas):
            r = self.replicas[rid]
            if r.front is None:
                continue
            if (r.state == REPLICA_PROBING
                    and r.probes_sent < self.cfg.respawn.half_open_probes):
                probing.append(r)
            elif r.state == REPLICA_LIVE:
                if r.breaker.state == "open":
                    continue
                if r.budget.exhausted():
                    r.budget.deny()
                    continue
                live.append(r)
        return probing + live

    def _place(self, req: Request) -> tuple:
        """Pick a replica for this request; returns (Replica | None, how).

        Order: half-open probes first, then longest shared prefix at or
        above ``min_affinity_tokens`` (ties: least-loaded, then lowest id),
        then least-loaded (same tiebreak). Deterministic for a fixed fleet
        state — the soak replays its routing."""
        cands = self._candidates()
        if not cands:
            return None, "no_live_replica"
        first = cands[0]
        if first.state == REPLICA_PROBING:
            return first, "probe"
        if self.cfg.probe_prefix:
            best = None
            for r in cands:
                shared = r.front.probe_prefix(req.prompt_ids)
                if shared >= self.cfg.min_affinity_tokens:
                    # a degraded disagg replica still wins on a strong
                    # prefix hit (the shared KV outweighs colocated
                    # throughput) but loses every tie to a healthy peer;
                    # a flagged straggler is demoted the same way
                    key = (-shared,
                           r._disagg_penalty() + self._gray_penalty(r),
                           r.front.queue_depth, r.id)
                    if best is None or key < best[0]:
                        best = (key, r)
            if best is not None:
                return best[1], "affinity"
        r = min(cands, key=lambda c: (c._disagg_penalty()
                                      + self._gray_penalty(c),
                                      c.front.queue_depth, c.id))
        return r, "least_loaded"

    def _gray_penalty(self, r: Replica) -> int:
        """1 when the straggler detector currently flags this replica (it
        loses every placement tie, like a degraded disagg front), else 0.
        Zero-cost identity when the gray plane is disabled: the sort keys
        collapse to the pre-gray ordering."""
        if self._straggler is None:
            return 0
        return 1 if r.id in self._gray_flagged else 0

    def submit(self, req: Request) -> int:
        """Route one request onto the fleet; returns the cluster request id.
        With no routable replica the request is refused with a terminal
        ``no_live_replica`` record (flushed by the next :meth:`drain`)."""
        self._tick()
        now = self.clock()
        with self._lock:
            self._seq += 1
            crid = self._seq
        target, how = self._place(req)
        if target is None:
            self.totals["no_replica_rejects"] += 1
            rec = self._refusal_record(crid, req, now)
            with self._lock:
                self._loose.append(rec)
            return crid
        if target.state == REPLICA_PROBING:
            target.probes_sent += 1
        self.totals["placed"] += 1
        self.totals[how if how in ("affinity", "least_loaded", "probe")
                    else "least_loaded"] += 1
        target.placed += 1
        local_rid, refusal = self._submit_to(target, req)
        self._placements[crid] = _Placement(
            crid=crid, req=req, replica_id=target.id, local_rid=local_rid,
            submitted_at=now, generation=target.generation)
        self._local_index[(target.id, target.generation, local_rid)] = crid
        if refusal is not None:
            # replica-level admission refusal, already terminal there —
            # absorb it through the normal path so breakers/probes see it
            final = self._absorb(target, refusal)
            if final is not None:
                with self._lock:
                    self._loose.append(final)
        reg = get_registry()
        if reg.enabled:
            reg.counter("edgellm_cluster_placements_total",
                        "router placements by policy").inc(policy=how)
        return crid

    def _submit_to(self, r: Replica, req: Request) -> tuple:
        sub_ex = getattr(r.front, "submit_ex", None)
        if sub_ex is not None:
            return sub_ex(req)
        return r.front.submit(req), None

    def _refusal_record(self, crid: int, req: Request,
                        now: float) -> RequestRecord:
        prompt = np.asarray(req.prompt_ids)
        b = 1 if prompt.ndim <= 1 else int(prompt.shape[0])
        s = int(prompt.size) // max(b, 1)
        return RequestRecord(
            request_id=crid, outcome=REJECTED, reason="no_live_replica",
            backend=None, priority=req.priority, submitted_at=now,
            started_at=None, finished_at=None, queue_wait_s=None, ttft_s=None,
            service_s=None, latency_s=None, deadline_s=req.deadline_s,
            deadline_met=None, prompt_tokens=s,
            requested_tokens=req.max_new_tokens, granted_tokens=None,
            capacity=None, batch=b, plan={"replica": None},
            brownout_level=0, retries_charged=0, jit_misses=None,
            tokens=None, recovery=None)

    # -- re-admission -------------------------------------------------------

    def _readmit(self, crid: int, resume: Optional[dict]) -> None:
        """Re-place one accepted request after its replica died. Bounded by
        ``max_readmissions`` for failure bounces; parks when no survivor can
        take it (accepted work is never dropped)."""
        pl = self._placements[crid]
        pl.resubmits += 1
        self.totals["readmitted"] += 1
        if pl.resubmits > self.cfg.max_readmissions:
            rec = dataclasses.replace(
                self._refusal_record(crid, pl.req, self.clock()),
                outcome=FAILED, reason="readmission_exhausted",
                submitted_at=pl.submitted_at,
                recovery={"readmissions": pl.resubmits,
                          "recompute_tokens": pl.recompute_tokens})
            del self._placements[crid]
            with self._lock:
                self._loose.append(rec)
            return
        target, _ = self._place(pl.req)
        if target is None:
            self.totals["parked_total"] += 1
            self._parked.append((crid, resume))
            return
        self._readmit_to(target, crid, resume)

    def _readmit_to(self, target: Replica, crid: int,
                    resume: Optional[dict]) -> None:
        pl = self._placements[crid]
        now = self.clock()
        remaining = self._remaining_deadline(pl, now)
        if remaining is not None and remaining <= 0.0:
            # deadline audit: admission checks the wait at enqueue, but a
            # park (or a kill + backoff) can eat the whole budget before
            # placement ever happens — finish timed_out here instead of
            # dispatching work nobody can use
            self._expire_placement(crid, now)
            return
        restore = getattr(target.front, "restore_inflight", None)
        if resume is not None and restore is not None:
            # checkpointed stream resumes where it stopped: token-identical
            # continuation, zero recompute
            local_rid = restore(resume)
            refusal = None
        else:
            if resume is not None:
                # scratch re-run: the tokens the dead replica already
                # produced are recomputed on the survivor
                pl.recompute_tokens += int(resume.get("tokens_done", 0))
                self.totals["recompute_tokens"] += int(
                    resume.get("tokens_done", 0))
            # deadline propagation: the survivor sees only the budget that
            # is still left, so its own admission/queue checks refuse work
            # that can no longer finish in time
            local_rid, refusal = self._submit_to(
                target, self._effective_req(pl, now))
        if target.state == REPLICA_PROBING:
            target.probes_sent += 1
        target.placed += 1
        pl.replica_id = target.id
        pl.local_rid = local_rid
        pl.generation = target.generation
        self._local_index[(target.id, target.generation, local_rid)] = crid
        if refusal is not None:
            final = self._absorb(target, refusal)
            if final is not None:
                with self._lock:
                    self._loose.append(final)

    def _remaining_deadline(self, pl: _Placement,
                            now: float) -> Optional[float]:
        if pl.req.deadline_s is None:
            return None
        return pl.req.deadline_s - (now - pl.submitted_at)

    def _effective_req(self, pl: _Placement, now: float) -> Request:
        """The request with its deadline decremented by the budget already
        spent at this router (park→place→queue→…): what a downstream stage
        may still burn. ``_finalize`` restores the original deadline on the
        way out, so records always carry the caller's contract."""
        remaining = self._remaining_deadline(pl, now)
        if remaining is None:
            return pl.req
        return dataclasses.replace(pl.req, deadline_s=remaining)

    def _expire_placement(self, crid: int, now: float) -> None:
        """Finish an accepted-but-expired request as ``timed_out`` with the
        typed ``deadline_expired`` reason (:class:`DeadlineExpired`)."""
        pl = self._placements.pop(crid)
        self.totals["deadline_expired"] += 1
        rec = dataclasses.replace(
            self._refusal_record(crid, pl.req, now),
            outcome=TIMED_OUT, reason=DeadlineExpired.reason,
            submitted_at=pl.submitted_at,
            queue_wait_s=now - pl.submitted_at, deadline_met=False,
            recovery=({"readmissions": pl.resubmits,
                       "recompute_tokens": pl.recompute_tokens}
                      if pl.resubmits else None))
        reg = get_registry()
        if reg.enabled:
            reg.counter("edgellm_gray_deadline_expired_total",
                        "requests refused after their deadline budget "
                        "expired pre-dispatch").inc()
        with self._lock:
            self._loose.append(rec)

    # -- drain / absorption -------------------------------------------------

    def drain(self, max_requests: Optional[int] = None) -> list:
        """Round-robin the live fleet until ``max_requests`` cluster-level
        terminal records are collected or nothing makes progress. Returns
        the records (request ids are CLUSTER ids; ``plan["replica"]`` names
        the serving replica)."""
        self._tick()
        out: list = []

        def flush_loose() -> None:
            with self._lock:
                while self._loose and (max_requests is None
                                       or len(out) < max_requests):
                    out.append(self._loose.pop(0))

        flush_loose()
        while max_requests is None or len(out) < max_requests:
            progress = False
            for rid in list(sorted(self.replicas)):
                r = self.replicas.get(rid)
                if r is None or r.front is None or r.state == REPLICA_DEAD:
                    continue
                if getattr(r.front, "batcher", None) is not None:
                    # a continuous-batching replica serves its whole queue
                    # through ONE ragged-step event loop — fairness is the
                    # round-robin over replicas, not over requests; overflow
                    # past the caller's cap parks in the loose buffer
                    recs = r.front.drain_batched()
                else:
                    recs = r.front.drain(max_requests=1)
                if recs:
                    progress = True
                    for rec in recs:
                        final = self._absorb(r, rec)
                        if final is not None:
                            out.append(final)
            self._tick()
            flush_loose()
            if not progress:
                break
        if max_requests is not None and len(out) > max_requests:
            # a batched replica drain can overshoot the cap in one pass
            with self._lock:
                self._loose[:0] = out[max_requests:]
            out = out[:max_requests]
        return out

    def _absorb(self, r: Replica, rec: RequestRecord
                ) -> Optional[RequestRecord]:
        """Fold one replica-local record into router state. Returns the
        finalized cluster-level record, or None when the record was
        absorbed (a replica-fatal failure whose request re-admitted)."""
        key = (r.id, r.generation, rec.request_id)
        crid = self._local_index.pop(key, None)
        if crid is None:
            if key in self._hedge_discard:
                # the losing leg of a settled hedge finished late on a
                # front without cancel support: exactly-once accounting
                # swallows its record here, never surfacing a duplicate
                self._hedge_discard.discard(key)
                self.totals["hedge_discarded"] += 1
                return None
            # not ours (e.g. a stream the replica served before adoption) —
            # surface verbatim rather than silently dropping
            return rec
        pl = self._placements[crid]
        hedged = pl.hedge_replica_id is not None
        from_hedge_leg = (hedged and r.id == pl.hedge_replica_id
                          and rec.request_id == pl.hedge_local_rid)
        r.budget.charge(rec.retries_charged)
        if rec.outcome in (COMPLETED, FAILED_OVER):
            r.breaker.record_success()
            r.completed += 1
            self._probe_result(r, ok=True)
            self._observe_latency(r, rec)
            if hedged:
                # first finisher wins: cancel/discard the other leg
                self._settle_hedge(pl, winner_hedge=from_hedge_leg)
            return self._finalize(r, rec, pl)
        if rec.outcome == FAILED:
            replica_fatal = (rec.reason.startswith(_REPLICA_FATAL_PREFIXES)
                             or rec.reason in _REPLICA_FATAL_REASONS)
            r.breaker.record_failure()
            r.failures += 1
            self._probe_result(r, ok=False)
            if replica_fatal:
                if r.state != REPLICA_DEAD:
                    self._kill(r, rec.reason)
                # _kill's drain may already have detached/promoted legs of
                # this placement; only readmit when no leg still covers it
                if hedged and self._detach_leg(crid, r.id):
                    return None
                self._readmit(crid, resume=None)
                return None
            if hedged:
                # one leg failed non-fatally; the other may still finish
                # clean — drop this leg only (the breaker already saw it)
                self._detach_leg(crid, r.id)
                return None
            return self._finalize(r, rec, pl)
        if rec.outcome == TIMED_OUT:
            # legs carry decremented deadlines, so one leg expiring means
            # the request's global budget is gone — settle the other leg
            # and finish timed_out
            if hedged:
                self._settle_hedge(pl, winner_hedge=from_hedge_leg)
            return self._finalize(r, rec, pl)
        # REJECTED / SHED
        if hedged:
            # an admission refusal on one leg of a still-covered request:
            # detach the refused leg, let the other run
            self._detach_leg(crid, r.id)
            return None
        if rec.outcome in (REJECTED, SHED) and pl.resubmits > 0:
            # a survivor's admission control refused re-admitted work: park
            # and retry later — accepted work is never lost to a refusal
            self.totals["parked_total"] += 1
            self._parked.append((crid, None))
            return None
        return self._finalize(r, rec, pl)

    # -- the gray-failure plane ---------------------------------------------

    def _observe_latency(self, r: Replica, rec: RequestRecord) -> None:
        """Feed one completed leg's end-to-end latency into the straggler
        detector (keyed by replica id)."""
        if self._straggler is None:
            return
        sample = rec.latency_s if rec.latency_s is not None else rec.service_s
        if sample is not None:
            self._straggler.observe(r.id, float(sample))

    def _gray_tick(self, now: float) -> None:
        """Refresh the straggler verdict set (spanned on every flip) and run
        one hedge pass over still-running placements."""
        flagged = set(self._straggler.stragglers())
        reg = get_registry()
        for rid in sorted(flagged - self._gray_flagged):
            with obs_span("gray.demote", replica=rid, direction="demote"):
                if reg.enabled:
                    reg.counter("edgellm_gray_demotions_total",
                                "straggler demotions (replica flagged "
                                "slow)").inc()
        for rid in sorted(self._gray_flagged - flagged):
            with obs_span("gray.demote", replica=rid, direction="promote"):
                pass
        self._gray_flagged = flagged
        self._hedge_tick(now)

    def _hedge_tick(self, now: float) -> None:
        """Hedge requests that have been running longer than the fleet's
        ``hedge_delay_quantile`` latency: re-place a second copy on another
        replica, first finisher wins. Bounded by ``max_hedge_fraction`` of
        primary placements; silent until the detector has samples."""
        gray = self.cfg.gray
        delay = self._straggler.fleet_quantile(gray.hedge_delay_quantile,
                                               exclude=self._gray_flagged)
        if delay is None:
            return
        parked = {crid for crid, _ in self._parked}
        for crid in sorted(self._placements):
            pl = self._placements.get(crid)
            if pl is None or pl.hedge_replica_id is not None:
                continue
            if crid in parked:
                continue   # not running anywhere: a park, not a straggle
            if now - pl.submitted_at <= delay:
                continue
            if (self.totals["hedges"] + 1
                    > gray.max_hedge_fraction
                    * max(self.totals["placed"], 1)):
                return     # hedge budget spent for now
            self._hedge(pl, now)

    def _hedge(self, pl: _Placement, now: float) -> None:
        remaining = self._remaining_deadline(pl, now)
        if remaining is not None and remaining <= 0.0:
            return   # budget already gone: the running leg times out alone
        cands = [c for c in self._candidates()
                 if c.state == REPLICA_LIVE and c.id != pl.replica_id
                 and c.id not in self._gray_flagged]
        ready = []
        for c in cands:
            lf = getattr(c.front, "load_fraction", None)
            if lf is None or lf() < 1.0:
                ready.append(c)
        if not ready:
            return
        target = min(ready, key=lambda c: (c._disagg_penalty(),
                                           c.front.queue_depth, c.id))
        with obs_span("cluster.hedge", crid=pl.crid,
                      primary=pl.replica_id, target=target.id):
            local_rid, refusal = self._submit_to(
                target, self._effective_req(pl, now))
            if refusal is not None:
                self.totals["hedge_refused"] += 1
                return
            target.placed += 1
            pl.hedge_replica_id = target.id
            pl.hedge_local_rid = local_rid
            pl.hedge_generation = target.generation
            pl.hedged_at = now
            self._local_index[(target.id, target.generation,
                               local_rid)] = pl.crid
            self.totals["hedges"] += 1
            reg = get_registry()
            if reg.enabled:
                reg.counter("edgellm_gray_hedges_total",
                            "hedge legs dispatched").inc()

    def _settle_hedge(self, pl: _Placement, winner_hedge: bool) -> None:
        """One leg of a hedged placement went terminal: cancel the loser
        where the front supports it, otherwise mark its key for discard so
        its late record is swallowed (exactly-once)."""
        if winner_hedge:
            loser_key = (pl.replica_id, pl.generation, pl.local_rid)
            self.totals["hedge_wins_hedge"] += 1
            win_leg = "hedge"
        else:
            loser_key = (pl.hedge_replica_id, pl.hedge_generation,
                         pl.hedge_local_rid)
            self.totals["hedge_wins_primary"] += 1
            win_leg = "primary"
        if self._local_index.pop(loser_key, None) is not None:
            loser = self.replicas.get(loser_key[0])
            cancel = (getattr(loser.front, "cancel", None)
                      if loser is not None and loser.front is not None
                      else None)
            if cancel is not None and cancel(loser_key[2]):
                self.totals["hedge_cancelled"] += 1
            else:
                self._hedge_discard.add(loser_key)
        if winner_hedge:
            # promote the winning hedge leg so _finalize and any later
            # bookkeeping see a coherent single-leg placement
            pl.replica_id = pl.hedge_replica_id
            pl.local_rid = pl.hedge_local_rid
            pl.generation = pl.hedge_generation
        pl.hedge_replica_id = None
        pl.hedge_local_rid = None
        pl.hedge_generation = None
        pl.hedged_at = None
        reg = get_registry()
        if reg.enabled:
            reg.counter("edgellm_gray_hedge_wins_total",
                        "settled hedges by winning leg").inc(leg=win_leg)

    def _detach_leg(self, crid: int, replica_id: int) -> bool:
        """Drop one leg of a hedged placement (its replica died, scaled
        away, or refused the work). Returns True when the other leg still
        covers the request — the caller must NOT readmit. False when the
        placement was not hedged (single-leg: normal recovery applies)."""
        pl = self._placements.get(crid)
        if pl is None or pl.hedge_replica_id is None:
            return False
        if replica_id == pl.hedge_replica_id:
            pass                       # hedge leg lost: primary covers
        elif replica_id == pl.replica_id:
            # primary lost: the hedge leg is the request now
            pl.replica_id = pl.hedge_replica_id
            pl.local_rid = pl.hedge_local_rid
            pl.generation = pl.hedge_generation
        else:
            return False
        pl.hedge_replica_id = None
        pl.hedge_local_rid = None
        pl.hedge_generation = None
        pl.hedged_at = None
        return True

    def _probe_result(self, r: Replica, ok: bool) -> None:
        if r.state != REPLICA_PROBING:
            return
        if not ok:
            # a failed probe re-opens: another backoff round (longer — the
            # attempt counter is still climbing)
            if r.state != REPLICA_DEAD:
                self._kill(r, "probe_failed")
            return
        r.probes_ok += 1
        if r.probes_ok >= self.cfg.respawn.half_open_probes:
            r.state = REPLICA_LIVE
            r.backoff_attempt = 0

    def _finalize(self, r: Replica, rec: RequestRecord,
                  pl: _Placement) -> RequestRecord:
        del self._placements[pl.crid]
        plan = dict(rec.plan) if rec.plan else {}
        plan["replica"] = r.id
        recovery = rec.recovery
        if pl.resubmits:
            recovery = dict(recovery or {})
            recovery["readmissions"] = pl.resubmits
            recovery["recompute_tokens"] = pl.recompute_tokens
        return dataclasses.replace(
            rec, request_id=pl.crid, plan=plan, recovery=recovery,
            submitted_at=pl.submitted_at,
            # a readmitted/hedged leg ran under a decremented deadline;
            # the cluster record restores the caller's original contract
            deadline_s=pl.req.deadline_s)

    # -- autoscaler ---------------------------------------------------------

    def _fleet_pressure(self) -> float:
        loads = []
        for r in self.replicas.values():
            if r.state != REPLICA_LIVE or r.front is None:
                continue
            lf = getattr(r.front, "load_fraction", None)
            # host-side router bookkeeping: load_fraction is a plain
            # python float, not a device value
            loads.append(float(lf()) if lf is not None else 0.0)  # graphlint: disable=EG005
        if not loads:
            return 1.0   # a fleet with zero live replicas is saturated
        return float(sum(loads) / len(loads))

    def _publish(self) -> None:
        reg = get_registry()
        if not reg.enabled:
            return
        live = sum(1 for r in self.replicas.values()
                   if r.state == REPLICA_LIVE)
        reg.gauge("edgellm_cluster_replicas",
                  "replicas in the fleet (any state)").set(
            len(self.replicas))
        reg.gauge("edgellm_cluster_live_replicas",
                  "replicas currently serving").set(live)
        reg.gauge("edgellm_cluster_parked",
                  "accepted requests waiting for a routable replica").set(
            len(self._parked))
        reg.gauge("edgellm_cluster_pressure",
                  "mean live-replica load fraction").set(
            self._fleet_pressure())
        if self._straggler is not None:
            reg.gauge("edgellm_gray_stragglers",
                      "replicas currently flagged slow").set(
                len(self._gray_flagged))
            delay = self._straggler.fleet_quantile(
                self.cfg.gray.hedge_delay_quantile,
                exclude=self._gray_flagged)
            if delay is not None:
                reg.gauge("edgellm_gray_hedge_delay_s",
                          "current hedge trigger delay (fleet latency "
                          "quantile)").set(delay)

    def _autoscale(self, now: float) -> None:
        """Simulated autoscaler, driven by the published
        ``edgellm_cluster_pressure`` gauge when observability is armed (the
        locally computed value otherwise — same number, no scrape loop)."""
        reg = get_registry()
        if reg.enabled:
            pressure = reg.gauge("edgellm_cluster_pressure",
                                 "mean live-replica load fraction").value()
        else:
            pressure = self._fleet_pressure()
        if now - self._last_scale_at < self.cfg.autoscaler.min_dwell_s:
            return
        live = [r for r in self.replicas.values()
                if r.state == REPLICA_LIVE and r.front is not None]
        asc = self.cfg.autoscaler
        if pressure >= asc.scale_up_pressure and len(live) < asc.max_replicas:
            with obs_span("cluster.autoscale", direction="up"):
                rid = self._next_replica_id
                self._next_replica_id += 1
                self.replicas[rid] = self._new_replica(rid)
                self._last_scale_at = now
                self.autoscale_events.append(
                    {"at_s": now, "direction": "up", "replica": rid,
                     "pressure": pressure})
        elif (pressure <= asc.scale_down_pressure
              and len(live) > asc.min_replicas):
            with obs_span("cluster.autoscale", direction="down"):
                victim = min(live, key=lambda r: (r.front.queue_depth, -r.id))
                front = victim.front
                victim.state = REPLICA_DEAD
                victim.front = None
                victim.respawn_at = None   # scaled away, not respawning
                for local_rid, req in front.drain_pending():
                    crid = self._local_index.pop(
                        (victim.id, victim.generation, local_rid), None)
                    if crid is not None and not self._detach_leg(
                            crid, victim.id):
                        self._readmit(crid, resume=None)
                ckpt = getattr(front, "checkpoint_inflight", None)
                if ckpt is not None:
                    for item in ckpt(self.cfg.checkpoint_dir):
                        crid = self._local_index.pop(
                            (victim.id, victim.generation,
                             item["local_rid"]), None)
                        if crid is not None and not self._detach_leg(
                                crid, victim.id):
                            self._readmit(crid, resume=item)
                del self.replicas[victim.id]
                self._last_scale_at = now
                self.autoscale_events.append(
                    {"at_s": now, "direction": "down", "replica": victim.id,
                     "pressure": pressure})

    # -- introspection ------------------------------------------------------

    @property
    def pending(self) -> int:
        """Accepted requests not yet terminal (in a queue, mid-flight, or
        parked)."""
        return len(self._placements)

    @property
    def busy(self) -> bool:
        return any(getattr(r.front, "busy", False)
                   for r in self.replicas.values() if r.front is not None)

    def next_event_s(self) -> Optional[float]:
        """The next scheduled instant anywhere in the fleet — the earliest
        pending respawn or simulated-replica phase completion. The soak
        driver advances the virtual clock here when a drain pass returns
        nothing (real replica fronts expose no ``next_event_s`` and do
        their work on the spot instead)."""
        times = [r.respawn_at for r in self.replicas.values()
                 if r.state == REPLICA_DEAD and r.respawn_at is not None]
        for r in self.replicas.values():
            if r.state == REPLICA_DEAD or r.front is None:
                continue
            nxt = getattr(r.front, "next_event_s", None)
            if nxt is not None:
                t = nxt()
                if t is not None:
                    times.append(t)
        return min(times) if times else None

    def flight_dumps(self) -> list:
        """Every per-replica post-mortem artifact path, in replica order."""
        out = []
        for rid in sorted(self.replicas):
            fl = self.replicas[rid].flight
            if fl is not None:
                out.extend(fl.dumps())
        return out

    def report(self) -> dict:
        rep = {
            "replicas": {rid: self.replicas[rid].summary()
                         for rid in sorted(self.replicas)},
            "totals": dict(self.totals),
            "pending": self.pending,
            "parked": len(self._parked),
            "kills": list(self.kills),
            "autoscale_events": list(self.autoscale_events),
            "pressure": self._fleet_pressure(),
            "gray": (None if self._straggler is None else {
                "flagged": sorted(self._gray_flagged),
                "hedge_delay_s": self._straggler.fleet_quantile(
                    self.cfg.gray.hedge_delay_quantile,
                    exclude=self._gray_flagged),
                "detector": self._straggler.summary(),
            }),
        }
        # counters in record_cluster_stats carry running totals: the
        # end-of-run consumer absorbs the final report exactly once
        return rep

    def health_summary(self) -> dict:
        states = {rid: self.replicas[rid].state
                  for rid in sorted(self.replicas)}
        live = sum(1 for s in states.values() if s == REPLICA_LIVE)
        return {
            "status": ("ok" if live == len(states) and states
                       else "degraded" if live else "down"),
            "replicas": states,
            "live": live,
            "pending": self.pending,
            "parked": len(self._parked),
        }


# ---------------------------------------------------------------------------
# the simulated replica: the 10⁶-request scale vehicle
# ---------------------------------------------------------------------------


def _crc(data: bytes, start: int = 0) -> int:
    return zlib.crc32(data, start) & 0xFFFFFFFF


def sim_reference_tokens(prompt: np.ndarray, n: int, *,
                         temperature: float = 0.0, rng_seed: int = 0,
                         vocab_size: int = 50_000,
                         start: int = 0, chain: Optional[int] = None
                         ) -> tuple:
    """The sim engine's pure decode function: a crc32 chain over (prompt,
    temperature bucket, seed, step). Deterministic and fault-free by
    construction — the identity replay recomputes it per completed request.
    Greedy (``temperature == 0``) depends only on the prompt; a sampled
    request folds in its recorded seed, mirroring the real stack's
    seed-pinned sampling streams. Returns ``(tokens[start:n], chain)`` so a
    checkpointed stream resumes the chain mid-sequence bit-identically."""
    if chain is None:
        h = _crc(np.ascontiguousarray(prompt, dtype=np.int64).tobytes())
        if temperature > 0.0:
            h = _crc(struct.pack("<dq", float(temperature), int(rng_seed)), h)
        for t in range(start):
            h = _crc(struct.pack("<q", t), h)
    else:
        h = int(chain)
    out = np.empty(max(n - start, 0), np.int32)
    for i, t in enumerate(range(start, n)):
        h = _crc(struct.pack("<q", t), h)
        out[i] = h % vocab_size
    return out, h


@dataclasses.dataclass(frozen=True)
class SimReplicaConfig:
    """One simulated replica's capacity model. ``chunk_tokens`` is the
    scheduler quantum — each ``drain`` call advances the running stream by
    at most this many tokens, so chaos lands mid-request and the
    DecodeCheckpoint drain path is real, not theoretical."""

    vocab_size: int = 50_000
    prefill_s_per_token: float = 1e-4
    decode_s_per_token: float = 2e-3
    chunk_tokens: int = 4
    max_queue_depth: int = 64
    prefix_block: int = 4
    index_capacity: int = 50_000
    # gray plane: refuse work whose (decremented) deadline has already
    # passed at prefill/decode chunk boundaries instead of burning tokens.
    # Off by default so a gray-disabled fleet behaves bit-identically.
    deadline_propagation: bool = False

    def __post_init__(self):
        if not isinstance(self.deadline_propagation, bool):
            raise ClusterConfigError(
                f"deadline_propagation must be a bool, got "
                f"{self.deadline_propagation!r}")
        if self.chunk_tokens < 1:
            raise ClusterConfigError(
                f"chunk_tokens must be >= 1, got {self.chunk_tokens!r}")
        if self.max_queue_depth < 1:
            raise ClusterConfigError(
                f"max_queue_depth must be >= 1, got "
                f"{self.max_queue_depth!r}")
        if self.prefix_block < 1:
            raise ClusterConfigError(
                f"prefix_block must be >= 1, got {self.prefix_block!r}")


@dataclasses.dataclass
class _SimStream:
    rid: int
    req: Request
    prompt: np.ndarray
    submitted_at: float
    started_at: Optional[float]     # None while prefill is in flight
    tokens: list
    chain: Optional[int]


class SimReplicaFront:
    """A deterministic stand-in replica: the ``ServeFront`` surface the
    router touches (submit_ex / drain / drain_pending / probe_prefix /
    queue_depth / busy / load_fraction / checkpoint_inflight /
    restore_inflight) over a discrete-event decode that produces
    :func:`sim_reference_tokens`.

    The front never advances the clock: each phase (prefill, then one
    decode chunk at a time) is *scheduled* to complete at ``_busy_until``
    on the shared virtual timeline, and ``drain`` applies whatever is due
    at the current instant. The driver advances the clock to
    :meth:`next_event_s` — so N replicas genuinely serve in parallel
    (fleet capacity scales with N), which is the property the equal-
    capacity goodput gate measures. Memory is O(queue depth), never
    O(requests served)."""

    def __init__(self, cfg: Optional[SimReplicaConfig] = None, *,
                 clock: Any, replica_id: int = 0):
        self.cfg = cfg if cfg is not None else SimReplicaConfig()
        self.clock = clock
        self.replica_id = replica_id
        self._seq = 0
        self._queue: collections.deque = collections.deque()
        self._restored: collections.deque = collections.deque()
        self._current: Optional[_SimStream] = None
        self._busy_until: Optional[float] = None
        self._fault_reason: Optional[str] = None
        self._corrupt_rate = 0.0
        self._service_mult = 1.0
        self._prefix_index: dict = {}   # crc(prefix block chain) -> True
        self.served = 0

    # -- the ServeFront surface the router uses -----------------------------

    @property
    def queue_depth(self) -> int:
        return len(self._queue) + len(self._restored)

    @property
    def busy(self) -> bool:
        return (self._current is not None or bool(self._queue)
                or bool(self._restored))

    def load_fraction(self) -> float:
        return min(1.0, self.queue_depth / self.cfg.max_queue_depth)

    def submit(self, req: Request) -> int:
        rid, _ = self.submit_ex(req)
        return rid

    def submit_ex(self, req: Request) -> tuple:
        self._seq += 1
        rid = self._seq
        if len(self._queue) >= self.cfg.max_queue_depth:
            return rid, self._record(rid, req, REJECTED, "queue_full",
                                     self.clock(), None, None)
        self._queue.append((rid, req, self.clock()))
        return rid, None

    def drain_pending(self) -> list:
        out = [(rid, req) for rid, req, _ in self._queue]
        out.extend((st.rid, st.req) for st in self._restored)
        self._queue.clear()
        self._restored.clear()
        return out

    def next_event_s(self) -> Optional[float]:
        """When the scheduled phase completes — the instant the driver
        should advance the virtual clock to. None when idle."""
        return self._busy_until if self._current is not None else None

    def probe_prefix(self, prompt_ids) -> int:
        prompt = np.asarray(prompt_ids, np.int64).reshape(-1)
        block = self.cfg.prefix_block
        matched = 0
        h = 0
        for k in range(block, len(prompt) + 1, block):
            h = _crc(prompt[k - block:k].tobytes(), h)
            if h not in self._prefix_index:
                break
            matched = k
        return matched

    def _index_prefix(self, prompt: np.ndarray) -> None:
        if len(self._prefix_index) >= self.cfg.index_capacity:
            self._prefix_index.clear()   # bounded: reset beats unbounded
        block = self.cfg.prefix_block
        h = 0
        for k in range(block, len(prompt) + 1, block):
            h = _crc(prompt[k - block:k].astype(np.int64).tobytes(), h)
            self._prefix_index[h] = True

    # -- chaos knobs --------------------------------------------------------

    def inject_fault(self, reason: str = "stage_lost:0") -> None:
        """Arm a replica-fatal failure: the next drain chunk fails its
        stream with this reason (the router's kill path takes over)."""
        self._fault_reason = reason

    def set_corrupt_rate(self, rate: float) -> None:
        """Link-corruption burst: completing requests fail terminally with
        ``substituted_payload`` at this seeded per-request rate."""
        self._corrupt_rate = float(rate)

    def set_service_multiplier(self, mult: float) -> None:
        """Gray-failure slowdown: stretch every subsequently *scheduled*
        prefill/decode phase by this factor. The replica stays alive and
        passes every health check — it is merely slow, which is the point."""
        if mult <= 0:
            raise ValueError(f"service multiplier must be > 0, got {mult!r}")
        self._service_mult = float(mult)

    # -- hedge support ------------------------------------------------------

    def cancel(self, rid: int) -> bool:
        """Abandon one queued or mid-flight stream (the losing leg of a
        settled hedge). True when found and dropped; False when the stream
        already went terminal (the router discards its record instead)."""
        for i, (qrid, _req, _at) in enumerate(self._queue):
            if qrid == rid:
                del self._queue[i]
                return True
        for i, st in enumerate(self._restored):
            if st.rid == rid:
                del self._restored[i]
                return True
        if self._current is not None and self._current.rid == rid:
            self._current = None
            self._busy_until = None
            return True
        return False

    # -- virtual-time decode ------------------------------------------------

    def _chunk_of(self, st: _SimStream) -> int:
        return min(self.cfg.chunk_tokens,
                   st.req.max_new_tokens - len(st.tokens))

    def _expired_record(self, st: _SimStream,
                        now: float) -> Optional[RequestRecord]:
        """With deadline propagation armed: refuse to schedule the next
        phase of a stream whose (decremented) deadline has already passed —
        a ``timed_out``/``deadline_expired`` terminal instead of tokens
        nobody can use."""
        if (not self.cfg.deadline_propagation
                or st.req.deadline_s is None
                or now - st.submitted_at < st.req.deadline_s):
            return None
        self._current = None
        self._busy_until = None
        return self._record(st.rid, st.req, TIMED_OUT,
                            DeadlineExpired.reason, st.submitted_at,
                            st.started_at, None, tokens_done=len(st.tokens))

    def drain(self, max_requests: Optional[int] = None) -> list:
        """Apply whatever is due at the current virtual instant: start a
        stream when idle, complete the scheduled prefill/decode chunk when
        its time has passed. At most one terminal record per call; []
        means blocked on virtual time (:meth:`next_event_s` says until
        when) or empty. Chunked on purpose — a kill between chunk
        boundaries lands mid-request."""
        del max_requests  # at most one record per call regardless
        while True:
            if self._current is None:
                self._busy_until = None
                nxt = self._pop_admissible()
                if nxt is None:
                    return []
                if isinstance(nxt, RequestRecord):
                    return [nxt]   # expired in queue
                self._current = nxt
                continue           # phase scheduled; due-check next pass
            st = self._current
            if self._fault_reason is not None:
                reason = self._fault_reason
                self._fault_reason = None
                self._current = None
                self._busy_until = None
                return [self._record(st.rid, st.req, FAILED, reason,
                                     st.submitted_at, st.started_at, None,
                                     tokens_done=len(st.tokens))]
            if self.clock() < self._busy_until - 1e-12:
                return []          # scheduled phase not due yet
            due_at = self._busy_until
            if st.started_at is None:
                # prefill completed: index the prompt, schedule first chunk
                st.started_at = due_at
                self._index_prefix(st.prompt)
                expired = self._expired_record(st, due_at)
                if expired is not None:
                    return [expired]
                self._busy_until = (due_at + self.cfg.decode_s_per_token
                                    * self._chunk_of(st)
                                    * self._service_mult)
                continue
            # decode chunk completed: append exactly the scheduled tokens
            k = self._chunk_of(st)
            toks, st.chain = sim_reference_tokens(
                st.prompt, len(st.tokens) + k,
                temperature=st.req.temperature, rng_seed=st.req.rng_seed,
                vocab_size=self.cfg.vocab_size, start=len(st.tokens),
                chain=st.chain)
            st.tokens.extend(int(t) for t in toks)
            if len(st.tokens) < st.req.max_new_tokens:
                expired = self._expired_record(st, due_at)
                if expired is not None:
                    return [expired]
                self._busy_until = (due_at + self.cfg.decode_s_per_token
                                    * self._chunk_of(st)
                                    * self._service_mult)
                continue
            self._current = None
            self._busy_until = None
            self.served += 1
            # seeded per-request corruption draw: deterministic chaos
            u = (_crc(struct.pack("<Q", st.chain)) + 0.5) / 2.0 ** 32
            if self._corrupt_rate > 0.0 and u < self._corrupt_rate:
                return [self._record(st.rid, st.req, FAILED,
                                     "substituted_payload", st.submitted_at,
                                     st.started_at, None,
                                     tokens_done=len(st.tokens))]
            return [self._record(st.rid, st.req, COMPLETED, "",
                                 st.submitted_at, st.started_at,
                                 np.asarray(st.tokens, np.int32))]

    def _pop_admissible(self):
        """Next stream to run: restored streams first (they were already
        admitted once, and resume decoding directly), then the FIFO queue
        with deadline expiry. Schedules the stream's next phase on the
        virtual timeline."""
        if self._restored:
            st = self._restored.popleft()
            self._busy_until = (self.clock() + self.cfg.decode_s_per_token
                                * self._chunk_of(st)
                                * self._service_mult)
            return st
        while self._queue:
            rid, req, sub_at = self._queue.popleft()
            wait = self.clock() - sub_at
            if req.deadline_s is not None and wait >= req.deadline_s:
                return self._record(rid, req, TIMED_OUT, "expired_in_queue",
                                    sub_at, None, None)
            prompt = np.asarray(req.prompt_ids, np.int32).reshape(-1)
            self._busy_until = (self.clock()
                                + self.cfg.prefill_s_per_token * prompt.size
                                * self._service_mult)
            return _SimStream(rid=rid, req=req, prompt=prompt,
                              submitted_at=sub_at, started_at=None,
                              tokens=[], chain=None)
        return None

    # -- checkpoint / restore (the replica-drain hatch) ---------------------

    def checkpoint_inflight(self, ckpt_dir: Optional[str] = None) -> list:
        """DecodeCheckpoint the mid-flight stream out of this front (the
        real CRC-framed container — spooled to ``ckpt_dir`` when given, held
        in memory otherwise). Clears the stream; the router re-admits it."""
        if self._current is None:
            return []
        st = self._current
        self._current = None
        ck = DecodeCheckpoint(
            arrays={"prompt_ids": st.prompt,
                    "tokens": np.asarray(st.tokens, np.int32)},
            meta={"kind": "sim_stream", "rid": int(st.rid),
                  "chain": int(st.chain) if st.chain is not None else None,
                  "temperature": float(st.req.temperature),
                  "rng_seed": int(st.req.rng_seed),
                  "max_new_tokens": int(st.req.max_new_tokens),
                  "submitted_at": float(st.submitted_at),
                  "replica": int(self.replica_id)})
        item = {"local_rid": st.rid, "req": st.req,
                "tokens_done": len(st.tokens)}
        if ckpt_dir is not None:
            os.makedirs(ckpt_dir, exist_ok=True)
            path = os.path.join(
                ckpt_dir, f"replica{self.replica_id}-r{st.rid}.ckpt")
            ck.save(path)
            item["path"] = path
        else:
            item["ckpt"] = ck
        return [item]

    def restore_inflight(self, item: dict) -> int:
        """Resume a checkpointed stream: the crc chain continues exactly
        where the dead replica stopped — token-identical, zero recompute."""
        ck = (DecodeCheckpoint.load(item["path"]) if "path" in item
              else item["ckpt"])
        if ck.meta.get("kind") != "sim_stream":
            raise ValueError(
                f"not a sim stream checkpoint: {ck.meta.get('kind')!r}")
        self._seq += 1
        rid = self._seq
        req = item["req"]
        st = _SimStream(
            rid=rid, req=req,
            prompt=np.asarray(ck.arrays["prompt_ids"], np.int32),
            submitted_at=float(ck.meta["submitted_at"]),
            started_at=self.clock(),
            tokens=[int(t) for t in ck.arrays["tokens"]],
            chain=(int(ck.meta["chain"])
                   if ck.meta["chain"] is not None else None))
        self._restored.append(st)
        return rid

    # -- records ------------------------------------------------------------

    def _record(self, rid: int, req: Request, outcome: str, reason: str,
                submitted_at: float, started_at: Optional[float],
                tokens: Optional[np.ndarray],
                tokens_done: int = 0) -> RequestRecord:
        now = self.clock()
        wait = (started_at - submitted_at if started_at is not None
                else now - submitted_at)
        service = now - started_at if started_at is not None else None
        latency = now - submitted_at if tokens is not None else None
        deadline_met = None
        if req.deadline_s is not None and latency is not None:
            deadline_met = latency <= req.deadline_s
        prompt_tokens = int(np.asarray(req.prompt_ids).size)
        return RequestRecord(
            request_id=rid, outcome=outcome, reason=reason, backend="sim",
            priority=req.priority, submitted_at=submitted_at,
            started_at=started_at,
            finished_at=now if tokens is not None else None,
            queue_wait_s=wait, ttft_s=(wait if tokens is not None else None),
            service_s=service, latency_s=latency, deadline_s=req.deadline_s,
            deadline_met=deadline_met, prompt_tokens=prompt_tokens,
            requested_tokens=req.max_new_tokens,
            granted_tokens=(req.max_new_tokens if tokens is not None
                            else None),
            capacity=None, batch=1,
            plan={"mode": "sim", "replica_gen": self.replica_id},
            brownout_level=0, retries_charged=0, jit_misses=0,
            tokens=(tokens[None, :] if tokens is not None else None),
            recovery=({"tokens_done": tokens_done} if tokens_done else None))

    def report(self) -> dict:
        return {"served": self.served, "queue_depth": self.queue_depth,
                "index_entries": len(self._prefix_index)}


def drive_cluster(cluster: ClusterFront, clock: Any, *,
                  max_records: Optional[int] = None) -> list:
    """Drain a simulated fleet to idle: alternate ``cluster.drain`` with
    advancing the virtual clock to :meth:`ClusterFront.next_event_s`
    (ClusterFront itself never moves the clock). Returns the terminal
    records collected. Stops when the fleet is idle with nothing scheduled
    — parked work with no pending respawn is left parked (the caller reads
    ``cluster.report()`` for it)."""
    out: list = []
    stalls = 0
    while max_records is None or len(out) < max_records:
        recs = cluster.drain(
            max_requests=(None if max_records is None
                          else max_records - len(out)))
        out.extend(recs)
        if recs:
            stalls = 0
            continue
        ev = cluster.next_event_s()
        if ev is None or not (cluster.pending or cluster.busy):
            break
        if ev > clock():
            clock.set_time(ev)
            stalls = 0
        else:
            stalls += 1      # an event that is due but yields nothing twice
            if stalls > 2:   # over means a wedged fleet — stop, don't spin
                break
    return out
