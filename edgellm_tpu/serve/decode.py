"""Batched autoregressive generation over the KV-cached decode runtime.

The serving loop the ROADMAP's north star needs and the evaluation entry
points cannot provide: ``forward`` reprocesses the whole window per emitted
token (N tokens = N full prefills), while this loop runs ONE prefill and then
O(1) ``decode_step`` calls against the cache.

Compilation contract: the per-step executable is compiled once per
(batch, capacity) shape. Capacity is static (it fixes the cache buffers);
``cache.length`` is a traced scalar, so every fill level of the cache — and
every emitted token — reuses the same executable. ``generate`` exposes the
jit cache-miss delta in its ``stats`` dict precisely so tests can assert the
no-retrace property instead of trusting it.

Sampling: ``temperature == 0`` is greedy argmax; ``temperature > 0`` draws
from ``categorical(logits / temperature)`` with a per-step ``fold_in`` of the
caller's key, so a fixed key is reproducible and steps are decorrelated. The
temperature is a static jit arg — the greedy executable contains no RNG at
all.

Survivability (``recovery=`` on both loops, see ``serve.recovery``): the
same loop can periodically snapshot its full generation state (KV cache,
position offset, RNG key, token prefix, fault counters) to an atomic
:class:`~edgellm_tpu.serve.recovery.DecodeCheckpoint`, guard each step with a
monotonic watchdog, survive an injected (or real) whole-stage loss by
re-planning the split onto the survivors and recomputing the lost KV state
from the generation prefix, and resume from a checkpoint token-identically
(:func:`resume_split`). With ``recovery=None`` — or a config with every
feature off — the loop drives the exact same runtime executables as before:
recovery is host-side orchestration, never a different graph.
"""
from __future__ import annotations

import time
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.typing import ArrayLike

from ..lint import graph_contract
from ..models.configs import ModelConfig
from ..models.transformer import (KVCache, _cast_params, block_verify,
                                  cache_from_state_dict, cache_state_dict,
                                  decode_step, embed, precompute_rope,
                                  prefill, unembed)
from ..obs.latency import LatencyObserver
from ..obs.metrics import (CounterSource, get_registry, record_decode_stats,
                           record_link_counters, record_link_health,
                           record_pipeline_stats, record_probe_decisions,
                           record_recovery_counters, record_wire_bytes)
from ..obs.tracing import span as obs_span
from ..obs.tracing import tracing_enabled
from .recovery import (CheckpointError, DecodeCheckpoint, DecodeTimeout,
                       LocalRuntime, RecoveryConfig, RecoveryCounters,
                       StageLostError, Watchdog, runtime_plan_meta)


def _sample(logits: jnp.ndarray, key: jax.Array,
            temperature: float) -> jnp.ndarray:
    """(B, V) fp32 logits -> (B,) int32 token ids."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(key, logits / temperature).astype(jnp.int32)


@graph_contract("decode.prefill", collectives={})
def _prefill_impl(cfg: ModelConfig, params: dict, prompt_ids: jnp.ndarray,
                  capacity: int,
                  compute_dtype: Optional[Any]) -> tuple[jnp.ndarray, KVCache]:
    logits, cache = prefill(cfg, params, prompt_ids, capacity,
                            compute_dtype=compute_dtype)
    return logits[:, -1], cache  # only the last position seeds generation


@graph_contract("decode.prefill_suffix", collectives={},
                donate=lambda ctx: ctx.get("donate_min", 2))
def _prefill_suffix_impl(cfg: ModelConfig, params: dict,
                         suffix_ids: jnp.ndarray, cache: KVCache,
                         compute_dtype: Optional[Any]
                         ) -> tuple[jnp.ndarray, KVCache]:
    """Prefill ONLY the unmatched suffix of a prompt whose prefix KV rows
    are already in ``cache`` (rows ``0 .. cache.length`` — gathered from
    shared pages by the prefix-cache admit path). A K-position twin of
    ``decode_step``: embed the (B, K) suffix, rotate at the absolute
    positions ``cache.length .. cache.length+K-1``, scan ``block_verify``
    over the layers (write K rows, attend causally against the filled
    prefix), and return ((B, K, V) fp32 logits, cache grown by K). Compiled
    once per (batch, K, capacity) shape — the admit path's analogue of the
    one-executable-per-geometry rule."""
    params = _cast_params(params, compute_dtype)
    hidden = embed(params, suffix_ids)  # (B, K, D)
    pos = cache.length
    kq = suffix_ids.shape[1]
    cos, sin = precompute_rope(cfg, cache.capacity)
    cos_t = jax.lax.dynamic_slice_in_dim(cos, pos, kq)
    sin_t = jax.lax.dynamic_slice_in_dim(sin, pos, kq)

    def body(h, xs):
        lp, kc, vc = xs
        h, kc, vc = block_verify(cfg, lp, h, cos_t, sin_t, kc, vc, pos)
        return h, (kc, vc)

    hidden, (k_new, v_new) = jax.lax.scan(
        body, hidden, (params["layers"], cache.k, cache.v))
    logits = unembed(cfg, params, hidden)  # (B, K, V) fp32
    return logits, KVCache(k_new, v_new, pos + kq)


@graph_contract("decode.step", collectives={},
                donate=lambda ctx: ctx.get("donate_min", 2))
def _step_impl(cfg: ModelConfig, params: dict, cache: KVCache,
               token_ids: jnp.ndarray, key: jax.Array, temperature: float,
               compute_dtype: Optional[Any]) -> tuple[jnp.ndarray, KVCache]:
    logits, cache = decode_step(cfg, params, cache, token_ids,
                                compute_dtype=compute_dtype)
    return _sample(logits, key, temperature), cache


_prefill_jit = jax.jit(_prefill_impl,
                       static_argnames=("cfg", "capacity", "compute_dtype"))
# suffix prefill donates its cache: the gathered shared-prefix rows flow in,
# the suffix rows land in place. One executable per (batch, K, capacity);
# like full prefill, its compiles are NOT counted as step-cache jit misses.
_prefill_suffix_jit = jax.jit(_prefill_suffix_impl,
                              static_argnames=("cfg", "compute_dtype"),
                              donate_argnums=(3,))
# the cache is donated: each step's (B, capacity) KV buffers alias the previous
# step's in the lowered executable instead of being copied per token (the
# "decode.step" graph contract asserts the aliasing survives)
_step_jit = jax.jit(_step_impl,
                    static_argnames=("cfg", "temperature", "compute_dtype"),
                    donate_argnames=("cache",))


def decode_step_cache_size() -> int:
    """Number of per-step executables compiled so far in this process — the
    jit-cache-miss counter ``generate`` reports deltas of."""
    return _step_jit._cache_size()


def _emit_hop_spans(rt: Any, delta: Optional[dict],
                    per_hop_bytes: Optional[list], *,
                    link_tier: Optional[int] = None,
                    **extra: Any) -> None:
    """One zero-duration ``split.hop`` span per boundary cut, at call
    granularity: {hop, cut layer, codec, wire bytes, ladder outcome} plus
    the caller's extras (µ-batch count, spec-burst count) — and, via the
    ambient :class:`~edgellm_tpu.obs.context.TraceContext`, the request
    labels. Tracing-gated so disabled tracing skips even the attribution
    arithmetic; runtimes without a boundary (LocalRuntime) have no
    ``hop_attribution`` and emit nothing."""
    if not tracing_enabled() or not hasattr(rt, "hop_attribution"):
        return
    for row in rt.hop_attribution(delta, per_hop_bytes,
                                  link_tier=link_tier):
        with obs_span("split.hop", **row, **extra):
            pass


def _validate_decode_args(prompt_ids, max_new_tokens, capacity, temperature,
                          rng_key):
    prompt_ids = jnp.asarray(prompt_ids)
    if prompt_ids.ndim != 2:
        raise ValueError(f"prompt_ids must be (B, S), got {prompt_ids.shape}")
    _, s = prompt_ids.shape
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    capacity = s + max_new_tokens if capacity is None else int(capacity)
    if s + max_new_tokens > capacity:
        raise ValueError(
            f"cache capacity overflow: prompt {s} + {max_new_tokens} new "
            f"tokens > capacity {capacity}")
    temperature = float(temperature)
    if temperature < 0.0:
        raise ValueError("temperature must be >= 0")
    key = jax.random.key(0) if rng_key is None else rng_key
    return prompt_ids, capacity, temperature, key


def generate(cfg: ModelConfig, params: dict, prompt_ids: ArrayLike,
             max_new_tokens: int,
             *,
             capacity: Optional[int] = None,
             temperature: float = 0.0,
             rng_key: Optional[jax.Array] = None,
             compute_dtype=None,
             stats: Optional[dict] = None,
             recovery: Optional[RecoveryConfig] = None,
             observe: Optional[LatencyObserver] = None) -> jnp.ndarray:
    """Generate ``max_new_tokens`` per batch row after a KV-cached prefill.

    prompt_ids: (B, S) int token ids. Returns (B, max_new_tokens) int32.
    ``capacity`` (static; default exactly prompt+new) bounds the cache —
    prompts that would overflow it raise instead of silently wrapping.
    ``stats``, when given, is filled with timing and the per-step jit
    cache-miss delta (0 on a warm shape, 1 on a cold one).

    ``observe``: a :class:`~edgellm_tpu.obs.latency.LatencyObserver` records
    TTFT and per-token latency histograms, blocking once per sampled token
    (the data-dependency boundary — never per op); its SLO summary is folded
    into ``stats``. ``observe=None`` (default) leaves the loop untouched.

    ``recovery``: a :class:`~edgellm_tpu.serve.recovery.RecoveryConfig`
    routes the generation through the survivable loop (checkpointing +
    watchdog) on a :class:`LocalRuntime` adapter around the same
    ``prefill``/``decode_step`` math; stage failover does not apply on a
    single device. ``recovery=None`` is the original loop, untouched.
    """
    prompt_ids, capacity, temperature, key = _validate_decode_args(
        prompt_ids, max_new_tokens, capacity, temperature, rng_key)
    b, s = prompt_ids.shape
    if recovery is not None:
        rt = LocalRuntime(cfg, compute_dtype)
        return _survivable_loop(rt, params, prompt_ids, max_new_tokens,
                                capacity, temperature, key, 0, stats,
                                recovery, raw_params=params, observe=observe)
    misses0 = decode_step_cache_size()
    if observe is not None:
        observe.start()

    t0 = time.monotonic()
    with obs_span("generate.prefill", batch=b, prompt_len=s):
        last_logits, cache = _prefill_jit(cfg, params, prompt_ids, capacity,
                                          compute_dtype)
        tok = _sample(last_logits, jax.random.fold_in(key, 0), temperature)
        jax.block_until_ready(tok)
    if observe is not None:
        observe.first_token(tok)
    t1 = time.monotonic()

    toks = [tok]
    with obs_span("generate.decode_loop", steps=max_new_tokens - 1):
        for t in range(1, max_new_tokens):
            tok, cache = _step_jit(cfg, params, cache, tok,
                                   jax.random.fold_in(key, t), temperature,
                                   compute_dtype)
            if observe is not None:
                observe.token(tok)
            toks.append(tok)
    out = jnp.stack(toks, axis=1)  # (B, max_new_tokens)
    jax.block_until_ready(out)
    t2 = time.monotonic()

    if stats is not None:
        steps = max_new_tokens - 1  # tokens emitted by the decode loop proper
        stats.update(
            capacity=capacity,
            prefill_s=t1 - t0,
            decode_s=t2 - t1,
            decode_steps=steps,
            decode_tokens_per_s=(b * steps / (t2 - t1)) if steps else 0.0,
            decode_step_cache_misses=decode_step_cache_size() - misses0,
        )
        if observe is not None:
            stats.update(observe.summary())
        record_decode_stats(stats)
    if observe is not None:
        observe.publish()
    return out


def generate_split(rt: Any, placed_params: dict, prompt_ids: ArrayLike,
                   max_new_tokens: int,
                   *,
                   capacity: Optional[int] = None,
                   temperature: float = 0.0,
                   rng_key: Optional[jax.Array] = None,
                   fault_step: int = 0,
                   stats: Optional[dict] = None,
                   recovery: Optional[RecoveryConfig] = None,
                   raw_params: Optional[dict] = None,
                   link_health: Optional[Any] = None,
                   speculative: Optional[Any] = None,
                   observe: Optional[LatencyObserver] = None) -> jnp.ndarray:
    """``generate`` over the pipeline-SPLIT decode runtime: one split prefill,
    then O(1) :meth:`SplitRuntime.decode_step` calls, every emitted token
    crossing each cut as a packed wire payload — and, when the runtime was
    built with faults, a sealed/verified/retried one (each step's fault stream
    is keyed by the cache fill level, so generation is seed-reproducible).

    ``rt`` is a :class:`~edgellm_tpu.parallel.split.SplitRuntime`;
    ``placed_params`` comes from ``rt.place_params``. ``fault_step`` seeds the
    prefill's fault stream (vary it across prompts to decorrelate them).
    ``stats`` gains the same timing fields as ``generate`` plus, under faults,
    ``link_counters`` — the per-hop detected/retried/recovered/substituted
    totals incurred by THIS call.

    ``link_health`` (a :class:`~edgellm_tpu.codecs.fec.LinkHealth`) observes
    this call's counter deltas and lands its windowed SLO summary — burn
    rate, corruption/repair/retry/hedge-win rates — in
    ``stats["link_health"]``; the caller reads ``link_health.tier`` between
    calls to walk the codec ladder (tier changes swap runtimes, so they
    cannot happen inside one call).

    ``recovery`` routes the call through the survivable loop: periodic
    :class:`DecodeCheckpoint` snapshots, a per-step watchdog, stage-failure
    injection, and boundary re-planning failover (which needs ``raw_params``
    — the unplaced parameter pytree — to re-place onto the surviving
    devices). ``recovery=None`` is the original loop on the exact same
    runtime executables.

    ``speculative``: an enabled :class:`~edgellm_tpu.serve.speculative.
    SpecConfig` routes the call through the draft/verify burst loop (greedy
    output token-identical, one boundary hop round per burst instead of per
    token; needs ``raw_params`` for the stage-0 draft). ``None`` — or a
    disabled config — is PURE host-side dispatch: the loop below runs
    unchanged and builds the exact pre-spec graphs (the graphlint identity
    contract holds because this branch never touches the verify executable).
    """
    if speculative is not None and getattr(speculative, "enabled", False):
        # lazy import: speculative imports this module's helpers
        from .speculative import generate_speculative

        return generate_speculative(
            rt, placed_params, prompt_ids, max_new_tokens, spec=speculative,
            capacity=capacity, temperature=temperature, rng_key=rng_key,
            fault_step=fault_step, stats=stats, recovery=recovery,
            raw_params=raw_params, link_health=link_health, observe=observe)
    prompt_ids, capacity, temperature, key = _validate_decode_args(
        prompt_ids, max_new_tokens, capacity, temperature, rng_key)
    b, s = prompt_ids.shape
    if recovery is not None:
        return _survivable_loop(rt, placed_params, prompt_ids, max_new_tokens,
                                capacity, temperature, key, fault_step, stats,
                                recovery, raw_params=raw_params,
                                observe=observe)
    counters0 = rt.link_counters() if isinstance(rt, CounterSource) else None
    if observe is not None:
        observe.start()

    t0 = time.monotonic()
    with obs_span("generate_split.prefill", batch=b, prompt_len=s):
        logits, cache = rt.prefill_decode(placed_params, prompt_ids, capacity,
                                          fault_step=fault_step)
        tok = _sample(logits[:, -1], jax.random.fold_in(key, 0), temperature)
        jax.block_until_ready(tok)
    if observe is not None:
        observe.first_token(tok)
    t1 = time.monotonic()

    toks = [tok]
    with obs_span("generate_split.decode_loop", steps=max_new_tokens - 1):
        for t in range(1, max_new_tokens):
            step_logits, cache = rt.decode_step(placed_params, cache, tok)
            tok = _sample(step_logits, jax.random.fold_in(key, t), temperature)
            if observe is not None:
                observe.token(tok)
            toks.append(tok)
    out = jnp.stack(toks, axis=1)  # (B, max_new_tokens)
    jax.block_until_ready(out)
    t2 = time.monotonic()

    counters1 = rt.link_counters() if isinstance(rt, CounterSource) else None
    delta = None
    if counters1 is not None:
        delta = {k: [int(x) for x in (v if counters0 is None
                                      else v - counters0[k])]
                 for k, v in counters1.items()}
    if link_health is not None:
        link_health.observe(delta)
    record_link_counters(delta)
    if link_health is not None:
        record_link_health(link_health.summary())
    pipelined = bool(getattr(rt, "pipelined", False))
    hop_bytes: Optional[list] = None
    if isinstance(rt, CounterSource) and (get_registry().enabled
                                          or tracing_enabled()):
        # under the µ-batch schedule each cut moves M smaller payloads per
        # step — report the bytes the wire actually carried
        hop_bytes = (rt.pipelined_decode_hop_bytes(b) if pipelined
                     else rt.decode_hop_bytes(b))
    if get_registry().enabled and hop_bytes is not None:
        record_wire_bytes(hop_bytes, kind="decode", steps=max_new_tokens - 1)
        if hasattr(rt, "wire_summary"):
            record_probe_decisions(rt.wire_summary(b, max(s, 1)))
    _emit_hop_spans(
        rt, delta,
        None if hop_bytes is None
        else [x * (max_new_tokens - 1) for x in hop_bytes],
        link_tier=getattr(link_health, "tier", None),
        microbatches=int(getattr(getattr(rt, "pipeline", None),
                                 "num_microbatches", 1) if pipelined else 1))
    if pipelined:
        record_pipeline_stats(rt.pipeline_summary())
    if stats is not None:
        steps = max_new_tokens - 1
        stats.update(
            capacity=capacity,
            prefill_s=t1 - t0,
            decode_s=t2 - t1,
            decode_steps=steps,
            decode_tokens_per_s=(b * steps / (t2 - t1)) if steps else 0.0,
        )
        if pipelined:
            stats["pipeline"] = rt.pipeline_summary()
        if delta is not None:
            stats["link_counters"] = delta
        if link_health is not None:
            stats["link_health"] = link_health.summary()
        if observe is not None:
            stats.update(observe.summary())
        record_decode_stats(stats)
    if observe is not None:
        observe.publish()
    return out


# ---------------------------------------------------------------------------
# the survivable loop: checkpoints, watchdog, stage failover, resume
# ---------------------------------------------------------------------------


def _write_checkpoint(rec: RecoveryConfig, rt, counters: RecoveryCounters,
                      prompt_ids, toks: list, cache, key, t: int,
                      run_meta: dict) -> None:
    """Snapshot everything step t+1 needs — token-identically — to the
    atomic checkpoint file. ``toks`` holds steps 0..t; the cache holds the
    prompt plus steps 0..t-1 (step t's token has not been fed back yet),
    which is exactly the loop state at the top of iteration t+1."""
    with obs_span("decode.checkpoint_write", step=t):
        arrays = {
            "prompt_ids": np.asarray(prompt_ids, np.int32),
            "tokens": np.stack([np.asarray(x) for x in toks], axis=1)
            .astype(np.int32),
            "rng_key": np.asarray(jax.random.key_data(key)),
        }
        cs = cache_state_dict(cache)
        arrays.update({"cache/k": cs["k"], "cache/v": cs["v"],
                       "cache/length": cs["length"]})
        meta = {**runtime_plan_meta(rt), **run_meta, "step": int(t),
                "recovery_counters": counters.as_dict()}
        link = rt.link_counters() if isinstance(rt, CounterSource) else None
        if link is not None:
            meta["link_counters"] = {k: [int(x) for x in v]
                                     for k, v in link.items()}
        DecodeCheckpoint(arrays, meta).save(rec.checkpoint_path)
        counters.checkpoints_written += 1


def _decode_failover(rt, raw_params, lost_stage: int, prompt_ids, toks: list,
                     capacity: int, fault_step: int,
                     counters: RecoveryCounters, rec: RecoveryConfig):
    """Re-plan the split onto the surviving stage(s) and rebuild the decode
    state there. The lost stage's KV cache is unrecoverable (its boundary
    inputs died with it), so the honest migration is a re-prefill of the
    whole generation prefix — prompt plus every token sampled so far — on
    the new plan; the re-prefill's last-position logits are exactly what the
    failed step would have produced, so the caller samples from them with
    the step's own folded key and continues. Returns
    (new_rt, new_placed, cache, last_logits)."""
    if not rec.replan:
        raise StageLostError(lost_stage)
    if counters.failovers >= rec.max_failovers:
        raise StageLostError(lost_stage)
    if raw_params is None:
        raise ValueError(
            "stage failover needs raw_params= (the unplaced parameter "
            "pytree) to re-place weights onto the surviving devices")
    counters.failovers += 1
    with obs_span("decode.failover", lost_stage=lost_stage):
        return _decode_failover_impl(rt, raw_params, lost_stage, prompt_ids,
                                     toks, capacity, fault_step, counters)


def _decode_failover_impl(rt, raw_params, lost_stage: int, prompt_ids,
                          toks: list, capacity: int, fault_step: int,
                          counters: RecoveryCounters):
    """The replan + re-place + re-prefill body of :func:`_decode_failover`
    (split out so the failover span covers exactly the expensive work)."""
    grid = np.asarray(rt.mesh.devices)  # (stage, data, model)
    survivors = np.delete(grid, lost_stage, axis=0)
    cfg = rt.cfg
    if survivors.shape[0] >= 2:
        # lazy import: serve -> parallel only on the failover path keeps the
        # module layering acyclic (parallel imports serve.recovery's error)
        from jax.sharding import Mesh

        from ..parallel.split import SplitRuntime

        new_split = rt.split.replan(cfg.num_layers, survivors.shape[0])
        # the µ-batch schedule survives failover: the batch is unchanged and
        # the replanned cuts reuse the (batch-invariant) original codec, so
        # the pipelined runtime's validation still holds on the new mesh
        new_rt = SplitRuntime(cfg, new_split,
                              Mesh(survivors, ("stage", "data", "model")),
                              faults=rt.faults, policy=rt.policy,
                              pipeline=getattr(rt, "pipeline", None))
    else:
        new_rt = LocalRuntime(cfg)  # one survivor: nothing left to cut
    counters.replans += 1
    new_placed = new_rt.place_params(raw_params)
    # via host: the sampled tokens are committed to the dead mesh, and the
    # re-planned runtime lives on a different device set
    prompt_np = np.asarray(prompt_ids)
    prefix = jnp.asarray(
        prompt_np if not toks else
        np.concatenate([prompt_np,
                        np.stack([np.asarray(x) for x in toks], axis=1)],
                       axis=1))
    logits, cache = new_rt.prefill_decode(new_placed, prefix, capacity,
                                          fault_step=fault_step)
    counters.recompute_tokens += int(prefix.shape[0] * prefix.shape[1])
    return new_rt, new_placed, cache, logits[:, -1]


def _survivable_loop(rt, placed, prompt_ids, max_new_tokens: int,
                     capacity: int, temperature: float, key, fault_step: int,
                     stats: Optional[dict], rec: RecoveryConfig,
                     raw_params: Optional[dict],
                     resume_state=None, resumed: bool = False,
                     observe: Optional[LatencyObserver] = None) -> jnp.ndarray:
    """The decode loop with recovery orchestration around the unchanged
    runtime executables. ``resume_state`` = (last_done_step, toks, cache)
    continues a checkpointed generation from step ``last_done_step + 1``."""
    counters = RecoveryCounters()
    wd = (Watchdog(rec.deadline_s, clock=rec.clock)
          if rec.deadline_s is not None else None)
    b, s = prompt_ids.shape
    sf = rec.stage_failure
    fail_pending = sf is not None
    run_meta = {"capacity": int(capacity), "temperature": float(temperature),
                "max_new_tokens": int(max_new_tokens),
                "fault_step": int(fault_step), "prompt_len": int(s),
                "batch": int(b)}
    counters0 = rt.link_counters() if isinstance(rt, CounterSource) else None
    halted_at = None
    if observe is not None:
        observe.start()

    def post_step(t, toks, cache) -> bool:
        """halt hook, periodic checkpoint, watchdog — in that order; returns
        True when the loop must stop (simulated kill)."""
        if rec.halt_at_step is not None and rec.halt_at_step == t:
            _write_checkpoint(rec, rt, counters, prompt_ids, toks, cache,
                              key, t, run_meta)
            return True
        if (rec.checkpoint_every and rec.checkpoint_path
                and t % rec.checkpoint_every == 0):
            _write_checkpoint(rec, rt, counters, prompt_ids, toks, cache,
                              key, t, run_meta)
        if wd is not None:
            ckpt_fn = ((lambda: _write_checkpoint(
                rec, rt, counters, prompt_ids, toks, cache, key, t, run_meta))
                if rec.checkpoint_path else None)
            try:
                wd.check(ckpt_fn)
            except DecodeTimeout:
                counters.watchdog_fires += 1
                if stats is not None:
                    stats["recovery_counters"] = counters.as_dict()
                raise
        return False

    t0 = time.monotonic()
    if wd is not None:
        wd.arm()
    if resume_state is None:
        if fail_pending and sf.at_step == 0:
            rt.mark_stage_lost(sf.stage)
        try:
            logits, cache = rt.prefill_decode(placed, prompt_ids, capacity,
                                              fault_step=fault_step)
            last = logits[:, -1]
        except StageLostError as e:
            fail_pending = False
            rt, placed, cache, last = _decode_failover(
                rt, raw_params, e.stage, prompt_ids, [], capacity,
                fault_step, counters, rec)
        tok = _sample(last, jax.random.fold_in(key, 0), temperature)
        jax.block_until_ready(tok)
        if observe is not None:
            observe.first_token(tok)
        t1 = time.monotonic()
        toks = [tok]
        start_t = 1
        if post_step(0, toks, cache):
            halted_at = 0
    else:
        last_done, toks, cache = resume_state
        tok = toks[-1]
        t1 = t0
        start_t = last_done + 1

    if halted_at is None:
        for t in range(start_t, max_new_tokens):
            if fail_pending and sf.at_step == t:
                rt.mark_stage_lost(sf.stage)
            try:
                step_logits, cache = rt.decode_step(placed, cache, tok)
                tok = _sample(step_logits, jax.random.fold_in(key, t),
                              temperature)
            except StageLostError as e:
                fail_pending = False
                rt, placed, cache, last = _decode_failover(
                    rt, raw_params, e.stage, prompt_ids, toks, capacity,
                    fault_step, counters, rec)
                tok = _sample(last, jax.random.fold_in(key, t), temperature)
            if observe is not None:
                observe.token(tok)
            toks.append(tok)
            if post_step(t, toks, cache):
                halted_at = t
                break

    # assemble via host: after a failover the prefix is committed to the dead
    # mesh and the tail to the survivors' — jnp.stack would refuse the mix
    out = jnp.asarray(np.stack([np.asarray(x) for x in toks], axis=1))
    jax.block_until_ready(out)
    t2 = time.monotonic()
    if resumed and halted_at is None:
        counters.resume_ok += 1

    delta = None
    if isinstance(rt, CounterSource) and (stats is not None
                                          or tracing_enabled()):
        counters1 = rt.link_counters()
        if counters1 is not None:
            # after a failover the runtime is new, so deltas vs the original
            # runtime's baseline are meaningless — report absolute totals
            delta = {k: [int(x) for x in
                         (v if counters0 is None or counters.failovers
                          else v - counters0[k])]
                     for k, v in counters1.items()}
    steps = len(toks) - (0 if resume_state is not None else 1)
    if stats is not None:
        stats.update(
            capacity=capacity,
            prefill_s=t1 - t0,
            decode_s=t2 - t1,
            decode_steps=steps,
            decode_tokens_per_s=(b * steps / (t2 - t1)) if steps
            and t2 > t1 else 0.0,
        )
        if halted_at is not None:
            stats["halted_at_step"] = halted_at
        stats["recovery_counters"] = counters.as_dict()
        if delta is not None:
            stats["link_counters"] = delta
            record_link_counters(delta)
        if observe is not None:
            stats.update(observe.summary())
        record_decode_stats(stats)
    if tracing_enabled() and hasattr(rt, "hop_attribution"):
        pipelined = bool(getattr(rt, "pipelined", False))
        hop_bytes = (rt.pipelined_decode_hop_bytes(b) if pipelined
                     else rt.decode_hop_bytes(b))
        _emit_hop_spans(
            rt, delta, [x * max(steps, 0) for x in hop_bytes],
            microbatches=int(getattr(getattr(rt, "pipeline", None),
                                     "num_microbatches", 1)
                             if pipelined else 1),
            failovers=int(counters.failovers))
    record_recovery_counters(counters)
    if observe is not None:
        observe.publish()
    return out


def resume_split(rt: Any, placed_params: dict, checkpoint_path: str, *,
                 stats: Optional[dict] = None,
                 recovery: Optional[RecoveryConfig] = None,
                 raw_params: Optional[dict] = None,
                 speculative: Optional[Any] = None,
                 observe: Optional[LatencyObserver] = None) -> jnp.ndarray:
    """Resume a checkpointed generation and return the FULL (B, max_new)
    token matrix — the checkpointed prefix plus the tokens decoded here,
    token-identical to the uninterrupted same-seed run.

    ``speculative``: an enabled SpecConfig resumes through the burst loop
    (:func:`~edgellm_tpu.serve.speculative.resume_speculative` — spec
    checkpoints land on burst boundaries, so the resumed stream matches the
    uninterrupted speculative run token for token); ``None``/disabled is the
    vanilla resume below, untouched.

    ``rt``/``placed_params`` must match the checkpoint's plan and model
    signature (validated; a mismatch is a typed :class:`CheckpointError` —
    same-plan resume restores the KV cache bit-exactly instead of
    recomputing it). ``recovery`` optionally re-arms checkpointing/watchdog/
    failover for the resumed tail; its ``stage_failure`` steps are absolute
    decode-step indices, comparable to the checkpoint's ``step``. Works for
    both split runtimes and :class:`LocalRuntime` (unsplit ``generate``
    checkpoints)."""
    if speculative is not None and getattr(speculative, "enabled", False):
        from .speculative import resume_speculative

        return resume_speculative(
            rt, placed_params, checkpoint_path, spec=speculative,
            stats=stats, recovery=recovery, raw_params=raw_params,
            observe=observe)
    with obs_span("decode.checkpoint_resume", path=checkpoint_path):
        ckpt = DecodeCheckpoint.load(checkpoint_path)
    meta = ckpt.meta
    want = runtime_plan_meta(rt)
    # num_microbatches defaults to 1 (sequential) so pre-pipeline
    # checkpoints resume onto unpipelined runtimes unchanged
    for k, label, dflt in (("mode", "runtime mode", None),
                           ("model", "model signature", None),
                           ("cuts", "split cuts", None),
                           ("hop_codecs", "hop codecs", None),
                           ("num_microbatches", "pipeline µ-batch count", 1)):
        if meta.get(k, dflt) != want.get(k, dflt):
            raise CheckpointError(
                f"checkpoint {checkpoint_path} was written for {label} "
                f"{meta.get(k)!r}, the resuming runtime has {want.get(k)!r}; "
                f"rebuild the runtime to match (or re-plan explicitly)")
    prompt_ids = jnp.asarray(ckpt.arrays["prompt_ids"])
    tokens = ckpt.arrays["tokens"]  # (B, step+1)
    key = jax.random.wrap_key_data(jnp.asarray(ckpt.arrays["rng_key"]))
    cache = cache_from_state_dict({"k": ckpt.arrays["cache/k"],
                                   "v": ckpt.arrays["cache/v"],
                                   "length": ckpt.arrays["cache/length"]})
    toks = [jnp.asarray(tokens[:, i]) for i in range(tokens.shape[1])]
    step = int(meta["step"])
    if len(toks) != step + 1:
        raise CheckpointError(
            f"checkpoint {checkpoint_path} is inconsistent: step {step} "
            f"with {len(toks)} sampled tokens")
    rec = recovery if recovery is not None else RecoveryConfig()
    if stats is not None:
        stats["resumed_from_step"] = step
        if "link_counters" in meta:
            stats["checkpoint_link_counters"] = meta["link_counters"]
    return _survivable_loop(
        rt, placed_params, prompt_ids, int(meta["max_new_tokens"]),
        int(meta["capacity"]), float(meta["temperature"]), key,
        int(meta["fault_step"]), stats, rec, raw_params,
        resume_state=(step, toks, cache), resumed=True, observe=observe)
