"""Batched autoregressive generation over the KV-cached decode runtime.

The serving loop the ROADMAP's north star needs and the evaluation entry
points cannot provide: ``forward`` reprocesses the whole window per emitted
token (N tokens = N full prefills), while this loop runs ONE prefill and then
O(1) ``decode_step`` calls against the cache.

Compilation contract: the per-step executable is compiled once per
(batch, capacity) shape. Capacity is static (it fixes the cache buffers);
``cache.length`` is a traced scalar, so every fill level of the cache — and
every emitted token — reuses the same executable. ``generate`` exposes the
jit cache-miss delta in its ``stats`` dict precisely so tests can assert the
no-retrace property instead of trusting it.

Sampling: ``temperature == 0`` is greedy argmax; ``temperature > 0`` draws
from ``categorical(logits / temperature)`` with a per-step ``fold_in`` of the
caller's key, so a fixed key is reproducible and steps are decorrelated. The
temperature is a static jit arg — the greedy executable contains no RNG at
all.
"""
from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp

from ..models.configs import ModelConfig
from ..models.transformer import decode_step, prefill


def _sample(logits, key, temperature: float):
    """(B, V) fp32 logits -> (B,) int32 token ids."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(key, logits / temperature).astype(jnp.int32)


def _prefill_impl(cfg, params, prompt_ids, capacity, compute_dtype):
    logits, cache = prefill(cfg, params, prompt_ids, capacity,
                            compute_dtype=compute_dtype)
    return logits[:, -1], cache  # only the last position seeds generation


def _step_impl(cfg, params, cache, token_ids, key, temperature, compute_dtype):
    logits, cache = decode_step(cfg, params, cache, token_ids,
                                compute_dtype=compute_dtype)
    return _sample(logits, key, temperature), cache


_prefill_jit = jax.jit(_prefill_impl,
                       static_argnames=("cfg", "capacity", "compute_dtype"))
_step_jit = jax.jit(_step_impl,
                    static_argnames=("cfg", "temperature", "compute_dtype"))


def decode_step_cache_size() -> int:
    """Number of per-step executables compiled so far in this process — the
    jit-cache-miss counter ``generate`` reports deltas of."""
    return _step_jit._cache_size()


def generate(cfg: ModelConfig, params: dict, prompt_ids, max_new_tokens: int,
             *,
             capacity: Optional[int] = None,
             temperature: float = 0.0,
             rng_key: Optional[jax.Array] = None,
             compute_dtype=None,
             stats: Optional[dict] = None) -> jnp.ndarray:
    """Generate ``max_new_tokens`` per batch row after a KV-cached prefill.

    prompt_ids: (B, S) int token ids. Returns (B, max_new_tokens) int32.
    ``capacity`` (static; default exactly prompt+new) bounds the cache —
    prompts that would overflow it raise instead of silently wrapping.
    ``stats``, when given, is filled with timing and the per-step jit
    cache-miss delta (0 on a warm shape, 1 on a cold one).
    """
    prompt_ids = jnp.asarray(prompt_ids)
    if prompt_ids.ndim != 2:
        raise ValueError(f"prompt_ids must be (B, S), got {prompt_ids.shape}")
    b, s = prompt_ids.shape
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    capacity = s + max_new_tokens if capacity is None else int(capacity)
    if s + max_new_tokens > capacity:
        raise ValueError(
            f"cache capacity overflow: prompt {s} + {max_new_tokens} new "
            f"tokens > capacity {capacity}")
    temperature = float(temperature)
    if temperature < 0.0:
        raise ValueError("temperature must be >= 0")
    key = jax.random.key(0) if rng_key is None else rng_key
    misses0 = decode_step_cache_size()

    t0 = time.monotonic()
    last_logits, cache = _prefill_jit(cfg, params, prompt_ids, capacity,
                                      compute_dtype)
    tok = _sample(last_logits, jax.random.fold_in(key, 0), temperature)
    jax.block_until_ready(tok)
    t1 = time.monotonic()

    toks = [tok]
    for t in range(1, max_new_tokens):
        tok, cache = _step_jit(cfg, params, cache, tok,
                               jax.random.fold_in(key, t), temperature,
                               compute_dtype)
        toks.append(tok)
    out = jnp.stack(toks, axis=1)  # (B, max_new_tokens)
    jax.block_until_ready(out)
    t2 = time.monotonic()

    if stats is not None:
        steps = max_new_tokens - 1  # tokens emitted by the decode loop proper
        stats.update(
            capacity=capacity,
            prefill_s=t1 - t0,
            decode_s=t2 - t1,
            decode_steps=steps,
            decode_tokens_per_s=(b * steps / (t2 - t1)) if steps else 0.0,
            decode_step_cache_misses=decode_step_cache_size() - misses0,
        )
    return out


def generate_split(rt, placed_params: dict, prompt_ids, max_new_tokens: int,
                   *,
                   capacity: Optional[int] = None,
                   temperature: float = 0.0,
                   rng_key: Optional[jax.Array] = None,
                   fault_step: int = 0,
                   stats: Optional[dict] = None) -> jnp.ndarray:
    """``generate`` over the pipeline-SPLIT decode runtime: one split prefill,
    then O(1) :meth:`SplitRuntime.decode_step` calls, every emitted token
    crossing each cut as a packed wire payload — and, when the runtime was
    built with faults, a sealed/verified/retried one (each step's fault stream
    is keyed by the cache fill level, so generation is seed-reproducible).

    ``rt`` is a :class:`~edgellm_tpu.parallel.split.SplitRuntime`;
    ``placed_params`` comes from ``rt.place_params``. ``fault_step`` seeds the
    prefill's fault stream (vary it across prompts to decorrelate them).
    ``stats`` gains the same timing fields as ``generate`` plus, under faults,
    ``link_counters`` — the per-hop detected/retried/recovered/substituted
    totals incurred by THIS call.
    """
    prompt_ids = jnp.asarray(prompt_ids)
    if prompt_ids.ndim != 2:
        raise ValueError(f"prompt_ids must be (B, S), got {prompt_ids.shape}")
    b, s = prompt_ids.shape
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    capacity = s + max_new_tokens if capacity is None else int(capacity)
    if s + max_new_tokens > capacity:
        raise ValueError(
            f"cache capacity overflow: prompt {s} + {max_new_tokens} new "
            f"tokens > capacity {capacity}")
    temperature = float(temperature)
    if temperature < 0.0:
        raise ValueError("temperature must be >= 0")
    key = jax.random.key(0) if rng_key is None else rng_key
    counters0 = rt.link_counters() if hasattr(rt, "link_counters") else None

    t0 = time.monotonic()
    logits, cache = rt.prefill_decode(placed_params, prompt_ids, capacity,
                                      fault_step=fault_step)
    tok = _sample(logits[:, -1], jax.random.fold_in(key, 0), temperature)
    jax.block_until_ready(tok)
    t1 = time.monotonic()

    toks = [tok]
    for t in range(1, max_new_tokens):
        step_logits, cache = rt.decode_step(placed_params, cache, tok)
        tok = _sample(step_logits, jax.random.fold_in(key, t), temperature)
        toks.append(tok)
    out = jnp.stack(toks, axis=1)  # (B, max_new_tokens)
    jax.block_until_ready(out)
    t2 = time.monotonic()

    if stats is not None:
        steps = max_new_tokens - 1
        stats.update(
            capacity=capacity,
            prefill_s=t1 - t0,
            decode_s=t2 - t1,
            decode_steps=steps,
            decode_tokens_per_s=(b * steps / (t2 - t1)) if steps else 0.0,
        )
        counters1 = rt.link_counters() if hasattr(rt, "link_counters") else None
        if counters1 is not None:
            stats["link_counters"] = {
                k: [int(x) for x in (v if counters0 is None
                                     else v - counters0[k])]
                for k, v in counters1.items()}
    return out
